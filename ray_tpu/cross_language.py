"""Cross-language boundary — msgpack-typed task calls.

Reference: `python/ray/cross_language.py` + the msgpack serialization
boundary the reference uses between language workers (`java_function`,
`cpp_function`: tasks named by symbol, arguments restricted to
msgpack-representable types).  Here the non-Python frontend is the C++
client (`cpp/`), which drives the cluster through the thin-client server
(`client/server.py`) over the same socket RPC the Python client uses —
frames msgpack instead of pickle, sniffed per-frame in `_private/rpc.py`.

Functions callable from other languages are named either by an explicit
`register("name", fn)` or by import path `"pkg.module:attr"`.  Values
cross the boundary as msgpack types; numpy arrays ride as a tagged map
{"__nd__": 1, dtype, shape, data} for zero-copy-ish dense transfer.
"""

from __future__ import annotations

import importlib
from typing import Any, Callable, Dict

import numpy as np

_REGISTRY: Dict[str, Callable] = {}


def register(name: str, fn: Callable) -> None:
    """Expose `fn` to cross-language callers under `name`."""
    _REGISTRY[name] = fn


def resolve(func: str) -> Callable:
    """Registered name first, then `"pkg.module:attr"` import path."""
    fn = _REGISTRY.get(func)
    if fn is not None:
        return fn
    if ":" not in func:
        raise KeyError(
            f"cross-language function '{func}' is not registered and is "
            f"not a 'module:attr' import path")
    mod_name, attr = func.split(":", 1)
    mod = importlib.import_module(mod_name)
    fn = mod
    for part in attr.split("."):
        fn = getattr(fn, part)
    if not callable(fn):
        raise TypeError(f"'{func}' resolved to non-callable {fn!r}")
    return fn


# ---------------------------------------------------------- C++ task libs
class _CppFunction:
    """A remote-able callable that executes a C++ task-library function
    (reference: `cross_language.cpp_function`; architecture note in
    `cpp/include/ray_tpu/task_lib.hpp` — the library is dlopen'd inside
    the Python worker and called over a msgpack C ABI)."""

    def __init__(self, lib_path: str, func_name: str):
        self._lib_path = lib_path
        self._func = func_name
        self.__name__ = f"cpp:{func_name}"
        self.__qualname__ = self.__name__

    def __call__(self, *args, **kwargs):
        import ctypes
        import os

        import msgpack

        if kwargs:
            raise TypeError(
                f"C++ task '{self._func}' is positional-only (msgpack "
                f"C ABI); got keyword args {sorted(kwargs)}")

        # Resolve relative paths in the *worker's* cwd: with runtime_env
        # working_dir the .so lands in the unpacked working dir, which is
        # the worker's cwd — an absolute driver-side path would not exist
        # on remote nodes.
        path = self._lib_path
        if not os.path.isabs(path):
            path = os.path.join(os.getcwd(), path)
        lib = _load_task_lib(path)
        packed = msgpack.packb([encode(a) for a in args],
                               use_bin_type=True)
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_size_t()
        rc = lib.ray_tpu_call(
            self._func.encode(), packed, len(packed),
            ctypes.byref(out), ctypes.byref(out_len))
        result = msgpack.unpackb(_read_and_free(lib, out, out_len),
                                 raw=False)
        if rc != 0:
            names = _list_task_lib(lib)
            raise RuntimeError(
                f"C++ task '{self._func}' failed: {result} "
                f"(library exports: {names})")
        return decode(result)


_TASK_LIBS: Dict[str, Any] = {}


def _read_and_free(lib, out, out_len) -> bytes:
    import ctypes

    try:
        return ctypes.string_at(out, out_len.value)
    finally:
        lib.ray_tpu_free(out)


def _load_task_lib(path: str):
    lib = _TASK_LIBS.get(path)
    if lib is None:
        import ctypes

        lib = ctypes.CDLL(path)
        lib.ray_tpu_call.restype = ctypes.c_int
        lib.ray_tpu_call.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t)]
        lib.ray_tpu_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        lib.ray_tpu_list_tasks.restype = ctypes.c_int
        lib.ray_tpu_list_tasks.argtypes = [
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t)]
        try:  # actor ABI is optional (task-only libraries lack it)
            lib.ray_tpu_actor_new.restype = ctypes.c_int
            lib.ray_tpu_actor_new.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.POINTER(ctypes.c_size_t)]
            lib.ray_tpu_actor_call.restype = ctypes.c_int
            lib.ray_tpu_actor_call.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_size_t,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.POINTER(ctypes.c_size_t)]
            lib.ray_tpu_actor_free.argtypes = [ctypes.c_void_p]
        except AttributeError:
            pass
        _TASK_LIBS[path] = lib
    return lib


def _list_task_lib(lib) -> list:
    import ctypes

    out = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_size_t()
    lib.ray_tpu_list_tasks(ctypes.byref(out), ctypes.byref(out_len))
    raw = _read_and_free(lib, out, out_len)
    return [n.decode() for n in raw.split(b"\0") if n]


class _CppActorBase:
    """Instance side of a C++ actor class: the constructor runs INSIDE
    the actor worker process, dlopens the task library, and instantiates
    the registered C++ actor; method calls dispatch by name over the
    msgpack C ABI (reference: the cpp worker's RAY_REMOTE actor classes;
    architecture note in `cpp/include/ray_tpu/task_lib.hpp`)."""

    _LIB: str = ""
    _CLS: str = ""

    def __init__(self, *args):
        import ctypes
        import os

        import msgpack

        path = self._LIB
        if not os.path.isabs(path):
            path = os.path.join(os.getcwd(), path)
        lib = _load_task_lib(path)
        packed = msgpack.packb([encode(a) for a in args],
                               use_bin_type=True)
        handle = ctypes.c_void_p()
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_size_t()
        rc = lib.ray_tpu_actor_new(
            self._CLS.encode(), packed, len(packed),
            ctypes.byref(handle), ctypes.byref(out), ctypes.byref(out_len))
        err = msgpack.unpackb(_read_and_free(lib, out, out_len), raw=False)
        if rc != 0:
            raise RuntimeError(
                f"C++ actor '{self._CLS}' construction failed: {err}")
        self._lib = lib
        self._handle = handle

    def __getattr__(self, method):
        # Worker-side dispatch: the runtime getattrs the instance by
        # method name, so C++ methods need no Python declarations.
        if method.startswith("_"):
            raise AttributeError(method)

        def _call(*args):
            import ctypes

            import msgpack

            packed = msgpack.packb([encode(a) for a in args],
                                   use_bin_type=True)
            out = ctypes.POINTER(ctypes.c_uint8)()
            out_len = ctypes.c_size_t()
            rc = self._lib.ray_tpu_actor_call(
                self._handle, method.encode(), packed, len(packed),
                ctypes.byref(out), ctypes.byref(out_len))
            result = msgpack.unpackb(
                _read_and_free(self._lib, out, out_len), raw=False)
            if rc != 0:
                raise RuntimeError(
                    f"C++ actor method '{self._CLS}.{method}' failed: "
                    f"{result}")
            return decode(result)

        return _call

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            try:
                self._lib.ray_tpu_actor_free(handle)
            except Exception:
                pass
            self._handle = None


def cpp_actor_class(lib_path: str, cls_name: str) -> type:
    """A Python actor class backed by a C++ actor from a task library;
    wrap with ray_tpu.remote(...) and use like any actor.  Path rules
    match cpp_function (relative paths resolve in the worker's cwd)."""
    cls = type(f"Cpp{cls_name}", (_CppActorBase,),
               {"_LIB": lib_path, "_CLS": cls_name})
    return cls


def cpp_function(lib_path: str, func_name: str) -> _CppFunction:
    """A callable running `func_name` from a C++ task library; wrap with
    ray_tpu.remote(...) to run it as a cluster task.  `lib_path` must be
    reachable on the worker's filesystem; a *relative* path is resolved
    in the worker's cwd, so ship the .so via runtime_env working_dir on
    multi-node clusters and pass its in-package relative path."""
    return _CppFunction(lib_path, func_name)


# ------------------------------------------------------------ value codec
def encode(value: Any) -> Any:
    """Python value -> msgpack-representable tree."""
    if isinstance(value, np.ndarray):
        c = np.ascontiguousarray(value)
        return {"__nd__": 1, "dtype": str(c.dtype),
                "shape": list(c.shape), "data": c.tobytes()}
    if isinstance(value, np.generic):
        return value.item()
    if isinstance(value, (list, tuple)):
        return [encode(v) for v in value]
    if isinstance(value, dict):
        return {k: encode(v) for k, v in value.items()}
    if value is None or isinstance(value, (bool, int, float, str, bytes)):
        return value
    raise TypeError(
        f"value of type {type(value).__name__} cannot cross the "
        f"language boundary (msgpack types + numpy arrays only)")


def decode(value: Any) -> Any:
    """msgpack tree -> Python value (reconstructing tagged ndarrays)."""
    if isinstance(value, dict):
        if value.get("__nd__") == 1:
            return np.frombuffer(
                value["data"], dtype=np.dtype(value["dtype"])
            ).reshape(value["shape"]).copy()
        return {k: decode(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [decode(v) for v in value]
    return value
