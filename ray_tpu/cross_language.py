"""Cross-language boundary — msgpack-typed task calls.

Reference: `python/ray/cross_language.py` + the msgpack serialization
boundary the reference uses between language workers (`java_function`,
`cpp_function`: tasks named by symbol, arguments restricted to
msgpack-representable types).  Here the non-Python frontend is the C++
client (`cpp/`), which drives the cluster through the thin-client server
(`client/server.py`) over the same socket RPC the Python client uses —
frames msgpack instead of pickle, sniffed per-frame in `_private/rpc.py`.

Functions callable from other languages are named either by an explicit
`register("name", fn)` or by import path `"pkg.module:attr"`.  Values
cross the boundary as msgpack types; numpy arrays ride as a tagged map
{"__nd__": 1, dtype, shape, data} for zero-copy-ish dense transfer.
"""

from __future__ import annotations

import importlib
from typing import Any, Callable, Dict

import numpy as np

_REGISTRY: Dict[str, Callable] = {}


def register(name: str, fn: Callable) -> None:
    """Expose `fn` to cross-language callers under `name`."""
    _REGISTRY[name] = fn


def resolve(func: str) -> Callable:
    """Registered name first, then `"pkg.module:attr"` import path."""
    fn = _REGISTRY.get(func)
    if fn is not None:
        return fn
    if ":" not in func:
        raise KeyError(
            f"cross-language function '{func}' is not registered and is "
            f"not a 'module:attr' import path")
    mod_name, attr = func.split(":", 1)
    mod = importlib.import_module(mod_name)
    fn = mod
    for part in attr.split("."):
        fn = getattr(fn, part)
    if not callable(fn):
        raise TypeError(f"'{func}' resolved to non-callable {fn!r}")
    return fn


# ------------------------------------------------------------ value codec
def encode(value: Any) -> Any:
    """Python value -> msgpack-representable tree."""
    if isinstance(value, np.ndarray):
        c = np.ascontiguousarray(value)
        return {"__nd__": 1, "dtype": str(c.dtype),
                "shape": list(c.shape), "data": c.tobytes()}
    if isinstance(value, np.generic):
        return value.item()
    if isinstance(value, (list, tuple)):
        return [encode(v) for v in value]
    if isinstance(value, dict):
        return {k: encode(v) for k, v in value.items()}
    if value is None or isinstance(value, (bool, int, float, str, bytes)):
        return value
    raise TypeError(
        f"value of type {type(value).__name__} cannot cross the "
        f"language boundary (msgpack types + numpy arrays only)")


def decode(value: Any) -> Any:
    """msgpack tree -> Python value (reconstructing tagged ndarrays)."""
    if isinstance(value, dict):
        if value.get("__nd__") == 1:
            return np.frombuffer(
                value["data"], dtype=np.dtype(value["dtype"])
            ).reshape(value["shape"]).copy()
        return {k: decode(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [decode(v) for v in value]
    return value
