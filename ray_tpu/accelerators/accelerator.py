"""Accelerator abstraction (reference: `_private/accelerators/accelerator.py:5`).

An AcceleratorManager knows how to: detect how many accelerators this node
has, name their type, read/set the process-level visibility env var, and
validate per-task request quantities.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Dict, List, Optional


class AcceleratorManager(ABC):
    @staticmethod
    @abstractmethod
    def get_resource_name() -> str:
        """Resource name used in the scheduler (e.g. "TPU")."""

    @staticmethod
    @abstractmethod
    def get_visible_accelerator_ids_env_var() -> str:
        """Env var controlling per-process accelerator visibility."""

    @staticmethod
    @abstractmethod
    def get_current_node_num_accelerators() -> int:
        """Autodetect this node's accelerator count."""

    @staticmethod
    @abstractmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        """E.g. "v5litepod" / "v4"."""

    @staticmethod
    @abstractmethod
    def validate_resource_request_quantity(quantity: float
                                           ) -> "tuple[bool, Optional[str]]":
        """(valid, error_message)."""

    @staticmethod
    @abstractmethod
    def set_current_process_visible_accelerator_ids(ids: List[str]) -> None:
        ...

    @staticmethod
    def get_current_node_extra_resources() -> Dict[str, float]:
        """Additional custom resources this accelerator contributes (e.g.
        pod-slice gang resources for TPU)."""
        return {}
