"""TPU accelerator manager — first-class TPU detection and scheduling glue.

Reference: `python/ray/_private/accelerators/tpu.py` (`TPUAcceleratorManager`
at `:75`; chip autodetect via `/dev/accel*`/vfio + GCE metadata at `:52`;
`TPU_VISIBLE_CHIPS` + host-bounds env setting at `:158`; pod-aware extra
resources `TPU-{type}-head` and per-pod-name resource at `:335`; request
quantity enforcement at `:144`).

Detection priority:
1. ``RAY_TPU_FAKE_CHIPS`` env (tests: fake N chips without hardware),
2. ``/dev/accel*`` device files (PCI TPU VM),
3. ``/sys/class/vfio`` entries (newer TPU VM images),
4. jax device enumeration if jax is already initialized on a TPU platform,
5. GCE metadata server (pod topology / accelerator type).
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Dict, List, Optional, Tuple

from ray_tpu.accelerators.accelerator import AcceleratorManager

TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
TPU_CHIPS_PER_HOST_BOUNDS_ENV = "TPU_CHIPS_PER_HOST_BOUNDS"
TPU_HOST_BOUNDS_ENV = "TPU_HOST_BOUNDS"
FAKE_CHIPS_ENV = "RAY_TPU_FAKE_CHIPS"
FAKE_POD_TYPE_ENV = "RAY_TPU_FAKE_POD_TYPE"  # e.g. "v5e-16"
FAKE_POD_NAME_ENV = "RAY_TPU_FAKE_POD_NAME"
FAKE_WORKER_ID_ENV = "RAY_TPU_FAKE_WORKER_ID"

GCE_METADATA_URL = "http://metadata.google.internal/computeMetadata/v1"

# Valid single-host chip request sizes (reference tpu.py:144: {1, 2, 4}).
VALID_CHIP_COUNTS = (1, 2, 4)


def _gce_metadata(path: str) -> Optional[str]:
    try:
        import urllib.request

        req = urllib.request.Request(
            f"{GCE_METADATA_URL}/{path}",
            headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=1) as resp:
            return resp.read().decode()
    except Exception:
        return None


class TPUAcceleratorManager(AcceleratorManager):
    @staticmethod
    def get_resource_name() -> str:
        return "TPU"

    @staticmethod
    def get_visible_accelerator_ids_env_var() -> str:
        return TPU_VISIBLE_CHIPS_ENV

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        fake = os.environ.get(FAKE_CHIPS_ENV)
        if fake is not None:
            return int(fake)
        chips = glob.glob("/dev/accel*")
        if chips:
            return len(chips)
        vfio = glob.glob("/dev/vfio/[0-9]*")
        if vfio:
            return len(vfio)
        # If jax has already INITIALIZED a backend in this process and it
        # is a TPU, trust it. Merely-imported jax is not enough: calling
        # jax.devices() would trigger backend init here, and when the
        # accelerator transport is down that call hangs — wedging
        # ray_tpu.init() itself (the round-4 dryrun lost its signal to
        # exactly this; jax is pre-imported in some environments).
        try:
            import sys

            jax = sys.modules.get("jax")
            if jax is not None:
                from jax._src import xla_bridge

                if not getattr(
                        xla_bridge, "backends_are_initialized",
                        lambda: bool(getattr(xla_bridge, "_backends",
                                             None)))():
                    return 0
                devs = jax.devices()
                if devs and "tpu" in devs[0].platform.lower() or (
                        devs and "TPU" in getattr(devs[0], "device_kind", "")):
                    return len([d for d in devs
                                if "TPU" in getattr(d, "device_kind", "")])
        except Exception:
            pass
        return 0

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        fake = os.environ.get(FAKE_POD_TYPE_ENV)
        if fake:
            return fake
        accel_type = _gce_metadata("instance/attributes/accelerator-type")
        return accel_type

    @staticmethod
    def get_current_pod_name() -> Optional[str]:
        fake = os.environ.get(FAKE_POD_NAME_ENV)
        if fake:
            return fake
        return _gce_metadata("instance/attributes/instance-id")

    @staticmethod
    def get_current_pod_worker_count() -> Optional[int]:
        accel_type = TPUAcceleratorManager.get_current_node_accelerator_type()
        if accel_type is None:
            return None
        chips = _pod_chip_count(accel_type)
        if chips is None:
            return None
        per_host = TPUAcceleratorManager.get_current_node_num_accelerators() or 4
        return max(1, chips // per_host)

    @staticmethod
    def validate_resource_request_quantity(quantity: float
                                           ) -> Tuple[bool, Optional[str]]:
        if quantity != int(quantity):
            if 0 < quantity < 1:
                return True, None  # fractional share of one chip
            return False, f"TPU request must be integral or <1, got {quantity}"
        if int(quantity) in VALID_CHIP_COUNTS or quantity == 0:
            return True, None
        return (False,
                f"TPU request quantity must be one of {VALID_CHIP_COUNTS} "
                f"(a single host's chips cannot be split further); got "
                f"{quantity}. For multi-host slices use pod gang resources "
                f"(e.g. 'TPU-v5e-16-head').")

    @staticmethod
    def set_current_process_visible_accelerator_ids(ids: List[str]) -> None:
        os.environ[TPU_VISIBLE_CHIPS_ENV] = ",".join(str(i) for i in ids)
        # Single-chip processes must also shrink the host bounds so the TPU
        # runtime doesn't try to grab the full host (reference tpu.py:158).
        n = len(ids)
        if n == 1:
            os.environ[TPU_CHIPS_PER_HOST_BOUNDS_ENV] = "1,1,1"
            os.environ[TPU_HOST_BOUNDS_ENV] = "1,1,1"
        elif n == 2:
            os.environ[TPU_CHIPS_PER_HOST_BOUNDS_ENV] = "1,2,1"
            os.environ[TPU_HOST_BOUNDS_ENV] = "1,1,1"
        else:
            os.environ.pop(TPU_CHIPS_PER_HOST_BOUNDS_ENV, None)
            os.environ.pop(TPU_HOST_BOUNDS_ENV, None)

    @staticmethod
    def get_current_node_extra_resources() -> Dict[str, float]:
        """Pod-gang resources (reference tpu.py:335): every host in a slice
        carries `TPU-{type}` and the pod-name resource; worker 0 additionally
        carries `TPU-{type}-head` so exactly one task can claim the slice."""
        out: Dict[str, float] = {}
        accel_type = TPUAcceleratorManager.get_current_node_accelerator_type()
        if not accel_type:
            return out
        version = _accel_version(accel_type)
        if version:
            out[f"TPU-{version}"] = \
                TPUAcceleratorManager.get_current_node_num_accelerators() or 1
        pod_name = TPUAcceleratorManager.get_current_pod_name()
        if pod_name:
            out[f"{pod_name}"] = 1
        worker_id = os.environ.get(FAKE_WORKER_ID_ENV)
        if worker_id is None:
            worker_id = _gce_metadata("instance/attributes/agent-worker-number")
        if worker_id is not None and str(worker_id).strip() == "0":
            out[f"TPU-{accel_type}-head"] = 1
        return out


def _accel_version(accel_type: str) -> Optional[str]:
    """'v5litepod-16' -> 'v5litepod'; 'v5e-16' -> 'v5e'; 'v4-8' -> 'v4'."""
    m = re.match(r"^(v\d+[a-z]*)-(\d+)$", accel_type)
    return m.group(1) if m else None


def _pod_chip_count(accel_type: str) -> Optional[int]:
    m = re.match(r"^v\d+[a-z]*-(\d+)$", accel_type)
    if not m:
        return None
    n = int(m.group(1))
    # v2/v3/v4 advertise cores; v5e/v5p/v6e advertise chips. Treat the suffix
    # as the chip count for v5e-style names.
    return n


# ---------------------------------------------------------------------------
# Public helpers (reference: `python/ray/util/accelerators/tpu.py`).
# ---------------------------------------------------------------------------

def pod_head_resource(accel_type: str) -> Dict[str, float]:
    """Resource demand that gang-claims a whole pod slice via its head."""
    return {f"TPU-{accel_type}-head": 1}


def get_current_pod_worker_count() -> Optional[int]:
    return TPUAcceleratorManager.get_current_pod_worker_count()


def get_current_pod_name() -> Optional[str]:
    return TPUAcceleratorManager.get_current_pod_name()


def get_num_tpu_chips_on_node() -> int:
    return TPUAcceleratorManager.get_current_node_num_accelerators()
