from ray_tpu.accelerators.accelerator import AcceleratorManager
from ray_tpu.accelerators.tpu import TPUAcceleratorManager

__all__ = ["AcceleratorManager", "TPUAcceleratorManager"]
