"""Cluster event schema registry — the failure-forensics vocabulary.

Reference: `src/ray/protobuf/event.proto` (structured export events) and
the `WorkerExitType` taxonomy consumed by `gcs_worker_manager`. Every
event the framework records in the GCS ClusterEventLog MUST use a type
declared here; a unit-test lint (tests/test_failure_forensics.py)
enforces that, plus that every registered type is documented in the
dashboard endpoint table (`ray_tpu/dashboard/head.py` docstring).

The taxonomy exists so a dead worker is diagnosable from the driver:
the raylet classifies each exit from the waitpid status (exit code vs.
signal, cross-checked against the memory monitor's kill list and the
pool's own intended retirements), and that classification rides the
worker-death error all the way into the exception message.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

SEVERITIES = ("INFO", "WARNING", "ERROR")

# Registered event types -> one-line description. One reviewable place;
# emission sites reference these names as string literals so the lint
# can cross-check them statically.
EVENT_TYPES: Dict[str, str] = {
    "WORKER_EXIT": "A worker process left the node's pool "
                   "(classified by exit taxonomy).",
    "ACTOR_DEATH": "An actor died permanently (restarts exhausted or "
                   "no_restart kill).",
    "ACTOR_RESTART": "An actor died and is being restarted.",
    "NODE_ADDED": "A raylet registered with the GCS.",
    "NODE_REMOVED": "A node was marked DEAD (drain, health-check "
                    "failure, ...).",
    "LEASE_RECLAIMED": "A raylet reclaimed a task-worker lease whose "
                       "owner died.",
    "TASK_RETRY": "A task attempt failed and is being retried.",
    "SPILL_PRESSURE": "An object store spilled under memory pressure.",
    "JOB_STARTED": "A driver registered a job.",
    "JOB_FINISHED": "A job was marked finished.",
    # Control-plane decisions (the metrics-driven controllers): each
    # carries the triggering metric reading in its extra fields so the
    # event log alone answers "why did it scale / preempt / throttle".
    "AUTOSCALE_UP": "The serve autoscaler added replicas to a "
                    "deployment.",
    "AUTOSCALE_DOWN": "The serve autoscaler removed replicas from a "
                      "deployment.",
    "PREEMPT_RESCHEDULE": "The memory monitor preemptively retired the "
                          "largest leased worker before the OOM-kill "
                          "threshold; its task reschedules via the "
                          "normal retry path.",
    "BACKPRESSURE_ADJUST": "A data executor retuned its inflight/queued "
                           "limits from the backpressure gauges.",
    # Train goodput / straggler plane (observability/goodput.py + the
    # GCS step matrix): both carry the forensics inline — the straggler
    # flag names the dominant phase, the stall event attaches the
    # auto-captured thread stacks of the stalled worker.
    "TRAIN_STRAGGLER": "A train worker's step time exceeded the pod "
                       "median by the straggler threshold (the event "
                       "names the dominant phase).",
    "TRAIN_STALL": "A train worker missed its step-report heartbeats; "
                   "thread stacks were auto-captured from the stalled "
                   "worker and attached.",
    # Serve cost-accounting / SLO plane (observability/accounting.py +
    # the GCS accounting ring): the burn event carries the fast/slow
    # burn rates and attainment so the autoscaler / quota controllers
    # can act on it without a second lookup.
    "SLO_BURN": "A serve lane is burning its SLO error budget: both "
                "the fast and slow burn-rate windows exceed their "
                "thresholds for TTFT/TPOT attainment.",
    # XLA attribution plane (observability/xla.py): the regression
    # sentinel compares every re-compile's cost analysis and every
    # sampled wall against the function's baseline program.
    "PERF_REGRESSION": "A tracked program's FLOPs, peak HBM bytes, or "
                       "sampled wall drifted past xla_regression_ratio "
                       "times its baseline (the event names the "
                       "program and the drifted dimension).",
}

# Worker exit taxonomy (reference: `WorkerExitType`). The raylet picks
# one per reaped worker; OOM_KILLED and INTENDED_EXIT take precedence
# over the raw waitpid status because the raylet itself caused those
# deaths (a SIGKILL it sent must not read as SYSTEM_ERROR).
WORKER_EXIT_TYPES = (
    "INTENDED_EXIT",   # clean exit 0, pool retirement, ray_tpu.kill
    "USER_ERROR",      # nonzero exit code (uncaught exception, sys.exit)
    "SYSTEM_ERROR",    # killed by a signal the framework didn't send
    "OOM_KILLED",      # shot by the node memory monitor
    "PREEMPT_RESCHEDULE",  # proactively retired below the kill
                           # threshold; task retries elsewhere
    "NODE_DEATH",      # the whole node went away
)

# Default severity per event type; emitters may override (e.g. a
# WORKER_EXIT is INFO when intended, ERROR when OOM-killed).
DEFAULT_SEVERITY: Dict[str, str] = {
    "WORKER_EXIT": "WARNING",
    "ACTOR_DEATH": "ERROR",
    "ACTOR_RESTART": "WARNING",
    "NODE_ADDED": "INFO",
    "NODE_REMOVED": "ERROR",
    "LEASE_RECLAIMED": "WARNING",
    "TASK_RETRY": "WARNING",
    "SPILL_PRESSURE": "WARNING",
    "JOB_STARTED": "INFO",
    "JOB_FINISHED": "INFO",
    "AUTOSCALE_UP": "INFO",
    "AUTOSCALE_DOWN": "INFO",
    "PREEMPT_RESCHEDULE": "WARNING",
    "BACKPRESSURE_ADJUST": "INFO",
    "TRAIN_STRAGGLER": "WARNING",
    "TRAIN_STALL": "ERROR",
    "SLO_BURN": "WARNING",
    "PERF_REGRESSION": "WARNING",
}

_EXIT_SEVERITY = {
    "INTENDED_EXIT": "INFO",
    "USER_ERROR": "WARNING",
    "SYSTEM_ERROR": "ERROR",
    "OOM_KILLED": "ERROR",
    # Deliberate, recoverable: the task retries — an ERROR here would
    # page on the controller doing its job.
    "PREEMPT_RESCHEDULE": "WARNING",
    "NODE_DEATH": "ERROR",
}


def make_event(event_type: str, message: str,
               severity: Optional[str] = None,
               node_id: Optional[str] = None,
               **extra: Any) -> Dict[str, Any]:
    """Build a validated, JSON-able event record. ``node_id`` and all
    ``extra`` values must already be plain (hex strings, ints) — events
    flow to the dashboard's JSON endpoints unmodified."""
    if event_type not in EVENT_TYPES:
        raise ValueError(f"unregistered cluster event type {event_type!r}; "
                         f"declare it in ray_tpu.observability.events")
    sev = severity or DEFAULT_SEVERITY[event_type]
    if sev not in SEVERITIES:
        raise ValueError(f"unknown severity {sev!r} (want one of "
                         f"{SEVERITIES})")
    event = {"type": event_type, "severity": sev, "message": message,
             "node_id": node_id, "ts": time.time()}
    event.update(extra)
    return event


def classify_worker_exit(returncode: Optional[int], *,
                         oom_killed: bool = False,
                         intended: bool = False,
                         preempted: bool = False) -> str:
    """Map a reaped worker's waitpid status to the exit taxonomy.

    Popen semantics: negative returncode = killed by that signal,
    0 = clean exit, positive = abnormal interpreter exit. The
    raylet-caused deaths override the raw status — the raylet SIGKILLs
    retired pool workers (intended), OOM victims, and memory-pressure
    preemptions. OOM wins over preemption: if the kill threshold fired
    on a worker already marked for preemption, the stronger verdict is
    the true one."""
    if oom_killed:
        return "OOM_KILLED"
    if preempted:
        return "PREEMPT_RESCHEDULE"
    if intended:
        return "INTENDED_EXIT"
    if returncode is None or returncode == 0:
        return "INTENDED_EXIT"
    if returncode < 0:
        return "SYSTEM_ERROR"
    return "USER_ERROR"


def exit_severity(exit_type: str) -> str:
    return _EXIT_SEVERITY.get(exit_type, "WARNING")


def format_exit_detail(info: Optional[Dict[str, Any]],
                       recent_events: Optional[List[Dict[str, Any]]] = None
                       ) -> str:
    """Render a worker-exit info record (raylet ``get_worker_exit_info``)
    plus recent same-node events into the suffix of a death error
    message. Returns "" when nothing is known."""
    if not info:
        return ""
    parts: List[str] = []
    exit_type = info.get("exit_type")
    if exit_type:
        code = info.get("exit_code")
        parts.append(f"exit type: {exit_type}"
                     + (f" (exit code {code})" if code is not None else ""))
    for key, label in (("last_lines", "last stdout lines"),
                       ("last_err_lines", "last stderr lines")):
        lines = info.get(key)
        if lines:
            body = "\n".join(f"    {ln}" for ln in lines)
            parts.append(f"{label}:\n{body}")
    if recent_events:
        body = "\n".join(
            f"    [{e.get('severity')}] {e.get('type')}: {e.get('message')}"
            for e in recent_events)
        parts.append(f"recent events on the node:\n{body}")
    if not parts:
        return ""
    return "\n  " + "\n  ".join(parts)
