"""Per-program XLA cost & roofline attribution plane.

Every other observability plane (traces PR 17, goodput PR 18, request
cost PR 19) stops at wall-clock time; this one reaches into the
compiler.  :class:`~ray_tpu.observability.jit.TrackedJit` already
intercepts every trace/compile — on each new program this module
captures XLA's own ``cost_analysis()`` (flops, bytes accessed,
transcendentals) and ``memory_analysis()`` (argument/output/temp/peak
HBM bytes) into a per-process :class:`ProgramRegistry` row keyed by
``(fn, program_signature)``.  The capture itself (an AOT compile of
the program's shape skeleton) runs on a serialized background worker —
the hot path queues a closure of ShapeDtypeStructs and returns; tests
synchronize with :func:`flush_captures`.  Steady-state execution walls are sampled
every Nth call (``xla_wall_sample_every``; 0 keeps ``block_until_ready``
entirely off the hot path) and divided into the chip-spec peaks
(observability/chipspec.py) to derive:

- **MFU** — achieved FLOP/s over the chip's peak FLOP/s,
- **MBU** — achieved HBM bytes/s over the chip's peak bandwidth,
- a **roofline verdict** — ``comm-bound`` when the exposed-collective
  fraction of the sampled wall (PR-12's overlap accounting) exceeds
  ``xla_comm_bound_fraction``, else ``compute-bound``/``memory-bound``
  by whichever side of the roofline the program sits on, and
- **lost-to-roofline headroom** — sampled wall minus the roofline-ideal
  wall, the seconds/call the fleet could reclaim at 100% utilization.

Rows publish fire-and-forget into the bounded GCS ring
(``report_xla_programs``; ``util.state.xla_summary()`` /
``GET /api/programs`` roll the fleet up) and export as the
``rtpu_xla_program_{flops,bytes_hbm,mfu,mbu}`` gauge families plus the
``rtpu_xla_program_wall_seconds`` histogram (trace exemplars).

The **regression sentinel** closes the loop: the first program a
function compiles becomes its baseline (flops, peak HBM, sampled wall);
any later re-compile or wall sample drifting past
``xla_regression_ratio`` emits ONE typed ``PERF_REGRESSION`` cluster
event naming the program and the drifted dimension, and re-arms only
when the dimension returns within the ratio (one event per episode —
a recompile that silently doubles FLOPs is visible the moment it
happens, and a noisy wall cannot page once per sample).

On CPU backends every row is tagged ``measurement: "cpu"`` (nominal
chipspec peaks): the plumbing is identical, the ratios prove wiring,
not performance.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ray_tpu.observability import chipspec

_lock = threading.Lock()
_registry: Optional["ProgramRegistry"] = None
_metrics = None

# Sampled program walls: sub-millisecond CPU ticks to multi-second
# pod-scale steps.
_WALL_BOUNDARIES = (0.0001, 0.0005, 0.001, 0.005, 0.025, 0.1, 0.5,
                    2.0, 10.0, 60.0)

_EWMA_ALPHA = 0.3


# ------------------------------------------------------------------ knobs

def attribution_enabled() -> bool:
    """The ``xla_attribution_instrumentation`` master switch."""
    try:
        from ray_tpu._private.config import GlobalConfig

        return bool(GlobalConfig.xla_attribution_instrumentation)
    except Exception:
        return False


def wall_sample_every() -> int:
    """Sampling period of steady-state walls; 0 disables sampling (and
    with it every ``block_until_ready`` the plane would issue)."""
    try:
        from ray_tpu._private.config import GlobalConfig

        return max(int(GlobalConfig.xla_wall_sample_every), 0)
    except Exception:
        return 0


# ---------------------------------------------------------------- metrics

class XlaMetrics:
    def __init__(self):
        from ray_tpu.util.metrics import Gauge, Histogram

        tag_keys = ("fn",)
        self.flops = Gauge(
            "xla_program_flops", tag_keys=tag_keys,
            description="XLA cost-analysis FLOPs of the newest compiled "
                        "program per tracked function.")
        self.bytes_hbm = Gauge(
            "xla_program_bytes_hbm", tag_keys=tag_keys,
            description="Peak HBM bytes (argument+output+temp-alias) of "
                        "the newest compiled program per tracked "
                        "function.")
        self.mfu = Gauge(
            "xla_program_mfu", tag_keys=tag_keys,
            description="Model FLOP utilization of the newest sampled "
                        "wall: achieved FLOP/s over the chip-spec peak "
                        "(CPU rows use the nominal cpu spec — plumbing, "
                        "not performance).")
        self.mbu = Gauge(
            "xla_program_mbu", tag_keys=tag_keys,
            description="Memory-bandwidth utilization of the newest "
                        "sampled wall: achieved HBM bytes/s over the "
                        "chip-spec peak bandwidth.")
        self.wall_seconds = Histogram(
            "xla_program_wall_seconds", boundaries=_WALL_BOUNDARIES,
            tag_keys=tag_keys,
            description="Sampled steady-state execution wall of tracked "
                        "programs (every xla_wall_sample_every-th call, "
                        "block_until_ready-fenced).")


def xla_metrics() -> XlaMetrics:
    global _metrics
    with _lock:
        if _metrics is None:
            _metrics = XlaMetrics()
        return _metrics


# --------------------------------------------------------------- registry

def _cost_dict(compiled) -> Dict[str, float]:
    """Normalize ``compiled.cost_analysis()`` — a dict on some backends,
    a list of per-computation dicts on others (CPU jax 0.4.x)."""
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost or {})


def _memory_dict(compiled) -> Dict[str, float]:
    """Flatten ``compiled.memory_analysis()`` (CompiledMemoryStats) into
    the row fields.  Peak HBM follows XLA's own accounting: arguments +
    outputs + temps, minus bytes aliased between them."""
    mem = compiled.memory_analysis()
    arg = float(getattr(mem, "argument_size_in_bytes", 0) or 0)
    out = float(getattr(mem, "output_size_in_bytes", 0) or 0)
    temp = float(getattr(mem, "temp_size_in_bytes", 0) or 0)
    alias = float(getattr(mem, "alias_size_in_bytes", 0) or 0)
    return {
        "arg_bytes": arg,
        "out_bytes": out,
        "temp_bytes": temp,
        "alias_bytes": alias,
        "peak_hbm_bytes": max(arg + out + temp - alias, 0.0),
    }


class ProgramRegistry:
    """Per-process table of compiled-program cost rows, keyed by
    ``(fn, signature)``, plus the per-function regression sentinel."""

    # Sentinel dimensions and the row/baseline field each compares.
    _SENTINEL_DIMS = ("flops", "peak_hbm_bytes", "wall_s")

    def __init__(self):
        self._lock = threading.Lock()
        self._rows: Dict[Tuple[str, str], Dict[str, Any]] = {}
        # fn -> {"flops", "peak_hbm_bytes", "wall_s"} of its FIRST
        # program — the drift reference.
        self._baselines: Dict[str, Dict[str, float]] = {}
        # fn -> set of dimensions currently in a fired episode.
        self._episodes: Dict[str, set] = {}

    # -- capture ----------------------------------------------------

    def record_compile(self, fn: str, signature: str, compiled,
                       compile_seconds: float,
                       calls: int = 0) -> Optional[Dict[str, Any]]:
        """Capture one newly compiled program's cost/memory analysis.
        Returns the (published) row, or None when the backend exposes
        no analysis for it."""
        try:
            cost = _cost_dict(compiled)
            mem = _memory_dict(compiled)
        except Exception:
            return None
        spec = chipspec.local_spec()
        row = {
            "fn": fn,
            "signature": signature,
            "flops": float(cost.get("flops", 0.0) or 0.0),
            "bytes_accessed": float(
                cost.get("bytes accessed", 0.0) or 0.0),
            "transcendentals": float(
                cost.get("transcendentals", 0.0) or 0.0),
            **mem,
            "compile_seconds": float(compile_seconds),
            "calls": int(calls),
            "samples": 0,
            "wall_s": None,
            "achieved_flops_per_s": None,
            "achieved_bytes_per_s": None,
            "mfu": None,
            "mbu": None,
            "exposed_comm_fraction": 0.0,
            "verdict": "unsampled",
            "lost_roofline_s_per_call": None,
            "lost_roofline_s_total": None,
            "spec": spec.spec,
            "measurement": spec.measurement,
            "pid": os.getpid(),
            "ts": time.time(),
        }
        with self._lock:
            fresh_program = (fn, signature) not in self._rows
            self._rows[(fn, signature)] = row
            baseline = self._baselines.get(fn)
            if baseline is None:
                # First program of this function: it IS the baseline.
                self._baselines[fn] = {
                    "flops": row["flops"],
                    "peak_hbm_bytes": row["peak_hbm_bytes"],
                    "wall_s": None,
                    "signature": signature,
                }
                baseline = None
        try:
            m = xla_metrics()
            tags = {"fn": fn}
            m.flops.set(row["flops"], tags=tags)
            m.bytes_hbm.set(row["peak_hbm_bytes"], tags=tags)
        except Exception:
            pass
        if baseline is not None and fresh_program:
            # A re-compile of a function with a baseline: check the
            # static dimensions for drift right now — a recompile that
            # doubles FLOPs must be visible before any wall sample.
            self._check_drift(fn, row, baseline,
                              dims=("flops", "peak_hbm_bytes"))
        _publish_row(row)
        return row

    def record_sample(self, fn: str, signature: str, wall_s: float,
                      exposed_comm_s: float = 0.0,
                      calls: int = 0,
                      trace_id: Optional[str] = None
                      ) -> Optional[Dict[str, Any]]:
        """Fold one sampled steady-state wall into the program's row:
        EWMA wall, achieved rates, MFU/MBU, roofline verdict, headroom.
        No-op for programs the registry never captured."""
        wall_s = float(wall_s)
        if wall_s <= 0:
            return None
        with self._lock:
            row = self._rows.get((fn, signature))
            if row is None:
                return None
            prev = row["wall_s"]
            row["wall_s"] = (wall_s if prev is None else
                             _EWMA_ALPHA * wall_s
                             + (1 - _EWMA_ALPHA) * prev)
            row["samples"] += 1
            if calls:
                row["calls"] = int(calls)
            row["ts"] = time.time()
            self._derive_locked(row, exposed_comm_s / wall_s)
            baseline = self._baselines.get(fn)
            if baseline is not None and baseline["wall_s"] is None \
                    and baseline["signature"] == signature:
                baseline["wall_s"] = row["wall_s"]
            row = dict(row)
        try:
            m = xla_metrics()
            tags = {"fn": fn}
            m.wall_seconds.observe(wall_s, tags=tags, trace_id=trace_id)
            if row["mfu"] is not None:
                m.mfu.set(row["mfu"], tags=tags)
            if row["mbu"] is not None:
                m.mbu.set(row["mbu"], tags=tags)
        except Exception:
            pass
        if baseline is not None:
            self._check_drift(fn, row, baseline, dims=("wall_s",))
        _publish_row(row)
        return row

    def _derive_locked(self, row: Dict[str, Any],
                       exposed_fraction: float) -> None:
        """Recompute the derived columns of one row in place (holding
        the registry lock)."""
        wall = row["wall_s"]
        row["achieved_flops_per_s"] = row["flops"] / wall
        row["achieved_bytes_per_s"] = row["bytes_accessed"] / wall
        row["exposed_comm_fraction"] = min(max(exposed_fraction, 0.0),
                                           1.0)
        spec = chipspec.lookup(row["spec"])
        if not spec.known:
            row["mfu"] = None
            row["mbu"] = None
            row["lost_roofline_s_per_call"] = None
            row["lost_roofline_s_total"] = None
            row["verdict"] = "unknown"
            return
        row["mfu"] = row["achieved_flops_per_s"] / spec.peak_flops
        row["mbu"] = (row["achieved_bytes_per_s"]
                      / spec.peak_hbm_bytes_per_s)
        # Roofline-ideal wall: the slower of "all flops at peak" and
        # "all bytes at peak bandwidth". What the sampled wall spends
        # beyond that is reclaimable headroom.
        ideal = max(row["flops"] / spec.peak_flops,
                    row["bytes_accessed"] / spec.peak_hbm_bytes_per_s)
        lost = max(wall - ideal, 0.0)
        row["lost_roofline_s_per_call"] = lost
        row["lost_roofline_s_total"] = lost * max(row["calls"], 1)
        try:
            from ray_tpu._private.config import GlobalConfig

            comm_threshold = float(GlobalConfig.xla_comm_bound_fraction)
        except Exception:
            comm_threshold = 0.5
        if row["exposed_comm_fraction"] > comm_threshold:
            row["verdict"] = "comm-bound"
        elif row["mfu"] >= row["mbu"]:
            row["verdict"] = "compute-bound"
        else:
            row["verdict"] = "memory-bound"

    # -- regression sentinel ----------------------------------------

    def _check_drift(self, fn: str, row: Dict[str, Any],
                     baseline: Dict[str, float], dims) -> None:
        """Compare ``row`` against the function's baseline on ``dims``;
        fire PERF_REGRESSION once per drifted-dimension episode."""
        try:
            from ray_tpu._private.config import GlobalConfig

            ratio_limit = float(GlobalConfig.xla_regression_ratio)
        except Exception:
            ratio_limit = 1.5
        if ratio_limit <= 0:
            return
        for dim in dims:
            base = baseline.get(dim)
            cur = row.get(dim)
            if not base or cur is None:
                continue
            ratio = float(cur) / float(base)
            with self._lock:
                episode = self._episodes.setdefault(fn, set())
                if ratio > ratio_limit:
                    if dim in episode:
                        continue  # already fired this episode
                    episode.add(dim)
                    fire = True
                else:
                    episode.discard(dim)  # back within: re-arm
                    fire = False
            if fire:
                _emit_regression(fn, row, dim, ratio, float(base),
                                 float(cur))

    # -- views ------------------------------------------------------

    def rows(self):
        with self._lock:
            return [dict(r) for r in self._rows.values()]

    def row(self, fn: str, signature: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            r = self._rows.get((fn, signature))
            return dict(r) if r else None

    def baseline(self, fn: str) -> Optional[Dict[str, float]]:
        with self._lock:
            b = self._baselines.get(fn)
            return dict(b) if b else None

    def clear(self) -> None:
        with self._lock:
            self._rows.clear()
            self._baselines.clear()
            self._episodes.clear()


def program_registry() -> ProgramRegistry:
    """The per-process registry singleton."""
    global _registry
    with _lock:
        if _registry is None:
            _registry = ProgramRegistry()
        return _registry


# ---------------------------------------------------- TrackedJit bridge

_capture_pool = None
_pending_captures: list = []


def _capture_executor():
    """One serialized background worker for AOT capture compiles: the
    ``compiled()`` call behind ``cost_analysis()`` is a real XLA
    compile (minutes at pod scale), and paying it inline would double
    every tracked compile wall. The wrapper's suppression flag is
    thread-local, so the worker's internal traces never touch the
    user-facing counters."""
    global _capture_pool
    with _lock:
        if _capture_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            _capture_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="xla-capture")
        return _capture_pool


def _capture(fn: str, signature: str, tracked, abs_args, abs_kwargs,
             seconds: float) -> None:
    try:
        compiled = tracked.compiled(*abs_args, **abs_kwargs)
        if compiled is None:
            return
        program_registry().record_compile(
            fn, signature, compiled, seconds,
            calls=getattr(tracked, "calls", 0))
    except Exception:
        pass  # a failed capture must never poison the worker


def on_tracked_compile(tracked, seconds: float, args, kwargs) -> None:
    """Attribution hook ``TrackedJit._on_compile`` calls on every new
    program: queue a background capture of its cost/memory analysis.
    Only the cheap argument abstraction happens on the caller — the
    closure holds ShapeDtypeStructs, never (possibly donated) device
    buffers, and the capture compile itself runs off the hot path."""
    if not attribution_enabled():
        return
    from ray_tpu.observability.jit import _arg_signature

    signature = _arg_signature(args, kwargs)
    try:
        abs_args, abs_kwargs = tracked._abstract_args(args, kwargs)
    except Exception:
        return
    fut = _capture_executor().submit(
        _capture, tracked.name, signature, tracked, abs_args,
        abs_kwargs, seconds)
    with _lock:
        _pending_captures.append(fut)
        # Bound the ledger: stragglers past this are unreachable from
        # flush_captures but still run to completion on the worker.
        del _pending_captures[:-256]


def flush_captures(timeout: float = 30.0) -> bool:
    """Block until every queued compile capture has landed in the
    registry (tests and benches synchronize on this before asserting;
    production code never needs it). True when the queue drained."""
    import concurrent.futures

    with _lock:
        pending = _pending_captures[:]
        _pending_captures.clear()
    if not pending:
        return True
    concurrent.futures.wait(pending, timeout=timeout)
    return all(f.done() for f in pending)


def on_tracked_sample(tracked, signature: str, wall_s: float,
                      exposed_comm_s: float) -> None:
    """Sampled-wall hook: fold one fenced execution wall into the row,
    stamping the live trace (if any) as the metric exemplar."""
    trace_id = None
    try:
        from ray_tpu.util.tracing import current_trace

        ctx = current_trace()
        if ctx is not None:
            trace_id = getattr(ctx, "trace_id", None)
    except Exception:
        pass
    program_registry().record_sample(
        tracked.name, signature, wall_s,
        exposed_comm_s=exposed_comm_s,
        calls=getattr(tracked, "calls", 0), trace_id=trace_id)


# ------------------------------------------------------------ publication

def _publish_row(row: Dict[str, Any]) -> bool:
    """Fire-and-forget report of one program row into the GCS ring
    (``report_xla_programs``). False (silently) outside a connected
    worker — a bare process still gets the local registry + metrics."""
    try:
        from ray_tpu._private.worker import global_worker_or_none

        w = global_worker_or_none()
        if w is None or getattr(w, "_dead", False):
            return False
        payload = dict(row)
        payload.setdefault("node_id", w.node_id)
        w.gcs.cast("report_xla_programs", row=payload)
        return True
    except Exception:
        return False


def _emit_regression(fn: str, row: Dict[str, Any], dim: str,
                     ratio: float, base: float, cur: float) -> None:
    """One typed PERF_REGRESSION cluster event naming the program and
    the drifted dimension."""
    message = (f"program {fn!r} {row.get('signature', '')}: {dim} "
               f"drifted to {ratio:.2f}x its baseline "
               f"({base:.4g} -> {cur:.4g})")
    try:
        from ray_tpu._private.worker import global_worker_or_none

        w = global_worker_or_none()
        if w is None or getattr(w, "_dead", False):
            return
        w.gcs.call(
            "report_cluster_event", event_type="PERF_REGRESSION",
            message=message,
            extra={"fn": fn, "signature": row.get("signature"),
                   "dimension": dim, "ratio": ratio,
                   "baseline": base, "current": cur,
                   "measurement": row.get("measurement")},
            timeout=5)
    except Exception:
        pass  # the sentinel must never take down the sampled call


def local_programs():
    """This process's registry rows (fleet view: util.state.xla_summary)."""
    return program_registry().rows()
