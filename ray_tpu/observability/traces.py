"""Tail-sampled trace store — the GCS-side home of request traces.

Trace-tagged SPAN events arrive with every ``push_task_events`` batch
and accumulate per trace_id. Nothing is kept or dropped until the trace
*completes* (its root span, tagged ``attrs["trace_root"]``, arrives) —
that is tail-sampling, the property head-sampling cannot give: the
decision sees the whole trace, so every slow or failed request survives
(they are the ones worth explaining) while fast, clean traffic is
down-sampled to ``trace_sample_rate`` to bound memory.

Everything is bounded: kept traces ride an LRU ring of ``maxlen``,
incomplete traces are capped at ``pending_max`` (evicting oldest-first —
a crashed hop that never sends its root cannot leak), and per-trace span
counts are capped. All drops are counted, never silent.
"""

from __future__ import annotations

import random
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

# A trace with more spans than this is a runaway loop, not a request;
# further spans are counted as dropped.
MAX_SPANS_PER_TRACE = 2048


def _normalize(event: Dict[str, Any]) -> Dict[str, Any]:
    """Span events carry binary task ids (ring-buffer format); the trace
    store is read by the dashboard's JSON layer, so normalize on entry."""
    tid = event.get("task_id")
    return {
        "trace_id": event.get("trace_id"),
        "span_id": event.get("span_id"),
        "parent_span_id": event.get("parent_span_id"),
        "name": event.get("name"),
        "ts": event.get("ts"),
        "dur": event.get("dur", 0.0),
        "attrs": dict(event.get("attrs") or {}),
        "owner_pid": event.get("owner_pid"),
        "task_id": tid.hex() if isinstance(tid, bytes) else tid,
    }


class TraceStore:
    """Bounded accumulation + tail-sampling. Single-threaded by design:
    the GCS handler loop is the only caller (same discipline as the
    task-event and cluster-event rings)."""

    def __init__(self, maxlen: int = 512,
                 keep_threshold_s: float = 0.5,
                 sample_rate: float = 0.01,
                 pending_max: Optional[int] = None,
                 rng: Optional[random.Random] = None):
        self.maxlen = int(maxlen)
        self.keep_threshold_s = float(keep_threshold_s)
        self.sample_rate = float(sample_rate)
        self.pending_max = int(pending_max if pending_max is not None
                               else 4 * self.maxlen)
        self._rng = rng if rng is not None else random.Random()
        self._traces: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._pending: "OrderedDict[str, List[Dict[str, Any]]]" = \
            OrderedDict()
        self.kept = 0
        self.sampled_out = 0
        self.evicted_pending = 0
        self.evicted_kept = 0
        self.spans_seen = 0
        self.spans_dropped = 0

    def add_span(self, event: Dict[str, Any]) -> None:
        trace_id = event.get("trace_id")
        if not trace_id or not event.get("span_id"):
            return
        self.spans_seen += 1
        span = _normalize(event)
        kept = self._traces.get(trace_id)
        if kept is not None:
            # Late arrival for a kept trace (other processes flush on
            # their own cadence) — attach, keeping the bound.
            if len(kept["spans"]) < MAX_SPANS_PER_TRACE:
                kept["spans"].append(span)
                kept["error"] = kept["error"] or \
                    bool(span["attrs"].get("error"))
            else:
                self.spans_dropped += 1
            return
        buf = self._pending.get(trace_id)
        if buf is None:
            buf = self._pending[trace_id] = []
            while len(self._pending) > self.pending_max:
                self._pending.popitem(last=False)
                self.evicted_pending += 1
        if len(buf) >= MAX_SPANS_PER_TRACE:
            self.spans_dropped += 1
            return
        buf.append(span)
        if span["attrs"].get("trace_root"):
            self._complete(trace_id, span)

    def _complete(self, trace_id: str, root: Dict[str, Any]) -> None:
        spans = self._pending.pop(trace_id, [])
        error = any(s["attrs"].get("error") for s in spans)
        if root["dur"] >= self.keep_threshold_s:
            reason = "slow"
        elif error:
            reason = "error"
        elif self._rng.random() < self.sample_rate:
            reason = "sampled"
        else:
            self.sampled_out += 1
            return
        self.kept += 1
        self._traces[trace_id] = {
            "trace_id": trace_id,
            "root_name": root["name"],
            "ts": root["ts"],
            "dur": root["dur"],
            "error": error,
            "keep_reason": reason,
            "spans": spans,
        }
        while len(self._traces) > self.maxlen:
            self._traces.popitem(last=False)
            self.evicted_kept += 1

    def get(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """A kept trace, or the partial accumulation of an in-flight one
        (``complete`` False) so debugging does not wait on sampling."""
        kept = self._traces.get(trace_id)
        if kept is not None:
            return {**kept, "complete": True}
        buf = self._pending.get(trace_id)
        if buf:
            return {"trace_id": trace_id, "root_name": None,
                    "ts": buf[0]["ts"], "dur": 0.0, "error": False,
                    "keep_reason": None, "spans": list(buf),
                    "complete": False}
        return None

    def summaries(self, limit: int = 100) -> List[Dict[str, Any]]:
        out = []
        for tr in reversed(self._traces.values()):
            out.append({k: tr[k] for k in
                        ("trace_id", "root_name", "ts", "dur", "error",
                         "keep_reason")} | {"num_spans": len(tr["spans"])})
            if len(out) >= limit:
                break
        return out

    def stats(self) -> Dict[str, Any]:
        return {
            "kept": self.kept, "sampled_out": self.sampled_out,
            "evicted_pending": self.evicted_pending,
            "evicted_kept": self.evicted_kept,
            "spans_seen": self.spans_seen,
            "spans_dropped": self.spans_dropped,
            "pending": len(self._pending), "stored": len(self._traces),
            "ts": time.time(),
        }
