"""Decoupled-RL (podracer) instrumentation.

The Podracer/Sebulba split (arXiv:2104.06272) turns one question into
the whole performance story: is acting or learning the bottleneck?
This metric set carries exactly the signals that answer it:

- throughput on both sides of the queue (``rl_env_steps_total`` from
  env runners vs ``rl_samples_total`` consumed by learner updates);
- the versioned weight channel (``rl_weight_version`` published by the
  learner pool, ``rl_weight_staleness`` = published-minus-behavior
  version observed at each update, ``rl_weight_publish_seconds``);
- the bounded sample queue (``rl_sample_queue_depth``,
  ``rl_backpressure_waits_total`` — acting throttled instead of
  OOMing, ``rl_dropped_stale_total`` — batches past the staleness
  clip);
- inference-server batching efficiency (``rl_infer_requests_total`` vs
  ``rl_infer_batches_total``; their ratio is the achieved batching
  factor, ``rl_infer_batch_rows`` the latest batch's row count).
"""

from __future__ import annotations

import threading

_rl = None
_lock = threading.Lock()

# Weight publication is an object-store put of a full pytree: 10ms..s.
_PUBLISH_BOUNDARIES = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0,
                       2.5, 5.0, 15.0, 60.0)


class RLMetrics:
    def __init__(self):
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        self.env_steps = Counter(
            "rl_env_steps_total",
            description="Environment steps sampled by env runners.")
        self.samples = Counter(
            "rl_samples_total",
            description="Sample rows consumed by learner-pool updates.")
        self.infer_requests = Counter(
            "rl_infer_requests_total",
            description="infer() requests handled by inference "
                        "servers.")
        self.infer_batches = Counter(
            "rl_infer_batches_total",
            description="Batched policy forwards run by inference "
                        "servers (requests/batches = achieved "
                        "batching factor).")
        self.dropped_stale = Counter(
            "rl_dropped_stale_total",
            description="Sample batches dropped because their behavior "
                        "weight version fell behind the staleness "
                        "clip.")
        self.backpressure_waits = Counter(
            "rl_backpressure_waits_total",
            description="Full-queue waits endured by the acting side "
                        "(throttling instead of unbounded buffering).")
        self.weight_version = Gauge(
            "rl_weight_version",
            description="Latest weight version published to the "
                        "WeightStore channel.")
        self.weight_staleness = Gauge(
            "rl_weight_staleness",
            description="Published-minus-behavior weight version of "
                        "the most recent learner-pool update.")
        self.queue_depth = Gauge(
            "rl_sample_queue_depth",
            description="Depth of the bounded sample queue between "
                        "acting and learning.")
        self.infer_batch_rows = Gauge(
            "rl_infer_batch_rows",
            description="Rows in the most recent inference-server "
                        "batch (after request coalescing, before "
                        "bucket padding).")
        self.publish_seconds = Histogram(
            "rl_weight_publish_seconds",
            boundaries=_PUBLISH_BOUNDARIES,
            description="Wall time of one WeightStore publish (object "
                        "store put + registry update).")


def rl_metrics() -> RLMetrics:
    global _rl
    with _lock:
        if _rl is None:
            _rl = RLMetrics()
        return _rl
