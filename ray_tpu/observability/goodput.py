"""Training goodput & straggler observability.

The train-tier questions that matter at pod scale on preemptible
slices (Podracer, arXiv:2104.06272): what fraction of wall time was
productive FLOPs, which worker is slowing the pod, and is a step
stalled or just slow. Three cooperating pieces answer them:

- :class:`StepPhases` — a per-step phase timer threaded through the
  training loops (`train/jax_backend.py`, the rllib learner paths)
  that decomposes each step into the ``TRAIN_PHASES`` vocabulary
  (data-wait / h2d / compute / exposed-collective / optimizer /
  checkpoint / weight-publish), emits
  ``rtpu_train_step_phase_seconds{phase}`` histograms (with trace
  exemplars) plus a ``train.step`` span, and publishes one
  ``(worker, step, phases, wall)`` row into the GCS step matrix
  (``report_train_steps``).
- :class:`GoodputLedger` — a per-worker wall-clock ledger classifying
  accounted time as productive vs lost-by-cause (stalled / recompiling
  / restarting / checkpointing), exported as the
  ``rtpu_train_goodput_ratio`` gauge and the cumulative
  ``rtpu_train_lost_seconds_total{cause}`` counter — the number
  elastic training (ROADMAP item 4) is judged by. ``TrackedJit``
  compile callbacks and the warmup/compile step feed the
  ``recompiling`` cause; split-phase ``record_overlap`` feeds the
  exposed-collective phase of the live step.
- :class:`StragglerDetector` — the cross-worker comparator over the
  GCS step matrix: a worker whose recent mean step time exceeds the
  pod median by ``train_straggler_threshold`` is flagged with the
  *dominant phase* (largest excess over the peer median per phase, so
  an injected data stall names ``data_wait`` even when compute
  dominates absolute time). The GCS turns flags into typed
  ``TRAIN_STRAGGLER`` cluster events; its stall watchdog turns missing
  step heartbeats into ``TRAIN_STALL`` events carrying auto-captured
  thread stacks of the stalled worker.

Everything is gated on the ``train_goodput_instrumentation`` knob so
the ``train_goodput_overhead`` bench can price the on/off delta.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, Optional

# Per-step phase vocabulary (display order). The classification below
# maps each phase into the goodput ledger's buckets: an accelerator
# doing optimizer math is productive; one waiting on the input
# pipeline, host->device transfer, or an exposed collective is stalled.
TRAIN_PHASES = ("data_wait", "h2d", "compute", "exposed_collective",
                "optimizer", "checkpoint", "weight_publish")

# Lost-time causes of the goodput ledger; "productive" is the
# complement. "restarting" is booked by elastic restart paths
# (ROADMAP item 4), "recompiling" by TrackedJit / warmup compile.
GOODPUT_CAUSES = ("stalled", "recompiling", "restarting", "checkpointing")

_PHASE_CLASS = {
    "data_wait": "stalled",
    "h2d": "stalled",
    "compute": "productive",
    "exposed_collective": "stalled",
    "optimizer": "productive",
    "checkpoint": "checkpointing",
    "weight_publish": "checkpointing",
}

# Training phases straddle sub-ms (queue pops) to minutes (pod-scale
# checkpoint persists).
_PHASE_BOUNDARIES = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0,
                     2.5, 5.0, 15.0, 60.0)

_goodput = None
_lock = threading.Lock()

# Process-wide "live" instrumentation targets: one training loop per
# process (train workers and learner actors are dedicated processes),
# so the TrackedJit compile hook and split-phase record_overlap can
# find where to book their time without threading handles everywhere.
_active_ledger: Optional["GoodputLedger"] = None
_active_step: Optional["StepPhases"] = None


class GoodputMetrics:
    def __init__(self):
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        self.step_phase_seconds = Histogram(
            "train_step_phase_seconds", boundaries=_PHASE_BOUNDARIES,
            tag_keys=("phase",),
            description="Wall time of one training-step phase "
                        "(data_wait/h2d/compute/exposed_collective/"
                        "optimizer/checkpoint/weight_publish); per-step "
                        "phase sums match step wall time.")
        self.goodput_ratio = Gauge(
            "train_goodput_ratio",
            description="Productive fraction of this worker's accounted "
                        "training wall time (1.0 = every second was "
                        "compute/optimizer FLOPs).")
        self.lost_seconds = Counter(
            "train_lost_seconds_total", tag_keys=("cause",),
            description="Cumulative non-productive training wall time "
                        "by cause (stalled/recompiling/restarting/"
                        "checkpointing).")


def goodput_metrics() -> GoodputMetrics:
    global _goodput
    with _lock:
        if _goodput is None:
            _goodput = GoodputMetrics()
        return _goodput


def goodput_enabled() -> bool:
    from ray_tpu._private.config import GlobalConfig

    return bool(GlobalConfig.train_goodput_instrumentation)


def classify_phase(phase: str) -> str:
    """Goodput bucket of a step phase: "productive" or a lost cause."""
    return _PHASE_CLASS.get(phase, "stalled")


# ------------------------------------------------------------------ ledger

class GoodputLedger:
    """Per-worker wall-clock classifier: productive vs lost-by-cause.

    Accounted time is whatever callers book (phase timers, compile
    hooks, restart paths) — the ratio is productive/accounted, so an
    uninstrumented gap neither inflates nor deflates it. Every booking
    refreshes the ``rtpu_train_goodput_ratio`` gauge; lost time also
    feeds the cumulative ``rtpu_train_lost_seconds_total{cause}``.
    """

    def __init__(self, worker: str = ""):
        self.worker = str(worker)
        self._t0 = time.perf_counter()
        self.productive_s = 0.0
        self.lost_s: Dict[str, float] = {c: 0.0 for c in GOODPUT_CAUSES}
        self._lk = threading.Lock()

    def note_productive(self, seconds: float) -> None:
        with self._lk:
            self.productive_s += max(float(seconds), 0.0)
        self._export()

    def lose(self, cause: str, seconds: float) -> None:
        if cause not in GOODPUT_CAUSES:
            raise ValueError(f"unknown goodput loss cause {cause!r} "
                             f"(want one of {GOODPUT_CAUSES})")
        seconds = max(float(seconds), 0.0)
        with self._lk:
            self.lost_s[cause] += seconds
        if seconds:
            goodput_metrics().lost_seconds.inc(seconds, {"cause": cause})
        self._export()

    def book_phases(self, durations: Dict[str, float]) -> None:
        """Classify one step's phase durations into the ledger."""
        for phase, dur in durations.items():
            bucket = classify_phase(phase)
            if bucket == "productive":
                self.note_productive(dur)
            else:
                self.lose(bucket, dur)

    def ratio(self) -> float:
        with self._lk:
            lost = sum(self.lost_s.values())
            accounted = self.productive_s + lost
            if accounted <= 0:
                return 1.0
            return self.productive_s / accounted

    def snapshot(self) -> Dict[str, Any]:
        with self._lk:
            lost = dict(self.lost_s)
            productive = self.productive_s
        total_lost = sum(lost.values())
        accounted = productive + total_lost
        return {
            "worker": self.worker,
            "wall_s": time.perf_counter() - self._t0,
            "productive_s": productive,
            "lost_s": lost,
            "accounted_s": accounted,
            "goodput_ratio": (productive / accounted
                              if accounted > 0 else 1.0),
        }

    def _export(self) -> None:
        try:
            goodput_metrics().goodput_ratio.set(self.ratio())
        except Exception:
            pass


def set_active_ledger(ledger: Optional[GoodputLedger]) -> None:
    global _active_ledger
    with _lock:
        _active_ledger = ledger


def active_ledger() -> Optional[GoodputLedger]:
    return _active_ledger


def record_recompile(seconds: float) -> None:
    """TrackedJit compile-callback hook: book compile wall time as
    ``recompiling`` against the process's active ledger (no-op when no
    training loop is live — serving-side compiles are not train loss)."""
    led = _active_ledger
    if led is not None:
        led.lose("recompiling", seconds)


def record_checkpoint(seconds: float) -> None:
    """Checkpoint-persist hook (train session): books into the live
    step's ``checkpoint`` phase when one is open, else straight into
    the phase histogram and the active ledger."""
    sp = _active_step
    if sp is not None:
        sp.add("checkpoint", seconds)
        return
    try:
        goodput_metrics().step_phase_seconds.observe(
            max(float(seconds), 0.0), {"phase": "checkpoint"})
    except Exception:
        pass
    led = _active_ledger
    if led is not None:
        led.lose("checkpointing", seconds)


def note_exposed_collective(seconds: float) -> None:
    """Split-phase overlap hook (`collective.record_overlap`): attribute
    exposed collective wall time to the live step. The step carves it
    out of the enclosing ``compute`` phase at finish, so per-step phase
    sums still match wall time."""
    sp = _active_step
    if sp is not None:
        sp.note_exposed(seconds)


# ------------------------------------------------------------- step timer

class StepPhases:
    """One training step's phase ledger.

    Use the ``phase(name)`` context for timed sections, ``add`` for
    externally-measured durations; ``finish()`` observes each phase
    into ``rtpu_train_step_phase_seconds{phase}`` (exemplar-linked to
    the ambient trace, if any), records a ``train.step`` span, books
    the ledger, and publishes the row to the GCS step matrix.
    """

    def __init__(self, step: int, worker: str = "",
                 ledger: Optional[GoodputLedger] = None):
        global _active_step
        self.step = int(step)
        self.worker = str(worker)
        self._ledger = ledger
        self.durations: Dict[str, float] = {}
        self._exposed = 0.0
        self._start_ts = time.time()
        self._t0 = time.perf_counter()
        with _lock:
            _active_step = self

    @contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def add(self, name: str, seconds: float) -> None:
        if name not in TRAIN_PHASES:
            raise ValueError(f"unknown train phase {name!r} "
                             f"(want one of {TRAIN_PHASES})")
        self.durations[name] = (self.durations.get(name, 0.0)
                                + max(float(seconds), 0.0))

    def note_exposed(self, seconds: float) -> None:
        self._exposed += max(float(seconds), 0.0)

    def finish(self, publish: bool = True) -> Dict[str, Any]:
        global _active_step
        wall = time.perf_counter() - self._t0
        with _lock:
            if _active_step is self:
                _active_step = None
        if self._exposed:
            # Exposed collective time happened INSIDE the timed compute
            # section; carve it out so phases partition the wall time.
            carve = min(self._exposed, self.durations.get("compute", 0.0))
            if carve:
                self.durations["compute"] -= carve
            self.add("exposed_collective", self._exposed)
        wall = max(wall, sum(self.durations.values()))

        trace_id = None
        try:
            from ray_tpu.util.tracing import current_trace, record_span

            tc = current_trace()
            if tc is not None:
                trace_id = tc.trace_id
            attrs: Dict[str, Any] = {"step": self.step,
                                     "worker": self.worker}
            for phase, dur in self.durations.items():
                attrs[f"{phase}_s"] = round(dur, 6)
            record_span("train.step", self._start_ts, wall, attrs)
        except Exception:
            pass
        try:
            m = goodput_metrics()
            for phase, dur in self.durations.items():
                m.step_phase_seconds.observe(dur, {"phase": phase},
                                             trace_id=trace_id)
        except Exception:
            pass
        if self._ledger is not None:
            self._ledger.book_phases(self.durations)
        row = {
            "worker": self.worker, "step": self.step,
            "wall_s": wall, "phases": dict(self.durations),
            "ts": time.time(),
        }
        if self._ledger is not None:
            row["goodput"] = self._ledger.snapshot()
        if publish:
            publish_train_step(row)
        return row


# --------------------------------------------------------- GCS publication

def publish_train_step(row: Dict[str, Any]) -> bool:
    """Fire-and-forget report of one step row into the GCS step matrix
    (``report_train_steps``). Doubles as the worker's step heartbeat:
    the GCS stall watchdog times out workers whose rows stop arriving.
    Returns False (silently) outside a connected worker — plain
    ``run_pod_training()`` in a bare process still gets local metrics.
    """
    try:
        from ray_tpu._private.worker import global_worker_or_none

        w = global_worker_or_none()
        if w is None or getattr(w, "_dead", False):
            return False
        payload = dict(row)
        payload.setdefault("worker_id", w.worker_id.binary())
        payload.setdefault("node_id", w.node_id)
        w.gcs.cast("report_train_steps", row=payload)
        return True
    except Exception:
        return False


def publish_train_done(worker: str) -> bool:
    """Mark a train worker's run complete so the stall watchdog stops
    expecting heartbeats from it (a finished run is not a stall)."""
    return publish_train_step({"worker": str(worker), "done": True})


# ------------------------------------------------------ straggler detector

class StragglerDetector:
    """Cross-worker step-time comparator over the step matrix.

    Keeps a bounded window of recent step walls and phase durations per
    worker; a worker whose windowed mean step time exceeds
    ``threshold``× the median of all workers' means is flagged. The
    flag names the *dominant phase*: the phase with the largest excess
    over the peer median of that phase — so a worker slowed by its
    input pipeline names ``data_wait`` even when everyone's ``compute``
    is larger in absolute terms. Re-flagging the same worker is
    suppressed for ``window`` further steps (one event per episode,
    not one per step).
    """

    def __init__(self, threshold: float = 1.5, window: int = 8,
                 min_workers: int = 2):
        self.threshold = float(threshold)
        self.window = max(int(window), 2)
        self.min_workers = max(int(min_workers), 2)
        self._walls: Dict[str, deque] = {}
        self._phases: Dict[str, Dict[str, deque]] = {}
        self._last_flag_step: Dict[str, int] = {}

    def observe(self, worker: str, step: int, wall_s: float,
                phases: Optional[Dict[str, float]] = None
                ) -> Optional[Dict[str, Any]]:
        """Feed one step row; returns a flag record when `worker` just
        crossed the straggler threshold, else None."""
        worker = str(worker)
        walls = self._walls.setdefault(worker,
                                       deque(maxlen=self.window))
        walls.append(max(float(wall_s), 0.0))
        per_phase = self._phases.setdefault(worker, {})
        for phase, dur in (phases or {}).items():
            per_phase.setdefault(
                phase, deque(maxlen=self.window)).append(float(dur))

        if len(self._walls) < self.min_workers:
            return None
        if len(walls) < max(2, self.window // 2):
            return None
        means = {w: sum(d) / len(d)
                 for w, d in self._walls.items() if d}
        median = _median(list(means.values()))
        mean_w = means[worker]
        if median <= 0 or mean_w <= self.threshold * median:
            self._last_flag_step.pop(worker, None)
            return None
        last = self._last_flag_step.get(worker)
        if last is not None and int(step) - last < self.window:
            return None
        self._last_flag_step[worker] = int(step)
        dominant, excess = self._dominant_phase(worker)
        return {
            "worker": worker, "step": int(step),
            "mean_step_s": mean_w, "median_step_s": median,
            "ratio": mean_w / median,
            "dominant_phase": dominant,
            "dominant_excess_s": excess,
        }

    def mean_step_s(self, worker: str) -> Optional[float]:
        d = self._walls.get(str(worker))
        return (sum(d) / len(d)) if d else None

    def _dominant_phase(self, worker: str):
        """Phase with the largest mean excess over the peer median."""
        phase_means: Dict[str, Dict[str, float]] = {}
        for w, per_phase in self._phases.items():
            for phase, d in per_phase.items():
                if d:
                    phase_means.setdefault(phase, {})[w] = \
                        sum(d) / len(d)
        best, best_excess = "", 0.0
        for phase, by_worker in phase_means.items():
            if worker not in by_worker:
                continue
            peer_median = _median(list(by_worker.values()))
            excess = by_worker[worker] - peer_median
            if excess > best_excess:
                best, best_excess = phase, excess
        if not best:
            # No phase data (or no excess): fall back to the biggest
            # absolute phase so the flag always names something.
            mine = {p: (sum(d) / len(d))
                    for p, d in self._phases.get(worker, {}).items() if d}
            if mine:
                best = max(mine, key=mine.get)
                best_excess = mine[best]
        return best, best_excess


def _median(values) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    n = len(ordered)
    mid = n // 2
    if n % 2:
        return float(ordered[mid])
    return float(ordered[mid - 1] + ordered[mid]) / 2.0
