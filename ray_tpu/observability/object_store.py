"""Object-store instrumentation: per-node memory-pressure metric set.

The store itself lives inside the raylet process; its stats are sampled
through the shared ``register_flush_sampler`` hook — the sampler reads
``NodeObjectStore.stats()`` right before every metrics flush, sets the
gauges, and advances the cumulative counters by the delta since the last
sample (the store keeps plain ints; Prometheus counters must only ever
``inc``).  The raylet's reporter loop pushes the resulting snapshots to
the GCS, whose tombstone folding keeps the counters monotone across
raylet exit (totals never regress on node churn).

Gauges are per-node labeled (``node=<node_id[:12]>``); NOT ``pid`` — the
gauge renderer appends its own ``pid=<source>`` label and duplicate
label names break the whole Prometheus scrape.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict

_singleton = None
_lock = threading.Lock()


class ObjectStoreMetrics:
    def __init__(self):
        from ray_tpu.util.metrics import Counter, Gauge

        node = ("node",)
        self.capacity = Gauge(
            "object_store_capacity_bytes", tag_keys=node,
            description="Shared-memory store capacity on the node.")
        self.used = Gauge(
            "object_store_used_bytes", tag_keys=node,
            description="Shared-memory bytes currently allocated.")
        self.num_objects = Gauge(
            "object_store_num_objects", tag_keys=node,
            description="Objects tracked by the node store (including "
                        "spilled entries).")
        self.pinned = Gauge(
            "object_store_pinned_bytes", tag_keys=node,
            description="Bytes of in-memory primary copies pinned "
                        "against eviction.")
        self.spilled = Gauge(
            "object_store_spilled_bytes", tag_keys=node,
            description="Bytes currently spilled to disk.")
        self.spills = Counter(
            "object_store_spills_total", tag_keys=node,
            description="Objects spilled to disk under memory pressure.")
        self.restores = Counter(
            "object_store_restores_total", tag_keys=node,
            description="Spilled objects restored into shared memory.")
        self.evictions = Counter(
            "object_store_evictions_total", tag_keys=node,
            description="Unpinned secondary copies evicted (dropped).")
        self.spill_time = Counter(
            "object_store_spill_seconds_total", tag_keys=node,
            description="Cumulative wall time spent writing spill files.")
        self.restore_time = Counter(
            "object_store_restore_seconds_total", tag_keys=node,
            description="Cumulative wall time spent restoring spill "
                        "files.")


def object_store_metrics() -> ObjectStoreMetrics:
    global _singleton
    with _lock:
        if _singleton is None:
            _singleton = ObjectStoreMetrics()
        return _singleton


# stats() key -> (metric attr, is_counter)
_FIELDS = (
    ("capacity", "capacity", False),
    ("used", "used", False),
    ("num_objects", "num_objects", False),
    ("pinned_bytes", "pinned", False),
    ("spilled_bytes", "spilled", False),
    ("num_spills", "spills", True),
    ("num_restores", "restores", True),
    ("num_evictions", "evictions", True),
    ("spill_time_s", "spill_time", True),
    ("restore_time_s", "restore_time", True),
)


def register_store_sampler(get_stats: Callable[[], Dict],
                           node: str) -> Callable[[], None]:
    """Register a flush sampler exporting one store's stats snapshot.

    ``get_stats`` is called at every metrics flush; counter fields
    advance by their delta since the previous sample so the exported
    series stay monotone even though the store keeps raw totals.
    Returns the sampler (tests call it directly to force a sample).
    """
    from ray_tpu.util.metrics import register_flush_sampler

    m = object_store_metrics()
    tags = {"node": node}
    last: Dict[str, float] = {}

    def sample() -> None:
        stats = get_stats()
        for key, attr, is_counter in _FIELDS:
            val = float(stats.get(key, 0))
            metric = getattr(m, attr)
            if is_counter:
                delta = val - last.get(key, 0.0)
                if delta > 0:
                    metric.inc(delta, tags=tags)
                last[key] = val
            else:
                metric.set(val, tags=tags)

    register_flush_sampler(sample)
    return sample
