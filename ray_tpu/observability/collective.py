"""Collective-op instrumentation.

One singleton feeding the shared metric registry: every op that goes
through the `ray_tpu.util.collective` API (and the device-side ring
kernels when invoked via a group) records

- ``rtpu_collective_ops_total{op,backend,dtype}`` — op count,
- ``rtpu_collective_bytes_total{op,backend,dtype}`` — payload bytes moved
  (the *input* tensor bytes: what the interconnect actually carries scales
  with this times the ring's ``2(n-1)/n`` factor),
- ``rtpu_collective_op_seconds{op,backend}`` — wall-time histogram,
- ``rtpu_collective_exposed_seconds{op,backend}`` /
  ``rtpu_collective_hidden_seconds{op,backend}`` — for split-phase
  (start/wait) collectives, how much of the issued-to-awaited span was
  NOT covered by compute (exposed) vs covered (hidden), and
- a ``collective:<op>`` timeline span per call (split-phase calls carry
  an ``overlapped`` attribute),

which is exactly what the PERF.md "is the interconnect the bottleneck?"
and "is communication hidden?" playbooks read: bytes/sec vs the ICI
envelope, op latency vs compute time between ops, and the exposed-comm
fraction ``exposed / (exposed + hidden)``.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

_collective = None
_lock = threading.Lock()
# Process-lifetime total of exposed split-phase seconds. The XLA
# attribution sampler diffs this around a sampled call to decide
# whether a program's wall is dominated by exposed communication
# (the "comm-bound" roofline verdict).
_exposed_total = 0.0

# Collective latencies straddle microseconds (small psum over ICI) to
# seconds (pod-scale gather on a cold link).
_OP_BOUNDARIES = (0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                  1.0, 5.0, 30.0)


class CollectiveMetrics:
    def __init__(self):
        from ray_tpu.util.metrics import Counter, Histogram

        tag_keys = ("op", "backend", "dtype")
        self.ops = Counter(
            "collective_ops_total", tag_keys=tag_keys,
            description="Collective ops executed via the "
                        "util.collective API.")
        self.bytes = Counter(
            "collective_bytes_total", tag_keys=tag_keys,
            description="Input payload bytes handed to collective ops "
                        "(wire bytes ≈ this × 2(n-1)/n for ring "
                        "allreduce, ×1/4 under int8 quantization).")
        self.op_seconds = Histogram(
            "collective_op_seconds", boundaries=_OP_BOUNDARIES,
            tag_keys=("op", "backend"),
            description="Wall time of one collective op, host round-trip "
                        "included.")
        self.exposed_seconds = Histogram(
            "collective_exposed_seconds", boundaries=_OP_BOUNDARIES,
            tag_keys=("op", "backend"),
            description="Split-phase collective wall time NOT covered by "
                        "overlapped compute (the part the step actually "
                        "waits on).")
        self.hidden_seconds = Histogram(
            "collective_hidden_seconds", boundaries=_OP_BOUNDARIES,
            tag_keys=("op", "backend"),
            description="Split-phase collective wall time hidden under "
                        "compute between start_* and wait_*.")


def collective_metrics() -> CollectiveMetrics:
    global _collective
    with _lock:
        if _collective is None:
            _collective = CollectiveMetrics()
        return _collective


def _tensor_stats(tensor):
    try:
        import numpy as np

        arr = np.asarray(tensor)
        return str(arr.dtype), int(arr.nbytes)
    except Exception:
        return "unknown", 0


@contextmanager
def observe_collective(op: str, backend: str, tensor=None,
                       overlapped=None):
    """Time one collective op: counters + latency histogram + a
    ``collective:<op>`` timeline span.  Pass ``overlapped=True|False``
    for split-phase calls so the span records whether the op ran under
    compute (the timeline then shows hidden vs exposed hops directly)."""
    from ray_tpu.util.tracing import record_span

    dtype, nbytes = _tensor_stats(tensor)
    m = collective_metrics()
    start = time.time()
    try:
        yield
    finally:
        dur = time.time() - start
        tags = {"op": op, "backend": backend, "dtype": dtype}
        m.ops.inc(1, tags)
        if nbytes:
            m.bytes.inc(nbytes, tags)
        m.op_seconds.observe(dur, {"op": op, "backend": backend})
        try:
            attrs = {"backend": backend, "dtype": dtype, "bytes": nbytes}
            if overlapped is not None:
                attrs["overlapped"] = bool(overlapped)
            record_span(f"collective:{op}", start, dur, attrs)
        except Exception:
            pass


def record_overlap(op: str, backend: str, issued_to_awaited_s: float,
                   compute_covered_s: float) -> dict:
    """Book a split-phase collective's wall time into the exposed/hidden
    histograms.

    ``issued_to_awaited_s`` is the span between ``start_*`` returning and
    ``wait_*`` completing; ``compute_covered_s`` is how much of that span
    was busy with overlapped compute.  What compute did not cover, the
    step serialized on: ``exposed = max(0, span - covered)``.  Returns
    ``{"exposed_s", "hidden_s", "exposed_fraction"}`` for callers (bench)
    that also report the numbers directly.
    """
    global _exposed_total
    span = max(float(issued_to_awaited_s), 0.0)
    covered = max(float(compute_covered_s), 0.0)
    exposed = max(0.0, span - covered)
    hidden = span - exposed
    with _lock:
        _exposed_total += exposed
    m = collective_metrics()
    tags = {"op": op, "backend": backend}
    m.exposed_seconds.observe(exposed, tags)
    m.hidden_seconds.observe(hidden, tags)
    try:
        # The live train step (if any) carves exposed time out of its
        # compute phase — the exposed_collective column of the step
        # ledger reuses this hook instead of re-timing the collective.
        from ray_tpu.observability.goodput import note_exposed_collective

        note_exposed_collective(exposed)
    except Exception:
        pass
    return {
        "exposed_s": exposed,
        "hidden_s": hidden,
        "exposed_fraction": exposed / span if span > 0 else 0.0,
    }


def cumulative_exposed_seconds() -> float:
    """Process-lifetime exposed split-phase collective seconds.  The
    XLA attribution plane reads the delta of this across a sampled
    program execution: when most of a sampled wall is exposed
    communication, the program's roofline verdict is "comm-bound"."""
    with _lock:
        return _exposed_total
