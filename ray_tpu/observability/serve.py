"""Serving instrumentation: the LLM engine's metric set.

One process-wide singleton (engines in the same replica share the
registry entries; counters/histograms aggregate across replicas on the
GCS scrape side). Latency semantics follow the serving literature:

- ``serve_queue_wait_seconds``: submit -> admitted into a decode slot.
- ``serve_ttft_seconds``: submit -> first generated token.
- ``serve_tpot_seconds``: mean per-output-token latency after the
  first token (one observation per finished request).
- ``serve_e2e_seconds``: submit -> finish.

Gauges (exported per-process with a pid label) carry the engine's live
state: queue depth, active slots, and batch utilization (active /
num_slots — the fraction of the ONE compiled decode program doing real
work; idle slots ride through the program as masked lanes).
"""

from __future__ import annotations

import threading

_singleton = None
_lock = threading.Lock()


class ServeMetrics:
    def __init__(self):
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        lat = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
               10.0, 30.0, 60.0)
        self.ttft = Histogram(
            "serve_ttft_seconds", boundaries=lat,
            description="Time to first token (submit -> first token).")
        self.tpot = Histogram(
            "serve_tpot_seconds",
            boundaries=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                        0.25, 0.5, 1.0),
            description="Mean per-output-token latency after the first "
                        "token, one observation per request.")
        self.e2e = Histogram(
            "serve_e2e_seconds", boundaries=lat,
            description="Request end-to-end latency (submit -> finish).")
        self.queue_wait = Histogram(
            "serve_queue_wait_seconds", boundaries=lat,
            description="Submit -> admission into a decode slot.")
        self.queue_depth = Gauge(
            "serve_queue_depth",
            description="Requests waiting for a decode slot.")
        self.active_slots = Gauge(
            "serve_active_slots",
            description="Decode slots with a live request.")
        self.batch_utilization = Gauge(
            "serve_batch_utilization",
            description="active_slots / num_slots of the compiled "
                        "decode program.")
        self.tokens = Counter(
            "serve_tokens_total",
            description="Generated tokens emitted to requests.")
        self.requests = Counter(
            "serve_requests_total", tag_keys=("finish_reason",),
            description="Finished requests by finish reason.")
        self.slot_reuses = Counter(
            "serve_slot_reuses_total",
            description="Decode-slot recycles (continuous batching at "
                        "work).")
        self.request_timeouts = Counter(
            "serve_request_timeouts_total",
            description="Server-side waits that gave up before the "
                        "engine finished the request.")
        # Set by the serve controller (one process), so the per-pid
        # gauge split still yields one authoritative series per
        # deployment — the grafana replica-count panel reads this.
        self.replicas = Gauge(
            "serve_replicas", tag_keys=("deployment",),
            description="Live replicas per deployment, as reconciled "
                        "by the serve controller.")
        # Paged KV cache (serve/llm/kv_cache.py): pool occupancy and
        # prefix reuse. used + free == the engine's num_kv_blocks, so
        # used / (used + free) is the HBM-side KV utilization panel.
        self.kv_blocks_used = Gauge(
            "serve_kv_blocks_used",
            description="Paged-KV pool blocks currently referenced by a "
                        "live sequence or the prefix cache.")
        self.kv_blocks_free = Gauge(
            "serve_kv_blocks_free",
            description="Paged-KV pool blocks on the free list.")
        self.prefix_hits = Counter(
            "serve_prefix_cache_hits_total",
            description="Admissions that reused >= 1 cached prompt "
                        "block (their prefill was skipped).")
        self.prefix_misses = Counter(
            "serve_prefix_cache_misses_total",
            description="Admissions that found no cached prompt prefix.")
        self.prefix_hit_tokens = Counter(
            "serve_prefix_cache_hit_tokens_total",
            description="Prompt positions whose prefill was skipped via "
                        "the prefix cache.")
        self.prefix_evictions = Counter(
            "serve_prefix_cache_evictions_total",
            description="Prefix-cache entries evicted under pool "
                        "pressure (LRU).")
        # LLM router (serve/llm/router.py): per-replica load as seen by
        # the queue-depth probe, and where requests actually went.
        self.router_queue_depth = Gauge(
            "serve_router_queue_depth", tag_keys=("replica",),
            description="Engine queue depth per LLM replica as last "
                        "probed by the router.")
        self.router_requests = Counter(
            "serve_router_requests_total", tag_keys=("replica",),
            description="Requests forwarded per LLM replica by the "
                        "router's power-of-two-choices pick.")
        # Disaggregated serving (serve/llm/disagg): KV-block migration
        # between the prefill and decode pools, SLO lanes, and
        # speculative decoding.
        self.kv_migrated_blocks = Counter(
            "serve_kv_migrated_blocks_total",
            description="Paged KV blocks adopted into an engine's pool "
                        "from an exported checkpoint (prefill->decode "
                        "migration or preempt->resume).")
        self.kv_migrated_bytes = Counter(
            "serve_kv_migrated_bytes_total",
            description="Bytes of KV payload adopted into an engine's "
                        "pool from exported checkpoints.")
        self.lane_queue_depth = Gauge(
            "serve_lane_queue_depth", tag_keys=("lane",),
            description="Requests waiting for a decode slot, split by "
                        "SLO lane (interactive | batch).")
        self.preemptions = Counter(
            "serve_preemptions_total", tag_keys=("lane",),
            description="Live decodes checkpointed and requeued to free "
                        "a slot for the interactive lane, by the "
                        "victim's lane.")
        self.spec_proposed = Counter(
            "serve_spec_proposed_tokens_total",
            description="Draft tokens proposed by speculative-decode "
                        "rounds (spec_k - 1 per live slot per round).")
        self.spec_accepted = Counter(
            "serve_spec_accepted_tokens_total",
            description="Draft tokens accepted by the target verify "
                        "step (the bonus token per round is not "
                        "counted).")
        self.spec_accept_ratio = Gauge(
            "serve_spec_accept_ratio",
            description="Lifetime accepted / proposed draft tokens for "
                        "this engine (decode speedup is about "
                        "1 + ratio * (spec_k - 1)).")
        self.router_lane_requests = Counter(
            "serve_router_lane_requests_total", tag_keys=("lane", "pool"),
            description="Requests forwarded by the LLM router, split by "
                        "SLO lane and destination pool (monolithic | "
                        "prefill | decode).")
        # KV memory hierarchy (kv_cache.KVTierManager): evicted prefix
        # blocks spill HBM -> host RAM -> object store and are promoted
        # back through the adopt scatter instead of re-prefilling.
        self.prefix_tier_hits = Counter(
            "serve_prefix_tier_hits_total", tag_keys=("tier",),
            description="Tier lookups that found a spilled chain link "
                        "(one count per block), by tier (host | store).")
        self.prefix_tier_misses = Counter(
            "serve_prefix_tier_misses_total", tag_keys=("tier",),
            description="Tier lookups that found nothing at a depth, by "
                        "tier — the re-prefilled side of the hierarchy.")
        self.prefix_tier_spills = Counter(
            "serve_prefix_tier_spills_total", tag_keys=("tier",),
            description="KV blocks spilled INTO a tier (host: prefix "
                        "eviction or peer pull; store: host-budget "
                        "demotion).")
        self.prefix_tier_promotes = Counter(
            "serve_prefix_tier_promotes_total", tag_keys=("tier",),
            description="KV blocks promoted OUT of a tier back into the "
                        "HBM pool via the adopt scatter (their prefill "
                        "was skipped).")
        self.kv_tier_bytes = Gauge(
            "serve_kv_tier_bytes", tag_keys=("tier",),
            description="Resident KV bytes per tier of the memory "
                        "hierarchy (hbm | host | store).")
        # Cluster-wide prefix index (GCS report/lookup_prefix_index):
        # what cache-aware routing sees and how fresh it is.
        self.router_cache_hops = Counter(
            "serve_router_cache_decisions_total", tag_keys=("outcome",),
            description="Cache-aware routing decisions by outcome "
                        "(scored: index applied; held: index stale, "
                        "plain p2c; pulled: peer KV pull issued).")
        self.router_index_age = Gauge(
            "serve_router_index_age_seconds",
            description="Age of the LLM router's newest cluster "
                        "prefix-index view (staleness HOLD beyond "
                        "serve_prefix_index_ttl_s).")


def serve_metrics() -> ServeMetrics:
    global _singleton
    with _lock:
        if _singleton is None:
            _singleton = ServeMetrics()
        return _singleton
