"""Chip peak-performance table: the denominator of MFU/MBU.

The XLA attribution plane (observability/xla.py) turns a compiled
program's cost analysis into *utilization* only by dividing achieved
FLOP/s and bytes/s by what the chip could do.  This module is the one
place those peaks live:

    spec = lookup("TPU v5 lite")
    mfu  = achieved_flops_per_s / spec.peak_flops

Published peaks (bf16 dense matmul FLOP/s and HBM bandwidth):

    ===========  ==============  =============
    chip         peak FLOP/s     HBM bytes/s
    ===========  ==============  =============
    TPU v4       275e12          1228e9
    TPU v5e      197e12           819e9
    TPU v5p      459e12          2765e9
    ===========  ==============  =============

Rules of the table:

- ``lookup`` normalizes the strings jax reports as ``device_kind``
  ("TPU v5 lite" -> v5e, "TPU v5p"/"TPU v5" -> v5p, ...).
- CPU backends resolve to a *nominal* spec tagged
  ``measurement="cpu"``: the plumbing (rows, ratios, summaries) works
  identically in tier-1 CPU tests, but every consumer can see the
  ratios prove wiring, not performance.
- Unknown kinds degrade to ``spec="unknown"`` with **no** peaks
  (``peak_flops is None``) — MFU/MBU for such rows is ``None``, never
  a number fabricated from a guessed denominator.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class ChipSpec:
    """Peak envelope of one chip generation.

    ``peak_flops``/``peak_hbm_bytes_per_s`` are per-chip bf16 dense
    peaks; ``None`` means the kind is unknown and no utilization ratio
    may be derived from this spec. ``measurement`` tags how rows built
    against this spec should be read: "tpu" (real roofline), "cpu"
    (plumbing proof only), or "unknown".
    """

    spec: str
    peak_flops: Optional[float]
    peak_hbm_bytes_per_s: Optional[float]
    measurement: str = "tpu"

    @property
    def known(self) -> bool:
        return self.peak_flops is not None


# Canonical spec rows, keyed by the normalized generation name.
_SPECS = {
    "v4": ChipSpec("v4", 275e12, 1228e9),
    "v5e": ChipSpec("v5e", 197e12, 819e9),
    "v5p": ChipSpec("v5p", 459e12, 2765e9),
    # Nominal CPU envelope: a modern server core's ~100 GFLOP/s and
    # ~100 GB/s memory stream. The numbers only exist so CPU-tier tests
    # exercise the full MFU/MBU path; the "cpu" tag marks every derived
    # ratio as a plumbing proof, not a performance claim.
    "cpu": ChipSpec("cpu", 100e9, 100e9, measurement="cpu"),
}

UNKNOWN = ChipSpec("unknown", None, None, measurement="unknown")

# device_kind substrings -> canonical generation, checked in order
# (first match wins, so "v5 lite"/"v5e" must precede the bare "v5"
# that v5p hosts sometimes report).
_KIND_PATTERNS = (
    ("v5 lite", "v5e"),
    ("v5litepod", "v5e"),
    ("v5e", "v5e"),
    ("v5p", "v5p"),
    ("v5", "v5p"),
    ("v4", "v4"),
    ("cpu", "cpu"),
)


def lookup(device_kind: Optional[str]) -> ChipSpec:
    """Resolve a jax ``device_kind`` (or mesh-inventory chip string) to
    its :class:`ChipSpec`. Unknown kinds return :data:`UNKNOWN` rather
    than fabricating peaks."""
    if not device_kind:
        return UNKNOWN
    kind = str(device_kind).strip().lower()
    for pattern, gen in _KIND_PATTERNS:
        if pattern in kind:
            return _SPECS[gen]
    return UNKNOWN


def local_spec() -> ChipSpec:
    """Spec of this process's default jax backend (first local device)."""
    try:
        import jax

        devices = jax.local_devices()
        if not devices:
            return UNKNOWN
        dev = devices[0]
        kind = getattr(dev, "device_kind", None) or dev.platform
        if dev.platform == "cpu":
            return _SPECS["cpu"]
        return lookup(kind)
    except Exception:
        return UNKNOWN
