"""Live profiling plane: wall-clock stack sampling + scheduling phases.

Reference: `dashboard/modules/reporter/profile_manager.py` (py-spy /
memray driven dump+profile endpoints) and `ray stack` — here implemented
in-process over ``sys._current_frames()`` so no external tool is needed
on the worker image. Three layers share this module:

- :class:`StackSampler` — a daemon-thread wall-clock sampler at a
  configurable Hz with bounded memory (at most
  ``profiler_max_unique_stacks`` distinct ``(thread, stack)`` keys are
  retained; overflow is counted in ``dropped``, never allocated) and
  per-thread attribution. Results render as collapsed-stack text
  (:func:`collapse`, flamegraph.pl input) or speedscope JSON
  (:func:`render_speedscope`, https://speedscope.app — one sampled
  profile per thread).
- one-shot stack dumps (:func:`capture_thread_stacks` /
  :func:`format_thread_stacks`) — the ``ray stack`` equivalent used by
  the worker's ``dump_stacks`` RPC and the SIGUSR2 wedge dump.
- the scheduling-latency breakdown schema: :data:`SCHED_PHASES` is the
  per-task lifecycle (PENDING → LEASE_GRANTED → WORKER_STARTED →
  ARGS_READY → RUNNING) threaded through the lease protocol and the
  task-event ring; :func:`observe_sched_phases` folds consecutive
  phase timestamps into the ``rtpu_sched_phase_seconds{phase}``
  histogram so "is it the scheduler or the user code" is a one-glance
  Grafana question (Ray, arXiv:1712.05889 §4 chases exactly these
  millisecond-scale scheduling overheads; Podracer, arXiv:2104.06272,
  shows host-side stalls are the dominant TPU perf bug).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

# ---------------------------------------------------------------------------
# Scheduling-phase schema (owner + worker sides of the lease protocol
# record these; timeline.py renders them as segmented submit arrows).
# ---------------------------------------------------------------------------

#: Per-task lifecycle phases in order. PENDING and LEASE_GRANTED are
#: stamped by the owner (submit / lease-batch pairing); WORKER_STARTED,
#: ARGS_READY and RUNNING are stamped on the executing worker and ride
#: back in the task reply (so one clock per segment endpoint pair —
#: owner-owner and worker-worker deltas never mix hosts' clocks; the
#: LEASE_GRANTED→WORKER_STARTED segment is the only cross-host one).
SCHED_PHASES = ("PENDING", "LEASE_GRANTED", "WORKER_STARTED",
                "ARGS_READY", "RUNNING")

#: Segment label keyed by the phase that *ends* it — the histogram
#: ``phase`` tag and the timeline segment name.
SCHED_SEGMENT_LABELS = {
    "LEASE_GRANTED": "lease_grant",    # submit -> a worker lease paired
    "WORKER_STARTED": "worker_start",  # push RPC -> worker picks it up
    "ARGS_READY": "args_fetch",        # function load + arg resolution
    "RUNNING": "exec_start",           # args ready -> user code entered
}

_sched_metrics = None
_sched_lock = threading.Lock()


def sched_metrics():
    """The ``rtpu_sched_phase_seconds{phase}`` histogram (lazy: importing
    this module must stay cheap enough for the RPC layer)."""
    global _sched_metrics
    with _sched_lock:
        if _sched_metrics is None:
            from ray_tpu.util.metrics import Histogram

            _sched_metrics = Histogram(
                "sched_phase_seconds",
                description="Scheduling-latency breakdown per task: "
                            "seconds spent in each submit->execution "
                            "phase (lease_grant, worker_start, "
                            "args_fetch, exec_start).",
                tag_keys=("phase",),
                boundaries=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                            0.05, 0.1, 0.25, 0.5, 1.0, 2.5))
        return _sched_metrics


def observe_sched_phases(ts_by_phase: Dict[str, float]) -> None:
    """Fold one task's phase timestamps into the phase histogram.
    Deltas are taken between *consecutive present* phases (a missing
    middle phase widens the next segment rather than dropping it) and
    clamped at zero — the LEASE_GRANTED→WORKER_STARTED hop crosses
    hosts, so clock skew must not produce negative observations."""
    present = [(p, ts_by_phase[p]) for p in SCHED_PHASES
               if p in ts_by_phase]
    if len(present) < 2:
        return
    h = sched_metrics()
    for (_, ta), (pb, tb) in zip(present, present[1:]):
        h.observe(max(tb - ta, 0.0),
                  tags={"phase": SCHED_SEGMENT_LABELS.get(pb, pb)})


# ---------------------------------------------------------------------------
# One-shot stack dumps (the `ray stack` path)
# ---------------------------------------------------------------------------

def capture_thread_stacks() -> List[Dict[str, Any]]:
    """All-thread Python stacks, structured. Lock-free and best-effort:
    safe to call from a wedged process."""
    frames = sys._current_frames()
    threads = {t.ident: t for t in threading.enumerate()}
    out: List[Dict[str, Any]] = []
    for ident, frame in frames.items():
        t = threads.get(ident)
        out.append({
            "thread_name": t.name if t else f"thread-{ident}",
            "ident": ident,
            "daemon": bool(t.daemon) if t else None,
            "stack": "".join(traceback.format_stack(frame)),
        })
    out.sort(key=lambda r: r["thread_name"])
    return out


def format_thread_stacks(
        threads: Optional[List[Dict[str, Any]]] = None) -> str:
    """Render :func:`capture_thread_stacks` as one text blob (the shape
    the dashboard's stack endpoints and the SIGUSR2 dump print)."""
    rows = capture_thread_stacks() if threads is None else threads
    return "\n".join(
        f"--- thread {r['thread_name']}"
        f"{' (daemon)' if r.get('daemon') else ''} ---\n{r['stack']}"
        for r in rows)


# ---------------------------------------------------------------------------
# Wall-clock stack sampler
# ---------------------------------------------------------------------------

def _fold_frame_stack(frame, max_frames: int) -> str:
    """Collapse one frame chain into ``file:func:line;...`` root-first
    (flamegraph folded-stack order)."""
    stack: List[str] = []
    f = frame
    while f is not None and len(stack) < max_frames:
        code = f.f_code
        stack.append(f"{code.co_filename.rsplit('/', 1)[-1]}:"
                     f"{code.co_name}:{f.f_lineno}")
        f = f.f_back
    return ";".join(reversed(stack))


class StackSampler:
    """Wall-clock sampling profiler over ``sys._current_frames()``.

    A daemon thread wakes ``hz`` times per second and folds every
    thread's current stack into a per-thread count table
    ``{thread_name: {folded_stack: n}}``. Wall-clock (not CPU): a thread
    parked in ``select()`` or a lock shows up at its park site — on TPU
    hosts that is the point, since the bug class is "the chips are idle
    because the host is blocked *here*" (Podracer §3).

    Memory is bounded: at most ``max_unique_stacks`` distinct
    ``(thread, stack)`` keys are kept; samples whose key would exceed
    the bound are counted in ``dropped`` instead of allocated, so a
    pathological workload (e.g. deep recursion with varying line
    numbers) cannot OOM the sampled process.
    """

    def __init__(self, hz: Optional[float] = None,
                 max_unique_stacks: Optional[int] = None,
                 max_frames: int = 128):
        from ray_tpu._private.config import GlobalConfig

        self.hz = float(hz) if hz else float(GlobalConfig.profiler_default_hz)
        self.hz = min(max(self.hz, 1.0), 1000.0)
        self.max_unique_stacks = int(
            max_unique_stacks if max_unique_stacks is not None
            else GlobalConfig.profiler_max_unique_stacks)
        self.max_frames = max_frames
        self._counts: Dict[str, Dict[str, int]] = {}
        self._unique = 0
        self._samples = 0
        self._dropped = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t0 = 0.0
        self._t1 = 0.0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "StackSampler":
        if self._thread is not None:
            raise RuntimeError("StackSampler already started")
        self._t0 = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, name="rtpu-stack-sampler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> Dict[str, Any]:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._t1 = time.monotonic()
        return self.snapshot()

    def snapshot(self) -> Dict[str, Any]:
        """Current aggregate (valid while running — partial profiles of
        a dying worker are exactly this snapshot)."""
        with self._lock:
            counts = {t: dict(s) for t, s in self._counts.items()}
            samples, dropped = self._samples, self._dropped
        end = self._t1 or time.monotonic()
        return {"counts": counts, "samples": samples, "dropped": dropped,
                "duration_s": max(end - self._t0, 0.0), "hz": self.hz}

    # -- sampling loop -----------------------------------------------------
    def _run(self) -> None:
        period = 1.0 / self.hz
        own = threading.get_ident()
        next_tick = time.monotonic()
        while not self._stop.is_set():
            names = {t.ident: t.name for t in threading.enumerate()}
            try:
                frames = sys._current_frames()
            except Exception:
                frames = {}
            with self._lock:
                for ident, frame in frames.items():
                    if ident == own:
                        continue  # never sample the sampler itself
                    thread = names.get(ident, f"thread-{ident}")
                    folded = _fold_frame_stack(frame, self.max_frames)
                    per = self._counts.setdefault(thread, {})
                    if folded in per:
                        per[folded] += 1
                    elif self._unique < self.max_unique_stacks:
                        per[folded] = 1
                        self._unique += 1
                    else:
                        self._dropped += 1
                        continue
                    self._samples += 1
            next_tick += period
            delay = next_tick - time.monotonic()
            if delay <= 0:
                # overran (huge thread count / GIL contention): resync
                # rather than burning CPU trying to catch up.
                next_tick = time.monotonic()
                continue
            self._stop.wait(delay)


# ---------------------------------------------------------------------------
# Aggregation / rendering
# ---------------------------------------------------------------------------

def merge_counts(into: Dict[str, Dict[str, int]],
                 add: Dict[str, Dict[str, int]],
                 thread_prefix: str = "") -> Dict[str, Dict[str, int]]:
    """Fold one sampler's per-thread counts into an accumulator (used by
    the chunked ``util.state.profile`` client and the dashboard's
    cluster-wide speedscope merge; ``thread_prefix`` namespaces threads
    from different workers)."""
    for thread, stacks in (add or {}).items():
        per = into.setdefault(thread_prefix + thread, {})
        for folded, n in stacks.items():
            per[folded] = per.get(folded, 0) + n
    return into


def collapse(counts: Dict[str, Dict[str, int]]) -> str:
    """Collapsed-stack text (``thread;frame;...;frame count`` lines,
    flamegraph.pl / speedscope importable), hottest first."""
    lines = [(n, f"{thread};{folded} {n}")
             for thread, stacks in counts.items()
             for folded, n in stacks.items()]
    return "\n".join(line for _, line in
                     sorted(lines, key=lambda kv: (-kv[0], kv[1])))


def render_speedscope(counts: Dict[str, Dict[str, int]],
                      name: str = "ray_tpu profile") -> Dict[str, Any]:
    """Speedscope file-format JSON (one ``sampled`` profile per thread,
    shared frame table). Save it and drop it on https://speedscope.app,
    or ``speedscope profile.json`` with the npm CLI."""
    frames: List[Dict[str, str]] = []
    frame_index: Dict[str, int] = {}
    profiles: List[Dict[str, Any]] = []
    for thread in sorted(counts):
        samples: List[List[int]] = []
        weights: List[int] = []
        for folded, n in sorted(counts[thread].items()):
            idxs = []
            for fr in folded.split(";"):
                i = frame_index.get(fr)
                if i is None:
                    i = frame_index[fr] = len(frames)
                    frames.append({"name": fr})
                idxs.append(i)
            samples.append(idxs)
            weights.append(n)
        profiles.append({
            "type": "sampled", "name": thread, "unit": "none",
            "startValue": 0, "endValue": sum(weights),
            "samples": samples, "weights": weights,
        })
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "name": name, "exporter": "ray_tpu.observability.profiling",
        "shared": {"frames": frames},
        "profiles": profiles,
    }


# ---------------------------------------------------------------------------
# TPU device capture (jax.profiler bracket; host flamegraphs and device
# traces come from the same util.state API)
# ---------------------------------------------------------------------------

def capture_tpu_trace(duration_s: float,
                      trace_dir: Optional[str] = None) -> Dict[str, Any]:
    """Run ``jax.profiler.start_trace``/``stop_trace`` for ``duration_s``
    and return ``{"artifact": dir}`` — or a no-op ``{"skipped": reason}``
    when the process has no TPU backend (CPU CI, driver processes).
    Blocking: callers run it in an executor thread."""
    try:
        import jax
    except Exception as e:  # noqa: BLE001
        return {"skipped": f"jax unavailable: {e!r}"}
    try:
        backend = jax.default_backend()
    except Exception as e:  # noqa: BLE001
        return {"skipped": f"jax backend init failed: {e!r}"}
    if backend != "tpu":
        return {"skipped": f"jax backend is {backend!r}, not tpu — "
                           "no device trace taken (host-side "
                           "profile() still works)"}
    if not trace_dir:
        from ray_tpu._private.config import GlobalConfig

        base = GlobalConfig.tpu_profile_dir
        if not base:
            import tempfile

            base = tempfile.gettempdir()
        trace_dir = os.path.join(
            base, f"rtpu-tpu-profile-{os.getpid()}-{int(time.time())}")
    os.makedirs(trace_dir, exist_ok=True)
    jax.profiler.start_trace(trace_dir)
    try:
        time.sleep(max(float(duration_s), 0.0))
    finally:
        jax.profiler.stop_trace()
    return {"artifact": trace_dir, "duration_s": float(duration_s)}
