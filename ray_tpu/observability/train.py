"""Training instrumentation: train sessions and RLlib learners.

Two singletons feeding the same registry the serving metrics use:

- :func:`train_metrics` — driven by ``train.report()`` in each train
  worker: inter-report step duration, reports, samples/sec and loss
  when the user's metrics dict carries them.
- :func:`learner_metrics` — driven by ``rllib.core.Learner.update()``
  (the jitted SPMD step, gradient psum included) and
  ``LearnerGroup.update()`` (the distributed lockstep step across the
  learner fleet).

Step-duration histograms use coarser boundaries than the serving set:
training steps live in the 10ms..minutes range.

The per-step *phase* decomposition (data-wait/h2d/compute/...), the
goodput ledger, and the cross-worker step matrix live next door in
``observability.goodput``; :func:`record_report_step` is the bridge
for report-driven user loops — each ``train.report()`` gap doubles as
a step-heartbeat row so the GCS straggler detector and stall watchdog
cover custom loops that never touch ``StepPhases``.
"""

from __future__ import annotations

import threading

_train = None
_learner = None
_lock = threading.Lock()

_STEP_BOUNDARIES = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0,
                    60.0, 300.0)


class TrainMetrics:
    def __init__(self):
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        self.reports = Counter(
            "train_reports_total",
            description="train.report() calls across train workers.")
        self.step_seconds = Histogram(
            "train_step_seconds", boundaries=_STEP_BOUNDARIES,
            description="Wall time between consecutive train.report() "
                        "calls (one training step per report).")
        self.samples_per_sec = Gauge(
            "train_samples_per_sec",
            description="Reported samples per second (needs a "
                        "samples-like key in the metrics dict).")
        self.loss = Gauge(
            "train_loss",
            description="Most recent reported loss per train worker.")


class LearnerMetrics:
    def __init__(self):
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        self.updates = Counter(
            "learner_updates_total",
            description="Learner gradient updates (per learner "
                        "process).")
        self.update_seconds = Histogram(
            "learner_update_seconds", boundaries=_STEP_BOUNDARIES,
            description="Wall time of one jitted SPMD update "
                        "(gradient psum included).")
        self.samples = Counter(
            "learner_samples_total",
            description="Samples consumed by learner updates.")
        self.loss = Gauge(
            "learner_loss",
            description="total_loss of the most recent update.")
        self.group_update_seconds = Histogram(
            "learner_group_update_seconds", boundaries=_STEP_BOUNDARIES,
            description="Wall time of one LearnerGroup lockstep update "
                        "across the fleet.")


def train_metrics() -> TrainMetrics:
    global _train
    with _lock:
        if _train is None:
            _train = TrainMetrics()
        return _train


def learner_metrics() -> LearnerMetrics:
    global _learner
    with _lock:
        if _learner is None:
            _learner = LearnerMetrics()
        return _learner


def record_report_step(rank: int, step: int,
                       step_s: "float | None") -> None:
    """Publish one report-driven step row into the GCS step matrix.

    Called by the train session per ``train.report()`` with the
    inter-report gap: no phase breakdown (the user loop is opaque),
    but the row IS the worker's step heartbeat — a custom loop that
    stops reporting trips the stall watchdog, and one consistently
    slower than its peers is flagged TRAIN_STRAGGLER on wall time.
    """
    try:
        from ray_tpu.observability.goodput import (
            goodput_enabled, publish_train_step)

        if step_s is None or not goodput_enabled():
            return
        publish_train_step({
            "worker": f"rank{int(rank)}", "step": int(step),
            "wall_s": float(step_s), "phases": {},
        })
    except Exception:
        pass  # telemetry must never fail a training step


def batch_num_samples(batch) -> int:
    """Leading-dim size of the first leaf (nested multi-agent batches
    count their first module's rows — a stable per-step proxy)."""
    try:
        import jax

        leaves = jax.tree.leaves(batch)
        return int(len(leaves[0])) if leaves else 0
    except Exception:
        return 0
