"""Control-plane plumbing shared by the metrics-driven controllers.

Three controllers close the observability loop (serve replica
autoscaling, data backpressure tuning, raylet memory preemption); this
module is what keeps their *decisions* as observable as the metrics
they read:

- ``rtpu_ctrl_decisions_total{controller,action}`` — one counter
  increment per decision, from whichever process decided.
- a decision span on the task timeline (``ctrl:<controller>``), so
  scale actions line up with the load that caused them.
- a typed cluster event (AUTOSCALE_UP/DOWN, BACKPRESSURE_ADJUST,
  PREEMPT_RESCHEDULE) carrying the triggering metric reading.
- the GCS decision ring (``list_ctrl_decisions`` / dashboard
  ``GET /api/controller``).

It also hosts :class:`Hysteresis`, the one gate both the serve
autoscaler and the backpressure tuner put between "the metric moved"
and "act on it": a proposed change must *hold* for a direction-specific
delay, and actions are spaced by a cooldown — an oscillating gauge
therefore cannot flap the controlled value.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from ray_tpu.util.metrics import Counter

_metrics = None


class ControlMetrics:
    """Lazy singleton so importing this module never starts the metrics
    flusher thread in processes that make no control decisions."""

    def __init__(self):
        self.decisions = Counter(
            "ctrl_decisions_total",
            description="Control-plane decisions by controller and "
                        "action (autoscale, backpressure, preemption).",
            tag_keys=("controller", "action"))


def control_metrics() -> ControlMetrics:
    global _metrics
    if _metrics is None:
        _metrics = ControlMetrics()
    return _metrics


def record_decision(controller: str, action: str, reason: str,
                    reading: Optional[Dict[str, Any]] = None, *,
                    event_type: Optional[str] = None,
                    message: Optional[str] = None,
                    node_id: Optional[str] = None,
                    severity: Optional[str] = None,
                    emit: bool = True) -> Dict[str, Any]:
    """Record one control decision everywhere it is observable.

    Always increments the decision counter and drops a timeline span
    (the raylet's registry rides its reporter push; worker processes
    flush normally). With ``emit=True`` and a live global worker, also
    ships the cluster event and the GCS decision-ring entry
    synchronously; async callers with their own GCS client (the raylet)
    pass ``emit=False`` and forward the returned payload themselves.
    """
    reading = dict(reading or {})
    payload = {"controller": controller, "action": action,
               "reason": reason, "reading": reading, "node_id": node_id}
    control_metrics().decisions.inc(
        1.0, tags={"controller": controller, "action": action})

    from ray_tpu.util import tracing
    now = time.time()
    tracing.record_span(
        f"ctrl:{controller}", now, 0.0,
        attrs={"action": action, "reason": reason, **reading})

    if not emit:
        return payload

    from ray_tpu._private.worker import global_worker_or_none
    w = global_worker_or_none()
    if w is None or getattr(w, "_dead", False):
        return payload
    try:
        w.gcs.call("report_ctrl_decision", timeout=5, **payload)
        if event_type is not None:
            w.gcs.call(
                "report_cluster_event", event_type=event_type,
                message=message or f"{controller}: {action} ({reason})",
                severity=severity, node_id=node_id,
                extra={"controller": controller, "action": action,
                       **reading}, timeout=5)
    except Exception:
        pass  # decisions must never take down the deciding loop
    return payload


class Hysteresis:
    """Hold-delay + cooldown gate for a controlled integer value.

    ``propose(current, desired, now)`` returns the value to act on:
    ``desired`` only once it has been continuously proposed for
    ``up_delay_s`` (increases) / ``down_delay_s`` (decreases) *and* at
    least ``cooldown_s`` has passed since the last granted change;
    ``current`` otherwise. A proposal that changes while held restarts
    its clock, so oscillation never accumulates toward an action.
    """

    def __init__(self, up_delay_s: float = 0.0,
                 down_delay_s: float = 0.0,
                 cooldown_s: float = 0.0):
        self.up_delay_s = float(up_delay_s)
        self.down_delay_s = float(down_delay_s)
        self.cooldown_s = float(cooldown_s)
        self._pending: Optional[Any] = None
        self._pending_since = 0.0
        self._last_action = 0.0

    def propose(self, current, desired, now: Optional[float] = None):
        now = time.time() if now is None else now
        if desired == current:
            self._pending = None
            return current
        if self._pending != desired:
            self._pending = desired
            self._pending_since = now
        delay = self.up_delay_s if desired > current else self.down_delay_s
        if now - self._pending_since < delay:
            return current
        if now - self._last_action < self.cooldown_s:
            return current
        self._pending = None
        self._last_action = now
        return desired

    def note_external_change(self, now: Optional[float] = None) -> None:
        """Start the cooldown window after a change made outside the
        gate (e.g. a redeploy reset the replica count)."""
        self._last_action = time.time() if now is None else now
        self._pending = None
