"""TPU-aware telemetry plane.

The two things that silently kill TPU performance are XLA recompiles
and idle device time (Podracer, arXiv:2104.06272, attributes its TPU
efficiency to exactly this per-step accounting). This package is the
shared instrumentation layer every hot path reports through:

- ``jit``: compile tracking for the ``jax.jit`` entry points we own —
  per-function trace/compile counters, compile wall-time histograms,
  ``jit_compile`` spans, and a recompile detector that warns once a
  function re-traces past its budget (:class:`TrackedJit`).
- ``device``: per-device HBM/count gauges sampled by the metrics
  flusher (``device.memory_stats()`` where the backend provides it).
- ``serve``: TTFT/TPOT/e2e/queue-wait histograms, queue-depth /
  active-slot / batch-utilization gauges, and token/request counters
  for the continuous-batching LLM engine.
- ``train``: step-duration / samples-per-sec / loss reporting for
  ``train`` sessions and RLlib learners.
- ``goodput``: the train-tier goodput & straggler plane — the
  :class:`StepPhases` per-step phase ledger
  (``rtpu_train_step_phase_seconds{phase}`` + ``train.step`` spans),
  the :class:`GoodputLedger` productive-vs-lost wall-clock accounting
  (``rtpu_train_goodput_ratio``,
  ``rtpu_train_lost_seconds_total{cause}``), the
  :class:`StragglerDetector` over the GCS cross-worker step matrix
  (``report/list_train_steps``), and the hooks the GCS stall watchdog
  builds TRAIN_STRAGGLER / TRAIN_STALL events from.
- ``rl``: the decoupled-RL (podracer) plane — env-step vs
  learner-sample throughput counters, weight version/staleness gauges
  for the versioned WeightStore channel, sample-queue depth and
  backpressure counters, inference-server batching factors.
- ``collective``: op/bytes counters and latency histograms for every
  ``util.collective`` op (``rtpu_collective_*{op,backend,dtype}``),
  plus ``collective:<op>`` timeline spans — the interconnect side of
  the idle-device question.
- ``data``: the Dataset executors' metric set — per-stage throughput
  counters finalized by ``DatasetStats`` plus live backpressure gauges
  (in-flight tasks, queued blocks) from the scheduler loops.
- ``object_store``: per-node object-store memory-pressure metrics
  (used/capacity/pinned/spilled gauges, spill/restore/eviction
  counters) sampled from ``NodeObjectStore.stats()`` at each flush.
- ``timeline``: the Chrome-trace builder shared by
  ``ray_tpu.timeline()`` and the dashboard's ``GET /api/timeline`` —
  including the segmented submit arrows of the scheduling-phase
  breakdown (PENDING → LEASE_GRANTED → WORKER_STARTED → ARGS_READY →
  RUNNING).
- ``profiling``: the live profiling plane — the wall-clock
  :class:`StackSampler` (bounded memory, per-thread attribution)
  behind ``util.state.profile()`` flamegraphs, the one-shot stack
  dumps behind ``util.state.stack()`` / ``GET /api/stacks``, the
  jax.profiler device-trace bracket behind ``util.state.tpu_profile()``
  and the ``rtpu_sched_phase_seconds{phase}`` scheduling-latency
  histogram.
- ``events``: the cluster event schema registry — typed,
  severity-tagged failure-forensics events (worker-exit taxonomy,
  actor death/restart, node membership, lease reclaim, OOM) recorded
  in the GCS ClusterEventLog and queried via
  ``ray_tpu.util.state.list_cluster_events`` / ``GET /api/events``.
- ``control``: the decision side of the loop — the
  ``rtpu_ctrl_decisions_total{controller,action}`` counter, the
  :func:`record_decision` fan-out (counter + timeline span + typed
  cluster event + GCS decision ring / ``GET /api/controller``), and
  the :class:`Hysteresis` hold-delay/cooldown gate shared by the serve
  autoscaler and the data backpressure tuner.

- ``xla`` / ``chipspec``: the fleet-wide XLA program cost & roofline
  attribution plane — on first compile every :class:`TrackedJit`
  program's ``cost_analysis()`` (FLOPs, HBM bytes accessed,
  transcendentals) and ``memory_analysis()`` (argument/output/temp/peak
  HBM bytes) land in the per-process :class:`ProgramRegistry`; every
  ``xla_wall_sample_every``-th steady-state call is fenced to sample an
  honest execution wall, which divided by the chip-spec peak table
  (``chipspec``: v4/v5e/v5p, CPU rows tagged ``measurement: cpu``)
  yields MFU/MBU and a compute-/memory-/comm-bound roofline verdict
  (the last folding the exposed-collective seconds the sampled call
  straddled). Rows publish over bounded GCS
  ``report/list_xla_programs`` RPCs, roll up via
  ``util.state.xla_summary()`` / ``GET /api/programs``, and export as
  ``rtpu_xla_program_{flops,bytes_hbm,mfu,mbu}`` gauges plus the
  exemplar-carrying ``rtpu_xla_program_wall_seconds`` histogram. The
  regression sentinel baselines each function's first program and emits
  one typed ``PERF_REGRESSION`` cluster event per drift episode when a
  re-compile's FLOPs/peak-HBM or a sampled wall moves past
  ``xla_regression_ratio``.

- ``accounting``: the per-request cost accounting & SLO attainment
  plane for the serving tier — the :class:`RequestMeter` attached to
  every engine request (prefill tokens computed vs avoided, decode
  tokens, speculative accept ratio, KV block-seconds, queue-wait and
  chip-seconds per phase, stamped ``{tenant, model, lane, trace_id}``),
  the :class:`TenantLedger` fold published to the GCS over bounded
  ``report/list_serve_accounting`` RPCs, and the :class:`SLOTracker`
  multi-window burn-rate evaluation of TTFT/TPOT attainment per lane
  that emits the typed ``SLO_BURN`` cluster event
  (``rtpu_serve_request_cost_*``, ``rtpu_serve_tenant_*_total{tenant}``,
  ``rtpu_serve_slo_attainment_ratio{lane}``, ``GET /api/accounting``).

Everything exports through the existing plane: metric objects are
``ray_tpu.util.metrics`` Counters/Gauges/Histograms (flushed to the GCS
``/metrics`` scrape endpoint with the ``rtpu_`` prefix), spans are
``ray_tpu.util.tracing`` events (rendered by ``ray_tpu.timeline()``).
"""

from ray_tpu.observability.accounting import (  # noqa: F401
    COST_PHASES,
    RequestMeter,
    SLOTracker,
    TenantLedger,
    TokenReconciler,
    accounting_enabled,
    accounting_metrics,
    fold_finished,
    publish_serve_row,
    slo_targets,
    tenant_ledger,
)
from ray_tpu.observability.chipspec import (  # noqa: F401
    ChipSpec,
    local_spec,
    lookup,
)
from ray_tpu.observability.jit import (  # noqa: F401
    RecompileWarning,
    TrackedJit,
    jit_stats,
    tracked_jit,
)
from ray_tpu.observability.xla import (  # noqa: F401
    ProgramRegistry,
    attribution_enabled,
    flush_captures,
    local_programs,
    program_registry,
    wall_sample_every,
    xla_metrics,
)
from ray_tpu.observability.device import (  # noqa: F401
    sample_device_metrics,
)
from ray_tpu.observability.control import (  # noqa: F401
    Hysteresis,
    control_metrics,
    record_decision,
)
from ray_tpu.observability.collective import (  # noqa: F401
    collective_metrics,
    observe_collective,
)
from ray_tpu.observability.data import data_metrics  # noqa: F401
from ray_tpu.observability.events import (  # noqa: F401
    EVENT_TYPES,
    SEVERITIES,
    WORKER_EXIT_TYPES,
    classify_worker_exit,
    make_event,
)
from ray_tpu.observability.goodput import (  # noqa: F401
    GOODPUT_CAUSES,
    TRAIN_PHASES,
    GoodputLedger,
    StepPhases,
    StragglerDetector,
    classify_phase,
    goodput_enabled,
    goodput_metrics,
    publish_train_done,
    publish_train_step,
    record_checkpoint,
    record_recompile,
)
from ray_tpu.observability.object_store import (  # noqa: F401
    object_store_metrics,
    register_store_sampler,
)
from ray_tpu.observability.profiling import (  # noqa: F401
    SCHED_PHASES,
    SCHED_SEGMENT_LABELS,
    StackSampler,
    capture_thread_stacks,
    collapse,
    format_thread_stacks,
    merge_counts,
    observe_sched_phases,
    render_speedscope,
)
from ray_tpu.observability.rl import rl_metrics  # noqa: F401
from ray_tpu.observability.serve import serve_metrics  # noqa: F401
from ray_tpu.observability.timeline import build_chrome_trace  # noqa: F401
from ray_tpu.observability.train import (  # noqa: F401
    batch_num_samples,
    learner_metrics,
    train_metrics,
)

__all__ = [
    "RecompileWarning", "TrackedJit", "tracked_jit", "jit_stats",
    "sample_device_metrics", "serve_metrics", "rl_metrics",
    "train_metrics",
    "learner_metrics", "batch_num_samples", "build_chrome_trace",
    "data_metrics", "object_store_metrics", "register_store_sampler",
    "EVENT_TYPES", "SEVERITIES", "WORKER_EXIT_TYPES",
    "classify_worker_exit", "make_event",
    "Hysteresis", "control_metrics", "record_decision",
    "collective_metrics", "observe_collective",
    "SCHED_PHASES", "SCHED_SEGMENT_LABELS", "StackSampler",
    "capture_thread_stacks", "collapse", "format_thread_stacks",
    "merge_counts", "observe_sched_phases", "render_speedscope",
    "GOODPUT_CAUSES", "TRAIN_PHASES", "GoodputLedger", "StepPhases",
    "StragglerDetector", "classify_phase", "goodput_enabled",
    "goodput_metrics", "publish_train_done", "publish_train_step",
    "record_checkpoint", "record_recompile",
    "COST_PHASES", "RequestMeter", "SLOTracker", "TenantLedger",
    "TokenReconciler", "accounting_enabled", "accounting_metrics",
    "fold_finished", "publish_serve_row", "slo_targets", "tenant_ledger",
    "ChipSpec", "local_spec", "lookup",
    "ProgramRegistry", "attribution_enabled", "flush_captures",
    "local_programs", "program_registry", "wall_sample_every",
    "xla_metrics",
]
