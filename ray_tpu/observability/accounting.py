"""Per-request cost accounting & SLO attainment for the serving tier.

The serving tier can trace a request hop-by-hop and meter the fleet in
aggregate, but neither answers "what did THIS request — or this tenant
— cost, and are we meeting the SLO we sold them?". Three cooperating
pieces answer it (the same measure-first shape as the train goodput
plane in :mod:`ray_tpu.observability.goodput`):

- :class:`RequestMeter` — attached to every ``LLMEngine`` request,
  integrating over its lifetime: prefill tokens computed vs avoided
  (prefix/tier hits), decode tokens, speculative accept counts, KV
  **block-seconds** (block occupancy integrated over hold time — the
  HBM-rent number; monotone across preempt/resume and never
  double-counted), queue wait and chip-seconds per phase — stamped
  with ``{tenant, model, lane, trace_id}``. A meter survives KV
  migration: the prefill tier ships :meth:`RequestMeter.snapshot` next
  to the exported ``KVState`` and the decode tier absorbs it, so
  prefill chip-seconds land on the same ledger row.
- :class:`TenantLedger` — a bounded per-tenant accumulator the
  finished meters fold into. Cardinality is bounded by construction:
  past ``serve_accounting_max_tenants`` distinct tenants, new ones
  fold into the ``__other__`` rollup row — which is what makes the
  ``rtpu_serve_tenant_*_total{tenant}`` counters declared here safe
  against the ``metric-label-cardinality`` lint rule (the emit site IS
  the bounded fold).
- :class:`SLOTracker` — per-lane TTFT/TPOT attainment against the
  ``serve_slo_ttft_ms`` / ``serve_slo_tpot_ms`` config targets, with
  multi-window burn rate (fast ~1m / slow ~1h): the fast window
  catches a regression in about a minute, but only fires when the
  slow window is also consuming budget, so a one-blip spike never
  pages. A not-burning → burning transition yields one flag dict per
  episode — the GCS turns it into a typed ``SLO_BURN`` cluster event.

Rows publish to the GCS over the bounded accounting ring
(``report_serve_accounting`` / ``list_serve_accounting`` /
``serve_accounting_summary`` — the train-step-ring shape), surface as
``util.state.serve_accounting()`` and ``GET /api/accounting``, and the
whole plane is gated on ``serve_accounting_instrumentation`` so the
``serve_accounting_overhead`` bench can price the on/off delta.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

# Chip-time phases a request is billed for. "prefill" covers the
# bucketed insert dispatch (and tier promotes) of its own admission;
# "decode" is its fair share (1/n_live) of each decode/verify tick it
# was live in. Scheduler-thread wall around the device programs — an
# attribution, not a hardware counter.
COST_PHASES = ("prefill", "decode")

# Rollup tenant key for overflow past serve_accounting_max_tenants.
OTHER_TENANT = "__other__"

_metrics = None
_ledger = None
_lock = threading.Lock()

# Test hooks: callables invoked with each finalized row folded in this
# process (the reconciliation self-check subscribes here).
_row_hooks: List[Callable[[Dict[str, Any]], None]] = []


class AccountingMetrics:
    """Metric surface of the accounting plane.

    The tenant-labelled counters are declared HERE (not in
    observability/serve.py) deliberately: every emit site routes
    through :class:`TenantLedger.fold`, whose ``__other__`` rollup
    bounds the tenant label set — the exemption contract of the
    ``metric-label-cardinality`` graftlint rule.
    """

    def __init__(self):
        from ray_tpu.util.metrics import Counter, Histogram

        cost_bounds = (0.0001, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                       1.0, 5.0, 15.0, 60.0)
        self.request_chip_seconds = Histogram(
            "serve_request_cost_chip_seconds", boundaries=cost_bounds,
            description="Per-request chip-seconds (prefill + decode "
                        "share), observed at request finish with the "
                        "request's trace id as the exemplar.")
        self.request_block_seconds = Histogram(
            "serve_request_cost_block_seconds", boundaries=cost_bounds,
            description="Per-request KV block-seconds (block occupancy "
                        "integrated over hold time — the HBM-rent "
                        "number).")
        self.tenant_tokens = Counter(
            "serve_tenant_tokens_total", tag_keys=("tenant",),
            description="Output tokens per tenant (bounded label set: "
                        "overflow tenants fold into __other__).")
        self.tenant_block_seconds = Counter(
            "serve_tenant_block_seconds_total", tag_keys=("tenant",),
            description="KV block-seconds per tenant — what each "
                        "tenant's requests rent in HBM block "
                        "occupancy.")
        self.tenant_chip_seconds = Counter(
            "serve_tenant_chip_seconds_total", tag_keys=("tenant",),
            description="Chip-seconds per tenant across prefill and "
                        "decode.")
        # The SLO attainment/burn gauges (rtpu_serve_slo_attainment_
        # ratio{lane}, rtpu_serve_slo_burn_rate{lane,window}) are NOT
        # declared here: the SLOTracker evaluates GCS-side, so the GCS
        # exports them natively in its /metrics exposition — same as
        # rtpu_nodes.


def accounting_metrics() -> AccountingMetrics:
    global _metrics
    with _lock:
        if _metrics is None:
            _metrics = AccountingMetrics()
        return _metrics


def accounting_enabled() -> bool:
    from ray_tpu._private.config import GlobalConfig

    return bool(GlobalConfig.serve_accounting_instrumentation)


def _clean_tag(value: str) -> str:
    """Tag values must not contain ',' (the registry's tuple encoding)."""
    return str(value).replace(",", "_") or "default"


# -------------------------------------------------------------- meter

class RequestMeter:
    """Resource integrator for one serve request.

    Mutated on the engine scheduler thread (plus the submit call);
    a lock keeps ``snapshot()`` safe from the replica thread after
    completion. Block-seconds integrate over an explicit open interval
    (``_blocks_held`` since ``_held_since``): acquire/release close
    the running interval first, so preempt → resume cycles stay
    monotone and a double release cannot subtract time.
    """

    def __init__(self, tenant: str = "default", model: str = "",
                 lane: str = "interactive",
                 trace_id: Optional[str] = None,
                 request_id: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lk = threading.Lock()
        self.tenant = _clean_tag(tenant)
        self.model = str(model)
        self.lane = str(lane)
        self.trace_id = trace_id
        self.request_id = request_id
        self.queue_wait_s: Optional[float] = None
        self.prefill_tokens_computed = 0
        self.prefill_tokens_avoided = 0
        self.tokens_out = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.block_seconds = 0.0
        self.chip_seconds: Dict[str, float] = {p: 0.0 for p in COST_PHASES}
        self.migrations = 0         # absorbed prefill-side snapshots
        self.ttft_s: Optional[float] = None
        self.tpot_s: Optional[float] = None
        self.e2e_s: Optional[float] = None
        self.finish_reason: Optional[str] = None
        self.finished = False
        self._blocks_held = 0
        self._held_since: Optional[float] = None

    # --- block-seconds integration -------------------------------------
    def _settle(self, now: float) -> None:
        if self._blocks_held > 0 and self._held_since is not None:
            dt = max(now - self._held_since, 0.0)
            self.block_seconds += dt * self._blocks_held
        self._held_since = now if self._blocks_held > 0 else None

    def blocks_acquired(self, n: int, now: Optional[float] = None) -> None:
        if n <= 0:
            return
        now = self._clock() if now is None else now
        with self._lk:
            self._settle(now)
            self._blocks_held += int(n)
            self._held_since = now

    def blocks_released(self, n: int, now: Optional[float] = None) -> None:
        if n <= 0:
            return
        now = self._clock() if now is None else now
        with self._lk:
            self._settle(now)
            self._blocks_held = max(self._blocks_held - int(n), 0)
            self._held_since = now if self._blocks_held > 0 else None

    @property
    def blocks_held(self) -> int:
        return self._blocks_held

    # --- counters --------------------------------------------------------
    def note_queue_wait(self, seconds: float) -> None:
        with self._lk:
            self.queue_wait_s = (self.queue_wait_s or 0.0) \
                + max(float(seconds), 0.0)

    def note_prefill(self, computed: int, avoided: int) -> None:
        with self._lk:
            self.prefill_tokens_computed += max(int(computed), 0)
            self.prefill_tokens_avoided += max(int(avoided), 0)

    def note_spec(self, proposed: int, accepted: int) -> None:
        with self._lk:
            self.spec_proposed += max(int(proposed), 0)
            self.spec_accepted += max(int(accepted), 0)

    def note_chip(self, phase: str, seconds: float) -> None:
        if phase not in COST_PHASES:
            raise ValueError(f"unknown cost phase {phase!r} "
                             f"(want one of {COST_PHASES})")
        with self._lk:
            self.chip_seconds[phase] += max(float(seconds), 0.0)

    # --- migration -------------------------------------------------------
    def absorb(self, snapshot: Optional[Dict[str, Any]]) -> None:
        """Fold a prefill-side snapshot into this (decode-side) meter so
        the whole migrated request lands on ONE ledger row. Identity
        (tenant / trace id) prefers the originating side: the row must
        key by the trace id the router returned as ``x-trace-id``.
        Token counts are NOT absorbed — the decode handle's token list
        is seeded with the prefill-side tokens already, and absorbing
        them too would double-count."""
        if not snapshot:
            return
        with self._lk:
            if snapshot.get("trace_id"):
                self.trace_id = snapshot["trace_id"]
            if snapshot.get("tenant"):
                self.tenant = _clean_tag(snapshot["tenant"])
            if snapshot.get("model"):
                self.model = str(snapshot["model"])
            self.prefill_tokens_computed += int(
                snapshot.get("prefill_tokens_computed", 0))
            self.prefill_tokens_avoided += int(
                snapshot.get("prefill_tokens_avoided", 0))
            self.spec_proposed += int(snapshot.get("spec_proposed", 0))
            self.spec_accepted += int(snapshot.get("spec_accepted", 0))
            self.block_seconds += float(snapshot.get("block_seconds", 0.0))
            for phase in COST_PHASES:
                self.chip_seconds[phase] += float(
                    snapshot.get("chip_seconds", {}).get(phase, 0.0))
            if snapshot.get("queue_wait_s") is not None:
                self.queue_wait_s = (self.queue_wait_s or 0.0) \
                    + float(snapshot["queue_wait_s"])
            if snapshot.get("ttft_s") is not None:
                self.ttft_s = float(snapshot["ttft_s"])
            self.migrations += int(snapshot.get("migrations", 0)) + 1

    # --- lifecycle -------------------------------------------------------
    def finalize(self, finish_reason: str, tokens_out: int,
                 ttft_s: Optional[float] = None,
                 tpot_s: Optional[float] = None,
                 e2e_s: Optional[float] = None,
                 now: Optional[float] = None) -> Dict[str, Any]:
        """Close the integration (any open block interval settles) and
        return the row dict. Idempotent: a second finalize re-returns
        the same totals without re-integrating."""
        now = self._clock() if now is None else now
        with self._lk:
            if not self.finished:
                self._settle(now)
                self._blocks_held = 0
                self._held_since = None
                self.finished = True
                self.finish_reason = str(finish_reason)
                self.tokens_out = int(tokens_out)
                # A ttft absorbed from the prefill side wins: the first
                # token was sampled there.
                if self.ttft_s is None and ttft_s is not None:
                    self.ttft_s = float(ttft_s)
                if tpot_s is not None:
                    self.tpot_s = float(tpot_s)
                if e2e_s is not None:
                    self.e2e_s = float(e2e_s)
        return self.snapshot()

    def snapshot(self) -> Dict[str, Any]:
        """Plain-dict view (picklable — this is what rides the disagg
        hand-off next to the KVState and what the GCS ring ingests)."""
        with self._lk:
            return {
                "tenant": self.tenant,
                "model": self.model,
                "lane": self.lane,
                "trace_id": self.trace_id,
                "request_id": self.request_id,
                "queue_wait_s": self.queue_wait_s,
                "prefill_tokens_computed": self.prefill_tokens_computed,
                "prefill_tokens_avoided": self.prefill_tokens_avoided,
                "tokens_out": self.tokens_out,
                "spec_proposed": self.spec_proposed,
                "spec_accepted": self.spec_accepted,
                "spec_accept_ratio": (
                    self.spec_accepted / self.spec_proposed
                    if self.spec_proposed else None),
                "block_seconds": self.block_seconds,
                "chip_seconds": dict(self.chip_seconds),
                "chip_seconds_total": sum(self.chip_seconds.values()),
                "migrations": self.migrations,
                "ttft_s": self.ttft_s,
                "tpot_s": self.tpot_s,
                "e2e_s": self.e2e_s,
                "finish_reason": self.finish_reason,
                "finished": self.finished,
            }


# -------------------------------------------------------------- ledger

class TenantLedger:
    """Bounded per-tenant cost accumulator.

    ``fold()`` returns the canonical tenant key the row was booked
    under — the caller emits tenant-labelled counters with THAT key,
    which is how the metric label set stays bounded: at most
    ``max_tenants`` distinct tenants plus the ``__other__`` rollup.
    """

    _FIELDS = ("tokens", "block_seconds", "chip_seconds",
               "prefill_tokens_computed", "prefill_tokens_avoided",
               "queue_wait_s")

    def __init__(self, max_tenants: Optional[int] = None):
        if max_tenants is None:
            from ray_tpu._private.config import GlobalConfig

            max_tenants = int(GlobalConfig.serve_accounting_max_tenants)
        self.max_tenants = max(int(max_tenants), 1)
        self._lk = threading.Lock()
        self._tenants: Dict[str, Dict[str, Any]] = {}

    def _slot_for(self, tenant: str) -> str:
        if tenant in self._tenants or \
                len(self._tenants) < self.max_tenants:
            return tenant
        return OTHER_TENANT

    def fold(self, row: Dict[str, Any]) -> str:
        tenant = _clean_tag(row.get("tenant") or "default")
        with self._lk:
            key = self._slot_for(tenant)
            t = self._tenants.setdefault(key, {
                "tenant": key, "requests": 0,
                **{f: 0.0 for f in self._FIELDS}})
            t["requests"] += 1
            t["tokens"] += float(row.get("tokens_out") or 0)
            t["block_seconds"] += float(row.get("block_seconds") or 0.0)
            t["chip_seconds"] += float(
                row.get("chip_seconds_total") or 0.0)
            t["prefill_tokens_computed"] += float(
                row.get("prefill_tokens_computed") or 0)
            t["prefill_tokens_avoided"] += float(
                row.get("prefill_tokens_avoided") or 0)
            t["queue_wait_s"] += float(row.get("queue_wait_s") or 0.0)
            t["last_trace_id"] = row.get("trace_id")
            t["last_lane"] = row.get("lane")
            return key

    def top(self, n: int) -> List[Dict[str, Any]]:
        """Top ``n`` tenants by chip-seconds (the cost currency)."""
        with self._lk:
            rows = sorted(self._tenants.values(),
                          key=lambda t: t["chip_seconds"], reverse=True)
            return [dict(r) for r in rows[:max(int(n), 0)]]

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lk:
            return {k: dict(v) for k, v in self._tenants.items()}

    def __len__(self) -> int:
        with self._lk:
            return len(self._tenants)


def tenant_ledger() -> TenantLedger:
    """Process-local ledger singleton (one per serve replica process)."""
    global _ledger
    with _lock:
        if _ledger is None:
            _ledger = TenantLedger()
        return _ledger


def register_row_hook(fn: Callable[[Dict[str, Any]], None]) -> None:
    """Test hook: ``fn(row)`` runs for every row folded in this
    process (the reconciliation self-check subscribes here)."""
    _row_hooks.append(fn)


def unregister_row_hook(fn: Callable[[Dict[str, Any]], None]) -> None:
    try:
        _row_hooks.remove(fn)
    except ValueError:
        pass


def fold_finished(row: Dict[str, Any]) -> str:
    """Fold one finalized meter row: tenant ledger + the metric surface
    (cost histograms with the trace exemplar, bounded tenant counters)
    + fire-and-forget publish into the GCS accounting ring. Returns the
    canonical tenant key the row was booked under. Never raises —
    accounting must never break the scheduler."""
    key = tenant_ledger().fold(row)
    try:
        m = accounting_metrics()
        trace_id = row.get("trace_id")
        chip = float(row.get("chip_seconds_total") or 0.0)
        m.request_chip_seconds.observe(chip, trace_id=trace_id)
        m.request_block_seconds.observe(
            float(row.get("block_seconds") or 0.0), trace_id=trace_id)
        tags = {"tenant": key}
        tokens = float(row.get("tokens_out") or 0)
        if tokens:
            m.tenant_tokens.inc(tokens, tags=tags)
        if row.get("block_seconds"):
            m.tenant_block_seconds.inc(float(row["block_seconds"]),
                                       tags=tags)
        if chip:
            m.tenant_chip_seconds.inc(chip, tags=tags)
    except Exception:
        pass
    for fn in list(_row_hooks):
        try:
            fn(row)
        except Exception:
            pass
    publish_serve_row(row)
    return key


def publish_serve_row(row: Dict[str, Any]) -> bool:
    """Fire-and-forget report of one accounting row into the GCS ring
    (``report_serve_accounting``). Returns False (silently) outside a
    connected worker — a bare-process engine still gets local metrics
    and the local ledger."""
    try:
        from ray_tpu._private.worker import global_worker_or_none

        w = global_worker_or_none()
        if w is None or getattr(w, "_dead", False):
            return False
        payload = dict(row)
        nid = w.node_id
        payload.setdefault(
            "node_id", nid.hex() if hasattr(nid, "hex") else nid)
        w.gcs.cast("report_serve_accounting", row=payload)
        return True
    except Exception:
        return False


# ------------------------------------------------------------ SLO targets

def _parse_lane_targets(spec: str, unit_scale: float = 1e-3
                        ) -> Dict[str, float]:
    """Parse ``"interactive=500,*=2000"`` (ms) into lane → seconds;
    a bare number applies to every lane (the ``*`` entry)."""
    out: Dict[str, float] = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            lane, _, val = part.partition("=")
            lane = lane.strip() or "*"
        else:
            lane, val = "*", part
        try:
            out[lane] = float(val) * unit_scale
        except ValueError:
            continue
    return out


def slo_targets() -> Dict[str, Tuple[float, float]]:
    """Resolved per-lane (ttft_s, tpot_s) targets from config. Lanes
    without an explicit entry use the ``*`` default; a missing ``*``
    falls back to +inf (never violated)."""
    from ray_tpu._private.config import GlobalConfig

    ttft = _parse_lane_targets(GlobalConfig.serve_slo_ttft_ms)
    tpot = _parse_lane_targets(GlobalConfig.serve_slo_tpot_ms)
    lanes = set(ttft) | set(tpot) | {"interactive", "batch"}
    lanes.discard("*")
    inf = float("inf")
    return {lane: (ttft.get(lane, ttft.get("*", inf)),
                   tpot.get(lane, tpot.get("*", inf)))
            for lane in lanes}


class SLOTracker:
    """Per-lane TTFT/TPOT attainment + multi-window burn rate.

    Pure host-side logic with an injectable clock (tests drive it with
    a fake). ``observe()`` returns a flag dict exactly once per
    not-burning → burning transition; the episode clears (and may
    re-fire later) once the fast burn drops below half the threshold —
    the same one-flag-per-episode discipline as the straggler
    detector."""

    _WINDOW_MAXLEN = 4096

    def __init__(self, targets: Optional[Dict[str, Tuple[float, float]]]
                 = None,
                 objective: Optional[float] = None,
                 fast_window_s: Optional[float] = None,
                 slow_window_s: Optional[float] = None,
                 burn_threshold: Optional[float] = None,
                 min_samples: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        from ray_tpu._private.config import GlobalConfig

        self._targets = targets
        self.objective = float(
            GlobalConfig.serve_slo_objective
            if objective is None else objective)
        self.objective = min(max(self.objective, 0.0), 0.9999)
        self.fast_window_s = float(
            GlobalConfig.serve_slo_burn_fast_window_s
            if fast_window_s is None else fast_window_s)
        self.slow_window_s = float(
            GlobalConfig.serve_slo_burn_slow_window_s
            if slow_window_s is None else slow_window_s)
        self.burn_threshold = float(
            GlobalConfig.serve_slo_burn_threshold
            if burn_threshold is None else burn_threshold)
        self.min_samples = int(
            GlobalConfig.serve_slo_min_samples
            if min_samples is None else min_samples)
        self._clock = clock
        self._lk = threading.Lock()
        # lane -> deque[(t, ok)] covering the slow window (the fast
        # window is a suffix of it).
        self._obs: Dict[str, deque] = {}
        self._burning: Dict[str, bool] = {}

    def _lane_targets(self, lane: str) -> Tuple[float, float]:
        targets = self._targets if self._targets is not None \
            else slo_targets()
        inf = float("inf")
        if lane in targets:
            return targets[lane]
        return targets.get("*", (inf, inf))

    def observe(self, lane: str, ttft_s: Optional[float],
                tpot_s: Optional[float],
                now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        now = self._clock() if now is None else now
        lane = str(lane or "interactive")
        ttft_t, tpot_t = self._lane_targets(lane)
        ok = ((ttft_s is None or ttft_s <= ttft_t)
              and (tpot_s is None or tpot_s <= tpot_t))
        with self._lk:
            q = self._obs.setdefault(
                lane, deque(maxlen=self._WINDOW_MAXLEN))
            q.append((now, bool(ok)))
            self._prune(q, now)
            return self._evaluate(lane, now)

    def _prune(self, q: deque, now: float) -> None:
        horizon = now - self.slow_window_s
        while q and q[0][0] < horizon:
            q.popleft()

    def _window_stats(self, lane: str, window_s: float, now: float
                      ) -> Tuple[int, float]:
        q = self._obs.get(lane, ())
        horizon = now - window_s
        n = bad = 0
        for t, ok in reversed(q):
            if t < horizon:
                break
            n += 1
            if not ok:
                bad += 1
        return n, (bad / n if n else 0.0)

    def attainment(self, lane: str, window: str = "fast",
                   now: Optional[float] = None) -> Optional[float]:
        now = self._clock() if now is None else now
        window_s = self.fast_window_s if window == "fast" \
            else self.slow_window_s
        with self._lk:
            n, err = self._window_stats(lane, window_s, now)
        return None if n == 0 else 1.0 - err

    def burn_rate(self, lane: str, window: str = "fast",
                  now: Optional[float] = None) -> Optional[float]:
        """Error-budget burn: error_rate / (1 - objective). 1.0 means
        consuming budget exactly at the objective's allowance; a full
        outage at objective 0.99 burns at 100x."""
        att = self.attainment(lane, window, now)
        if att is None:
            return None
        return (1.0 - att) / (1.0 - self.objective)

    def burning(self, lane: str) -> bool:
        return bool(self._burning.get(str(lane)))

    def _evaluate(self, lane: str, now: float) -> Optional[Dict[str, Any]]:
        """Burn-state machine for one lane; caller holds the lock."""
        n_fast, err_fast = self._window_stats(
            lane, self.fast_window_s, now)
        _, err_slow = self._window_stats(lane, self.slow_window_s, now)
        budget = 1.0 - self.objective
        fast_burn = err_fast / budget
        slow_burn = err_slow / budget
        was = self._burning.get(lane, False)
        if was:
            if fast_burn < self.burn_threshold / 2.0:
                self._burning[lane] = False
            return None
        if (n_fast >= self.min_samples
                and fast_burn >= self.burn_threshold
                and slow_burn >= 1.0):
            self._burning[lane] = True
            ttft_t, tpot_t = self._lane_targets(lane)
            return {
                "lane": lane,
                "fast_burn": round(fast_burn, 3),
                "slow_burn": round(slow_burn, 3),
                "attainment_fast": round(1.0 - err_fast, 4),
                "attainment_slow": round(1.0 - err_slow, 4),
                "objective": self.objective,
                "ttft_target_s": ttft_t,
                "tpot_target_s": tpot_t,
                "window_fast_s": self.fast_window_s,
                "window_slow_s": self.slow_window_s,
            }
        return None

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Per-lane SLO view for the accounting summary: attainment and
        burn per window, burn state, targets."""
        now = self._clock() if now is None else now
        out: Dict[str, Any] = {}
        with self._lk:
            lanes = list(self._obs)
        for lane in lanes:
            ttft_t, tpot_t = self._lane_targets(lane)
            entry = {"ttft_target_s": ttft_t, "tpot_target_s": tpot_t,
                     "objective": self.objective,
                     "burning": self.burning(lane)}
            for window in ("fast", "slow"):
                att = self.attainment(lane, window, now)
                entry[f"attainment_{window}"] = att
                entry[f"burn_{window}"] = (
                    None if att is None
                    else (1.0 - att) / (1.0 - self.objective))
            out[lane] = entry
        return out


# --------------------------------------------------- reconciliation hook

class TokenReconciler:
    """Debug self-check: over a window, the sum of per-request meter
    token counts must equal the ``rtpu_serve_tokens_total`` delta —
    catching double-count/drop bugs in the fold path. Use as a context
    manager around a serve window, then assert ``.holds()``:

        with TokenReconciler() as rec:
            ...serve requests to completion...
        assert rec.holds(), rec.detail()

    Process-local by construction (``util.metrics.local_summary`` —
    zero-RPC), so it compares exactly the requests THIS process both
    metered and counted.
    """

    def __init__(self):
        self._rows: List[Dict[str, Any]] = []
        self._before = 0.0
        self._after: Optional[float] = None

    @staticmethod
    def _tokens_total() -> float:
        from ray_tpu.util.metrics import local_summary

        rec = local_summary(["serve_tokens_total"]) \
            .get("serve_tokens_total")
        if not rec:
            return 0.0
        return float(sum(rec.get("data", {}).values()))

    def _on_row(self, row: Dict[str, Any]) -> None:
        self._rows.append(row)

    def __enter__(self) -> "TokenReconciler":
        self._before = self._tokens_total()
        register_row_hook(self._on_row)
        return self

    def __exit__(self, *exc) -> None:
        unregister_row_hook(self._on_row)
        self._after = self._tokens_total()

    @property
    def counter_delta(self) -> float:
        after = self._after if self._after is not None \
            else self._tokens_total()
        return after - self._before

    @property
    def meter_sum(self) -> float:
        return float(sum(r.get("tokens_out") or 0 for r in self._rows))

    def holds(self) -> bool:
        return abs(self.counter_delta - self.meter_sum) < 1e-9

    def detail(self) -> str:
        return (f"meter sum {self.meter_sum} vs counter delta "
                f"{self.counter_delta} over {len(self._rows)} rows")


def _reset_for_tests() -> None:
    """Drop process-local accounting state (ledger + hooks); metric
    objects persist (the registry aliases re-declarations)."""
    global _ledger
    with _lock:
        _ledger = None
    del _row_hooks[:]
