"""Chrome-trace builder over GCS task events.

The one rendering of the head's task-event ring buffer, shared by
``ray_tpu.timeline()`` (driver API) and the dashboard's
``GET /api/timeline`` (download endpoint). Output loads in
chrome://tracing / Perfetto:

- ``cat:"task"``     one complete (``ph:"X"``) event per task
  execution, RUNNING -> FINISHED/FAILED, rowed by worker address.
  Still-running tasks render to the ring's newest timestamp (not
  ``time.time()`` at render — repeated downloads of a live job must be
  monotone) and carry ``args.state="RUNNING"``, ``args.incomplete``.
- ``cat:"submit"``   the submission->execution path, rowed by the
  submitting driver/worker pid. With scheduling-phase events present
  (PENDING -> LEASE_GRANTED -> WORKER_STARTED -> ARGS_READY ->
  RUNNING) it renders one segment per phase hop (named
  ``<task>:<phase>``, ``args.phase`` = lease_grant / worker_start /
  args_fetch / exec_start); otherwise the single PENDING -> RUNNING
  arrow.
- ``cat:"span"``     user spans from ``ray_tpu.util.tracing`` —
  including the telemetry plane's ``jit_compile`` and per-request
  ``llm.*`` lifecycle spans.
"""

from __future__ import annotations

from typing import Dict, List

from ray_tpu.observability.profiling import (
    SCHED_PHASES,
    SCHED_SEGMENT_LABELS,
)


def build_chrome_trace(events: List[Dict]) -> List[Dict]:
    by_task: Dict[bytes, Dict[str, Dict]] = {}
    spans: List[Dict] = []
    horizon = 0.0  # ring's newest timestamp = render-time "now"
    for e in events:
        ts = e.get("ts")
        if isinstance(ts, (int, float)) and ts > horizon:
            horizon = ts
        if e["state"] == "SPAN":
            spans.append(e)
            continue
        slot = by_task.setdefault(e["task_id"], {})
        prev = slot.get(e["state"])
        # Duplicate states keep the newest event: the owner stamps a
        # push-time RUNNING (so live/crashed tasks render at all) and,
        # on reply, the worker's exec-start-accurate RUNNING — the
        # refined one wins deterministically.
        if prev is None or e["ts"] >= prev["ts"]:
            slot[e["state"]] = e
    trace: List[Dict] = []
    for tid, states in by_task.items():
        run, end = states.get("RUNNING"), (
            states.get("FINISHED") or states.get("FAILED"))
        if not run:
            continue
        worker = ":".join(map(str, run.get("worker_addr", ["?"])))
        # Incomplete (still-RUNNING) tasks extend to the ring horizon:
        # a function of the event data only, so re-rendering the same
        # ring yields the same trace and successive downloads of a
        # live job only ever grow the bar.
        end_ts = end["ts"] if end else max(horizon, run["ts"])
        args = {"task_id": tid.hex(),
                "state": end["state"] if end else "RUNNING"}
        if not end:
            args["incomplete"] = True
        trace.append({
            "name": run["name"], "cat": "task", "ph": "X",
            "ts": run["ts"] * 1e6, "dur": max(end_ts - run["ts"], 0) * 1e6,
            "pid": worker, "tid": worker,
            "args": args,
        })
        owner = states.get("PENDING") or run
        drv = f"driver-{owner.get('owner_pid', '?')}"
        present = [(p, states[p]) for p in SCHED_PHASES if p in states]
        if len(present) >= 2:
            # Segmented submit arrows: one bar per phase hop between
            # consecutive *present* phases (a phase evicted from the
            # ring widens the next hop instead of dropping it).
            for (_, ea), (pb, eb) in zip(present, present[1:]):
                label = SCHED_SEGMENT_LABELS.get(pb, pb)
                trace.append({
                    "name": f"{run['name']}:{label}", "cat": "submit",
                    "ph": "X", "ts": ea["ts"] * 1e6,
                    "dur": max(eb["ts"] - ea["ts"], 0) * 1e6,
                    "pid": drv, "tid": drv,
                    "args": {"task_id": tid.hex(), "phase": label},
                })
    for e in spans:  # user spans from ray_tpu.util.tracing
        trace.append({
            "name": e["name"], "cat": "span", "ph": "X",
            "ts": e["ts"] * 1e6, "dur": max(e.get("dur", 0), 0) * 1e6,
            "pid": f"spans-{e.get('owner_pid', '?')}",
            "tid": e["task_id"].hex()[:12],
            "args": e.get("attrs", {}),
        })
    return trace
