"""Chrome-trace builder over GCS task events.

The one rendering of the head's task-event ring buffer, shared by
``ray_tpu.timeline()`` (driver API) and the dashboard's
``GET /api/timeline`` (download endpoint). Output loads in
chrome://tracing / Perfetto:

- ``cat:"task"``     one complete (``ph:"X"``) event per task
  execution, RUNNING -> FINISHED/FAILED, rowed by worker address.
- ``cat:"submit"``   the submission->execution flow arrow
  (PENDING -> RUNNING), rowed by submitting driver/worker pid.
- ``cat:"span"``     user spans from ``ray_tpu.util.tracing`` —
  including the telemetry plane's ``jit_compile`` and per-request
  ``llm.*`` lifecycle spans.
"""

from __future__ import annotations

import time
from typing import Dict, List


def build_chrome_trace(events: List[Dict]) -> List[Dict]:
    by_task: Dict[bytes, Dict[str, Dict]] = {}
    spans: List[Dict] = []
    for e in events:
        if e["state"] == "SPAN":
            spans.append(e)
            continue
        by_task.setdefault(e["task_id"], {})[e["state"]] = e
    trace: List[Dict] = []
    for tid, states in by_task.items():
        run, end = states.get("RUNNING"), (
            states.get("FINISHED") or states.get("FAILED"))
        if not run:
            continue
        worker = ":".join(map(str, run.get("worker_addr", ["?"])))
        end_ts = end["ts"] if end else time.time()
        trace.append({
            "name": run["name"], "cat": "task", "ph": "X",
            "ts": run["ts"] * 1e6, "dur": max(end_ts - run["ts"], 0) * 1e6,
            "pid": worker, "tid": worker,
            "args": {"task_id": tid.hex(),
                     "state": end["state"] if end else "RUNNING"},
        })
        sub = states.get("PENDING")
        if sub:  # flow arrow: submission -> execution
            trace.append({
                "name": run["name"], "cat": "submit", "ph": "X",
                "ts": sub["ts"] * 1e6,
                "dur": max(run["ts"] - sub["ts"], 0) * 1e6,
                "pid": f"driver-{sub.get('owner_pid', '?')}",
                "tid": f"driver-{sub.get('owner_pid', '?')}",
                "args": {"task_id": tid.hex()},
            })
    for e in spans:  # user spans from ray_tpu.util.tracing
        trace.append({
            "name": e["name"], "cat": "span", "ph": "X",
            "ts": e["ts"] * 1e6, "dur": e.get("dur", 0) * 1e6,
            "pid": f"spans-{e.get('owner_pid', '?')}",
            "tid": e["task_id"].hex()[:12],
            "args": e.get("attrs", {}),
        })
    return trace
