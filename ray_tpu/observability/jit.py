"""JIT compile telemetry: trace/compile accounting for jitted programs.

XLA programs are shape-specialized, so a hot path that feeds a jitted
function changing shapes/dtypes retraces (and recompiles) silently —
the serving engine's ad-hoc ``_traces`` guard existed precisely to
catch that. :class:`TrackedJit` generalizes it onto a shared API:

    tick = tracked_jit(tick_fn, name="engine_tick", trace_budget=1,
                       donate_argnums=(1,))
    out = tick(params, state)           # drop-in for jax.jit(tick_fn)
    tick.traces                         # programs traced by THIS wrapper

Each new trace increments the ``jit_traces_total`` / ``jit_compiles_total``
counters (tagged by function name), observes the first-call wall time —
trace + lower + compile + first execute, the cost a user actually waits
for — into the ``jit_compile_seconds`` histogram, and records a
``jit_compile`` span so ``ray_tpu.timeline()`` shows compiles inline
with the run. When an instance re-traces past ``trace_budget`` it warns
ONCE with :class:`RecompileWarning` naming the function and the
argument signature that caused the re-trace.

Budgets are per-instance (a fresh engine legitimately re-traces its own
programs); the counters aggregate per function name across instances
and processes.

On top of the trace guard rides the XLA attribution plane
(observability/xla.py): each new program's ``cost_analysis()`` /
``memory_analysis()`` is captured through the :meth:`compiled` accessor
(one shared AOT artifact per signature, built on the plane's background
capture worker so the extra compile never lands on the caller), and
every ``xla_wall_sample_every``-th steady-state call
is fenced with ``block_until_ready`` to sample an honest execution wall
(0 disables sampling: the fence never runs on the hot path).
"""

from __future__ import annotations

import threading
import time
import warnings
from typing import Any, Callable, Dict, Optional

_lock = threading.Lock()
# name -> {"traces": int, "compiles": int, "compile_seconds_total": float}
_stats: Dict[str, Dict[str, float]] = {}

_metrics = None


class RecompileWarning(UserWarning):
    """A tracked jitted function re-traced beyond its trace budget."""


def _jit_metrics():
    """Lazy module-level metric singletons (one registry entry per
    process regardless of how many TrackedJit instances exist)."""
    global _metrics
    if _metrics is None:
        from ray_tpu.util.metrics import Counter, Histogram

        _metrics = {
            "traces": Counter(
                "jit_traces_total",
                description="XLA traces of tracked jitted functions.",
                tag_keys=("fn",)),
            "compiles": Counter(
                "jit_compiles_total",
                description="XLA compiles of tracked jitted functions.",
                tag_keys=("fn",)),
            "compile_seconds": Histogram(
                "jit_compile_seconds",
                description="First-call wall time of newly traced "
                            "programs (trace+compile+execute).",
                boundaries=(0.01, 0.05, 0.25, 1.0, 5.0, 15.0, 60.0,
                            300.0),
                tag_keys=("fn",)),
        }
    return _metrics


def _arg_signature(args, kwargs) -> str:
    """Compact human-readable shape/dtype signature for the warning."""
    def one(a: Any) -> str:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None:
            return f"{dtype}[{','.join(map(str, shape))}]"
        if isinstance(a, (dict, list, tuple)):
            return type(a).__name__
        return f"{type(a).__name__}:{a!r}"[:40]

    parts = [one(a) for a in args]
    parts += [f"{k}={one(v)}" for k, v in kwargs.items()]
    return "(" + ", ".join(parts) + ")"


class TrackedJit:
    """``jax.jit`` plus trace/compile telemetry and a recompile budget.

    The wrapped python callable only runs when jax traces a new
    program, so ``traces`` counts compiled programs exactly — the same
    mechanism as the engine's original ``_traces`` guard.
    """

    def __init__(self, fn: Callable, *, name: Optional[str] = None,
                 trace_budget: Optional[int] = None, **jit_kwargs):
        import jax

        self.name = name or getattr(fn, "__name__", "jitted")
        self.traces = 0
        self.calls = 0
        if trace_budget is None:
            from ray_tpu._private.config import GlobalConfig

            trace_budget = GlobalConfig.jit_recompile_warn_budget
        self.trace_budget = trace_budget
        self._warned = False
        self._fn = fn
        self._jit_kwargs = dict(jit_kwargs)
        # AOT artifacts per argument signature, shared between the
        # attribution hook and compiled() callers — one lowered program
        # instead of a re-lower per consumer.
        self._compiled_cache: Dict[str, Any] = {}
        # While the attribution hook lowers through the jit wrapper the
        # probe still runs under tracing; this re-entrancy flag keeps
        # those internal traces out of the user-facing counters.
        self._suppress = threading.local()
        from ray_tpu.observability import xla as _xla

        self._sample_every = _xla.wall_sample_every() \
            if _xla.attribution_enabled() else 0

        def probe(*args, **kwargs):
            # Runs only under tracing: count the new program here. The
            # mutation is the whole point — it fires once per trace, not
            # per call, which is exactly what a retrace counter wants.
            if not getattr(self._suppress, "on", False):
                self.traces += 1  # graftlint: disable=jit-global-mutation
                with _lock:
                    st = _stats.setdefault(self.name, {
                        "traces": 0, "compiles": 0,
                        "compile_seconds_total": 0.0})
                    st["traces"] += 1
            return fn(*args, **kwargs)

        self._jitted = jax.jit(probe, **jit_kwargs)

    def __call__(self, *args, **kwargs):
        self.calls += 1
        sample = (self._sample_every > 0
                  and self.calls % self._sample_every == 0)
        exposed0 = _cumulative_exposed() if sample else 0.0
        before = self.traces
        t0 = time.perf_counter()
        out = self._jitted(*args, **kwargs)
        if self.traces > before:
            dt = time.perf_counter() - t0
            self._on_compile(dt, args, kwargs)
        elif sample:
            self._sample_wall(out, t0, exposed0, args, kwargs)
        return out

    def _on_compile(self, seconds: float, args, kwargs) -> None:
        with _lock:
            st = _stats[self.name]
            st["compiles"] += 1
            st["compile_seconds_total"] += seconds
        try:
            m = _jit_metrics()
            tags = {"fn": self.name}
            m["compiles"].inc(1.0, tags=tags)
            m["traces"].inc(1.0, tags=tags)
            m["compile_seconds"].observe(seconds, tags=tags)
        except Exception:
            pass  # telemetry must never break the hot path
        try:
            # Compile wall time is lost training time: the goodput
            # ledger books it as "recompiling" when a train loop is
            # live in this process (no-op otherwise).
            from ray_tpu.observability.goodput import record_recompile

            record_recompile(seconds)
        except Exception:
            pass
        try:
            from ray_tpu.util.tracing import record_span

            record_span("jit_compile", time.time() - seconds, seconds,
                        attrs={"fn": self.name, "traces": self.traces})
        except Exception:
            pass
        try:
            # XLA attribution: capture this program's cost/memory
            # analysis into the per-process ProgramRegistry.
            from ray_tpu.observability import xla as _xla

            if _xla.attribution_enabled():
                _xla.on_tracked_compile(self, seconds, args, kwargs)
        except Exception:
            pass
        if (self.trace_budget and self.traces > self.trace_budget
                and not self._warned):
            self._warned = True
            warnings.warn(
                f"jitted function {self.name!r} traced {self.traces} "
                f"programs (budget {self.trace_budget}); last re-trace "
                f"caused by call {_arg_signature(args, kwargs)} — "
                f"check for varying shapes/dtypes/static args on the "
                f"hot path", RecompileWarning, stacklevel=4)

    def _sample_wall(self, out, t0: float, exposed0: float,
                     args, kwargs) -> None:
        """Fence the sampled call and hand its wall (plus the exposed
        collective seconds it straddled) to the attribution plane."""
        try:
            import jax

            jax.block_until_ready(out)
            wall = time.perf_counter() - t0
            exposed = max(_cumulative_exposed() - exposed0, 0.0)
            from ray_tpu.observability import xla as _xla

            _xla.on_tracked_sample(self, _arg_signature(args, kwargs),
                                   wall, exposed)
        except Exception:
            pass  # sampling must never break the hot path

    # -- AOT surface -------------------------------------------------

    def _abstract_args(self, args, kwargs):
        """Shape/dtype skeletons of a call: lowering through these never
        touches (possibly donated, possibly dead) device buffers."""
        import jax

        def one(a):
            shape = getattr(a, "shape", None)
            dtype = getattr(a, "dtype", None)
            if shape is not None and dtype is not None:
                return jax.ShapeDtypeStruct(shape, dtype)
            return a

        static_nums = self._jit_kwargs.get("static_argnums") or ()
        if isinstance(static_nums, int):
            static_nums = (static_nums,)
        static_names = self._jit_kwargs.get("static_argnames") or ()
        if isinstance(static_names, str):
            static_names = (static_names,)
        abs_args = tuple(
            a if i in static_nums else jax.tree_util.tree_map(one, a)
            for i, a in enumerate(args))
        abs_kwargs = {
            k: (v if k in static_names
                else jax.tree_util.tree_map(one, v))
            for k, v in kwargs.items()}
        return abs_args, abs_kwargs

    def compiled(self, *args, **kwargs):
        """AOT-compiled artifact for this call signature (lower +
        compile, cached per signature). The attribution hook and user
        code share the one artifact, so asking for ``cost_analysis()``
        never re-lowers a program the wrapper already built. Returns
        None when the backend cannot lower (telemetry callers treat
        that as "no analysis")."""
        key = _arg_signature(args, kwargs)
        cached = self._compiled_cache.get(key)
        if cached is not None:
            return cached
        try:
            abs_args, abs_kwargs = self._abstract_args(args, kwargs)
            self._suppress.on = True
            try:
                artifact = self._jitted.lower(
                    *abs_args, **abs_kwargs).compile()
            finally:
                self._suppress.on = False
            self._compiled_cache[key] = artifact
            return artifact
        except Exception:
            return None

    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    def eval_shape(self, *args, **kwargs):
        """Shape evaluation against the RAW function: never traces the
        probe, so speculative shape queries cannot inflate the
        trace/compile counters or mark a program as seen."""
        import jax

        return jax.eval_shape(self._fn, *args, **kwargs)

    def clear_cache(self) -> None:
        """Drop the jit trace cache AND the AOT artifact cache together
        — after this, the next call re-traces (and re-counts) like a
        fresh wrapper, and ``compiled()`` re-lowers."""
        self._compiled_cache.clear()
        try:
            self._jitted.clear_cache()
        except Exception:
            pass

    # jax.clear_caches()-era spelling; same semantics.
    clear_caches = clear_cache


def _cumulative_exposed() -> float:
    """Total exposed split-phase collective seconds this process has
    booked so far (observability/collective.py); 0.0 when the plane is
    unused. Deltas around a sampled call feed the comm-bound verdict."""
    try:
        from ray_tpu.observability.collective import (
            cumulative_exposed_seconds,
        )

        return cumulative_exposed_seconds()
    except Exception:
        return 0.0


def tracked_jit(fn: Optional[Callable] = None, *,
                name: Optional[str] = None,
                trace_budget: Optional[int] = None,
                **jit_kwargs):
    """Drop-in ``jax.jit`` replacement with compile telemetry.

    Usable directly (``tracked_jit(fn, donate_argnums=...)``) or as a
    decorator (``@tracked_jit(name="step")``).
    """
    if fn is None:
        def deco(f):
            return TrackedJit(f, name=name, trace_budget=trace_budget,
                              **jit_kwargs)
        return deco
    return TrackedJit(fn, name=name, trace_budget=trace_budget,
                      **jit_kwargs)


def jit_stats() -> Dict[str, Dict[str, float]]:
    """Per-function aggregate {traces, compiles, compile_seconds_total}
    for every tracked function in this process."""
    with _lock:
        return {k: dict(v) for k, v in _stats.items()}
