"""Data-pipeline instrumentation: the Dataset executors' metric set.

One process-wide singleton (every StreamingExecutor / ConcurrentExecutor
run in a process shares the registry entries; counters aggregate across
processes on the GCS scrape side, so ``rtpu_data_rows_out_total`` is the
whole cluster's ingestion throughput).

Counters carry per-stage totals finalized at the end of each run by
``DatasetStats``; the gauges are live backpressure state updated from
inside the scheduler loops:

- ``data_inflight_tasks{stage}``: remote tasks currently in flight for
  the stage (the concurrency the scheduler actually achieved);
- ``data_queued_blocks{stage}``: blocks sitting in the stage's input
  queue waiting for a free slot — a persistently deep queue on stage N
  with idle in-flight on stage N+1 means N+1 is the bottleneck.
"""

from __future__ import annotations

import threading

_singleton = None
_lock = threading.Lock()


class DataMetrics:
    def __init__(self):
        from ray_tpu.util.metrics import Counter, Gauge

        self.blocks_out = Counter(
            "data_blocks_out_total", tag_keys=("stage",),
            description="Blocks produced by a Dataset stage.")
        self.rows_out = Counter(
            "data_rows_out_total", tag_keys=("stage",),
            description="Rows produced by a Dataset stage.")
        self.bytes_out = Counter(
            "data_bytes_out_total", tag_keys=("stage",),
            description="Block bytes produced by a Dataset stage.")
        self.tasks = Counter(
            "data_tasks_submitted_total", tag_keys=("stage", "kind"),
            description="Remote submissions per stage (kind=task|actor).")
        self.stage_wall = Counter(
            "data_stage_wall_seconds_total", tag_keys=("stage",),
            description="Wall time spent producing a stage's output.")
        self.stage_blocked = Counter(
            "data_stage_blocked_seconds_total", tag_keys=("stage",),
            description="Time a stage spent blocked waiting on its "
                        "input stream.")
        self.inflight = Gauge(
            "data_inflight_tasks", tag_keys=("stage",),
            description="Remote tasks currently in flight for a stage.")
        self.queued = Gauge(
            "data_queued_blocks", tag_keys=("stage",),
            description="Blocks queued at a stage's input awaiting a "
                        "launch slot (backpressure depth).")


def data_metrics() -> DataMetrics:
    global _singleton
    with _lock:
        if _singleton is None:
            _singleton = DataMetrics()
        return _singleton
