"""Per-device gauges: HBM in use / capacity and device inventory.

Sampled by the metrics flusher (registered lazily as a flush sampler —
``ray_tpu.util.metrics.register_flush_sampler``), so any process that
touches the observability plane exports its accelerator view on the
same cadence as its other metrics. Idle-HBM headroom and a device
count that doesn't match the slice topology are the first things to
check when a TPU job underperforms.

Deliberately conservative about initialization: sampling NEVER
initializes a jax backend (that can cost seconds over a tunneled TPU
connection, in processes that never run device code) — it only reads
from backends that are already live.
"""

from __future__ import annotations

import sys
from typing import Dict

_gauges = None
_registered = False


def _device_gauges():
    global _gauges
    if _gauges is None:
        from ray_tpu.util.metrics import Gauge

        _gauges = {
            "used": Gauge(
                "device_hbm_used_bytes",
                description="Device memory in use (device.memory_stats "
                            "bytes_in_use).",
                tag_keys=("device", "kind")),
            "total": Gauge(
                "device_hbm_total_bytes",
                description="Device memory capacity (device.memory_stats "
                            "bytes_limit).",
                tag_keys=("device", "kind")),
            "count": Gauge(
                "device_count",
                description="Visible devices by kind/platform.",
                tag_keys=("kind", "platform")),
        }
    return _gauges


def _live_backend_devices():
    """Devices of already-initialized backends only; [] otherwise."""
    if "jax" not in sys.modules:
        return []
    try:
        from jax._src import xla_bridge

        if not getattr(xla_bridge, "_backends", None):
            return []
        import jax

        return list(jax.devices())
    except Exception:
        return []


def sample_device_metrics() -> int:
    """Set the device gauges from the live backend; returns the number
    of devices sampled (0 when no backend is initialized)."""
    devices = _live_backend_devices()
    if not devices:
        return 0
    g = _device_gauges()
    by_kind: Dict[tuple, int] = {}
    for d in devices:
        kind = getattr(d, "device_kind", "unknown")
        platform = getattr(d, "platform", "unknown")
        by_kind[(kind, platform)] = by_kind.get((kind, platform), 0) + 1
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if not ms:
            continue
        tags = {"device": str(getattr(d, "id", "?")), "kind": kind}
        used = ms.get("bytes_in_use")
        total = ms.get("bytes_limit") or ms.get("bytes_reservable_limit")
        if used is not None:
            g["used"].set(float(used), tags=tags)
        if total is not None:
            g["total"].set(float(total), tags=tags)
    for (kind, platform), n in by_kind.items():
        g["count"].set(float(n), tags={"kind": kind,
                                       "platform": platform})
    return len(devices)


def ensure_sampler_registered() -> None:
    """Idempotently hook device sampling into the metrics flusher."""
    global _registered
    if _registered:
        return
    _registered = True
    from ray_tpu.util.metrics import register_flush_sampler

    register_flush_sampler(sample_device_metrics)
