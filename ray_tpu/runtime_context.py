"""Runtime context (reference: `python/ray/runtime_context.py`)."""

from __future__ import annotations

from typing import Dict, List, Optional


class RuntimeContext:
    def __init__(self, worker):
        self._worker = worker

    def get_job_id(self) -> str:
        return self._worker.job_id.hex()

    def get_node_id(self) -> str:
        return self._worker.node_id.hex()

    def get_worker_id(self) -> str:
        return self._worker.worker_id.hex()

    def get_task_id(self) -> Optional[str]:
        tid = self._worker.current_task_id()
        return tid.hex() if tid else None

    def get_actor_id(self) -> Optional[str]:
        aid = self._worker.current_actor_id()
        return aid.hex() if aid else None

    def get_tpu_ids(self) -> List[int]:
        """TPU chip ids assigned to the current task/actor by the raylet."""
        return self._worker.current_tpu_ids()

    @property
    def gcs_address(self):
        return self._worker.gcs_addr

    @property
    def was_current_actor_reconstructed(self) -> bool:
        return False


def get_runtime_context() -> RuntimeContext:
    from ray_tpu._private.worker import global_worker

    return RuntimeContext(global_worker())
