"""Block model: the unit of distributed data.

A block is a pyarrow.Table (reference: `python/ray/data/block.py` — blocks
are arrow tables / pandas frames moved through the object store).  The
BlockAccessor converts between user-facing batch formats ("numpy" dict of
arrays, "pandas", "pyarrow", or plain row dicts) and the canonical arrow
representation.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Union

import numpy as np

try:
    import pyarrow as pa
except ImportError:  # pragma: no cover - pyarrow is in the base image
    pa = None

Block = "pa.Table"
Batch = Union[Dict[str, np.ndarray], "pa.Table", Any]


def _ensure_pa():
    if pa is None:
        raise ImportError("pyarrow is required for ray_tpu.data")


class BlockAccessor:
    """Wraps one arrow-table block."""

    def __init__(self, table: "pa.Table"):
        self._t = table

    # ------------------------------------------------------------ construct
    @staticmethod
    def from_rows(rows: List[Any]) -> "pa.Table":
        _ensure_pa()
        if not rows:
            return pa.table({})
        if isinstance(rows[0], dict):
            cols: Dict[str, list] = {}
            for r in rows:
                for k in r:
                    cols.setdefault(k, [])
            for r in rows:
                for k in cols:
                    cols[k].append(r.get(k))
            return pa.table(
                {k: pa.array(v) for k, v in cols.items()})
        # Plain values -> single "item" column (reference convention).
        return pa.table({"item": pa.array(rows)})

    @staticmethod
    def from_batch(batch: Batch) -> "pa.Table":
        _ensure_pa()
        if pa is not None and isinstance(batch, pa.Table):
            return batch
        if isinstance(batch, dict):
            arrays = {}
            for k, v in batch.items():
                v = np.asarray(v)
                if v.ndim > 1:
                    # Tensor column: dense fixed-shape tensors (images,
                    # embeddings); nested lists only for object dtypes.
                    try:
                        arrays[k] = pa.FixedShapeTensorArray\
                            .from_numpy_ndarray(np.ascontiguousarray(v))
                    except (ValueError, pa.ArrowInvalid, TypeError):
                        arrays[k] = pa.array(v.tolist())
                else:
                    arrays[k] = pa.array(v)
            return pa.table(arrays)
        try:  # pandas
            import pandas as pd

            if isinstance(batch, pd.DataFrame):
                return pa.Table.from_pandas(batch, preserve_index=False)
        except ImportError:
            pass
        raise TypeError(f"unsupported batch type: {type(batch)}")

    # ------------------------------------------------------------- convert
    def to_batch(self, batch_format: str = "numpy") -> Batch:
        if batch_format in ("pyarrow", "arrow"):
            return self._t
        if batch_format == "pandas":
            return self._t.to_pandas()
        if batch_format in ("numpy", "default"):
            out: Dict[str, np.ndarray] = {}
            for name in self._t.column_names:
                col = self._t.column(name)
                if isinstance(col.type, getattr(pa, "FixedShapeTensorType",
                                                ())):
                    # Tensor column (e.g. images): dense ndarray, not
                    # object-of-lists.
                    arr = (col.combine_chunks()
                           if isinstance(col, pa.ChunkedArray) else col)
                    out[name] = arr.to_numpy_ndarray()
                    continue
                try:
                    out[name] = col.to_numpy(zero_copy_only=False)
                except (pa.ArrowInvalid, ValueError):
                    out[name] = np.asarray(col.to_pylist())
                if out[name].dtype == object:
                    try:
                        out[name] = np.stack(
                            [np.asarray(x) for x in out[name]])
                    except Exception:
                        pass
            return out
        raise ValueError(f"unknown batch_format {batch_format!r}")

    def rows(self) -> Iterable[Dict[str, Any]]:
        cols = self._t.column_names
        for i in range(self._t.num_rows):
            yield {c: self._t.column(c)[i].as_py() for c in cols}

    # --------------------------------------------------------------- shape
    @property
    def table(self) -> "pa.Table":
        return self._t

    def num_rows(self) -> int:
        return self._t.num_rows

    def size_bytes(self) -> int:
        return self._t.nbytes

    def slice(self, start: int, end: int) -> "pa.Table":
        return self._t.slice(start, end - start)

    @staticmethod
    def concat(blocks: List["pa.Table"]) -> "pa.Table":
        _ensure_pa()
        blocks = [b for b in blocks if b.num_rows > 0] or blocks[:1]
        if not blocks:
            return pa.table({})
        return pa.concat_tables(blocks, promote_options="default")

    def schema(self) -> Optional["pa.Schema"]:
        return self._t.schema
