"""Datasources: produce ReadTasks — serializable thunks yielding blocks.

Reference model: `python/ray/data/datasource/datasource.py` (Datasource /
ReadTask).  A read op materializes into N ReadTasks; the streaming executor
runs each as a remote task, so reads scale out and interleave with
downstream transforms.
"""

from __future__ import annotations

import os
import struct as _struct
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np

from ray_tpu.data.block import BlockAccessor

# A ReadTask is a zero-arg callable returning an iterable of blocks.
ReadTask = Callable[[], Iterable[Any]]


class Datasource:
    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None


class RangeDatasource(Datasource):
    def __init__(self, n: int):
        self._n = n

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = self._n
        parallelism = max(1, min(parallelism, n) if n else 1)
        chunk = (n + parallelism - 1) // parallelism if n else 0
        tasks: List[ReadTask] = []
        for i in range(parallelism):
            lo, hi = i * chunk, min((i + 1) * chunk, n)
            if lo >= hi:
                break

            def make(lo=lo, hi=hi):
                def read():
                    yield BlockAccessor.from_batch(
                        {"id": np.arange(lo, hi, dtype=np.int64)})
                return read
            tasks.append(make())
        return tasks or [lambda: iter(
            [BlockAccessor.from_batch({"id": np.zeros(0, np.int64)})])]


class ItemsDatasource(Datasource):
    def __init__(self, items: List[Any]):
        self._items = list(items)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        items = self._items
        n = len(items)
        parallelism = max(1, min(parallelism, n) if n else 1)
        chunk = (n + parallelism - 1) // parallelism if n else 0
        tasks: List[ReadTask] = []
        for i in range(parallelism):
            part = items[i * chunk:(i + 1) * chunk]
            if not part:
                break

            def make(part=part):
                def read():
                    yield BlockAccessor.from_rows(part)
                return read
            tasks.append(make())
        return tasks or [lambda: iter([BlockAccessor.from_rows([])])]


class _FileDatasource(Datasource):
    """One read task per file."""

    def __init__(self, paths: Any):
        if isinstance(paths, (str, os.PathLike)):
            paths = [paths]
        expanded: List[str] = []
        for p in paths:
            p = os.fspath(p)
            if os.path.isdir(p):
                expanded.extend(
                    sorted(os.path.join(p, f) for f in os.listdir(p)
                           if not f.startswith(".")))
            else:
                expanded.append(p)
        self._paths = expanded

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self._paths:
            def make(path=path):
                def read():
                    yield from self._read_file(path)
                return read
            tasks.append(make())
        return tasks

    def _read_file(self, path: str):
        raise NotImplementedError


class ParquetDatasource(_FileDatasource):
    def _read_file(self, path: str):
        import pyarrow.parquet as pq

        yield pq.read_table(path)


class CSVDatasource(_FileDatasource):
    def _read_file(self, path: str):
        import pyarrow.csv as pacsv

        yield pacsv.read_csv(path)


class JSONDatasource(_FileDatasource):
    """Newline-delimited JSON (reference: `datasource/json_datasource.py`)."""

    def _read_file(self, path: str):
        import pyarrow.json as pajson

        yield pajson.read_json(path)


class ImageDatasource(_FileDatasource):
    """Image files -> {"image": fixed-shape tensor, "path"} rows
    (reference: `datasource/image_datasource.py`). `size=(H, W)` resizes
    so a directory of mixed sizes yields one uniform tensor column —
    what a TPU input pipeline needs for static shapes."""

    _EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")

    def __init__(self, paths: Any, size=None, mode: str = "RGB"):
        super().__init__(paths)
        self._paths = [p for p in self._paths
                       if p.lower().endswith(self._EXTS)]
        self._size = size
        self._mode = mode

    def _read_file(self, path: str):
        import pyarrow as pa
        from PIL import Image

        img = Image.open(path)
        if self._mode:
            img = img.convert(self._mode)
        if self._size is not None:
            h, w = self._size
            img = img.resize((w, h))
        arr = np.asarray(img)
        if self._size is not None:
            # Dense fixed-shape tensor column (np.stack, not arr[None]:
            # a size-1 view axis gets stride 0, which
            # FixedShapeTensorArray rejects).
            tensor = pa.FixedShapeTensorArray.from_numpy_ndarray(
                np.stack([arr]))
        else:
            # Without a target size images may differ per file; a
            # fixed-shape type per block would fail to concatenate.
            # Nested lists unify across blocks (ragged column).
            tensor = pa.array([arr.tolist()])
        yield pa.table({"image": tensor, "path": pa.array([path])})


class TextDatasource(_FileDatasource):
    def _read_file(self, path: str):
        with open(path, "r", encoding="utf-8") as f:
            lines = [ln.rstrip("\n") for ln in f]
        yield BlockAccessor.from_batch({"text": np.asarray(lines, object)})


class BinaryDatasource(_FileDatasource):
    def _read_file(self, path: str):
        with open(path, "rb") as f:
            data = f.read()
        import pyarrow as pa

        yield pa.table({"bytes": pa.array([data], pa.binary()),
                        "path": pa.array([path])})


class TFRecordDatasource(_FileDatasource):
    """TFRecord files of tf.train.Example protos, parsed WITHOUT a
    tensorflow dependency (reference: `datasource/tfrecords_datasource
    .py`, which shells out to TF) — the record framing (length + masked
    crc) and the three-feature-list Example wire format are small enough
    to decode directly. The main TPU-training ingest format."""

    def _read_file(self, path: str):
        rows = []
        with open(path, "rb") as f:
            while True:
                header = f.read(8)
                if len(header) < 8:
                    break
                (length,) = _struct.unpack("<Q", header)
                f.read(4)  # masked crc of length (not verified)
                payload = f.read(length)
                if len(payload) < length:
                    raise ValueError(
                        f"truncated TFRecord in {path}: record declared "
                        f"{length} bytes, got {len(payload)} (interrupted "
                        "writer or partial download)")
                f.read(4)  # masked crc of payload
                rows.append(_parse_tf_example(payload))
        yield BlockAccessor.from_rows(rows)


def _sign64(v: int) -> int:
    """Varints are unsigned on the wire; int64 fields sign-extend."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _read_varint(buf: bytes, pos: int):
    shift = 0
    out = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _iter_fields(buf: bytes):
    """(field_number, wire_type, value) over a protobuf message body."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:            # varint
            val, pos = _read_varint(buf, pos)
        elif wire == 1:          # 64-bit
            val = buf[pos:pos + 8]
            pos += 8
        elif wire == 2:          # length-delimited
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:          # 32-bit
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def _parse_tf_example(payload: bytes) -> dict:
    """tf.train.Example: field 1 = Features{field 1 = map<string,
    Feature>}; Feature = oneof bytes_list(1)/float_list(2)/int64_list(3),
    each a repeated field 1 (floats packed LE, ints packed varint)."""
    row: dict = {}
    for field, _w, features in _iter_fields(payload):
        if field != 1:
            continue
        for f2, _w2, entry in _iter_fields(features):
            if f2 != 1:
                continue
            key, feature = None, b""
            for f3, _w3, v in _iter_fields(entry):
                if f3 == 1:
                    key = v.decode()
                elif f3 == 2:
                    feature = v
            if key is None:
                continue
            values: list = []
            for f4, _w4, flist in _iter_fields(feature):
                if f4 == 1:      # bytes_list
                    for f5, _w5, b in _iter_fields(flist):
                        if f5 == 1:
                            values.append(b)
                elif f4 == 2:    # float_list (packed floats)
                    for f5, w5, v in _iter_fields(flist):
                        if f5 != 1:
                            continue
                        if w5 == 2:
                            values.extend(
                                _struct.unpack(f"<{len(v) // 4}f", v))
                        else:
                            values.append(_struct.unpack("<f", v)[0])
                elif f4 == 3:    # int64_list (packed varints)
                    for f5, w5, v in _iter_fields(flist):
                        if f5 != 1:
                            continue
                        if w5 == 2:
                            pos = 0
                            while pos < len(v):
                                iv, pos = _read_varint(v, pos)
                                values.append(_sign64(iv))
                        else:
                            values.append(v)
            row[key] = values[0] if len(values) == 1 else values
    return row


class WebDatasetDatasource(_FileDatasource):
    """WebDataset tar shards (reference: `datasource/webdataset_
    datasource.py`): each sample is the group of tar members sharing a
    basename up to the first dot; the remainder is the field name.
    `.txt`/`.cls`/`.json` members decode; everything else stays bytes."""

    def _read_file(self, path: str):
        import json as _json
        import tarfile

        rows = []
        current_key = None
        row: dict = {}
        with tarfile.open(path, "r") as tar:
            for member in tar:
                if not member.isfile():
                    continue
                base = os.path.basename(member.name)
                if "." in base:
                    key, ext = base.split(".", 1)
                else:
                    key, ext = base, "bin"
                if key != current_key:
                    if row:
                        rows.append(row)
                    current_key, row = key, {"__key__": key}
                data = tar.extractfile(member).read()
                if ext in ("txt", "text"):
                    row[ext] = data.decode()
                elif ext == "cls":
                    row[ext] = int(data.decode().strip())
                elif ext == "json":
                    row[ext] = _json.loads(data)
                else:
                    row[ext] = data
        if row:
            rows.append(row)
        yield BlockAccessor.from_rows(rows)


class NumpyDatasource(Datasource):
    def __init__(self, arr: np.ndarray, column: str = "data"):
        self._arr = np.asarray(arr)
        self._col = column

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = len(self._arr)
        parallelism = max(1, min(parallelism, n) if n else 1)
        chunk = (n + parallelism - 1) // parallelism if n else 0
        tasks: List[ReadTask] = []
        for i in range(parallelism):
            part = self._arr[i * chunk:(i + 1) * chunk]
            if len(part) == 0:
                break

            def make(part=part):
                def read():
                    yield BlockAccessor.from_batch({self._col: part})
                return read
            tasks.append(make())
        return tasks


# ---------------------------------------------------------------------------
# Datasinks (reference: `data/datasource/datasink.py` — write plugin model)
# ---------------------------------------------------------------------------

class Datasink:
    """Writes one block per invocation; `Dataset.write_datasink` fans the
    blocks out as tasks when a cluster is up."""

    def prepare(self) -> None:
        """Called once driver-side before any write."""

    def write_block(self, block, idx: int) -> Any:
        raise NotImplementedError


class _FileDatasink(Datasink):
    def __init__(self, path: str):
        self._path = os.fspath(path)

    def prepare(self) -> None:
        os.makedirs(self._path, exist_ok=True)

    def _dest(self, idx: int, ext: str) -> str:
        return os.path.join(self._path, f"block-{idx:06d}.{ext}")


class ParquetDatasink(_FileDatasink):
    def write_block(self, block, idx: int) -> str:
        import pyarrow.parquet as pq

        dest = self._dest(idx, "parquet")
        pq.write_table(block, dest)
        return dest


class CSVDatasink(_FileDatasink):
    def write_block(self, block, idx: int) -> str:
        from pyarrow import csv as pacsv

        dest = self._dest(idx, "csv")
        pacsv.write_csv(block, dest)
        return dest


class JSONDatasink(_FileDatasink):
    def write_block(self, block, idx: int) -> str:
        import json

        dest = self._dest(idx, "json")
        with open(dest, "w") as f:
            for row in BlockAccessor(block).rows():
                f.write(json.dumps(row, default=str) + "\n")
        return dest


# --------------------------------------------------------------------- SQL
class SQLDatasource(Datasource):
    """DB-API 2.0 query source (reference: `datasource/sql_datasource.py`
    — takes a `connection_factory` so any driver works; sqlite3 from the
    stdlib is the tested one).  One read task per `shard` predicate, or a
    single task for the whole query."""

    def __init__(self, sql: str, connection_factory: Callable,
                 shards: Optional[List[str]] = None):
        self._sql = sql
        self._factory = connection_factory
        self._shards = shards

    def get_read_tasks(self, parallelism: int):
        queries = ([self._sql] if not self._shards else
                   [f"{self._sql} {predicate}" for predicate in self._shards])

        def _task(sql=None, factory=self._factory):
            conn = factory()
            try:
                cur = conn.cursor()
                cur.execute(sql)
                cols = [d[0] for d in cur.description]
                rows = [dict(zip(cols, r)) for r in cur.fetchall()]
            finally:
                conn.close()
            yield BlockAccessor.from_rows(rows)

        import functools

        return [functools.partial(_task, sql=q) for q in queries]


class SQLDatasink(Datasink):
    """INSERT blocks into an existing (or auto-created) table through a
    DB-API connection_factory (reference: `datasource/sql_datasink.py`)."""

    def __init__(self, table: str, connection_factory: Callable,
                 create_if_missing: bool = True):
        self._table = table
        self._factory = connection_factory
        self._create = create_if_missing

    @staticmethod
    def _sql_type(v) -> str:
        if isinstance(v, (bool, int, np.integer)):
            return "INTEGER"
        if isinstance(v, (float, np.floating)):
            return "REAL"
        if isinstance(v, (bytes, bytearray)):
            return "BLOB"
        return "TEXT"

    def write_block(self, block, idx: int) -> int:
        rows = list(BlockAccessor(block).rows())
        if not rows:
            return 0
        # Column union over the whole block: heterogeneous rows insert
        # NULL for keys they lack instead of crashing mid-INSERT.
        cols: List[str] = []
        for r in rows:
            for c in r:
                if c not in cols:
                    cols.append(c)
        conn = self._factory()
        try:
            cur = conn.cursor()
            if self._create:
                sample = {c: next(r[c] for r in rows if c in r)
                          for c in cols}
                decls = ", ".join(
                    f"{c} {self._sql_type(sample[c])}" for c in cols)
                cur.execute(
                    f"CREATE TABLE IF NOT EXISTS {self._table} ({decls})")
            ph = ", ".join("?" for _ in cols)
            cur.executemany(
                f"INSERT INTO {self._table} ({', '.join(cols)}) "
                f"VALUES ({ph})",
                [tuple(_sql_value(r.get(c)) for c in cols) for r in rows])
            conn.commit()
        finally:
            conn.close()
        return len(rows)


def _sql_value(v):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist().__repr__()
    return v


# ---------------------------------------------------------- TFRecord sink
# crc32c (Castagnoli, reflected poly 0x82F63B78) + TFRecord masking — the
# write half of the dependency-free framing the reader above parses.
# Table built at import: concurrent write tasks share one worker process
# (thread pool), and a lazy fill would race.
def _crc32c_table():
    table = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
        table.append(c)
    return table


_CRC32C_TABLE = _crc32c_table()

try:  # native implementation when present (large image-bytes records
    # would pay ~1 us/byte in the Python loop)
    from google_crc32c import value as _crc32c_native
except ImportError:  # pragma: no cover - environment-dependent
    _crc32c_native = None


def _crc32c(data: bytes) -> int:
    if _crc32c_native is not None:
        return _crc32c_native(data)
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC32C_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _pb_field(field: int, wire: int, payload: bytes) -> bytes:
    key = _varint(field << 3 | wire)
    if wire == 2:
        return key + _varint(len(payload)) + payload
    return key + payload


def _encode_feature(values) -> bytes:
    if not isinstance(values, (list, tuple, np.ndarray)):
        values = [values]
    values = list(values)
    first = values[0] if values else b""
    if isinstance(first, (bytes, bytearray, str)):
        body = b"".join(
            _pb_field(1, 2, v.encode() if isinstance(v, str) else bytes(v))
            for v in values)
        return _pb_field(1, 2, body)                      # bytes_list
    if isinstance(first, (float, np.floating)):
        packed = _struct.pack(f"<{len(values)}f", *values)
        return _pb_field(2, 2, _pb_field(1, 2, packed))   # float_list
    packed = b"".join(_varint(int(v) & 0xFFFFFFFFFFFFFFFF)
                      for v in values)
    return _pb_field(3, 2, _pb_field(1, 2, packed))       # int64_list


def _encode_tf_example(row: Dict[str, Any]) -> bytes:
    entries = b""
    for key, values in row.items():
        entry = _pb_field(1, 2, key.encode()) + \
            _pb_field(2, 2, _encode_feature(values))
        entries += _pb_field(1, 2, entry)
    return _pb_field(1, 2, entries)  # Example{features = Features{map}}


class TFRecordDatasink(_FileDatasink):
    """tf.train.Example TFRecord writer with valid masked-crc framing
    (reference: `datasource/tfrecords_datasink.py`); round-trips through
    TFRecordDatasource and external TF readers."""

    def write_block(self, block, idx: int) -> str:
        dest = self._dest(idx, "tfrecords")
        with open(dest, "wb") as f:
            for row in BlockAccessor(block).rows():
                payload = _encode_tf_example(row)
                header = _struct.pack("<Q", len(payload))
                f.write(header)
                f.write(_struct.pack("<I", _masked_crc(header)))
                f.write(payload)
                f.write(_struct.pack("<I", _masked_crc(payload)))
        return dest


# ------------------------------------------------------------- misc sinks
class NumpyDatasink(_FileDatasink):
    """One .npz per block, one array per column (reference:
    `datasource/numpy_datasink.py`)."""

    def write_block(self, block, idx: int) -> str:
        dest = self._dest(idx, "npz")
        rows = list(BlockAccessor(block).rows())
        cols: Dict[str, list] = {}
        for r in rows:
            for k, v in r.items():
                cols.setdefault(k, []).append(v)
        arrays = {}
        for k, v in cols.items():
            try:
                arrays[k] = np.asarray(v)
            except ValueError as e:
                # Ragged columns have no dense .npz representation
                # (object arrays need allow_pickle and defeat the point).
                raise ValueError(
                    f"column '{k}' is ragged (rows have differing "
                    f"shapes) and cannot be written as .npz — pad it or "
                    f"use write_parquet/write_json") from e
        np.savez(dest, **arrays)
        return dest


class WebDatasetDatasink(_FileDatasink):
    """One tar shard per block; each row's columns become members named
    `{key}.{column}` (reference: `datasource/webdataset_datasink.py`).
    Round-trips through WebDatasetDatasource."""

    def write_block(self, block, idx: int) -> str:
        import io
        import json as _json
        import tarfile

        dest = self._dest(idx, "tar")
        with tarfile.open(dest, "w") as tar:
            for ri, row in enumerate(BlockAccessor(block).rows()):
                key = row.get("__key__", f"{idx:06d}-{ri:06d}")
                for col, v in row.items():
                    if col == "__key__":
                        continue
                    if isinstance(v, (bytes, bytearray)):
                        data = bytes(v)
                    elif col == "json" or isinstance(v, (dict, list)):
                        data = _json.dumps(v).encode()
                    else:
                        data = str(v).encode()
                    info = tarfile.TarInfo(f"{key}.{col}")
                    info.size = len(data)
                    tar.addfile(info, io.BytesIO(data))
        return dest
