"""Datasources: produce ReadTasks — serializable thunks yielding blocks.

Reference model: `python/ray/data/datasource/datasource.py` (Datasource /
ReadTask).  A read op materializes into N ReadTasks; the streaming executor
runs each as a remote task, so reads scale out and interleave with
downstream transforms.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np

from ray_tpu.data.block import BlockAccessor

# A ReadTask is a zero-arg callable returning an iterable of blocks.
ReadTask = Callable[[], Iterable[Any]]


class Datasource:
    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None


class RangeDatasource(Datasource):
    def __init__(self, n: int):
        self._n = n

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = self._n
        parallelism = max(1, min(parallelism, n) if n else 1)
        chunk = (n + parallelism - 1) // parallelism if n else 0
        tasks: List[ReadTask] = []
        for i in range(parallelism):
            lo, hi = i * chunk, min((i + 1) * chunk, n)
            if lo >= hi:
                break

            def make(lo=lo, hi=hi):
                def read():
                    yield BlockAccessor.from_batch(
                        {"id": np.arange(lo, hi, dtype=np.int64)})
                return read
            tasks.append(make())
        return tasks or [lambda: iter(
            [BlockAccessor.from_batch({"id": np.zeros(0, np.int64)})])]


class ItemsDatasource(Datasource):
    def __init__(self, items: List[Any]):
        self._items = list(items)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        items = self._items
        n = len(items)
        parallelism = max(1, min(parallelism, n) if n else 1)
        chunk = (n + parallelism - 1) // parallelism if n else 0
        tasks: List[ReadTask] = []
        for i in range(parallelism):
            part = items[i * chunk:(i + 1) * chunk]
            if not part:
                break

            def make(part=part):
                def read():
                    yield BlockAccessor.from_rows(part)
                return read
            tasks.append(make())
        return tasks or [lambda: iter([BlockAccessor.from_rows([])])]


class _FileDatasource(Datasource):
    """One read task per file."""

    def __init__(self, paths: Any):
        if isinstance(paths, (str, os.PathLike)):
            paths = [paths]
        expanded: List[str] = []
        for p in paths:
            p = os.fspath(p)
            if os.path.isdir(p):
                expanded.extend(
                    sorted(os.path.join(p, f) for f in os.listdir(p)
                           if not f.startswith(".")))
            else:
                expanded.append(p)
        self._paths = expanded

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for path in self._paths:
            def make(path=path):
                def read():
                    yield from self._read_file(path)
                return read
            tasks.append(make())
        return tasks

    def _read_file(self, path: str):
        raise NotImplementedError


class ParquetDatasource(_FileDatasource):
    def _read_file(self, path: str):
        import pyarrow.parquet as pq

        yield pq.read_table(path)


class CSVDatasource(_FileDatasource):
    def _read_file(self, path: str):
        import pyarrow.csv as pacsv

        yield pacsv.read_csv(path)


class JSONDatasource(_FileDatasource):
    """Newline-delimited JSON (reference: `datasource/json_datasource.py`)."""

    def _read_file(self, path: str):
        import pyarrow.json as pajson

        yield pajson.read_json(path)


class ImageDatasource(_FileDatasource):
    """Image files -> {"image": fixed-shape tensor, "path"} rows
    (reference: `datasource/image_datasource.py`). `size=(H, W)` resizes
    so a directory of mixed sizes yields one uniform tensor column —
    what a TPU input pipeline needs for static shapes."""

    _EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")

    def __init__(self, paths: Any, size=None, mode: str = "RGB"):
        super().__init__(paths)
        self._paths = [p for p in self._paths
                       if p.lower().endswith(self._EXTS)]
        self._size = size
        self._mode = mode

    def _read_file(self, path: str):
        import pyarrow as pa
        from PIL import Image

        img = Image.open(path)
        if self._mode:
            img = img.convert(self._mode)
        if self._size is not None:
            h, w = self._size
            img = img.resize((w, h))
        arr = np.asarray(img)
        if self._size is not None:
            # Dense fixed-shape tensor column (np.stack, not arr[None]:
            # a size-1 view axis gets stride 0, which
            # FixedShapeTensorArray rejects).
            tensor = pa.FixedShapeTensorArray.from_numpy_ndarray(
                np.stack([arr]))
        else:
            # Without a target size images may differ per file; a
            # fixed-shape type per block would fail to concatenate.
            # Nested lists unify across blocks (ragged column).
            tensor = pa.array([arr.tolist()])
        yield pa.table({"image": tensor, "path": pa.array([path])})


class TextDatasource(_FileDatasource):
    def _read_file(self, path: str):
        with open(path, "r", encoding="utf-8") as f:
            lines = [ln.rstrip("\n") for ln in f]
        yield BlockAccessor.from_batch({"text": np.asarray(lines, object)})


class BinaryDatasource(_FileDatasource):
    def _read_file(self, path: str):
        with open(path, "rb") as f:
            data = f.read()
        import pyarrow as pa

        yield pa.table({"bytes": pa.array([data], pa.binary()),
                        "path": pa.array([path])})


class NumpyDatasource(Datasource):
    def __init__(self, arr: np.ndarray, column: str = "data"):
        self._arr = np.asarray(arr)
        self._col = column

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = len(self._arr)
        parallelism = max(1, min(parallelism, n) if n else 1)
        chunk = (n + parallelism - 1) // parallelism if n else 0
        tasks: List[ReadTask] = []
        for i in range(parallelism):
            part = self._arr[i * chunk:(i + 1) * chunk]
            if len(part) == 0:
                break

            def make(part=part):
                def read():
                    yield BlockAccessor.from_batch({self._col: part})
                return read
            tasks.append(make())
        return tasks


# ---------------------------------------------------------------------------
# Datasinks (reference: `data/datasource/datasink.py` — write plugin model)
# ---------------------------------------------------------------------------

class Datasink:
    """Writes one block per invocation; `Dataset.write_datasink` fans the
    blocks out as tasks when a cluster is up."""

    def prepare(self) -> None:
        """Called once driver-side before any write."""

    def write_block(self, block, idx: int) -> Any:
        raise NotImplementedError


class _FileDatasink(Datasink):
    def __init__(self, path: str):
        self._path = os.fspath(path)

    def prepare(self) -> None:
        os.makedirs(self._path, exist_ok=True)

    def _dest(self, idx: int, ext: str) -> str:
        return os.path.join(self._path, f"block-{idx:06d}.{ext}")


class ParquetDatasink(_FileDatasink):
    def write_block(self, block, idx: int) -> str:
        import pyarrow.parquet as pq

        dest = self._dest(idx, "parquet")
        pq.write_table(block, dest)
        return dest


class CSVDatasink(_FileDatasink):
    def write_block(self, block, idx: int) -> str:
        from pyarrow import csv as pacsv

        dest = self._dest(idx, "csv")
        pacsv.write_csv(block, dest)
        return dest


class JSONDatasink(_FileDatasink):
    def write_block(self, block, idx: int) -> str:
        import json

        dest = self._dest(idx, "json")
        with open(dest, "w") as f:
            for row in BlockAccessor(block).rows():
                f.write(json.dumps(row, default=str) + "\n")
        return dest
