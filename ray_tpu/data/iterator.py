"""DataIterator: the consumption-side handle (train-ingestion surface).

Reference model: `python/ray/data/iterator.py` (DataIterator) and
`_internal/execution/streaming_split` — `streaming_split(n)` returns n
iterators sharing one coordinator actor; output blocks are dispatched to
whichever consumer asks next (dynamic balancing), and every epoch re-executes
the plan from the start.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockAccessor


def _rebatch(blocks: Iterator[Any], batch_size: Optional[int],
             batch_format: str, drop_last: bool,
             shuffle_buffer: Optional[int] = None,
             seed: Optional[int] = None) -> Iterator[Any]:
    """Slice a stream of arrow blocks into exact-size batches."""
    import pyarrow as pa

    rng = np.random.default_rng(seed)
    buf: List[Any] = []
    buffered = 0

    def emit(table):
        return BlockAccessor(table).to_batch(batch_format)

    for block in blocks:
        if block.num_rows == 0:
            continue
        if shuffle_buffer:
            idx = rng.permutation(block.num_rows)
            block = block.take(idx)
        if batch_size is None:
            yield emit(block)
            continue
        buf.append(block)
        buffered += block.num_rows
        while buffered >= batch_size:
            table = BlockAccessor.concat(buf)
            out = table.slice(0, batch_size)
            remainder = table.slice(batch_size, table.num_rows - batch_size)
            buf = [remainder] if remainder.num_rows else []
            buffered = remainder.num_rows
            yield emit(out)
    if buffered and batch_size is not None and not drop_last:
        yield emit(BlockAccessor.concat(buf))


class DataIterator:
    """Iterates one split (or the whole dataset) epoch by epoch."""

    def __init__(self, block_source: Callable[[], Iterator[Any]]):
        self._block_source = block_source

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy", drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None
                     ) -> Iterator[Any]:
        yield from _rebatch(self._block_source(), batch_size, batch_format,
                            drop_last, local_shuffle_buffer_size,
                            local_shuffle_seed)

    def iter_jax_batches(self, *, sharding=None, dtypes=None, **kw):
        """Batches as jax arrays placed on device (the TPU-native analog of
        the reference's `iter_torch_batches`, `data/iterator.py:258`).
        `sharding`: optional jax Sharding for the host->device put."""
        return _iter_jax_batches(self.iter_batches(**kw), sharding, dtypes)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for block in self._block_source():
            yield from BlockAccessor(block).rows()

    def materialize(self):
        from ray_tpu.data.dataset import MaterializedDataset

        return MaterializedDataset.from_blocks(list(self._block_source()))


@ray_tpu.remote(num_cpus=0.5)
class _SplitCoordinator:
    """Owns the streaming execution for streaming_split consumers.

    One instance per split() call; consumers pull with `next_block(epoch)`.
    The first request of a new epoch restarts the stream; blocks go to
    whichever consumer asks next (reference: output-bundle dispatch in
    streaming_split's coordinator).
    """

    def __init__(self, ops: List[Any], in_flight: int = 4):
        from ray_tpu.data._internal.stats import DatasetStats

        self._ops = ops
        self._in_flight = in_flight
        self._epoch = -1
        self._stream: Optional[Iterator[Any]] = None
        self._lock = threading.Lock()
        # Aggregate across epochs; each epoch's executor merges into this
        # on completion, and the driver's Dataset.stats() pulls it back.
        self._stats = DatasetStats()

    def next_block(self, epoch: int):
        with self._lock:
            if epoch > self._epoch:
                from ray_tpu.data._internal.streaming_executor import (
                    StreamingExecutor,
                )

                self._epoch = epoch
                self._stream = StreamingExecutor(
                    self._ops, self._in_flight,
                    stats_parent=self._stats).stream_blocks()
            if epoch < self._epoch or self._stream is None:
                return None  # stale epoch: treat as exhausted
            try:
                return next(self._stream)
            except StopIteration:
                self._stream = None
                return None

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return self._stats.to_dict()


class SplitIterator(DataIterator):
    """One consumer of a streaming_split; picklable across workers."""

    def __init__(self, coordinator, split_index: int):
        self._coord = coordinator
        self._index = split_index
        self._epoch = 0
        super().__init__(self._pull_blocks)

    def _pull_blocks(self) -> Iterator[Any]:
        epoch = self._epoch
        self._epoch += 1
        while True:
            block = ray_tpu.get(self._coord.next_block.remote(epoch),
                                timeout=600)
            if block is None:
                return
            yield block

    def stats(self) -> str:
        """Summary of the shared execution behind all splits (the
        coordinator's per-epoch aggregate), rendered like
        ``Dataset.stats()``."""
        from ray_tpu.data._internal.stats import DatasetStats

        d = ray_tpu.get(self._coord.stats.remote(), timeout=30)
        return DatasetStats.from_dict(d).summary(
            f"streaming_split consumer {self._index}")

    def __reduce__(self):
        return (_rebuild_split_iterator, (self._coord, self._index))


def _rebuild_split_iterator(coord, index):
    return SplitIterator(coord, index)


def _iter_jax_batches(batch_iter, sharding=None, dtypes=None):
    import jax
    import jax.numpy as jnp

    for batch in batch_iter:
        out = {}
        for k, v in batch.items():
            arr = jnp.asarray(v) if dtypes is None else jnp.asarray(
                v, dtype=dtypes.get(k))
            if sharding is not None:
                arr = jax.device_put(arr, sharding)
            out[k] = arr
        yield out
