"""BigQuery source/sink over the plugin Datasource/Datasink model.

Reference: `python/ray/data/datasource/bigquery_datasource.py:1` /
`bigquery_datasink.py` (read via the BigQuery client with parallel
result streams; write via load jobs). Redesigned without the
google-cloud-bigquery dependency (not in the image): the REST v2 API
over an injectable transport —

* read (table mode): `tables.get` for row count + schema, then ONE read
  task per `startIndex/maxResults` range of `tabledata.list` — real
  parallel range reads, the REST analogue of the Storage API's streams.
* read (query mode): `jobs.query` (synchronous) + `getQueryResults`
  pagination as a single task.
* write: `insertAll` streaming inserts per block, table auto-created
  from the first block's schema via `tables.insert`.

The default transport authenticates with the GCE metadata-server token
(same pattern as the GCE TPU provider); tests inject a fake transport
(`tests/test_data_bigquery.py`).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.datasource import Datasink, Datasource, ReadTask

BQ_API = "https://bigquery.googleapis.com/bigquery/v2"


def bq_transport(method: str, url: str, body: Optional[dict] = None) -> dict:
    """Default REST transport: the shared GCE metadata-token transport
    (one auth implementation for all Google APIs), 120s for query jobs."""
    from ray_tpu.autoscaler.gcp_tpu_provider import rest_transport

    return rest_transport(method, url, body, timeout=120.0)


def _coerce(value, bq_type: str):
    if value is None:
        return None
    t = (bq_type or "STRING").upper()
    if t in ("INTEGER", "INT64"):
        return int(value)
    if t in ("FLOAT", "FLOAT64", "NUMERIC", "BIGNUMERIC"):
        return float(value)
    if t in ("BOOLEAN", "BOOL"):
        return value in (True, "true", "TRUE", "True", 1, "1")
    return value


def _rows_from_reply(reply: dict, schema_fields: List[dict]) -> List[dict]:
    out = []
    for row in reply.get("rows", []):
        out.append({f["name"]: _coerce(cell.get("v"), f.get("type"))
                    for f, cell in zip(schema_fields, row.get("f", []))})
    return out


class BigQueryDatasource(Datasource):
    """`table="ds.tbl"` for parallel range reads, or `query="SELECT..."`
    for a query-job read."""

    def __init__(self, project: str, *, table: Optional[str] = None,
                 query: Optional[str] = None,
                 transport: Optional[Callable] = None):
        if bool(table) == bool(query):
            raise ValueError(
                "exactly one of table='dataset.table' or query=... is "
                "required")
        self._project = project
        self._table = table
        self._query = query
        self._t = transport or bq_transport

    def _table_url(self) -> str:
        ds, tbl = self._table.split(".", 1)
        return (f"{BQ_API}/projects/{self._project}/datasets/{ds}"
                f"/tables/{tbl}")

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        if self._query is not None:
            return [functools.partial(_run_query_task, self._t,
                                      self._project, self._query)]
        meta = self._t("GET", self._table_url())
        total = int(meta.get("numRows", 0))
        fields = meta.get("schema", {}).get("fields", [])
        parallelism = max(1, min(parallelism, total) if total else 1)
        chunk = (total + parallelism - 1) // parallelism if total else 0
        tasks: List[ReadTask] = []
        for i in range(parallelism):
            start = i * chunk
            count = min(chunk, total - start)
            if count <= 0:
                break
            tasks.append(functools.partial(
                _range_read_task, self._t, self._table_url(), fields,
                start, count))
        return tasks or [functools.partial(
            _range_read_task, self._t, self._table_url(), fields, 0, 0)]

    def estimate_inmemory_data_size(self) -> Optional[int]:
        if self._table is None:
            return None
        try:
            return int(self._t("GET", self._table_url()).get("numBytes", 0))
        except Exception:
            return None


def _range_read_task(transport, table_url: str, fields: List[dict],
                     start: int, count: int):
    rows: List[dict] = []
    fetched = 0
    page_token = None
    while fetched < count or (count == 0 and fetched == 0):
        url = (f"{table_url}/data?startIndex={start + fetched}"
               f"&maxResults={min(10000, count - fetched) or 1}")
        if page_token:
            url += f"&pageToken={page_token}"
        reply = transport("GET", url)
        batch = _rows_from_reply(reply, fields)
        rows.extend(batch)
        fetched += len(batch)
        page_token = reply.get("pageToken")
        if not batch:
            break
    yield BlockAccessor.from_rows(rows)


def _run_query_task(transport, project: str, query: str):
    import time as _time

    reply = transport("POST", f"{BQ_API}/projects/{project}/queries",
                      {"query": query, "useLegacySql": False})
    job_id = reply.get("jobReference", {}).get("jobId")
    # A long query can outlive the synchronous jobs.query window:
    # jobComplete=false means NO rows/schema yet — poll getQueryResults
    # until the job lands instead of yielding a silently empty dataset.
    while not reply.get("jobComplete", True):
        _time.sleep(1.0)
        reply = transport(
            "GET", f"{BQ_API}/projects/{project}/queries/{job_id}")
    fields = reply.get("schema", {}).get("fields", [])
    rows = _rows_from_reply(reply, fields)
    token = reply.get("pageToken")
    while token and job_id:
        page = transport(
            "GET", f"{BQ_API}/projects/{project}/queries/{job_id}"
                   f"?pageToken={token}")
        rows.extend(_rows_from_reply(page, fields))
        token = page.get("pageToken")
    yield BlockAccessor.from_rows(rows)


class BigQueryDatasink(Datasink):
    """Streaming-insert writer; creates the destination table from the
    first block's inferred schema when missing."""

    _BQ_TYPES = {"int": "INTEGER", "float": "FLOAT", "bool": "BOOLEAN",
                 "str": "STRING"}

    def __init__(self, project: str, table: str,
                 transport: Optional[Callable] = None,
                 create_if_missing: bool = True):
        self._project = project
        self._dataset, self._table = table.split(".", 1)
        self._t = transport or bq_transport
        self._create = create_if_missing
        self._ensured = False

    def _table_url(self) -> str:
        return (f"{BQ_API}/projects/{self._project}/datasets/"
                f"{self._dataset}/tables/{self._table}")

    def _infer_schema(self, rows: List[dict]) -> List[dict]:
        fields: List[dict] = []
        seen: Dict[str, str] = {}
        for r in rows:
            for k, v in r.items():
                if k in seen or v is None:
                    continue
                if isinstance(v, bool):
                    t = "BOOLEAN"
                elif isinstance(v, int):
                    t = "INTEGER"
                elif isinstance(v, float):
                    t = "FLOAT"
                else:
                    t = "STRING"
                seen[k] = t
                fields.append({"name": k, "type": t, "mode": "NULLABLE"})
        return fields

    def _ensure_table(self, rows: List[dict]) -> None:
        if self._ensured or not self._create:
            return
        try:
            self._t("GET", self._table_url())
        except Exception:
            try:
                self._t("POST",
                        f"{BQ_API}/projects/{self._project}/datasets/"
                        f"{self._dataset}/tables",
                        {"tableReference": {"projectId": self._project,
                                            "datasetId": self._dataset,
                                            "tableId": self._table},
                         "schema": {"fields": self._infer_schema(rows)}})
            except Exception as e:
                # Parallel write tasks race the auto-create: every loser
                # gets 409/duplicate while the table now exists — that
                # is success, not failure.
                msg = str(e).lower()
                if not ("409" in msg or "duplicate" in msg
                        or "already exists" in msg):
                    raise
        self._ensured = True

    # insertAll hard limits: 10,000 rows / 10 MB per request; 500 rows
    # is the documented recommendation.
    _INSERT_CHUNK = 500

    def write_block(self, block, idx: int) -> int:
        rows = [dict(r) for r in BlockAccessor(block).rows()]
        if not rows:
            return 0
        self._ensure_table(rows)
        for lo in range(0, len(rows), self._INSERT_CHUNK):
            chunk = rows[lo:lo + self._INSERT_CHUNK]
            reply = self._t(
                "POST", f"{self._table_url()}/insertAll",
                {"rows": [{"insertId": f"blk{idx}-{lo + i}", "json": r}
                          for i, r in enumerate(chunk)]})
            errors = reply.get("insertErrors")
            if errors:
                raise RuntimeError(
                    f"BigQuery insertAll rejected rows: {errors[:3]}")
        return len(rows)
