"""Concurrent operator scheduler — every runnable operator in flight at
once, under per-operator resource budgets and pluggable backpressure.

Reference model: `python/ray/data/_internal/execution/streaming_executor.py
:55` (the scheduling loop over operator states), `resource_manager.py`
(per-op budgets carved from the cluster total) and
`backpressure_policy/` (ConcurrencyCapBackpressurePolicy,
StreamingOutputBackpressurePolicy). This is the push-mode core the
pull-based StreamingExecutor delegates to when the plan has more than
one remote stage: while a source read task is still producing, map tasks
for already-produced blocks are simultaneously in flight and actor-pool
stages are transforming earlier blocks — no stage barrier anywhere.

Blocks travel BETWEEN operators as ObjectRefs (task output straight into
the next task's argument), so intermediate data never materializes in
the driver; only final outputs are fetched, in order.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, Iterator, List, Optional

import ray_tpu


def _set_backpressure_gauges(stage: str, inflight: int, queued: int) -> None:
    """Live scheduler state on /metrics (best-effort): in-flight tasks
    and input-queue depth per op — a deep queue with idle in-flight
    downstream pinpoints the bottleneck stage."""
    try:
        from ray_tpu.observability.data import data_metrics

        m = data_metrics()
        m.inflight.set(inflight, tags={"stage": stage})
        m.queued.set(queued, tags={"stage": stage})
    except Exception:
        pass


# --------------------------------------------------------------- policies

class BackpressurePolicy:
    """Decides whether an operator may launch one more task now."""

    def can_launch(self, op: "_OpState", execr: "ConcurrentExecutor"
                   ) -> bool:
        raise NotImplementedError


class ConcurrencyCapPolicy(BackpressurePolicy):
    """Cap each op's in-flight tasks at its resource budget (reference:
    ConcurrencyCapBackpressurePolicy). The budget is the *base*: the
    executor's BackpressureTuner scales it up or down from the live
    ``rtpu_data_inflight_tasks`` / ``rtpu_data_queued_blocks`` gauges."""

    def can_launch(self, op, execr):
        cap = op.budget_slots
        tuner = getattr(execr, "tuner", None)
        if tuner is not None:
            cap = tuner.cap(op.name, cap)
        return len(op.pending) < cap


class OutputBufferPolicy(BackpressurePolicy):
    """Bound how far an op may run ahead of its consumer (reference:
    StreamingOutputBackpressurePolicy): stop launching when the
    downstream input queue is already deep — a slow consumer throttles
    the whole chain instead of buffering unboundedly.

    The FINAL op is exempt: its output buffer holds refs awaiting
    in-order emission, and one straggling low sequence number can park
    many later refs there — counting them would block launching exactly
    the straggler's task, a permanent deadlock. The consumer's generator
    suspension + the concurrency cap already bound the final stage."""

    def __init__(self, max_queued_outputs: int = 16):
        self.max_queued = max_queued_outputs

    def can_launch(self, op, execr):
        nxt = execr.op_after(op)
        if nxt is None:
            return True
        limit = self.max_queued
        tuner = getattr(execr, "tuner", None)
        if tuner is not None:
            limit = tuner.limit(op.name, limit)
        return len(nxt.inputs) + len(op.pending) < limit


DEFAULT_POLICIES = (ConcurrencyCapPolicy(), OutputBufferPolicy())


# --------------------------------------------------------------- op states

from ray_tpu.data._internal.remote_ops import (  # noqa: E402
    MapWorker, run_map, run_read,
)


class _OpState:
    """Scheduler-side state for one physical operator."""

    def __init__(self, name: str, budget_slots: int):
        self.name = name
        self.budget_slots = budget_slots
        self.inputs: deque = deque()          # (seq, payload)
        self.pending: Dict[Any, int] = {}     # ref -> seq
        self.exhausted = False                # no more inputs will arrive

    def done(self) -> bool:
        return self.exhausted and not self.inputs and not self.pending

    # launch one task from the input queue; returns the new ref or None
    def launch(self, execr: "ConcurrentExecutor"):
        raise NotImplementedError


class _SourceState(_OpState):
    def __init__(self, read_tasks: List[Any], fused, budget_slots: int,
                 name: str = "source"):
        super().__init__(name, budget_slots)
        for i, t in enumerate(read_tasks):
            self.inputs.append((i, t))
        self._fused = fused
        self.exhausted = True  # the input list is fully known up front

    def launch(self, execr):
        seq, task = self.inputs.popleft()
        ref = run_read.remote(task, self._fused)
        self.pending[ref] = seq
        return ref


class _InputRefsState(_OpState):
    """Source stage over pre-existing block refs — nothing to launch; the
    refs ARE the outputs (they flow straight to the next op)."""

    def __init__(self, refs: List[Any]):
        super().__init__("input", 0)
        self.refs = refs


class _TaskMapState(_OpState):
    def __init__(self, fused_fn, budget_slots: int, index: int,
                 name: Optional[str] = None):
        super().__init__(name or f"map:{index}", budget_slots)
        self._fn = fused_fn

    def launch(self, execr):
        seq, payload = self.inputs.popleft()
        # payload may be an ObjectRef (upstream task output) — passed as
        # an arg so the block list moves store-to-store, never via the
        # driver.
        ref = run_map.remote(payload, self._fn)
        self.pending[ref] = seq
        return ref


class _ActorMapState(_OpState):
    """Stateful-UDF stage on a pool of actors (reference:
    actor_pool_map_operator)."""

    def __init__(self, op, budget_slots: int, index: int,
                 name: Optional[str] = None):
        from ray_tpu.data._internal.plan import MapBatches

        super().__init__(name or f"actor_map:{index}",
                         min(budget_slots, (op.concurrency or 2) * 2))
        self._op = MapBatches(op.fn, batch_size=op.batch_size,
                              batch_format=op.batch_format,
                              fn_kwargs=op.fn_kwargs)
        self._size = op.concurrency or 2
        self._opts = {"num_cpus": op.num_cpus}
        if op.num_tpus:
            self._opts["num_tpus"] = op.num_tpus
        self._pool: Optional[List[Any]] = None
        self._rr = 0

    def _ensure_pool(self):
        if self._pool is None:
            self._pool = [
                MapWorker.options(**self._opts).remote(self._op)
                for _ in range(self._size)]
        return self._pool

    def launch(self, execr):
        pool = self._ensure_pool()
        seq, payload = self.inputs.popleft()
        actor = pool[self._rr % len(pool)]
        self._rr += 1
        ref = actor.apply_list.remote(payload)
        self.pending[ref] = seq
        return ref

    def close(self):
        for a in self._pool or []:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


# ---------------------------------------------------------------- executor

class ConcurrentExecutor:
    """Run Source -> Map* chains with every op concurrently in flight.

    Outputs are yielded strictly in source order (ordering is part of the
    Dataset contract — limit/zip depend on it); completion may happen in
    any order, the reorder buffer lives only at the very end.
    """

    def __init__(self, source: _OpState, map_states: List[_OpState],
                 policies=DEFAULT_POLICIES, stats=None, tuner=None):
        self.ops: List[_OpState] = [source] + list(map_states)
        self.policies = list(policies)
        self.outputs: Dict[int, Any] = {}  # seq -> final ref
        self._next_emit = 0
        self._total: Optional[int] = None
        # Submission counts / backpressure samples land here; the owning
        # StreamingExecutor finalizes (spans + counter export).
        self.stats = stats
        if tuner is None:
            from ray_tpu.data._internal.backpressure import (
                BackpressureTuner,
            )

            tuner = BackpressureTuner()
        self.tuner = tuner

    def op_after(self, op: _OpState) -> Optional[_OpState]:
        i = self.ops.index(op)
        return self.ops[i + 1] if i + 1 < len(self.ops) else None

    @staticmethod
    def budgets(n_ops: int) -> int:
        """Per-op concurrency budget: an equal share of cluster CPUs,
        floor 2 so every op always makes progress (reference:
        resource_manager.py's per-op resource split)."""
        try:
            total = int(ray_tpu.cluster_resources().get("CPU", 8))
        except Exception:
            total = 8
        return max(2, total // max(n_ops, 1))

    # ------------------------------------------------------------ running
    def stream(self) -> Iterator[Any]:
        src = self.ops[0]
        if isinstance(src, _InputRefsState):
            nxt = self.ops[1] if len(self.ops) > 1 else None
            if nxt is None:
                for i, r in enumerate(src.refs):
                    self.outputs[i] = r
            else:
                for i, r in enumerate(src.refs):
                    nxt.inputs.append((i, r))
                nxt.exhausted = True
            self._total = len(src.refs)
            self.ops = self.ops[1:]
        else:
            self._total = len(src.inputs)

        try:
            while True:
                self._launch_all()
                yield from self._drain_ready_outputs()
                if self._next_emit >= (self._total or 0) and not any(
                        op.pending or op.inputs for op in self.ops):
                    break
                self._wait_any()
            yield from self._drain_ready_outputs(final=True)
        finally:
            for op in self.ops:
                _set_backpressure_gauges(op.name, 0, 0)
                if isinstance(op, _ActorMapState):
                    op.close()

    def _launch_all(self) -> None:
        if self.tuner is not None:
            self.tuner.maybe_evaluate()
        for op in self.ops:
            launched = 0
            while op.inputs and all(p.can_launch(op, self)
                                    for p in self.policies):
                op.launch(self)
                launched += 1
            if self.stats is not None and launched:
                st = self.stats.stage(op.name)
                if isinstance(op, _ActorMapState):
                    st.actor_tasks_submitted += launched
                else:
                    st.tasks_submitted += launched
            _set_backpressure_gauges(op.name, len(op.pending),
                                     len(op.inputs))

    def _wait_any(self) -> None:
        refs = [r for op in self.ops for r in op.pending]
        if not refs:
            # Nothing in flight but also nothing launchable (policies
            # blocking, or inputs waiting on the consumer): don't spin.
            import time as _time

            _time.sleep(0.02)
            return
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=5.0,
                                fetch_local=False)
        for ref in ready:
            self._complete(ref)

    def _complete(self, ref) -> None:
        for i, op in enumerate(self.ops):
            if ref in op.pending:
                seq = op.pending.pop(ref)
                nxt = self.ops[i + 1] if i + 1 < len(self.ops) else None
                if nxt is None:
                    self.outputs[seq] = ref
                else:
                    nxt.inputs.append((seq, ref))
                    if op.done():
                        nxt.exhausted = True
                return

    def _drain_ready_outputs(self, final: bool = False) -> Iterator[Any]:
        while self._next_emit in self.outputs:
            ref = self.outputs.pop(self._next_emit)
            self._next_emit += 1
            blocks = (ray_tpu.get(ref, timeout=600)
                      if not isinstance(ref, list) else ref)
            blocks = blocks if isinstance(blocks, list) else [blocks]
            yield from blocks


def build_pipeline(first, fused, map_stages: List[Any],
                   policies=DEFAULT_POLICIES,
                   stats=None) -> Optional[ConcurrentExecutor]:
    """Build a ConcurrentExecutor for a Source + map-stage prefix, or
    None when the source kind can't feed it. ``map_stages`` entries are
    either fused-op lists or actor MapBatches ops (split_stages output)."""
    from ray_tpu.data._internal import plan as plan_mod

    n_ops = 1 + len(map_stages)
    slots = ConcurrentExecutor.budgets(n_ops)
    if isinstance(first, plan_mod.Read):
        tasks = first.datasource.get_read_tasks(
            first.parallelism if first.parallelism > 0 else 8)
        source: _OpState = _SourceState(tasks, fused, slots,
                                        name=plan_mod.stage_name(first))
    elif isinstance(first, plan_mod.InputBlocks):
        from ray_tpu import ObjectRef

        refs = []
        for r in first.refs:
            if isinstance(r, ObjectRef):
                refs.append(r)
            else:
                refs.append(ray_tpu.put(r if isinstance(r, list) else [r]))
        if fused is not None:
            # Run the fused stage as the first map over the refs.
            map_stages = [None] + list(map_stages)
        source = _InputRefsState(refs)
    else:
        return None

    states: List[_OpState] = []
    for idx, stage in enumerate(map_stages):
        if stage is None:  # the fused fn carried over from the source
            states.append(_TaskMapState(fused, slots, idx, name="fused_map"))
        elif isinstance(stage, list):
            states.append(_TaskMapState(
                plan_mod.compile_block_fn(stage), slots, idx,
                name=plan_mod.stage_name(stage)))
        else:  # actor MapBatches
            states.append(_ActorMapState(stage, slots, idx,
                                         name=plan_mod.stage_name(stage)))
    return ConcurrentExecutor(source, states, policies, stats=stats)
