"""All-to-all ops: distributed sort and hash groupby over tasks.

Reference: `python/ray/data/_internal/planner/exchange/` (sort/shuffle
task schedulers): map tasks partition each input block (by sampled range
boundaries for sort, by key hash for groupby), reduce tasks combine one
partition each. Partitioned chunks stay in the object store between the
map and reduce stages (map tasks return one ref per partition; reduce
tasks take refs), so the dataset never round-trips through the driver.
"""

from __future__ import annotations

import hashlib
from typing import Any, List

import numpy as np

import ray_tpu


def _sort_table(table, key: str, descending: bool):
    import pyarrow.compute as pc

    order = "descending" if descending else "ascending"
    idx = pc.sort_indices(table, sort_keys=[(key, order)])
    return table.take(idx)


def _partition_ids(col: np.ndarray, boundaries: List[Any],
                   descending: bool) -> np.ndarray:
    """Partition index per row. `boundaries` are sorted in output order
    (ascending or descending). No negation tricks — works for strings and
    unsigned ints too."""
    if descending:
        # partition p = #{boundaries >= value}; count via the ascending
        # view of the boundaries.
        asc = np.asarray(boundaries[::-1])
        return len(boundaries) - np.searchsorted(asc, col, side="left")
    return np.searchsorted(np.asarray(boundaries), col, side="right")


@ray_tpu.remote
def _range_partition_block(table, key: str, boundaries: List[Any],
                           descending: bool):
    """Split one block into len(boundaries)+1 range chunks (unsorted —
    the reduce stage sorts)."""
    import pyarrow as pa

    idx = _partition_ids(np.asarray(table.column(key)), boundaries,
                         descending)
    return [table.filter(pa.array(idx == p))
            for p in range(len(boundaries) + 1)]


@ray_tpu.remote
def _merge_sorted(*chunks, key: str, descending: bool):
    import pyarrow as pa

    non_empty = [c for c in chunks if c.num_rows]
    if not non_empty:
        return pa.table({})
    return _sort_table(pa.concat_tables(non_empty, promote_options="default"),
                       key, descending)


def distributed_sort(blocks: List[Any], key: str,
                     descending: bool = False) -> List[Any]:
    """blocks: arrow tables (values, not refs). Returns sorted blocks."""
    blocks = [b for b in blocks if b.num_rows]
    if not blocks:
        return []
    if len(blocks) == 1:
        return [_sort_table(blocks[0], key, descending)]

    # Sample range boundaries from the key distribution.
    samples = np.concatenate([
        np.random.default_rng(0).choice(
            np.asarray(b.column(key)), size=min(100, b.num_rows),
            replace=False)
        for b in blocks
    ])
    samples = np.sort(samples)
    if descending:
        samples = samples[::-1]
    n_parts = len(blocks)
    boundaries = [samples[int(len(samples) * (i + 1) / n_parts)]
                  for i in range(n_parts - 1)]

    # Map stage: one ref per (block, partition) — chunks stay in plasma.
    part_refs = [
        _range_partition_block.options(num_returns=n_parts).remote(
            b, key, boundaries, descending)
        for b in blocks
    ]
    merged = [
        _merge_sorted.remote(*[refs[p] for refs in part_refs],
                             key=key, descending=descending)
        for p in range(n_parts)
    ]
    return [b for b in ray_tpu.get(merged, timeout=600) if b.num_rows]


def _stable_hash(value: Any) -> int:
    """Process-independent hash (builtin hash() is randomized per worker
    for str/bytes, which would scatter one group across partitions)."""
    data = value if isinstance(value, bytes) else repr(value).encode()
    return int.from_bytes(
        hashlib.blake2b(data, digest_size=8).digest(), "big")


@ray_tpu.remote
def _hash_partition_block(table, key: str, n_parts: int):
    import pyarrow as pa

    col = np.asarray(table.column(key))
    hashes = np.fromiter((_stable_hash(x) % n_parts for x in col.tolist()),
                         dtype=np.int64, count=len(col))
    return [table.filter(pa.array(hashes == p)) for p in range(n_parts)]


@ray_tpu.remote
def _aggregate_partition(*chunks, key: str, aggs: List[tuple]):
    """aggs: [(column, fn)] with fn in {count,sum,mean,min,max}."""
    import pyarrow as pa

    non_empty = [c for c in chunks if c.num_rows]
    if not non_empty:
        return pa.table({})
    table = pa.concat_tables(non_empty, promote_options="default")
    return table.group_by(key).aggregate(list(aggs))


def distributed_groupby(blocks: List[Any], key: str,
                        aggs: List[tuple]) -> List[Any]:
    blocks = [b for b in blocks if b.num_rows]
    if not blocks:
        return []
    n_parts = max(1, min(len(blocks), 16))
    part_refs = [
        _hash_partition_block.options(num_returns=n_parts).remote(
            b, key, n_parts)
        for b in blocks
    ]
    agg_refs = [
        _aggregate_partition.remote(*[refs[p] for refs in part_refs],
                                    key=key, aggs=aggs)
        for p in range(n_parts)
    ]
    return [b for b in ray_tpu.get(agg_refs, timeout=600) if b.num_rows]


# ------------------------------------------------------------------ local
# Single-process fallbacks (no cluster up) sharing one concat path.

def _concat(blocks):
    import pyarrow as pa

    non_empty = [b for b in blocks if b.num_rows]
    if not non_empty:
        return None
    return pa.concat_tables(non_empty, promote_options="default")


def local_sort(blocks: List[Any], key: str, descending: bool) -> List[Any]:
    table = _concat(blocks)
    return [] if table is None else [_sort_table(table, key, descending)]


def local_groupby(blocks: List[Any], key: str,
                  aggs: List[tuple]) -> List[Any]:
    table = _concat(blocks)
    if table is None:
        return []
    return [table.group_by(key).aggregate(list(aggs))]


# ---------------------------------------------------------------------------
# All-to-all random shuffle / repartition over object refs (reference:
# `execution/operators/all_to_all_operator.py` + shuffle task scheduler):
# map tasks split each input into N chunks, reduce tasks combine chunk p of
# every input. Block data moves store-to-store; the driver only holds refs.
# ---------------------------------------------------------------------------

@ray_tpu.remote
def _shuffle_map(blocks: List[Any], n: int, seed: int):
    """One read-task output (list of tables) -> n random-assigned chunks."""
    import pyarrow as pa

    if not isinstance(blocks, list):
        blocks = [blocks]
    tables = [t for t in blocks if t.num_rows]
    if not tables:
        empty = pa.table({})
        return [empty] * n if n > 1 else [empty]
    table = pa.concat_tables(tables, promote_options="default")
    rng = np.random.default_rng(seed)
    assign = rng.integers(0, n, table.num_rows)
    return [table.filter(pa.array(assign == p)) for p in range(n)]


@ray_tpu.remote
def _shuffle_reduce(seed: int, *chunks):
    import pyarrow as pa

    non_empty = [c for c in chunks if c.num_rows]
    if not non_empty:
        return pa.table({})
    table = pa.concat_tables(non_empty, promote_options="default")
    perm = np.random.default_rng(seed).permutation(table.num_rows)
    return table.take(perm)


def distributed_random_shuffle(list_refs: List[Any], n_out: int,
                               seed) -> List[Any]:
    """list_refs: refs of block-lists. Returns n_out refs of output blocks."""
    base = 0 if seed is None else int(seed)
    n_out = max(1, n_out)
    parts = []
    for i, ref in enumerate(list_refs):
        out = _shuffle_map.options(num_returns=n_out).remote(
            ref, n_out, base + 7919 * (i + 1))
        parts.append(out if isinstance(out, list) else [out])
    return [
        _shuffle_reduce.remote(base + 104729 * (p + 1),
                               *[parts[i][p] for i in range(len(parts))])
        for p in range(n_out)
    ]


@ray_tpu.remote
def _split_chunks(blocks: List[Any], n: int):
    """Split one input's rows into n contiguous, evenly-sized chunks."""
    import pyarrow as pa

    if not isinstance(blocks, list):
        blocks = [blocks]
    tables = [t for t in blocks if t.num_rows]
    if not tables:
        empty = pa.table({})
        return [empty] * n if n > 1 else [empty]
    table = pa.concat_tables(tables, promote_options="default")
    total = table.num_rows
    per, extra = divmod(total, n)
    out, lo = [], 0
    for p in range(n):
        size = per + (1 if p < extra else 0)
        out.append(table.slice(lo, size))
        lo += size
    return out


@ray_tpu.remote
def _concat_chunks(*chunks):
    import pyarrow as pa

    non_empty = [c for c in chunks if c.num_rows]
    if not non_empty:
        return pa.table({})
    return pa.concat_tables(non_empty, promote_options="default")


def distributed_repartition(list_refs: List[Any], n: int) -> List[Any]:
    """Approximately even n-way repartition over refs (each input
    contributes one slice to every output)."""
    n = max(1, n)
    parts = []
    for ref in list_refs:
        out = _split_chunks.options(num_returns=n).remote(ref, n)
        parts.append(out if isinstance(out, list) else [out])
    return [_concat_chunks.remote(*[parts[i][p]
                                    for i in range(len(parts))])
            for p in range(n)]
