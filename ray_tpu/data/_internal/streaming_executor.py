"""Pull-based streaming executor.

Reference model: `python/ray/data/_internal/execution/streaming_executor.py`
— operators execute as waves of remote tasks with a bounded in-flight
window; downstream consumption pulls blocks through the pipeline, so a slow
consumer backpressures the reads instead of materializing the dataset.

TPU-first framing: the ops plane (this executor) runs on CPU workers via
ray_tpu tasks; it exists to keep the accelerator-side input queue full.
When no cluster is initialized the executor degrades to inline execution —
same plan, local thunks — so Datasets work in plain unit tests and inside
already-remote workers without nested clusters.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Iterator, List, Optional

import ray_tpu
from ray_tpu._private.worker import global_worker_or_none
from ray_tpu.data._internal import plan as plan_mod
from ray_tpu.data._internal.stats import DatasetStats
from ray_tpu.data.block import BlockAccessor

DEFAULT_IN_FLIGHT = 8


def _cluster_available() -> bool:
    return global_worker_or_none() is not None


def _set_inflight(stage: str, n: int) -> None:
    """Backpressure gauge: remote tasks submitted but not yet consumed
    for one stage (best-effort — telemetry never fails the pipeline)."""
    try:
        from ray_tpu.observability.data import data_metrics

        data_metrics().inflight.set(n, tags={"stage": stage})
    except Exception:
        pass


from ray_tpu.data._internal.remote_ops import (  # noqa: E402
    MapWorker, run_read,
)

# Back-compat alias: the scheduler primitives live in remote_ops so the
# pull- and push-mode executors share one definition.
_run_read = run_read


@ray_tpu.remote
def _as_block_list(item, fused) -> List[Any]:
    blocks = item if isinstance(item, list) else [item]
    if fused is not None:
        blocks = [fused(b) for b in blocks]
    return blocks


@ray_tpu.remote
def _gather_slices(parts: List[Any]) -> List[Any]:
    """parts: list of (blocks_list, lo, hi) row-ranges to concat."""
    out = []
    for blocks, lo, hi in parts:
        acc = 0
        for b in blocks:
            n = b.num_rows
            s, e = max(lo - acc, 0), min(hi - acc, n)
            if s < e:
                out.append(b.slice(s, e - s))
            acc += n
    return [BlockAccessor.concat(out)] if out else []


class StreamingExecutor:
    """Executes a logical op list, yielding blocks (arrow tables)."""

    def __init__(self, ops: List[Any], in_flight: int = DEFAULT_IN_FLIGHT,
                 stats_parent: Optional[DatasetStats] = None):
        self._ops = ops
        self._in_flight = in_flight
        # Run-local stats; folded into stats_parent (the Dataset's or
        # coordinator's aggregate) when the stream closes.
        self.stats = DatasetStats()
        self._stats_parent = stats_parent
        # Closes the loop on the backpressure gauges: in-flight windows
        # below start from the static config but get scaled by the tuner
        # reading rtpu_data_* back through the MetricsHub.
        from ray_tpu.data._internal.backpressure import BackpressureTuner

        self._tuner = BackpressureTuner()

    # ------------------------------------------------------------- public
    def stream_blocks(self) -> Iterator[Any]:
        """Yield output blocks with streaming/backpressure semantics."""
        stages = plan_mod.split_stages(self._ops)
        try:
            yield from self._run_stages(stages)
        finally:
            # Reached on exhaustion AND on early close (limit / abandoned
            # consumer): spans + metrics always flush.
            self.stats.finalize()
            if self._stats_parent is not None:
                self._stats_parent.merge(self.stats)

    # ------------------------------------------------------------ internal
    def _run_stages(self, stages: List[Any]) -> Iterator[Any]:
        if not stages:
            return
        first, rest = stages[0], stages[1:]
        src_name = plan_mod.stage_name(first)

        # Fuse a map-stage directly into the source wave.
        fused: Optional[Callable] = None
        if rest and isinstance(rest[0], list):
            fused = plan_mod.compile_block_fn(rest[0])
            src_name = f"{src_name}->{plan_mod.stage_name(rest[0])}"
            rest = rest[1:]

        # All-to-all barrier directly after the (fused) source: run it as
        # a distributed exchange over refs — block bytes move store to
        # store, never through this process (so a shuffle larger than
        # driver memory works).
        if (rest and _cluster_available()
                and isinstance(rest[0], (plan_mod.RandomShuffle,
                                         plan_mod.Repartition))):
            refs = self._source_refs(first, fused, src_name)
            if refs is not None:
                from ray_tpu.data._internal import shuffle as shuffle_mod

                barrier = rest[0]
                if isinstance(barrier, plan_mod.RandomShuffle):
                    out_refs = shuffle_mod.distributed_random_shuffle(
                        refs, n_out=max(len(refs), 1), seed=barrier.seed)
                else:
                    out_refs = shuffle_mod.distributed_repartition(
                        refs, barrier.n)
                barrier_out = self.stats.wrap_output(
                    plan_mod.stage_name(barrier),
                    self._stream_input(out_refs, None))
                yield from self._apply_rest(barrier_out, rest[1:])
                return

        # Concurrent pipelined prefix: when MORE remote stages follow the
        # (fused) source — an actor-pool map, further fused maps — run
        # the whole prefix under the concurrent operator scheduler so
        # stage N+1 transforms earlier blocks while stage N is still
        # producing (reference: streaming_executor.py:55's operator
        # scheduling loop). The tail (limits, barriers, zip/union) stays
        # on the pull path.
        prefix: List[Any] = []
        tail = list(rest)
        while tail and (isinstance(tail[0], list)
                        or (isinstance(tail[0], plan_mod.MapBatches)
                            and tail[0].uses_actors)):
            prefix.append(tail.pop(0))
        if prefix and _cluster_available() and isinstance(
                first, (plan_mod.Read, plan_mod.InputBlocks)):
            from ray_tpu.data._internal.concurrent_executor import (
                build_pipeline,
            )

            pipe = build_pipeline(first, fused, prefix, stats=self.stats)
            if pipe is not None:
                yield from self._apply_rest(pipe.stream(), tail)
                return

        if isinstance(first, plan_mod.Read):
            tasks = first.datasource.get_read_tasks(
                first.parallelism if first.parallelism > 0 else 8)
            source = self._stream_tasks(tasks, fused, src_name)
        elif isinstance(first, plan_mod.InputBlocks):
            source = self._stream_input(first.refs, fused)
        else:
            raise TypeError(f"bad source op {first}")

        yield from self._apply_rest(
            self.stats.wrap_output(src_name, source), rest)

    def _source_refs(self, first, fused,
                     name: Optional[str] = None) -> Optional[List[Any]]:
        """Materialize the source stage as refs of block-lists (no driver
        fetch). None when the source kind doesn't support it."""
        from ray_tpu import ObjectRef

        st = self.stats.stage(name) if name else None
        if isinstance(first, plan_mod.Read):
            tasks = first.datasource.get_read_tasks(
                first.parallelism if first.parallelism > 0 else 8)
            if st is not None:
                st.tasks_submitted += len(tasks)
            return [_run_read.remote(t, fused) for t in tasks]
        if isinstance(first, plan_mod.InputBlocks):
            refs = []
            for r in first.refs:
                if isinstance(r, ObjectRef) and fused is None:
                    refs.append(r)
                elif isinstance(r, ObjectRef):
                    if st is not None:
                        st.tasks_submitted += 1
                    refs.append(_as_block_list.remote(r, fused))
                else:
                    blocks = r if isinstance(r, list) else [r]
                    if fused is not None:
                        blocks = [fused(b) for b in blocks]
                    refs.append(ray_tpu.put(blocks))
            return refs
        return None

    def _apply_rest(self, source: Iterator[Any], stages: List[Any]
                    ) -> Iterator[Any]:
        if not stages:
            yield from source
            return
        head, rest = stages[0], stages[1:]
        name = plan_mod.stage_name(head)
        # Input side of the timing shim: time this stage spends pulling
        # `source` is its blocked-on-input time.
        inner = self.stats.wrap_input(name, source)
        if isinstance(head, list):
            fn = plan_mod.compile_block_fn(head)
            produced = (fn(b) for b in inner)
        elif isinstance(head, plan_mod.Limit):
            def limited():
                seen = 0
                for b in inner:
                    take = min(b.num_rows, head.n - seen)
                    if take < b.num_rows:
                        b = b.slice(0, take)
                    seen += take
                    yield b
                    if seen >= head.n:
                        return  # early exit stops upstream submission
            produced = limited()
        elif isinstance(head, plan_mod.MapBatches) and head.uses_actors:
            produced = self._actor_pool_map(inner, head, name)
        elif isinstance(head, plan_mod.Repartition):
            produced = self._repartition_lazy(inner, head.n)
        elif isinstance(head, plan_mod.RandomShuffle):
            produced = self._shuffle_lazy(inner, head.seed)
        elif isinstance(head, plan_mod.Union):
            def unioned():
                yield from inner
                for branch in head.branches:
                    yield from StreamingExecutor(
                        branch, self._in_flight,
                        stats_parent=self.stats).stream_blocks()
            produced = unioned()
        elif isinstance(head, plan_mod.Zip):
            produced = self._zip(inner, head.other)
        else:
            raise TypeError(f"unsupported stage {head}")
        yield from self._apply_rest(
            self.stats.wrap_output(name, produced), rest)

    def _repartition_lazy(self, source: Iterator[Any], n: int
                          ) -> Iterator[Any]:
        yield from self._repartition(list(source), n)

    def _shuffle_lazy(self, source: Iterator[Any], seed: Optional[int]
                      ) -> Iterator[Any]:
        yield from self._shuffle(list(source), seed)

    def _zip(self, source: Iterator[Any], other_ops: List[Any]
             ) -> Iterator[Any]:
        """Column-wise arrow merge with block realignment — no per-row
        Python dict churn; only block slicing happens driver-side."""
        import pyarrow as pa

        right_iter = StreamingExecutor(
            other_ops, self._in_flight,
            stats_parent=self.stats).stream_blocks()
        rbuf: list = []      # right arrow tables not yet consumed
        rrows = 0

        def take(n: int) -> "pa.Table":
            nonlocal rrows
            if n == 0:
                return pa.table({})
            while rrows < n:
                nxt = next(right_iter, None)
                if nxt is None:
                    raise ValueError(
                        "zip(): right dataset has fewer rows than left")
                t = BlockAccessor(nxt).table
                rbuf.append(t)
                rrows += t.num_rows
            parts, need = [], n
            while need:
                t = rbuf[0]
                if t.num_rows <= need:
                    parts.append(rbuf.pop(0))
                    need -= t.num_rows
                else:
                    parts.append(t.slice(0, need))
                    rbuf[0] = t.slice(need)
                    need = 0
            rrows -= n
            return parts[0] if len(parts) == 1 else pa.concat_tables(parts)

        for block in source:
            lt = BlockAccessor(block).table
            if lt.num_rows == 0:
                continue  # nothing to pair; avoids schema-less output
            rt = take(lt.num_rows)
            merged = lt
            for name, col in zip(rt.column_names, rt.columns):
                out = f"{name}_1" if name in lt.column_names else name
                merged = merged.append_column(out, col)
            yield merged
        # Compare remaining ROWS, not block presence: trailing zero-row
        # blocks (e.g. from a filter) are not a length mismatch. Bounded
        # drain — stop at the first nonzero block rather than executing
        # the whole remaining right pipeline for an exact count.
        leftover = rrows
        while leftover == 0:
            nxt = next(right_iter, None)
            if nxt is None:
                break
            leftover += BlockAccessor(nxt).num_rows()
        if leftover:
            raise ValueError(
                "zip(): right dataset has more rows than left")

    # -------------------------------------------------------- actor pool
    def _actor_pool_map(self, source: Iterator[Any], op,
                        name: Optional[str] = None) -> Iterator[Any]:
        """Stateful-UDF stage on a pool of actors (reference:
        `execution/operators/actor_pool_map_operator.py`): the class
        constructs once per actor; blocks pipeline through the pool with
        a bounded in-flight window per actor."""
        from ray_tpu.data._internal.plan import MapBatches, compile_block_fn

        inline_op = MapBatches(op.fn, batch_size=op.batch_size,
                               batch_format=op.batch_format,
                               fn_kwargs=op.fn_kwargs)
        if not _cluster_available():
            fn = compile_block_fn([inline_op])
            for b in source:
                yield fn(b)
            return

        name = name or plan_mod.stage_name(op)
        st = self.stats.stage(name)
        size = op.concurrency or 2
        opts = {"num_cpus": op.num_cpus}
        if op.num_tpus:
            opts["num_tpus"] = op.num_tpus
        pool = [MapWorker.options(**opts).remote(inline_op)
                for _ in range(size)]
        try:
            pending: deque = deque()   # (ref) in submission order
            rr = 0
            per_actor_window = 2
            for block in source:
                self._tuner.maybe_evaluate()
                while len(pending) >= self._tuner.cap(
                        name, size * per_actor_window):
                    yield ray_tpu.get(pending.popleft(), timeout=600)
                    _set_inflight(name, len(pending))
                pending.append(pool[rr % size].apply.remote(block))
                st.actor_tasks_submitted += 1
                rr += 1
                _set_inflight(name, len(pending))
            while pending:
                yield ray_tpu.get(pending.popleft(), timeout=600)
                _set_inflight(name, len(pending))
        finally:
            _set_inflight(name, 0)
            for a in pool:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass

    # -------------------------------------------------------------- waves
    def _stream_tasks(self, read_tasks: List[Any], fused,
                      name: Optional[str] = None) -> Iterator[Any]:
        if not _cluster_available():
            for t in read_tasks:
                for block in t():
                    yield fused(block) if fused is not None else block
            return
        st = self.stats.stage(name) if name else None
        # Byte-budget backpressure (reference:
        # `execution/backpressure_policy/streaming_output_backpressure_policy`):
        # the in-flight window adapts to observed task-output size so a
        # wide dataset doesn't buffer gigabytes while a narrow one still
        # pipelines deeply.
        target_bytes = 256 * 1024 * 1024
        ema_task_bytes: Optional[float] = None
        pending: deque = deque()
        it = iter(read_tasks)
        exhausted = False
        try:
            while pending or not exhausted:
                if ema_task_bytes:
                    budget = max(2, int(target_bytes / max(ema_task_bytes, 1)))
                else:
                    budget = self._in_flight
                window = min(max(2, budget), 4 * self._in_flight)
                # Gauge-driven scaling on top of the byte budget: the
                # tuner widens the window when reads are pinned at the
                # cap with nothing queued, narrows it when the consumer
                # falls behind.
                self._tuner.maybe_evaluate()
                window = self._tuner.cap(name or "source", window)
                while not exhausted and len(pending) < window:
                    try:
                        t = next(it)
                    except StopIteration:
                        exhausted = True
                        break
                    pending.append(_run_read.remote(t, fused))
                    if st is not None:
                        st.tasks_submitted += 1
                if name:
                    _set_inflight(name, len(pending))
                if pending:
                    blocks = ray_tpu.get(pending.popleft(), timeout=600)
                    size = sum(BlockAccessor(b).size_bytes() for b in blocks)
                    ema_task_bytes = (size if ema_task_bytes is None
                                      else 0.7 * ema_task_bytes + 0.3 * size)
                    yield from blocks
        finally:
            if name:
                _set_inflight(name, 0)

    def _stream_input(self, refs: List[Any], fused) -> Iterator[Any]:
        from ray_tpu import ObjectRef

        for r in refs:
            block = (ray_tpu.get(r, timeout=600)
                     if isinstance(r, ObjectRef) else r)
            blocks = block if isinstance(block, list) else [block]
            for b in blocks:
                yield fused(b) if fused is not None else b

    # ------------------------------------------------------------ barriers
    def _repartition(self, blocks: List[Any], n: int) -> Iterator[Any]:
        total = sum(b.num_rows for b in blocks)
        per = total // n if n else 0
        extras = total - per * n
        lo = 0
        for i in range(n):
            size = per + (1 if i < extras else 0)
            hi = lo + size
            out = []
            acc = 0
            for b in blocks:
                bn = b.num_rows
                s, e = max(lo - acc, 0), min(hi - acc, bn)
                if s < e:
                    out.append(b.slice(s, e - s))
                acc += bn
            yield (BlockAccessor.concat(out) if out
                   else BlockAccessor.from_rows([]))
            lo = hi

    def _shuffle(self, blocks: List[Any], seed: Optional[int]
                 ) -> Iterator[Any]:
        """Global random shuffle: concatenate -> permute -> re-split.

        Driver-side materialization (the reference's all-to-all shuffle is
        a scale-out version of the same barrier; at this executor's scale
        the permutation happens in one process)."""
        import numpy as np

        if not blocks:
            return
        table = BlockAccessor.concat(blocks)
        rng = np.random.default_rng(seed)
        perm = rng.permutation(table.num_rows)
        table = table.take(perm)
        nb = max(len(blocks), 1)
        per = (table.num_rows + nb - 1) // nb or 1
        for lo in range(0, table.num_rows, per):
            yield table.slice(lo, min(per, table.num_rows - lo))
