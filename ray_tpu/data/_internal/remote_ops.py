"""Remote execution primitives shared by the pull-based
StreamingExecutor and the push-based ConcurrentExecutor — one definition
of how a read task / fused map / actor map runs remotely, so fixes land
in both schedulers."""

from __future__ import annotations

from typing import Any, List

import ray_tpu


@ray_tpu.remote
def run_read(read_task, fused_fn) -> List[Any]:
    blocks = []
    for block in read_task():
        if fused_fn is not None:
            block = fused_fn(block)
        blocks.append(block)
    return blocks


@ray_tpu.remote
def run_map(blocks, fused_fn) -> List[Any]:
    # Inputs may be a single block (e.g. refs from
    # MaterializedDataset.from_blocks) or a block list (task outputs).
    blocks = blocks if isinstance(blocks, list) else [blocks]
    return [fused_fn(b) for b in blocks]


@ray_tpu.remote
class MapWorker:
    """Stateful-UDF pool actor (reference: actor_pool_map_operator)."""

    def __init__(self, op_):
        from ray_tpu.data._internal.plan import compile_block_fn

        self._fn = compile_block_fn([op_])

    def apply(self, block):
        return self._fn(block)

    def apply_list(self, blocks):
        blocks = blocks if isinstance(blocks, list) else [blocks]
        return [self._fn(b) for b in blocks]
