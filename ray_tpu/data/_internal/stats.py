"""Per-stage Dataset execution statistics.

Reference model: `python/ray/data/_internal/stats.py` (DatasetStats /
StageStatsSummary) — every executed stage records wall time, block/row/
byte counts and where the time went (blocked on input vs executing), and
``Dataset.stats()`` renders the per-operator summary that is the primary
tool for finding input-pipeline bottlenecks.

Mechanics: the executors wrap each stage's input and output iterators in
counting/timing shims (`wrap_input` / `wrap_output`).  For a stage S:

- ``blocked_on_input_s``: time S spent inside ``next()`` on its
  upstream iterator (waiting for input);
- ``wall_time_s``: time spent inside ``next()`` on S's *output* —
  i.e. everything S did to produce blocks, including its input waits,
  but excluding time the downstream consumer sat on the block;
- ``executing_s``: the difference — S's own compute/submission time.

On stream completion (or early close, e.g. ``limit``) the run emits one
``data.stage:<name>`` span per stage into the task-event ring buffer
(so pipelines render in ``ray_tpu.timeline()`` next to train steps) and
bumps the ``data_*`` counters exported on ``/metrics`` with the
``rtpu_`` prefix.  Multiple runs/consumers of one Dataset merge into a
single aggregate (``DatasetStats.merge``), which is what
``streaming_split`` coordinators ship back to the driver as dicts.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, Iterator, List, Optional


_COUNT_FIELDS = (
    "wall_time_s", "blocked_on_input_s",
    "blocks_in", "rows_in", "bytes_in",
    "blocks_out", "rows_out", "bytes_out",
    "tasks_submitted", "actor_tasks_submitted",
)


@dataclasses.dataclass
class StageStats:
    """Counters for one physical stage of one (or more, merged) runs."""

    name: str
    wall_time_s: float = 0.0
    blocked_on_input_s: float = 0.0
    blocks_in: int = 0
    rows_in: int = 0
    bytes_in: int = 0
    blocks_out: int = 0
    rows_out: int = 0
    bytes_out: int = 0
    tasks_submitted: int = 0
    actor_tasks_submitted: int = 0
    start_ts: float = 0.0  # wall clock of the first output pull

    @property
    def executing_s(self) -> float:
        return max(self.wall_time_s - self.blocked_on_input_s, 0.0)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "StageStats":
        return StageStats(**{k: d[k] for k in d
                             if k in StageStats.__dataclass_fields__})


def _block_meta(block) -> tuple:
    """(rows, bytes) of a block; defensive — stats must never break a
    pipeline over an exotic block type."""
    try:
        from ray_tpu.data.block import BlockAccessor

        acc = BlockAccessor(block)
        return acc.num_rows(), acc.size_bytes()
    except Exception:
        return 0, 0


class DatasetStats:
    """Ordered per-stage stats for one execution (or a merged aggregate).

    Thread-safe for the merge/stage paths (streaming_split consumers pull
    concurrently); the per-block hot path mutates plain attributes of a
    StageStats owned by a single generator chain.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.stages: Dict[str, StageStats] = {}  # insertion-ordered
        self.runs = 0
        self.start_ts: float = 0.0
        self.end_ts: float = 0.0
        self._finalized = False

    # Locks don't pickle; stats objects travel driver <-> coordinator.
    def __getstate__(self):
        d = dict(self.__dict__)
        d.pop("_lock", None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._lock = threading.Lock()

    # ------------------------------------------------------------ recording
    def stage(self, name: str) -> StageStats:
        with self._lock:
            st = self.stages.get(name)
            if st is None:
                st = self.stages[name] = StageStats(name)
            return st

    def wrap_input(self, name: str, source: Iterator[Any]) -> Iterator[Any]:
        """Count a stage's input stream; time inside ``next(source)`` is
        the stage's blocked-on-input time."""
        st = self.stage(name)
        it = iter(source)

        def gen():
            while True:
                t0 = time.perf_counter()
                try:
                    block = next(it)
                except StopIteration:
                    st.blocked_on_input_s += time.perf_counter() - t0
                    return
                st.blocked_on_input_s += time.perf_counter() - t0
                rows, nbytes = _block_meta(block)
                st.blocks_in += 1
                st.rows_in += rows
                st.bytes_in += nbytes
                yield block

        return gen()

    def wrap_output(self, name: str, source: Iterator[Any]) -> Iterator[Any]:
        """Count a stage's output stream; time inside ``next(source)`` is
        the stage's wall time (its input waits included, its consumer's
        time excluded)."""
        st = self.stage(name)
        it = iter(source)

        def gen():
            if not self.start_ts:
                self.start_ts = time.time()
            while True:
                if not st.start_ts:
                    st.start_ts = time.time()
                t0 = time.perf_counter()
                try:
                    block = next(it)
                except StopIteration:
                    st.wall_time_s += time.perf_counter() - t0
                    return
                st.wall_time_s += time.perf_counter() - t0
                rows, nbytes = _block_meta(block)
                st.blocks_out += 1
                st.rows_out += rows
                st.bytes_out += nbytes
                yield block

        return gen()

    # ------------------------------------------------------------- closing
    def finalize(self) -> None:
        """Emit this run's spans + metrics exactly once (also reached on
        early close, e.g. a ``limit`` stopping the stream)."""
        with self._lock:
            if self._finalized:
                return
            self._finalized = True
            self.end_ts = time.time()
            self.runs = max(self.runs, 1)
        try:
            self._emit()
        except Exception:
            pass  # telemetry must never fail the pipeline

    def _emit(self) -> None:
        from ray_tpu.observability.data import data_metrics
        from ray_tpu.util import tracing

        m = data_metrics()
        for st in self.stages.values():
            tags = {"stage": st.name}
            m.blocks_out.inc(st.blocks_out, tags=tags)
            m.rows_out.inc(st.rows_out, tags=tags)
            m.bytes_out.inc(st.bytes_out, tags=tags)
            m.stage_wall.inc(st.wall_time_s, tags=tags)
            m.stage_blocked.inc(st.blocked_on_input_s, tags=tags)
            if st.tasks_submitted:
                m.tasks.inc(st.tasks_submitted,
                            tags={"stage": st.name, "kind": "task"})
            if st.actor_tasks_submitted:
                m.tasks.inc(st.actor_tasks_submitted,
                            tags={"stage": st.name, "kind": "actor"})
            tracing.record_span(
                f"data.stage:{st.name}",
                st.start_ts or self.start_ts, st.wall_time_s,
                attrs={"blocks_out": st.blocks_out, "rows_out": st.rows_out,
                       "bytes_out": st.bytes_out,
                       "blocked_s": round(st.blocked_on_input_s, 6),
                       "executing_s": round(st.executing_s, 6)})

    # ----------------------------------------------------------- aggregation
    def merge(self, other: "DatasetStats") -> None:
        """Fold another run/consumer into this aggregate (field-wise sums;
        used by Dataset across runs and by streaming_split across the
        coordinator's epochs)."""
        with self._lock:
            for st in other.stages.values():
                mine = self.stages.get(st.name)
                if mine is None:
                    mine = self.stages[st.name] = StageStats(st.name)
                for f in _COUNT_FIELDS:
                    setattr(mine, f, getattr(mine, f) + getattr(st, f))
                if st.start_ts and (not mine.start_ts
                                    or st.start_ts < mine.start_ts):
                    mine.start_ts = st.start_ts
            self.runs += max(other.runs, 1)
            if other.start_ts and (not self.start_ts
                                   or other.start_ts < self.start_ts):
                self.start_ts = other.start_ts
            self.end_ts = max(self.end_ts, other.end_ts)

    def to_dict(self) -> Dict[str, Any]:
        return {"runs": self.runs, "start_ts": self.start_ts,
                "end_ts": self.end_ts,
                "stages": [st.to_dict() for st in self.stages.values()]}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "DatasetStats":
        out = DatasetStats()
        out.runs = d.get("runs", 1)
        out.start_ts = d.get("start_ts", 0.0)
        out.end_ts = d.get("end_ts", 0.0)
        for sd in d.get("stages", []):
            st = StageStats.from_dict(sd)
            out.stages[st.name] = st
        return out

    # ------------------------------------------------------------- rendering
    def summary(self, plan_desc: str = "") -> str:
        if not self.stages:
            return (f"{plan_desc}\nNo execution stats recorded yet — "
                    f"consume the dataset first (count/take/iter_batches).")
        lines: List[str] = []
        if plan_desc:
            lines.append(plan_desc)
        lines.append(f"Execution stats over {max(self.runs, 1)} run(s):")
        total_wall = 0.0
        for i, st in enumerate(self.stages.values()):
            total_wall += st.wall_time_s
            lines.append(
                f"Stage {i} {st.name}: {st.blocks_out} blocks produced "
                f"in {st.wall_time_s:.2f}s")
            lines.append(
                f"* Rows: {st.rows_in} in / {st.rows_out} out; bytes: "
                f"{_fmt_bytes(st.bytes_in)} in / "
                f"{_fmt_bytes(st.bytes_out)} out")
            lines.append(
                f"* Tasks submitted: {st.tasks_submitted} task(s), "
                f"{st.actor_tasks_submitted} actor task(s)")
            lines.append(
                f"* Time blocked on input: {st.blocked_on_input_s:.2f}s; "
                f"executing: {st.executing_s:.2f}s")
        span = (self.end_ts - self.start_ts
                if self.end_ts and self.start_ts else total_wall)
        lines.append(f"Total wall time: {max(span, 0.0):.2f}s "
                     f"(sum of stage time: {total_wall:.2f}s)")
        return "\n".join(lines)


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}TiB"
