"""Logical plan: lazy operator DAG with map fusion.

Reference model: `python/ray/data/_internal/logical_plan.py` + operator
fusion in `_internal/planner/`.  Consecutive row/batch transforms fuse into
one per-block function, so a `read -> map_batches -> filter` pipeline runs
as a single wave of remote tasks.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional

from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.datasource import Datasource


@dataclasses.dataclass
class Op:
    """Base logical operator."""


@dataclasses.dataclass
class Read(Op):
    datasource: Datasource
    parallelism: int = -1


@dataclasses.dataclass
class InputBlocks(Op):
    """Pre-materialized blocks (object refs or inline tables)."""
    refs: List[Any] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class MapBatches(Op):
    fn: Callable
    batch_size: Optional[int] = None
    batch_format: str = "numpy"
    fn_kwargs: dict = dataclasses.field(default_factory=dict)
    # Stateful-UDF execution (reference: actor_pool_map_operator.py):
    # a class UDF + concurrency>0 runs on a pool of actors, one instance
    # per actor (model loaded once), instead of stateless tasks.
    concurrency: Optional[int] = None
    num_cpus: float = 1
    num_tpus: float = 0

    @property
    def uses_actors(self) -> bool:
        return self.concurrency is not None or isinstance(self.fn, type)


@dataclasses.dataclass
class MapRows(Op):
    fn: Callable


@dataclasses.dataclass
class FlatMap(Op):
    fn: Callable


@dataclasses.dataclass
class Filter(Op):
    fn: Callable


@dataclasses.dataclass
class Limit(Op):
    n: int = 0


@dataclasses.dataclass
class Repartition(Op):
    n: int = 1


@dataclasses.dataclass
class RandomShuffle(Op):
    seed: Optional[int] = None


MAP_LIKE = (MapBatches, MapRows, FlatMap, Filter)


def compile_block_fn(ops: List[Op]) -> Callable[[Any], Any]:
    """Fuse a run of map-like ops into one block -> block function."""

    def apply(block):
        import pyarrow as pa

        for op in ops:
            acc = BlockAccessor(block)
            if isinstance(op, MapBatches):
                fn = op.fn() if isinstance(op.fn, type) else op.fn
                outs = []
                n = acc.num_rows()
                bs = op.batch_size or n or 1
                for lo in range(0, max(n, 1), bs):
                    if n == 0:
                        break
                    sub = BlockAccessor(acc.slice(lo, min(lo + bs, n)))
                    out = fn(sub.to_batch(op.batch_format),
                             **op.fn_kwargs)
                    outs.append(BlockAccessor.from_batch(out))
                block = (BlockAccessor.concat([o for o in outs])
                         if outs else pa.table({}))
            elif isinstance(op, MapRows):
                block = BlockAccessor.from_rows(
                    [op.fn(dict(r)) for r in acc.rows()])
            elif isinstance(op, FlatMap):
                rows = []
                for r in acc.rows():
                    rows.extend(op.fn(dict(r)))
                block = BlockAccessor.from_rows(rows)
            elif isinstance(op, Filter):
                block = BlockAccessor.from_rows(
                    [dict(r) for r in acc.rows() if op.fn(dict(r))])
            else:
                raise TypeError(f"not a map-like op: {op}")
        return block

    return apply


def op_name(op: Op) -> str:
    """Snake_case display name of one logical op (stats/metrics label)."""
    name = type(op).__name__
    out = [name[0].lower()]
    for ch in name[1:]:
        if ch.isupper():
            out.append("_")
        out.append(ch.lower())
    return "".join(out)


def stage_name(stage: Any) -> str:
    """Display name of one split_stages() entry: a source/barrier op, an
    actor-pool MapBatches, or a fused run of map-like ops (joined with
    ``->`` the way the planner fused them)."""
    if isinstance(stage, list):
        return "->".join(op_name(op) for op in stage) or "noop"
    if isinstance(stage, MapBatches) and stage.uses_actors:
        return "actor_" + op_name(stage)
    return op_name(stage)


def split_stages(ops: List[Op]) -> List[Any]:
    """Group the op list into stages: each stage is either a source op, a
    barrier op, an actor-pool MapBatches, or a fused list of map-like
    ops."""
    stages: List[Any] = []
    run: List[Op] = []
    for op in ops:
        if isinstance(op, MapBatches) and op.uses_actors:
            if run:
                stages.append(list(run))
                run = []
            stages.append(op)
        elif isinstance(op, MAP_LIKE):
            run.append(op)
        else:
            if run:
                stages.append(list(run))
                run = []
            stages.append(op)
    if run:
        stages.append(list(run))
    return stages


@dataclasses.dataclass
class Union(Op):
    """Concatenate other datasets' streams after this one (reference:
    `Dataset.union`)."""
    branches: List[List[Op]] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Zip(Op):
    """Column-wise zip with another dataset, row-aligned (reference:
    `Dataset.zip`; right-hand duplicate column names get an `_1`
    suffix)."""
    other: List[Op] = dataclasses.field(default_factory=list)
