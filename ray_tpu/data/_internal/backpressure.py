"""Metrics-driven backpressure tuning for the Dataset executors.

The executors publish their scheduler state as gauges
(``rtpu_data_inflight_tasks{stage}`` / ``rtpu_data_queued_blocks
{stage}``, set by the launch loops); this module closes the loop by
reading those same gauges back — through the MetricsHub, with the
zero-RPC :func:`~ray_tpu.util.metrics.local_summary` fetch, since the
gauges live in the executor's own process — and scaling the static
inflight/queued limits:

- deep queued output (consumer behind) -> step the producing stage's
  limits DOWN, so blocks stop piling into the object store;
- in-flight pinned at the cap with an empty output queue (pipeline
  starving) -> step the limits UP, bounded by
  ``data_backpressure_max_scale``;
- neither -> decay back toward the configured base.

Steps are discrete (×1.5 per level) and pass the shared
:class:`~ray_tpu.observability.control.Hysteresis` gate, so one noisy
sample never moves a limit and oscillating load cannot flap it. Every
granted adjustment is a recorded control decision
(``rtpu_ctrl_decisions_total{controller="data_backpressure"}`` + a
``BACKPRESSURE_ADJUST`` cluster event carrying the gauge readings).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from ray_tpu.observability.control import Hysteresis, record_decision

_STEP = 1.5


class BackpressureTuner:
    """Per-stage limit multipliers driven by the backpressure gauges.

    Pull-based: the executors call :meth:`cap` / :meth:`limit` from
    their launch loops (cheap — dict lookups) and
    :meth:`maybe_evaluate` once per loop iteration, which re-reads the
    gauges at most every ``data_backpressure_interval_s`` seconds.
    """

    def __init__(self, hub=None, interval_s: Optional[float] = None,
                 max_scale: Optional[float] = None,
                 queue_limit: int = 16):
        from ray_tpu._private.config import GlobalConfig

        if interval_s is None:
            interval_s = GlobalConfig.data_backpressure_interval_s
        if max_scale is None:
            max_scale = GlobalConfig.data_backpressure_max_scale
        self.interval_s = float(interval_s)
        self.enabled = self.interval_s > 0
        self.queue_limit = queue_limit
        self.max_level = 0
        while _STEP ** (self.max_level + 1) <= max(max_scale, 1.0):
            self.max_level += 1
        if hub is None and self.enabled:
            from ray_tpu.util.metrics import MetricsHub, local_summary

            hub = MetricsHub(fetch=local_summary,
                             min_refresh_s=self.interval_s / 2)
        self.hub = hub
        self._levels: Dict[str, int] = {}
        self._gates: Dict[str, Hysteresis] = {}
        self._cap_bases: Dict[str, int] = {}
        self._last_eval = 0.0

    def _scaled(self, stage: str, base: int) -> int:
        lvl = self._levels.get(stage, 0)
        return max(1, int(round(base * (_STEP ** lvl))))

    def cap(self, stage: str, base: int) -> int:
        """Tuned in-flight task cap for ``stage`` (records ``base`` so
        evaluation knows what "pinned at the cap" means)."""
        if not self.enabled:
            return base
        self._cap_bases[stage] = base
        return self._scaled(stage, base)

    def limit(self, stage: str, base: int) -> int:
        """Tuned queued-output limit for ``stage`` (same level as the
        cap: a throttled stage runs fewer tasks AND buffers less)."""
        if not self.enabled:
            return base
        return self._scaled(stage, base)

    def maybe_evaluate(self, now: Optional[float] = None) -> None:
        if not self.enabled or self.hub is None:
            return
        now = time.time() if now is None else now
        if now - self._last_eval < self.interval_s:
            return
        self._last_eval = now
        self.hub.refresh(prefixes=["data_"])
        for stage, base in list(self._cap_bases.items()):
            inflight_s = self.hub.query("data_inflight_tasks",
                                        labels={"stage": stage})
            queued_s = self.hub.query("data_queued_blocks",
                                      labels={"stage": stage})
            if not inflight_s and not queued_s:
                continue  # gauges not wired for this stage yet
            if (inflight_s and inflight_s.stale()) or \
                    (queued_s and queued_s.stale()):
                continue  # hold: a frozen gauge is not a low gauge
            inflight = int(inflight_s.latest or 0)
            queued = int(queued_s.latest or 0)
            lvl = self._levels.get(stage, 0)
            cap = self._scaled(stage, base)
            desired = lvl
            if queued >= max(2, self._scaled(stage, self.queue_limit) // 2):
                desired = max(lvl - 1, -self.max_level)
            elif inflight >= cap and queued <= 1:
                desired = min(lvl + 1, self.max_level)
            elif lvl != 0 and queued <= 1 and inflight < max(1, cap // 2):
                desired = lvl + (1 if lvl < 0 else -1)
            gate = self._gates.setdefault(stage, Hysteresis(
                self.interval_s, self.interval_s, self.interval_s))
            granted = gate.propose(lvl, desired, now)
            if granted == lvl:
                continue
            self._levels[stage] = granted
            new_cap = self._scaled(stage, base)
            reading = {"stage": stage, "inflight": inflight,
                       "queued": queued, "cap_from": cap,
                       "cap_to": new_cap, "level": granted}
            try:
                record_decision(
                    "data_backpressure",
                    "raise_limits" if granted > lvl else "lower_limits",
                    "queued-block depth vs in-flight cap", reading,
                    event_type="BACKPRESSURE_ADJUST",
                    message=f"stage {stage}: inflight cap {cap} -> "
                            f"{new_cap} (inflight={inflight}, "
                            f"queued={queued})")
            except Exception:
                pass
