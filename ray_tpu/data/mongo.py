"""MongoDB source/sink over the plugin Datasource/Datasink model.

Reference: `python/ray/data/datasource/mongo_datasource.py:1` (reads via
pymongo/pymongoarrow with per-partition match pipelines; writes via
insert_many). Redesigned for this image (no pymongo baked in): all server
traffic goes through an injectable `client_factory` returning a minimal
client surface —

    client[db][coll].count_documents(filter) -> int
    client[db][coll].find(filter, projection) -> cursor (iterable of
        dicts) supporting .sort(key, dir).skip(n).limit(n)
    client[db][coll].aggregate(pipeline) -> iterable of dicts
    client[db][coll].insert_many(docs) -> result
    client.close()

The default factory imports pymongo lazily and raises a clear error when
it is unavailable; tests inject an in-memory fake
(`tests/test_data_mongo.py`). Parallel reads partition with
sort(_id)+skip/limit per task — deterministic ranges without server-side
splitVector, the REST-less analogue of the reference's partitioned match
pipelines.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.datasource import Datasink, Datasource, ReadTask


def default_client_factory(uri: str):
    """Lazy pymongo import (not baked into this image — callers on a real
    deployment bring their own driver or inject a factory)."""
    try:
        import pymongo
    except ImportError as e:
        raise ImportError(
            "read_mongo/write_mongo need the 'pymongo' driver or an "
            "injected client_factory(uri); pymongo is not installed in "
            "this environment") from e
    return pymongo.MongoClient(uri)


def _clean(doc: Dict[str, Any], drop_id: bool) -> Dict[str, Any]:
    if drop_id and "_id" in doc:
        doc = {k: v for k, v in doc.items() if k != "_id"}
    return doc


class MongoDatasource(Datasource):
    """Parallel collection reads: each read task scans one
    sort(_id)+skip/limit range (or runs the user's aggregation pipeline
    as a single task, matching the reference's pipeline mode)."""

    def __init__(self, uri: str, database: str, collection: str, *,
                 filter: Optional[dict] = None,
                 pipeline: Optional[List[dict]] = None,
                 projection: Optional[dict] = None,
                 drop_id: bool = True,
                 client_factory: Optional[Callable] = None):
        self._uri = uri
        self._db = database
        self._coll = collection
        self._filter = filter or {}
        self._pipeline = pipeline
        self._projection = projection
        self._drop_id = drop_id
        self._factory = client_factory or default_client_factory

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        if self._pipeline is not None:
            return [functools.partial(
                _pipeline_read_task, self._factory, self._uri, self._db,
                self._coll, self._pipeline, self._drop_id)]
        # Partition by _id BOUNDARY VALUES, not per-task skip/limit:
        # boundary scans are index-seekable ($gte/$lt on _id), total
        # server work stays O(N), and ranges stay stable under
        # concurrent inserts (a skip-based split shifts every range when
        # a low-_id doc lands mid-read). Planning pays P-1 index-only
        # skip probes once. User filters on `_id` are conjoined with the
        # range predicate via $and.
        client = self._factory(self._uri)
        try:
            coll = client[self._db][self._coll]
            total = int(coll.count_documents(self._filter))
            parallelism = max(1, min(parallelism, total) if total else 1)
            chunk = (total + parallelism - 1) // parallelism if total else 0
            boundaries = []
            for i in range(1, parallelism):
                probe = list(coll.find(self._filter, {"_id": 1})
                             .sort("_id", 1).skip(i * chunk).limit(1))
                if not probe:
                    break
                boundaries.append(probe[0]["_id"])
        finally:
            client.close()
        edges = [None] + boundaries + [None]
        tasks: List[ReadTask] = []
        for lo, hi in zip(edges[:-1], edges[1:]):
            tasks.append(functools.partial(
                _range_read_task, self._factory, self._uri, self._db,
                self._coll, self._filter, self._projection, lo, hi,
                self._drop_id))
        return tasks


def _range_read_task(factory, uri, db, coll, filt, projection, lo, hi,
                     drop_id):
    """One _id range scan: [lo, hi) with None = unbounded."""
    id_range = {}
    if lo is not None:
        id_range["$gte"] = lo
    if hi is not None:
        id_range["$lt"] = hi
    if not id_range:
        query = dict(filt or {})
    elif filt and "_id" in filt:
        # Never clobber a user _id condition — conjoin with the range.
        query = {"$and": [dict(filt), {"_id": id_range}]}
    else:
        query = {**(filt or {}), "_id": id_range}
    client = factory(uri)
    try:
        rows = [_clean(dict(d), drop_id)
                for d in client[db][coll].find(query, projection)
                .sort("_id", 1)]
    finally:
        client.close()
    yield BlockAccessor.from_rows(rows)


def _pipeline_read_task(factory, uri, db, coll, pipeline, drop_id):
    client = factory(uri)
    try:
        rows = [_clean(dict(d), drop_id)
                for d in client[db][coll].aggregate(pipeline)]
    finally:
        client.close()
    yield BlockAccessor.from_rows(rows)


class MongoDatasink(Datasink):
    """insert_many per block (reference: `mongo_datasink.py` write via
    pymongo bulk inserts)."""

    _INSERT_CHUNK = 1000

    def __init__(self, uri: str, database: str, collection: str,
                 client_factory: Optional[Callable] = None):
        self._uri = uri
        self._db = database
        self._coll = collection
        self._factory = client_factory or default_client_factory

    def write_block(self, block, idx: int) -> int:
        rows = [dict(r) for r in BlockAccessor(block).rows()]
        if not rows:
            return 0
        client = self._factory(self._uri)
        try:
            for lo in range(0, len(rows), self._INSERT_CHUNK):
                client[self._db][self._coll].insert_many(
                    rows[lo:lo + self._INSERT_CHUNK])
        finally:
            client.close()
        return len(rows)
