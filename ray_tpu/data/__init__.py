"""ray_tpu.data — lazy streaming datasets for accelerator ingestion.

Public API parity (reference `python/ray/data/__init__.py`): read_* creation
functions, Dataset transforms (map/map_batches/filter/flat_map/limit/
repartition/random_shuffle), consumption (iter_batches/take/count), and
`streaming_split` train ingestion.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from ray_tpu.data._internal import plan as _plan
from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.dataset import Dataset, MaterializedDataset
from ray_tpu.data.datasource import (
    BinaryDatasource, CSVDatasink, CSVDatasource, Datasink, Datasource,
    ImageDatasource, ItemsDatasource, JSONDatasink, JSONDatasource,
    NumpyDatasource, ParquetDatasink, ParquetDatasource, RangeDatasource,
    TextDatasource,
)
from ray_tpu.data.iterator import DataIterator


def _read(ds: Datasource, parallelism: int = -1) -> Dataset:
    return Dataset([_plan.Read(ds, parallelism)])


def range(n: int, *, override_num_blocks: int = -1, **_ignored) -> Dataset:  # noqa: A001
    return _read(RangeDatasource(n),
                 override_num_blocks if override_num_blocks > 0 else 8)


def from_items(items: List[Any], *, override_num_blocks: int = -1,
               **_ignored) -> Dataset:
    return _read(ItemsDatasource(items),
                 override_num_blocks if override_num_blocks > 0 else 8)


def from_numpy(arr: "np.ndarray", column: str = "data",
               *, override_num_blocks: int = -1) -> Dataset:
    return _read(NumpyDatasource(arr, column),
                 override_num_blocks if override_num_blocks > 0 else 8)


def from_pandas(df) -> Dataset:
    import pyarrow as pa

    return MaterializedDataset.from_blocks(
        [pa.Table.from_pandas(df, preserve_index=False)])


def from_arrow(table) -> Dataset:
    return MaterializedDataset.from_blocks([table])


def read_parquet(paths, **_ignored) -> Dataset:
    return _read(ParquetDatasource(paths))


def read_csv(paths, **_ignored) -> Dataset:
    return _read(CSVDatasource(paths))


def read_json(paths, **_ignored) -> Dataset:
    return _read(JSONDatasource(paths))


def read_text(paths, **_ignored) -> Dataset:
    return _read(TextDatasource(paths))


def read_datasource(datasource: Datasource, *, parallelism: int = -1,
                    **_ignored) -> Dataset:
    """Custom Datasource ingest (reference: `ray.data.read_datasource`)."""
    return _read(datasource, parallelism)


def read_tfrecords(paths, **_ignored) -> Dataset:
    """TFRecord/tf.train.Example ingest (no tensorflow dependency)."""
    from ray_tpu.data.datasource import TFRecordDatasource

    return _read(TFRecordDatasource(paths))


def read_webdataset(paths, **_ignored) -> Dataset:
    """WebDataset tar shards: one row per sample key."""
    from ray_tpu.data.datasource import WebDatasetDatasource

    return _read(WebDatasetDatasource(paths))


def read_sql(sql: str, connection_factory, *, shards=None,
             **_ignored) -> Dataset:
    """DB-API query ingest (reference: `ray.data.read_sql`); optional
    `shards` = list of SQL predicates appended per read task."""
    from ray_tpu.data.datasource import SQLDatasource

    return _read(SQLDatasource(sql, connection_factory, shards=shards))


def read_bigquery(project: str, *, table: Optional[str] = None,
                  query: Optional[str] = None, transport=None,
                  **_ignored) -> Dataset:
    """BigQuery ingest (reference: `ray.data.read_bigquery`):
    `table="dataset.table"` reads in parallel row ranges, `query=...`
    runs a query job. `transport` overrides the REST transport (tests)."""
    from ray_tpu.data.bigquery import BigQueryDatasource

    return _read(BigQueryDatasource(project, table=table, query=query,
                                    transport=transport))


def read_mongo(uri: str, database: str, collection: str, *,
               filter: Optional[dict] = None,
               pipeline: Optional[List[dict]] = None,
               projection: Optional[dict] = None,
               client_factory=None, **_ignored) -> Dataset:
    """MongoDB ingest (reference: `ray.data.read_mongo`): parallel
    sort(_id)+skip/limit range scans, or a single-task aggregation
    `pipeline`. `client_factory(uri)` overrides the pymongo default
    (tests / custom drivers); it must be picklable."""
    from ray_tpu.data.mongo import MongoDatasource

    return _read(MongoDatasource(uri, database, collection, filter=filter,
                                 pipeline=pipeline, projection=projection,
                                 client_factory=client_factory))


def read_images(paths, *, size=None, mode="RGB", **_ignored) -> Dataset:
    """Image directory/files -> rows with a dense "image" tensor column
    (reference: `read_api.py` read_images). `size=(H, W)` resizes for the
    static shapes a TPU input pipeline needs."""
    return _read(ImageDatasource(paths, size=size, mode=mode))


def from_huggingface(hf_dataset) -> Dataset:
    """Zero-copy-ish ingest of a `datasets.Dataset` (reference:
    `read_api.py` from_huggingface): its arrow table becomes blocks."""
    if getattr(hf_dataset, "_indices", None) is not None:
        # Row selection/order (select/shuffle/train_test_split) lives in
        # the indices mapping, not the underlying table.
        hf_dataset = hf_dataset.flatten_indices()
    table = hf_dataset.data.table.combine_chunks()
    return MaterializedDataset.from_blocks([table])


def from_torch(torch_dataset) -> Dataset:
    """Materialize a torch Dataset as rows under an "item" column
    (reference: `read_api.py` from_torch). Map-style datasets index
    through __len__ (bare iteration never terminates unless __getitem__
    raises IndexError); iterable-style datasets just iterate."""
    import builtins

    if hasattr(torch_dataset, "__len__"):
        # builtins.range: this module's own range() API shadows it.
        items = [torch_dataset[i]
                 for i in builtins.range(len(torch_dataset))]
    else:
        items = list(torch_dataset)
    return from_items([{"item": x} for x in items])


def read_binary_files(paths, **_ignored) -> Dataset:
    return _read(BinaryDatasource(paths))


__all__ = [
    "Block", "BlockAccessor", "DataIterator", "Dataset",
    "MaterializedDataset", "Datasource", "range", "from_items",
    "from_numpy", "from_pandas", "from_arrow", "read_parquet", "read_csv",
    "read_json", "read_text", "read_binary_files", "read_images",
    "from_huggingface", "from_torch", "Datasink", "ParquetDatasink",
    "CSVDatasink", "JSONDatasink", "read_datasource", "read_tfrecords",
    "read_webdataset", "read_sql", "read_bigquery", "read_mongo",
]

from ray_tpu._private.usage_stats import record_library_usage as _rlu

_rlu("data")
del _rlu
