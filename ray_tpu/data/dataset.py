"""Dataset: lazy, distributed data pipelines.

Reference surface: `python/ray/data/dataset.py` (Dataset) — lazy logical
plan, map fusion, pull-based streaming execution over the tasks/actors
runtime, `streaming_split` for train ingestion, all-to-all sort/groupby
over tasks (`_internal/shuffle.py`), and file sinks. Out of scope: joins
and the arrow-native shuffle service.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data._internal import plan as plan_mod
from ray_tpu.data._internal.stats import DatasetStats
from ray_tpu.data._internal.streaming_executor import (
    DEFAULT_IN_FLIGHT, StreamingExecutor, _cluster_available,
)
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.iterator import DataIterator, SplitIterator, _SplitCoordinator


class _RowUdf:
    """Row-wise UDF adapted to the block/batch interface so map/filter/
    flat_map can ride the distributed map_batches machinery when the
    caller asks for concurrency or custom resources."""

    def __init__(self, fn: Callable, kind: str):
        self.fn = fn
        self.kind = kind

    def __call__(self, table):
        acc = BlockAccessor(table)
        if self.kind == "map":
            rows = [self.fn(dict(r)) for r in acc.rows()]
        elif self.kind == "flat_map":
            rows = [o for r in acc.rows() for o in self.fn(dict(r))]
        else:  # filter
            rows = [r for r in acc.rows() if self.fn(dict(r))]
        return BlockAccessor.from_rows(rows)


class Dataset:
    def __init__(self, ops: List[plan_mod.Op]):
        self._ops = ops
        # Execution stats accumulate here across every consumption of
        # this Dataset object (reference: `DatasetStats` in
        # `python/ray/data/_internal/stats.py`). Transforms return NEW
        # Dataset objects with fresh stats — stats describe executions
        # of *this* plan.
        self._stats = DatasetStats()
        self._split_coords: List[Any] = []

    # ------------------------------------------------------------ transforms
    def _with(self, op: plan_mod.Op) -> "Dataset":
        return Dataset(self._ops + [op])

    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None,
                    batch_format: str = "numpy",
                    fn_kwargs: Optional[Dict] = None,
                    concurrency: Optional[int] = None,
                    num_cpus: float = 1, num_tpus: float = 0,
                    **_ignored) -> "Dataset":
        """Per-batch transform. A CLASS `fn` (or explicit `concurrency`)
        runs on a pool of stateful actors — the constructor runs once per
        actor, so model weights load once per worker, and `num_tpus`
        reserves accelerator chips per actor (reference:
        `actor_pool_map_operator.py` / `ActorPoolStrategy`)."""
        return self._with(plan_mod.MapBatches(
            fn, batch_size=batch_size, batch_format=batch_format,
            fn_kwargs=fn_kwargs or {}, concurrency=concurrency,
            num_cpus=num_cpus, num_tpus=num_tpus))

    def map(self, fn: Callable, *, concurrency: Optional[int] = None,
            num_cpus: Optional[float] = None, num_tpus: float = 0,
            **unknown) -> "Dataset":
        """Per-row transform. ``concurrency``/``num_cpus``/``num_tpus``
        are honored by routing through the distributed map_batches
        machinery (reference: `python/ray/data/dataset.py` map's
        ray_remote_args); anything else raises instead of silently
        running serial (which the old ``**_ignored`` did)."""
        return self._row_op(plan_mod.MapRows, fn, "map", concurrency,
                            num_cpus, num_tpus, unknown)

    def flat_map(self, fn: Callable, *, concurrency: Optional[int] = None,
                 num_cpus: Optional[float] = None, num_tpus: float = 0,
                 **unknown) -> "Dataset":
        return self._row_op(plan_mod.FlatMap, fn, "flat_map", concurrency,
                            num_cpus, num_tpus, unknown)

    def filter(self, fn: Callable, *, concurrency: Optional[int] = None,
               num_cpus: Optional[float] = None, num_tpus: float = 0,
               **unknown) -> "Dataset":
        return self._row_op(plan_mod.Filter, fn, "filter", concurrency,
                            num_cpus, num_tpus, unknown)

    def _row_op(self, op_cls, fn, kind: str, concurrency, num_cpus,
                num_tpus, unknown: Dict) -> "Dataset":
        if unknown:
            raise TypeError(
                f"{kind}() got unsupported options {sorted(unknown)}; "
                "supported: concurrency, num_cpus, num_tpus")
        if concurrency is None and num_cpus is None and not num_tpus:
            return self._with(op_cls(fn))
        return self._with(plan_mod.MapBatches(
            _RowUdf(fn, kind), batch_format="pyarrow",
            concurrency=concurrency,
            num_cpus=1 if num_cpus is None else num_cpus,
            num_tpus=num_tpus))

    def limit(self, n: int) -> "Dataset":
        return self._with(plan_mod.Limit(n))

    def repartition(self, n: int, **_ignored) -> "Dataset":
        return self._with(plan_mod.Repartition(n))

    def random_shuffle(self, *, seed: Optional[int] = None, **_ignored
                       ) -> "Dataset":
        return self._with(plan_mod.RandomShuffle(seed))

    def union(self, *others: "Dataset") -> "Dataset":
        return self._with(plan_mod.Union([o._ops for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        return self._with(plan_mod.Zip(other._ops))

    # ------------------------------------------------------------ all-to-all
    def sort(self, key: str, descending: bool = False) -> "Dataset":
        """Distributed range sort (sample boundaries -> partition ->
        per-partition merge; see `_internal/shuffle.py`)."""
        from ray_tpu.data._internal import shuffle

        blocks = list(self._stream())
        if _cluster_available():
            out = shuffle.distributed_sort(blocks, key, descending)
        else:
            out = shuffle.local_sort(blocks, key, descending)
        return MaterializedDataset.from_blocks(out)

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    # ----------------------------------------------------------- consumption
    def _stream(self, in_flight: int = DEFAULT_IN_FLIGHT) -> Iterator[Any]:
        return StreamingExecutor(self._ops, in_flight,
                                 stats_parent=self._stats).stream_blocks()

    def iter_batches(self, **kw) -> Iterator[Any]:
        return DataIterator(self._stream).iter_batches(**kw)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        return DataIterator(self._stream).iter_rows()

    def iterator(self) -> DataIterator:
        return DataIterator(self._stream)

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for block in self.limit(n)._stream():
            out.extend(BlockAccessor(block).rows())
            if len(out) >= n:
                break
        return out[:n]

    def to_pandas(self):
        """Materialize into one pandas DataFrame (reference:
        `Dataset.to_pandas` — driver-memory bound by design)."""
        from ray_tpu.data.block import BlockAccessor

        blocks = list(self._stream())
        if not blocks:
            import pandas as pd

            return pd.DataFrame()
        return BlockAccessor.concat(blocks).to_pandas()

    def take_all(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for block in self._stream():
            out.extend(BlockAccessor(block).rows())
        return out

    def count(self) -> int:
        return sum(b.num_rows for b in self._stream())

    def sum(self, column: str) -> Any:
        total = 0
        for b in self._stream():
            arr = BlockAccessor(b).to_batch("numpy").get(column)
            if arr is not None and len(arr):
                total += np.asarray(arr).sum()
        return total

    def schema(self):
        for block in self.limit(1)._stream():
            if block.num_rows or block.num_columns:
                return BlockAccessor(block).schema()
        return None

    def columns(self) -> Optional[List[str]]:
        s = self.schema()
        return list(s.names) if s is not None else None

    def show(self, limit: int = 20) -> None:
        for row in self.take(limit):
            print(row)

    def stats(self) -> str:
        """Per-stage execution statistics for every run of this Dataset,
        rendered Ray-style (reference: `Dataset.stats()`): block/row/byte
        throughput, task submissions, and time blocked on input vs
        executing per stage.  streaming_split runs execute inside a
        coordinator actor, so their stats are fetched and folded in
        here."""
        agg = DatasetStats()
        agg.merge(self._stats)
        agg.runs = self._stats.runs  # merge() inflates empty runs to 1
        for coord in self._split_coords:
            try:
                remote = ray_tpu.get(coord.stats.remote(), timeout=30)
                agg.merge(DatasetStats.from_dict(remote))
            except Exception:
                pass  # coordinator may already be dead; report what we have
        return agg.summary(self._plan_desc())

    def _plan_desc(self) -> str:
        stages = plan_mod.split_stages(self._ops)
        return f"Dataset({len(self._ops)} ops, {len(stages)} stages)"

    # ------------------------------------------------------------- splitting
    def materialize(self) -> "MaterializedDataset":
        blocks = list(self._stream())
        return MaterializedDataset.from_blocks(blocks)

    def split(self, n: int, *, equal: bool = False, **_ignored
              ) -> List["MaterializedDataset"]:
        blocks = list(self.repartition(max(n, 1))._stream()) if equal else \
            list(self._stream())
        parts: List[List[Any]] = [[] for _ in range(n)]
        for i, b in enumerate(blocks):
            parts[i % n].append(b)
        return [MaterializedDataset.from_blocks(p) for p in parts]

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints: Optional[List] = None
                        ) -> List[DataIterator]:
        """n coordinated iterators over one shared streaming execution
        (the train-ingestion path: one per train worker)."""
        if not _cluster_available():
            # Local fallback: pre-split materialized data.
            return [DataIterator((lambda p=p: iter(p)))
                    for p in self._split_blocks_local(n)]
        coord = _SplitCoordinator.options(
            name=f"split-coord-{id(self)}-{np.random.randint(1 << 30)}",
        ).remote(self._ops)
        self._split_coords.append(coord)
        return [SplitIterator(coord, i) for i in range(n)]

    def _split_blocks_local(self, n: int) -> List[List[Any]]:
        blocks = list(self.repartition(n)._stream())
        parts: List[List[Any]] = [[] for _ in range(n)]
        for i, b in enumerate(blocks):
            parts[i % n].append(b)
        return parts

    # -------------------------------------------------------------- writing
    def write_datasink(self, sink) -> List[Any]:
        """Write every block through a Datasink plugin (reference:
        `datasource/datasink.py`); blocks write in parallel tasks when a
        cluster is up."""
        sink.prepare()
        if _cluster_available():
            refs = [_write_block_task.remote(sink, block, i)
                    for i, block in enumerate(self._stream())
                    if block.num_rows]
            return ray_tpu.get(refs, timeout=600)
        return [sink.write_block(block, i)
                for i, block in enumerate(self._stream())
                if block.num_rows]

    def write_parquet(self, path: str) -> List[str]:
        from ray_tpu.data.datasource import ParquetDatasink

        return self.write_datasink(ParquetDatasink(path))

    def write_csv(self, path: str) -> List[str]:
        from ray_tpu.data.datasource import CSVDatasink

        return self.write_datasink(CSVDatasink(path))

    def write_json(self, path: str) -> List[str]:
        from ray_tpu.data.datasource import JSONDatasink

        return self.write_datasink(JSONDatasink(path))

    def write_tfrecords(self, path: str) -> List[str]:
        from ray_tpu.data.datasource import TFRecordDatasink

        return self.write_datasink(TFRecordDatasink(path))

    def write_numpy(self, path: str) -> List[str]:
        from ray_tpu.data.datasource import NumpyDatasink

        return self.write_datasink(NumpyDatasink(path))

    def write_webdataset(self, path: str) -> List[str]:
        from ray_tpu.data.datasource import WebDatasetDatasink

        return self.write_datasink(WebDatasetDatasink(path))

    def write_sql(self, table: str, connection_factory) -> List[Any]:
        """connection_factory must be picklable (top-level function):
        blocks insert from parallel tasks when a cluster is up."""
        from ray_tpu.data.datasource import SQLDatasink

        return self.write_datasink(SQLDatasink(table, connection_factory))

    def write_bigquery(self, project: str, table: str,
                       transport=None) -> List[Any]:
        """Streaming-insert blocks into `dataset.table` (reference:
        `Dataset.write_bigquery`); a custom `transport` must be picklable
        for parallel task writes."""
        from ray_tpu.data.bigquery import BigQueryDatasink

        return self.write_datasink(
            BigQueryDatasink(project, table, transport=transport))

    def write_mongo(self, uri: str, database: str, collection: str,
                    client_factory=None) -> List[Any]:
        """insert_many blocks into the collection (reference:
        `Dataset.write_mongo`); a custom `client_factory(uri)` must be
        picklable for parallel task writes."""
        from ray_tpu.data.mongo import MongoDatasink

        return self.write_datasink(
            MongoDatasink(uri, database, collection,
                          client_factory=client_factory))

    # ---------------------------------------------------------------- misc
    def __repr__(self) -> str:  # pragma: no cover
        return self._plan_desc()


@ray_tpu.remote
def _write_block_task(sink, block, idx):
    return sink.write_block(block, idx)


class GroupedData:
    """`ds.groupby(key)` result (reference: `data/grouped_data.py`):
    hash-partitioned distributed aggregation."""

    _AGGS = {"count", "sum", "mean", "min", "max"}

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _agg(self, pairs) -> Dataset:
        from ray_tpu.data._internal import shuffle

        blocks = list(self._ds._stream())
        if _cluster_available():
            out = shuffle.distributed_groupby(blocks, self._key, pairs)
        else:
            out = shuffle.local_groupby(blocks, self._key, pairs)
        return MaterializedDataset.from_blocks(out)

    def count(self) -> Dataset:
        return self._agg([(self._key, "count")])

    def sum(self, column: str) -> Dataset:
        return self._agg([(column, "sum")])

    def mean(self, column: str) -> Dataset:
        return self._agg([(column, "mean")])

    def min(self, column: str) -> Dataset:
        return self._agg([(column, "min")])

    def max(self, column: str) -> Dataset:
        return self._agg([(column, "max")])

    def aggregate(self, **column_fns) -> Dataset:
        pairs = []
        for column, fn in column_fns.items():
            if fn not in self._AGGS:
                raise ValueError(f"unknown aggregation '{fn}'")
            pairs.append((column, fn))
        return self._agg(pairs)


class MaterializedDataset(Dataset):
    """Dataset backed by already-computed blocks (kept as object refs when a
    cluster is up, inline tables otherwise)."""

    @staticmethod
    def from_blocks(blocks: List[Any]) -> "MaterializedDataset":
        if _cluster_available():
            refs = [ray_tpu.put(b) for b in blocks]
        else:
            refs = blocks
        return MaterializedDataset([plan_mod.InputBlocks(refs)])
