"""Fused blockwise lm_head + cross-entropy — logits never hit HBM whole.

The standard training loss materializes logits [B,S,V] (≈1 GiB bf16 at
B=16, S=1024, V=32k) plus fp32 reductions, then reads them again in the
backward pass. This op streams over vocab blocks with an online
logsumexp (same trick flash attention uses along sequence), so peak
memory is O(B·S·D + D·block) and the lm_head matmul fuses with its
reduction. The backward recomputes each block's logits (remat) and
accumulates dh and d(head) per block.

Numerics: identical quantity (logsumexp(logits) - logits[target]) up to
fp32 accumulation order. The matmuls stay in the input dtype (bf16 on
TPU — MXU path); reductions accumulate in fp32.

No reference-code counterpart: net-new TPU-side design (the reference
trains via torch autograd over materialized logits).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _pad_vocab(head: jax.Array, block: int):
    d, v = head.shape
    nblk = -(-v // block)
    pad = nblk * block - v
    if pad:
        head = jnp.pad(head, ((0, 0), (0, pad)))
    return head.reshape(d, nblk, block).transpose(1, 0, 2), v, nblk


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def blockwise_xent(h: jax.Array, head: jax.Array, targets: jax.Array,
                   block: int = 8192) -> jax.Array:
    """Per-token NLL: logsumexp(h @ head) - (h @ head)[target].

    h: [N, D] hidden states; head: [D, V]; targets: [N] int32.
    Returns nll [N] float32.
    """
    nll, _ = _xent_fwd_impl(h, head, targets, block)
    return nll


def _xent_fwd_impl(h, head, targets, block):
    n, d = h.shape
    blocks, vocab, nblk = _pad_vocab(head, block)
    neg = jnp.float32(-1e30)

    def step(carry, blk_head):
        m, s, tgt, idx = carry
        # [N, block] — the only logits alive at any moment.
        logits = jax.lax.dot_general(
            h, blk_head, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        base = idx * block
        # Mask padding columns in the final block.
        col = base + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        logits = jnp.where(col < vocab, logits, neg)
        bmax = logits.max(axis=-1)
        m_new = jnp.maximum(m, bmax)
        s = s * jnp.exp(m - m_new) + jnp.exp(
            logits - m_new[:, None]).sum(axis=-1)
        in_blk = (targets >= base) & (targets < base + block)
        local = jnp.clip(targets - base, 0, block - 1)
        tgt = jnp.where(
            in_blk, jnp.take_along_axis(
                logits, local[:, None], axis=1)[:, 0], tgt)
        return (m_new, s, tgt, idx + 1), None

    init = (jnp.full((n,), neg, jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.int32(0))
    (m, s, tgt, _), _ = jax.lax.scan(step, init, blocks)
    lse = m + jnp.log(s)
    return lse - tgt, (lse,)


def _xent_fwd(h, head, targets, block):
    nll, (lse,) = _xent_fwd_impl(h, head, targets, block)
    return nll, (h, head, targets, lse)


def _xent_bwd(block, res, g):
    """g: d(nll) [N]. dh = (softmax - onehot) @ head.T * g;
    dhead = h.T @ ((softmax - onehot) * g). Blocks recomputed."""
    h, head, targets, lse = res
    n, d = h.shape
    blocks, vocab, nblk = _pad_vocab(head, block)

    def step(carry, blk_head):
        dh, dhead_blks, idx = carry
        logits = jax.lax.dot_general(
            h, blk_head, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        base = idx * block
        col = base + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        p = jnp.where(col < vocab,
                      jnp.exp(logits - lse[:, None]), 0.0)
        onehot = (col == targets[:, None]).astype(jnp.float32)
        gl = (p - onehot) * g[:, None]          # [N, block] f32
        glc = gl.astype(h.dtype)
        dh = dh + jax.lax.dot_general(          # [N, D]
            glc, blk_head, (((1,), (1,)), ((), ())))
        dblk = jax.lax.dot_general(             # [D, block]
            h, glc, (((0,), (0,)), ((), ())))
        dhead_blks = jax.lax.dynamic_update_index_in_dim(
            dhead_blks, dblk.astype(head.dtype), idx, 0)
        return (dh, dhead_blks, idx + 1), None

    init = (jnp.zeros((n, d), h.dtype),
            jnp.zeros((nblk, d, block), head.dtype),
            jnp.int32(0))
    (dh, dhead_blks, _), _ = jax.lax.scan(step, init, blocks)
    dhead = dhead_blks.transpose(1, 0, 2).reshape(d, nblk * block)[:, :vocab]
    return dh, dhead, None


blockwise_xent.defvjp(_xent_fwd, _xent_bwd)
