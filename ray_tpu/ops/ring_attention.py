"""Ring attention — context parallelism over a mesh axis.

Long-context attention where the sequence is sharded across devices
(SURVEY §5 long-context; net-new vs the reference, which has no in-repo
kernels).  Each device holds a local query/key/value shard [B, S/n, H, D];
key/value shards rotate around the ring via ``lax.ppermute`` while every
device accumulates its queries' attention over the full sequence with an
online (streaming) softmax — the global [S, S] score matrix never exists,
and peak activation memory is O(S/n · S/n) per device per step.

Usage — under ``shard_map`` with the sequence axis bound::

    out = shard_map(
        lambda q, k, v: ring_attention(q, k, v, causal=True, axis_name="sp"),
        mesh=mesh,
        in_specs=P(None, "sp", None, None),
        out_specs=P(None, "sp", None, None),
    )(q, k, v)

or via :func:`ring_attention_global`, which applies the shard_map for you.
Called WITHOUT the axis bound (single-host tests, attn_impl="ring" on an
unsharded model) it degrades to exact single-device attention.

The communication pattern (kv rotation on a ring, one ``ppermute`` hop per
step, compute overlapping the next hop's transfer) is the TPU-idiomatic
equivalent of the reference's NCCL send/recv context parallelism: the hops
ride neighbouring ICI links, so bandwidth scales with the ring size.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.util.collective.pallas import (
    select_impl, start_ring_permute, wait_ring_permute,
)

_NEG = -1e30


def _axis_size(axis_name: str) -> Optional[int]:
    """Static size of a bound mesh axis, or None when unbound."""
    try:
        return lax.axis_size(axis_name)
    except (NameError, KeyError, ValueError, TypeError, AttributeError):
        # AttributeError: lax.axis_size itself is absent on older jax
        # (0.4.x spellings handled below).
        pass
    try:
        # psum of a python scalar folds to a static int when the axis is
        # bound and raises NameError when it is not — works on every jax
        # this repo supports (0.4.x included, where the lookups below
        # return ints or are missing entirely).
        size = lax.psum(1, axis_name)
        if isinstance(size, int):
            return size
    except Exception:
        pass
    try:  # older spellings
        frame = jax.core.axis_frame(axis_name)  # type: ignore
        return frame if isinstance(frame, int) else frame.size
    except Exception:
        pass
    try:
        frame = jax.core.get_axis_env().axis_frame(axis_name)  # type: ignore
        return frame.size
    except Exception:
        return None


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   causal: bool = True, axis_name: str = "sp",
                   impl: str = "lax") -> jax.Array:
    """Per-shard ring attention. q, k, v: [B, S_local, H, D].

    Inside ``shard_map`` (axis bound): the full-sequence result for the
    local query shard. Outside: falls back to exact local attention.

    ``impl`` selects the KV-exchange backend.  ``"lax"`` (default) is the
    ``ppermute`` rotation — differentiable, so it is what training uses.
    ``"pallas"``/``"pallas_interpret"``/``"auto"`` route the rotation
    through the split-phase Pallas ring (`start_ring_permute` before the
    block compute, `wait_ring_permute` after), putting the hop's DMA
    explicitly under the attention matmuls — the overlap the serving path
    wants for long-context KV exchange.  `pallas_call` has no autodiff
    rule, so the Pallas path is forward-only (inference/serving).
    """
    n = _axis_size(axis_name)
    if n is None or n == 1:
        from ray_tpu.models.llama import xla_attention

        return xla_attention(q, k, v, causal=causal)

    resolved = select_impl(impl)
    use_split = resolved in ("pallas", "pallas_interpret")

    B, Sl, H, D = q.shape
    scale = 1.0 / math.sqrt(D)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    rows = jnp.arange(Sl)

    @jax.checkpoint
    def _block(q, k_cur, v_cur, src, m, l, acc):
        """One ring step: attend local q against the kv shard currently
        held (originating from shard ``src``), online-softmax style."""
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_cur,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = (my * Sl + rows)[:, None]
            k_pos = (src * Sl + rows)[None, :]
            mask = q_pos >= k_pos                        # [Sl, Sl]
            s = jnp.where(mask[None, None], s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))      # [B,H,Sq]
        p = jnp.exp(s - m_new[..., None])
        if causal:
            p = jnp.where(mask[None, None], p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v_cur.dtype), v_cur,
                        preferred_element_type=jnp.float32)
        acc_new = acc * alpha.transpose(0, 2, 1)[..., None] + pv
        return m_new, l_new, acc_new

    def body(carry, step):
        m, l, acc, k_cur, v_cur = carry
        src = (my - step) % n
        if use_split:
            # Split-phase: the next shard's hop is in flight while this
            # shard's attention block computes — explicit overlap rather
            # than hoping the scheduler finds it.
            kh = start_ring_permute(k_cur, axis_name, n=n, impl=resolved)
            vh = start_ring_permute(v_cur, axis_name, n=n, impl=resolved)
            m, l, acc = _block(q, k_cur, v_cur, src, m, l, acc)
            k_nxt = wait_ring_permute(kh)
            v_nxt = wait_ring_permute(vh)
        else:
            m, l, acc = _block(q, k_cur, v_cur, src, m, l, acc)
            # Rotate kv one hop; XLA overlaps the transfer with the next
            # iteration's compute where dependencies allow.
            k_nxt = lax.ppermute(k_cur, axis_name, perm)
            v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (m, l, acc, k_nxt, v_nxt), None

    def _vary(x):
        # shard_map vma typing: carries computed from axis_index become
        # "varying" over the axis; the zero-init carries must be cast to
        # match or lax.scan rejects the body signature.
        if hasattr(lax, "pcast"):
            return lax.pcast(x, (axis_name,), to="varying")
        if hasattr(lax, "pvary"):
            return lax.pvary(x, (axis_name,))
        return x

    m0 = _vary(jnp.full((B, H, Sl), _NEG, jnp.float32))
    l0 = _vary(jnp.zeros((B, H, Sl), jnp.float32))
    acc0 = _vary(jnp.zeros((B, Sl, H, D), jnp.float32))
    (m, l, acc, _, _), _ = lax.scan(
        body, (m0, l0, acc0, k, v), jnp.arange(n))

    l_safe = jnp.where(l == 0.0, 1.0, l)                 # fully-masked rows
    out = acc / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention_global(q: jax.Array, k: jax.Array, v: jax.Array,
                          mesh, causal: bool = True,
                          seq_axis: str = "sp",
                          impl: str = "lax") -> jax.Array:
    """Global-view convenience wrapper: q, k, v are full [B, S, H, D]
    arrays; the sequence dim is sharded over ``mesh[seq_axis]`` and the
    ring runs under ``shard_map``."""
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map  # jax >= 0.7 spelling
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map  # type: ignore

    spec = P(None, seq_axis, None, None)
    # check_rep off: Pallas kernels are opaque to the replication checker,
    # and on jax 0.4.x even the lax ring trips its scan-carry vma typing
    # (the axis_index-derived carries).  Correctness is covered by the
    # parity tests, not the static checker.
    fn = shard_map(
        partial(ring_attention, causal=causal, axis_name=seq_axis,
                impl=impl),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)
    return fn(q, k, v)
