"""Flash attention — pallas TPU kernels, forward + backward.

The hot op of the flagship model (net-new vs the reference, which has no
in-repo kernels — SURVEY §5 long-context). FlashAttention-2 style:

- forward: blockwise streaming attention with online softmax; per-row
  logsumexp (LSE) is written out for the backward pass. The [S, S] score
  matrix never exists in HBM.
- backward: two pallas passes plus a cheap elementwise delta precompute:
  (1) dk/dv: for each key block, stream query blocks, recomputing P from
      Q,K and the saved LSE; (2) dq: for each query block, stream key
      blocks. Peak memory stays O(S * D) — this is what lets batch and
      sequence scale on a 16G v5e chip (the XLA fallback's O(S^2) f32
      probabilities OOM first).

Layout: [B, S, H, D] public API (matches models/llama.py); kernels run in
[B, H, S, D]. Non-TPU platforms fall back to the XLA path end to end.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30
_LANE = 128

# Test hook: when True, the pallas kernels run (in interpret mode off-TPU)
# instead of falling back to XLA — lets CPU tests exercise the real kernel
# bodies (values AND grads) against the reference attention.
FORCE_PALLAS_INTERPRET = False


def _cdiv(a: int, b: int) -> int:
    return (a + b - 1) // b


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scratch, l_scratch,
                acc_scratch, *, scale: float, causal: bool,
                block_q: int, block_k: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    q_start = qi * block_q
    k_start = ki * block_k
    run = True if not causal else (k_start <= q_start + block_q - 1)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)

        m_prev = m_scratch[:, 0][:, None]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_scratch[:, 0][:, None] + jnp.sum(
            p, axis=1, keepdims=True)
        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scratch[:] = acc_scratch[:] * alpha + pv

    @pl.when(ki == nk - 1)
    def _finalize():
        m = m_scratch[:, 0][:, None]
        l = l_scratch[:, 0][:, None]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scratch[:] / l_safe).astype(o_ref.dtype)
        lse = jnp.where(l == 0.0, NEG_INF, m + jnp.log(l_safe))
        lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[2:])


def _flash_fwd_bhsd(q, k, v, causal, block_q, block_k, scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    interpret = not _on_tpu()
    B, H, S, D = q.shape
    Sk = k.shape[2]
    grid = (B, H, _cdiv(S, block_q), _cdiv(Sk, block_k))
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, _LANE), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, qi, ki: (b, h, ki, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, qi, ki: (b, h, ki, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, _LANE),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANE), jnp.float32),
            pltpu.VMEM((block_q, _LANE), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse[:, :, :, 0]


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------

def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scratch, dv_scratch, *,
                    scale: float, causal: bool, block_q: int, block_k: int):
    """grid (B, H, nk, nq): one key block accumulates over query blocks."""
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_scratch[:] = jnp.zeros_like(dk_scratch)
        dv_scratch[:] = jnp.zeros_like(dv_scratch)

    q_start = qi * block_q
    k_start = ki * block_k
    run = True if not causal else (q_start + block_q - 1 >= k_start)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]                                  # [bq, D]
        k = k_ref[0, 0]                                  # [bk, D]
        v = v_ref[0, 0]
        do = do_ref[0, 0]                                # [bq, D] bf16
        lse = lse_ref[0, 0][:, 0][:, None]               # [bq, 1]
        delta = delta_ref[0, 0][:, 0][:, None]           # [bq, 1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                             # [bq, bk] f32
        # All matmul INPUTS stay bf16 (f32 operands run the MXU at a
        # fraction of peak on TPU); accumulation is f32 via
        # preferred_element_type.
        p_lo = p.astype(q.dtype)
        # dv += P^T dO
        dv_scratch[:] += jax.lax.dot_general(
            p_lo, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dP = dO V^T ; dS = P * (dP - delta) * scale
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bq, bk]
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        # dk += dS^T q
        dk_scratch[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scratch[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scratch[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_scratch, *, scale: float, causal: bool,
                   block_q: int, block_k: int):
    """grid (B, H, nq, nk): one query block accumulates over key blocks."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_scratch[:] = jnp.zeros_like(dq_scratch)

    q_start = qi * block_q
    k_start = ki * block_k
    run = True if not causal else (k_start <= q_start + block_q - 1)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, 0][:, None]
        delta = delta_ref[0, 0][:, 0][:, None]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dq_scratch[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scratch[:].astype(dq_ref.dtype)


def _bhsd_bwd(q, k, v, do, o, lse, causal, block_q, block_k, scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    interpret = not _on_tpu()
    B, H, S, D = q.shape
    Sk = k.shape[2]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                              # [B,H,S]
    lse_l = jnp.broadcast_to(lse[..., None], (B, H, S, _LANE))
    delta_l = jnp.broadcast_to(delta[..., None], (B, H, S, _LANE))

    row_specs = [
        pl.BlockSpec((1, 1, block_q, D), lambda b, h, ki, qi: (b, h, qi, 0)),
        pl.BlockSpec((1, 1, block_k, D), lambda b, h, ki, qi: (b, h, ki, 0)),
        pl.BlockSpec((1, 1, block_k, D), lambda b, h, ki, qi: (b, h, ki, 0)),
        pl.BlockSpec((1, 1, block_q, D), lambda b, h, ki, qi: (b, h, qi, 0)),
        pl.BlockSpec((1, 1, block_q, _LANE),
                     lambda b, h, ki, qi: (b, h, qi, 0)),
        pl.BlockSpec((1, 1, block_q, _LANE),
                     lambda b, h, ki, qi: (b, h, qi, 0)),
    ]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        out_shape=(
            jax.ShapeDtypeStruct((B, H, Sk, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Sk, D), q.dtype),
        ),
        grid=(B, H, _cdiv(Sk, block_k), _cdiv(S, block_q)),
        in_specs=row_specs,
        out_specs=(
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, ki, qi: (b, h, ki, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, ki, qi: (b, h, ki, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse_l, delta_l)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        grid=(B, H, _cdiv(S, block_q), _cdiv(Sk, block_k)),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, qi, ki: (b, h, ki, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, qi, ki: (b, h, ki, 0)),
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, _LANE),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, _LANE),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, qi, ki: (b, h, qi, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse_l, delta_l)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public API with XLA fallback + custom VJP
# ---------------------------------------------------------------------------

def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _pad_to(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _xla_attention(q, k, v, causal):
    from ray_tpu.models.llama import xla_attention

    return xla_attention(q, k, v, causal=causal)


def _blocks(S: int, Sk: int, causal: bool = True) -> Tuple[int, int]:
    """Tile sizes for the pallas grid (RAY_TPU_FLASH_BLOCK_Q/K override
    for tuning sweeps). In causal mode divisibility is NOT required:
    `_prep` pads the sequence up to the tile multiple, padded keys are
    excluded by the kernel's absolute-index masks, and padded query rows
    are sliced off the output. Non-causal has no mask to hide padded
    keys behind, so its key tile must divide Sk exactly."""
    def _env(name: str) -> int:
        raw = os.environ.get(name, "").strip()
        return int(raw) if raw.isdigit() else 0

    pad_s = -(-S // _LANE) * _LANE
    pad_sk = -(-Sk // _LANE) * _LANE
    # v5e sweep at seq 1024 / head dim 128 (PERF.md): bigger tiles win
    # monotonically up to 1024 (68.9% MFU vs 53.5% at 128-tiles); 1024
    # caps VMEM use for long sequences.
    bq = min(_env("RAY_TPU_FLASH_BLOCK_Q") or 1024, pad_s)
    bk = min(_env("RAY_TPU_FLASH_BLOCK_K") or 1024, pad_sk)
    if not causal and Sk % bk:
        bk = _LANE  # caller enforces Sk % 128 == 0 for non-causal
    return bq, bk


def _use_kernel(q, k) -> bool:
    if q.shape[1] < 128 or k.shape[1] < 128:
        return False
    return _on_tpu() or FORCE_PALLAS_INTERPRET


def _prep(x, block, lane=_LANE):
    """[B,S,H,D] -> padded [B,H,S,D]."""
    return _pad_to(_pad_to(x.transpose(0, 2, 1, 3), 2, block), 3, lane)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True) -> jax.Array:
    """q,k,v: [B, S, H, D] -> [B, S, H, D]."""
    out, _ = _flash_fwd(q, k, v, causal)
    return out


def _flash_fwd(q, k, v, causal):
    B, S, H, D = q.shape
    Sk = k.shape[1]
    if not _use_kernel(q, k):
        return _xla_attention(q, k, v, causal), (q, k, v, None, None)
    if not causal and (S % 128 or Sk % 128):
        raise NotImplementedError(
            "non-causal flash requires seq_len % 128 == 0")
    block_q, block_k = _blocks(S, Sk, causal)
    qt, kt, vt = _prep(q, block_q), _prep(k, block_k), _prep(v, block_k)
    out, lse = _flash_fwd_bhsd(qt, kt, vt, causal, block_q, block_k,
                               scale=1.0 / math.sqrt(D))
    public = out[:, :, :S, :D].transpose(0, 2, 1, 3)
    return public, (q, k, v, out, lse)


def _flash_bwd(causal, residuals, g):
    q, k, v, o_pad, lse = residuals
    B, S, H, D = q.shape
    if o_pad is None:  # XLA fallback path
        _, vjp = jax.vjp(
            lambda q, k, v: _xla_attention(q, k, v, causal), q, k, v)
        return vjp(g)
    Sk = k.shape[1]
    block_q, block_k = _blocks(S, Sk, causal)
    qt, kt, vt = _prep(q, block_q), _prep(k, block_k), _prep(v, block_k)
    do = _prep(g.astype(q.dtype), block_q)
    dq, dk, dv = _bhsd_bwd(qt, kt, vt, do, o_pad, lse, causal,
                           block_q, block_k, scale=1.0 / math.sqrt(D))
    dq = dq[:, :, :S, :D].transpose(0, 2, 1, 3)
    dk = dk[:, :, :Sk, :D].transpose(0, 2, 1, 3)
    dv = dv[:, :, :Sk, :D].transpose(0, 2, 1, 3)
    return dq, dk, dv


flash_attention.defvjp(
    lambda q, k, v, causal: _flash_fwd(q, k, v, causal),
    _flash_bwd,
)
