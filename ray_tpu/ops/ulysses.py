"""Ulysses-style sequence parallelism — all-to-all head/sequence reshard.

The config alternative to ring attention for long-context training
(SURVEY §5; the pattern of DeepSpeed-Ulysses, re-expressed as XLA
collectives over ICI). Where ring attention keeps queries home and
rotates KV shards around the ring, Ulysses re-shards: each device starts
with the full head set for a sequence shard [B, S/n, H, D], all-to-alls
into the full sequence for a head subset [B, S, H/n, D], runs ordinary
(flash) attention locally — exact, no online-softmax ring recursion —
and all-to-alls back.

Trade-off vs ring: two all-to-alls of the whole activation instead of
n-1 KV ppermute hops; exactness and a simpler kernel, but parallelism is
capped by the head count (n must divide both H and H_kv for GQA).

Usage mirrors `ops/ring_attention.py`::

    out = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, causal=True,
                                          axis_name="sp"),
        mesh=mesh, in_specs=P(None, "sp", None, None),
        out_specs=P(None, "sp", None, None),
    )(q, k, v)

or `ulysses_attention_global(q, k, v, mesh)` which applies the shard_map,
or `parallel.context_parallel_attention(mesh, impl="ulysses")` to plug
into the model layer. Called without the axis bound it degrades to exact
single-device attention.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.ops.attention import flash_attention
from ray_tpu.ops.ring_attention import _axis_size


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      causal: bool = True,
                      axis_name: str = "sp") -> jax.Array:
    """Per-shard Ulysses attention. q: [B, S_local, H, D]; k/v may carry
    fewer (grouped-query) heads. Requires the head counts to be divisible
    by the sequence-axis size."""
    n = _axis_size(axis_name)
    H, Hkv = q.shape[2], k.shape[2]
    if n is None or n == 1:  # axis unbound: plain exact attention
        if Hkv != H:
            k = jnp.repeat(k, H // Hkv, axis=2)
            v = jnp.repeat(v, H // Hkv, axis=2)
        return flash_attention(q, k, v, causal)
    if H % n or Hkv % n:
        raise ValueError(
            f"ulysses: sequence-axis size {n} must divide n_heads={H} "
            f"and n_kv_heads={Hkv} (use ring attention otherwise)")
    # [B, S/n, H, D] -> [B, S, H/n, D]: trade the sequence shard for a
    # head shard (one fused all-to-all per tensor over ICI).
    reshard = lambda x: lax.all_to_all(          # noqa: E731
        x, axis_name, split_axis=2, concat_axis=1, tiled=True)
    qg, kg, vg = reshard(q), reshard(k), reshard(v)
    if Hkv != H:
        # Grouped-query: expand the local KV head shard to the query
        # head count AFTER the reshard (ships Hkv/n heads over ICI,
        # repeats locally — cheaper than repeating before).
        kg = jnp.repeat(kg, H // Hkv, axis=2)
        vg = jnp.repeat(vg, H // Hkv, axis=2)
    out = flash_attention(qg, kg, vg, causal)
    # [B, S, H/n, D] -> [B, S/n, H, D]
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention_global(q, k, v, mesh, causal: bool = True,
                             seq_axis: str = "sp"):
    """Apply the shard_map over `mesh[seq_axis]` for global [B, S, H, D]
    inputs sharded on the sequence dimension."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(None, seq_axis, None, None)
    return shard_map(
        lambda a, b, c: ulysses_attention(a, b, c, causal=causal,
                                          axis_name=seq_axis),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False,
    )(q, k, v)
