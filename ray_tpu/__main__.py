from ray_tpu.scripts.cli import main

main()
