"""Backend plugin interface (reference: `train/backend.py` — Backend with
on_start/on_training_start/on_shutdown hooks + BackendConfig)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from ray_tpu.train._internal.worker_group import WorkerGroup


@dataclass
class BackendConfig:
    @property
    def backend_cls(self):
        return Backend


class Backend:
    """Distributed-framework setup hooks running against the worker group."""

    def on_start(self, worker_group: "WorkerGroup",
                 backend_config: BackendConfig) -> None:
        pass

    def on_training_start(self, worker_group: "WorkerGroup",
                          backend_config: BackendConfig) -> None:
        pass

    def on_shutdown(self, worker_group: "WorkerGroup",
                    backend_config: BackendConfig) -> None:
        pass
