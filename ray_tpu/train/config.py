"""Train/AIR configuration dataclasses.

Reference: `python/ray/air/config.py` — ScalingConfig (`:101`),
FailureConfig (`:375`), CheckpointConfig (`:425`), RunConfig.
TPU-first deltas: `use_tpu`/`chips_per_worker` replace `use_gpu`, and
`topology` lets a trainer claim a whole pod slice via gang resources.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    chips_per_worker: int = 4
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    topology: Optional[str] = None  # e.g. "v5e-16": claim a whole pod slice

    def worker_resources(self) -> Dict[str, float]:
        if self.resources_per_worker is not None:
            return dict(self.resources_per_worker)
        if self.use_tpu:
            return {"CPU": 1, "TPU": self.chips_per_worker}
        return {"CPU": 1}

    def bundle(self) -> Dict[str, float]:
        return self.worker_resources()


@dataclass
class FailureConfig:
    max_failures: int = 0


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclass
class RunConfig:
    name: Optional[str] = None
    # Local path OR a pyarrow-fs URI (s3://, gs://, file://); with a URI
    # (or an explicit storage_filesystem) the run stages locally and syncs
    # checkpoints to storage (reference: train/_internal/storage.py).
    storage_path: Optional[str] = None
    storage_filesystem: Optional[Any] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    verbose: int = 1

    def is_remote_storage(self) -> bool:
        from ray_tpu.train.storage import is_uri

        return self.storage_filesystem is not None or is_uri(
            self.storage_path)

    def resolved_storage_path(self) -> str:
        """LOCAL working root: remote storage stages under a local dir
        and syncs up per checkpoint."""
        if self.is_remote_storage():
            import hashlib

            digest = hashlib.md5(
                str(self.storage_path).encode()).hexdigest()[:10]
            return os.path.join(os.path.expanduser("~/ray_tpu_staging"),
                                digest)
        return self.storage_path or os.path.expanduser("~/ray_tpu_results")


@dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional["Any"]  # ray_tpu.train.Checkpoint
    path: str
    metrics_dataframe: Optional[List[Dict[str, Any]]] = None
    error: Optional[Any] = None  # str or exception
    config: Optional[Dict[str, Any]] = None  # trial config (tune runs)
