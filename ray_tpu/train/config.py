"""Train/AIR configuration dataclasses.

Reference: `python/ray/air/config.py` — ScalingConfig (`:101`),
FailureConfig (`:375`), CheckpointConfig (`:425`), RunConfig.
TPU-first deltas: `use_tpu`/`chips_per_worker` replace `use_gpu`, and
`topology` lets a trainer claim a whole pod slice via gang resources.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    chips_per_worker: int = 4
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    topology: Optional[str] = None  # e.g. "v5e-16": claim a whole pod slice

    def worker_resources(self) -> Dict[str, float]:
        if self.resources_per_worker is not None:
            return dict(self.resources_per_worker)
        if self.use_tpu:
            return {"CPU": 1, "TPU": self.chips_per_worker}
        return {"CPU": 1}

    def bundle(self) -> Dict[str, float]:
        return self.worker_resources()


@dataclass
class FailureConfig:
    max_failures: int = 0


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    verbose: int = 1

    def resolved_storage_path(self) -> str:
        return self.storage_path or os.path.expanduser("~/ray_tpu_results")


@dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional["Any"]  # ray_tpu.train.Checkpoint
    path: str
    metrics_dataframe: Optional[List[Dict[str, Any]]] = None
    error: Optional[Any] = None  # str or exception
    config: Optional[Dict[str, Any]] = None  # trial config (tune runs)
