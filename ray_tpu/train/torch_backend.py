"""TorchBackend — torch.distributed process groups for CPU-side torch
training (reference: `train/torch/config.py:146` — pick nccl vs gloo,
broadcast rank-0 address, `dist.init_process_group` at `:108`).

On this stack the accelerator path is jax/XLA (`JaxBackend`); the torch
backend exists for CPU-tensor workloads and to keep the reference's
pluggable-Backend story intact: the SAME BackendExecutor/WorkerGroup
machinery boots either framework — only the rendezvous hook differs.
Only gloo is supported (no NCCL on TPU hosts; the tensor plane between
chips is XLA over ICI, SURVEY §5 two-plane design).

    trainer = TorchTrainer(
        train_loop, scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()

Inside `train_loop`, `torch.distributed` is initialized (gloo) and
`ray_tpu.train.report()` works as with JaxTrainer.
"""

from __future__ import annotations

from dataclasses import dataclass

from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.jax_backend import _free_port_on_worker


@dataclass
class TorchConfig(BackendConfig):
    backend: str = "gloo"          # the only supported process-group kind
    init_timeout_s: float = 120.0

    @property
    def backend_cls(self):
        return TorchBackend


def _setup_torch_process_group(master_addr: str, master_port: int,
                               world_size: int, rank: int,
                               backend: str, timeout_s: float) -> bool:
    import datetime
    import os

    import torch.distributed as dist

    os.environ["MASTER_ADDR"] = master_addr
    os.environ["MASTER_PORT"] = str(master_port)
    dist.init_process_group(
        backend=backend, world_size=world_size, rank=rank,
        timeout=datetime.timedelta(seconds=timeout_s))
    return dist.is_initialized()


def _shutdown_torch_process_group() -> None:
    import torch.distributed as dist

    try:
        if dist.is_initialized():
            dist.destroy_process_group()
    except Exception:
        pass


class TorchBackend(Backend):
    def on_start(self, worker_group, backend_config: TorchConfig) -> None:
        import ray_tpu

        if backend_config.backend != "gloo":
            raise ValueError(
                f"backend={backend_config.backend!r}: only 'gloo' is "
                "supported (inter-chip tensors ride XLA/ICI, not NCCL)")
        # The group forms even at world_size 1 (the reference does too):
        # DDP and dist.* calls in the user loop must work at any scale.
        world_size = worker_group.num_workers
        meta0 = worker_group.metadata()[0]
        port = worker_group.execute_single(0, _free_port_on_worker)
        ok = ray_tpu.get([
            w.execute.remote(_setup_torch_process_group, meta0["ip"], port,
                             world_size, rank, backend_config.backend,
                             backend_config.init_timeout_s)
            for rank, w in enumerate(worker_group.workers)
        ], timeout=600)
        if not all(ok):
            raise RuntimeError(f"torch process group failed to form: {ok}")

    def on_shutdown(self, worker_group,
                    backend_config: TorchConfig) -> None:
        try:
            worker_group.execute(_shutdown_torch_process_group)
        except Exception:
            pass
