"""ray_tpu.train — distributed training orchestration (Ray Train parity,
TPU-native: JaxTrainer/JaxBackend instead of Torch/DDP)."""

from ray_tpu.train._internal.session import (
    get_context, get_dataset_shard, report,
)
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.sharded_checkpoint import (  # noqa: F401
    load_sharded, save_sharded,
)
from ray_tpu.train.config import (
    CheckpointConfig, FailureConfig, Result, RunConfig, ScalingConfig,
)
from ray_tpu.train.jax_backend import JaxBackend, JaxConfig
from ray_tpu.train.torch_backend import TorchBackend, TorchConfig
from ray_tpu.train.trainer import (
    DataParallelTrainer, JaxTrainer, TorchTrainer,
)
from ray_tpu.train._internal.backend_executor import TrainingFailedError

__all__ = [
    "JaxTrainer", "DataParallelTrainer", "JaxBackend", "JaxConfig",
    "TorchTrainer", "TorchBackend", "TorchConfig",
    "Backend", "BackendConfig", "ScalingConfig", "RunConfig",
    "FailureConfig", "CheckpointConfig", "Checkpoint", "Result",
    "report", "get_context", "get_dataset_shard", "TrainingFailedError",
]

from ray_tpu._private.usage_stats import record_library_usage as _rlu

_rlu("train")
del _rlu
