"""JaxBackend — the TPU-native Train backend.

This is the component BASELINE.json's north star names: the analogue of the
reference's TorchBackend/TorchConfig (`train/torch/config.py:146` — pick
process-group backend, broadcast rank-0 address, `dist.init_process_group`
at `:108`), re-designed for jax:

- on_start: rank 0 picks a coordinator port; every worker calls
  `jax.distributed.initialize(coordinator, num_processes, process_id)`.
  After that, `jax.devices()` on any worker sees the GLOBAL device set —
  on a TPU pod slice, collectives between them ride ICI, and the SPMD
  mesh spans the slice.
- Workers then build meshes via `ray_tpu.train.jax_utils` / collective
  `get_group_mesh` and run pjit'd steps; there is no DDP wrapper — data/
  model parallelism are sharding annotations, not engines.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ray_tpu.train.backend import Backend, BackendConfig


@dataclass
class JaxConfig(BackendConfig):
    # "tpu" on real hardware; "cpu" for the fake-mesh test tier
    # (the moral equivalent of the reference's _fake_gpus/gloo tiers).
    platform: Optional[str] = None
    # CPU tier only: per-process virtual device count
    # (jax.config jax_num_cpu_devices).
    num_cpu_devices: Optional[int] = None
    # Default mesh axes for workers that call `pod_train_loop` /
    # `run_pod_training` without an explicit mesh: data absorbs whatever
    # the fsdp/tensor factors leave over (parallel.make_mesh semantics).
    mesh_axes: Optional[dict] = None
    # "replicated" | "sharded" — ZeRO-style cross-replica sharding of the
    # optimizer update (parallel.zero) for loops driven via this config.
    weight_update: str = "replicated"
    # Chunked split-phase overlap of grad reduce-scatter / param allgather
    # with optimizer math (parallel.zero overlap schedule).  Only valid
    # with a pure data mesh; implies the explicit sharded update route.
    overlap: bool = False

    @property
    def backend_cls(self):
        return JaxBackend


def _setup_jax_distributed(coordinator: Optional[str], world_size: int,
                           rank: int, platform: Optional[str],
                           num_cpu_devices: Optional[int]) -> int:
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    if num_cpu_devices and (platform == "cpu"):
        try:
            jax.config.update("jax_num_cpu_devices", num_cpu_devices)
        except AttributeError:
            # jax < 0.5 has no jax_num_cpu_devices; the XLA flag is the
            # same knob but is only read at backend init, so it must land
            # in the environment before the first device query.
            import os

            flag = ("--xla_force_host_platform_device_count="
                    f"{num_cpu_devices}")
            existing = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in existing:
                os.environ["XLA_FLAGS"] = f"{existing} {flag}".strip()
    if world_size > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=world_size,
            process_id=rank,
        )
    return jax.device_count()


def _shutdown_jax_distributed() -> None:
    import jax

    try:
        jax.distributed.shutdown()
    except Exception:
        pass


class JaxBackend(Backend):
    def on_start(self, worker_group, backend_config: JaxConfig) -> None:
        import ray_tpu

        world_size = worker_group.num_workers
        coordinator = None
        if world_size > 1:
            meta0 = worker_group.metadata()[0]
            port = worker_group.execute_single(0, _free_port_on_worker)
            coordinator = f"{meta0['ip']}:{port}"
        device_counts = ray_tpu.get([
            w.execute.remote(_setup_jax_distributed, coordinator, world_size,
                             rank, backend_config.platform,
                             backend_config.num_cpu_devices)
            for rank, w in enumerate(worker_group.workers)
        ], timeout=600)
        # All workers must agree on the global device count — a mismatch
        # means a partial gang (some host failed to join its slice).
        if len(set(device_counts)) != 1:
            raise RuntimeError(
                f"inconsistent global device count across workers: "
                f"{device_counts}")

    def on_shutdown(self, worker_group, backend_config: JaxConfig) -> None:
        try:
            worker_group.execute(_shutdown_jax_distributed)
        except Exception:
            pass


def _free_port_on_worker() -> int:
    import socket

    s = socket.socket()
    s.bind(("0.0.0.0", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# Pod-scale sharded training loop.  One canonical path from "workers joined
# the gang" to "tokens/sec/chip": build the multi-host data×fsdp×tensor
# mesh, shard a Llama model over it, and run the pjit train step with the
# ZeRO weight-update knob.  `JaxTrainer(pod_train_loop, ...)` uses it as a
# train_loop_per_worker; the multichip dryrun calls `run_pod_training`
# directly so both exercise the identical code path.
# ---------------------------------------------------------------------------

def run_pod_training(model_config=None, mesh_axes=None, steps: int = 4,
                     batch_size: Optional[int] = None, seq_len: int = 33,
                     weight_update: str = "replicated",
                     learning_rate: float = 1e-3, seed: int = 0,
                     overlap: bool = False, n_chunks: int = 4,
                     collective: str = "auto", report=None) -> dict:
    """Run `steps` sharded Llama train steps; returns throughput metrics.

    The returned dict carries ``tokens_per_sec`` / ``tokens_per_sec_per_chip``
    measured over the post-compile steps (step 0 is the compile+warmup step
    and is excluded), which is what MULTICHIP_rXX.json and ROADMAP item 1
    compare against the single-chip figure.

    ``overlap=True`` routes the loop through the explicit chunked
    split-phase ZeRO step (`parallel.zero.build_zero_train_step` with
    ``overlap=True``): grad reduce-scatter and param allgather hops are
    pipelined chunk-by-chunk under the optimizer math instead of running
    as one exposed collective.  Requires a pure data mesh (the chunk
    schedule owns the whole flat parameter vector).

    When ``train_goodput_instrumentation`` is on (default), the loop
    runs under the per-step phase ledger (`observability.goodput`):
    each step is decomposed into h2d/compute/exposed-collective/
    weight-publish phases (``rtpu_train_step_phase_seconds{phase}`` +
    ``train.step`` spans), the warmup compile step is booked as
    ``recompiling`` lost time, and each step publishes a heartbeat row
    into the GCS step matrix (straggler + stall detection). The
    returned dict then carries ``goodput`` (the worker ledger
    snapshot) and ``phase_seconds`` (per-phase sums over the timed
    steps).
    """
    import time

    import jax
    import numpy as np
    import optax

    from ray_tpu._private.config import GlobalConfig
    from ray_tpu.observability.goodput import (
        GoodputLedger, StepPhases, goodput_metrics, publish_train_done,
        set_active_ledger,
    )

    from ray_tpu.models.llama import LlamaConfig, init_params, loss_fn
    from ray_tpu.parallel import (
        batch_sharding, build_train_step, build_zero_train_step,
        create_train_state, create_zero_state, llama_param_shardings,
        make_mesh, shard_params,
    )

    if model_config is None:
        model_config = LlamaConfig(
            vocab_size=512, dim=128, n_layers=4, n_heads=8, n_kv_heads=4,
            hidden_dim=256, max_seq_len=128)
    mesh = make_mesh(dict(mesh_axes) if mesh_axes else {"data": -1})
    n_devices = int(np.prod(mesh.devices.shape))

    if overlap:
        non_data = [ax for ax in mesh.axis_names
                    if ax != "data" and mesh.shape[ax] > 1]
        if non_data:
            raise ValueError(
                f"overlap=True needs a pure data mesh, got non-trivial "
                f"axes {non_data} — the chunked schedule shards the whole "
                "flat parameter vector over 'data'")
        weight_update = "sharded"

    params = init_params(model_config, jax.random.key(seed))
    shardings = llama_param_shardings(model_config, mesh)
    bsh = batch_sharding(mesh)
    optimizer = optax.adamw(learning_rate)
    params_shape = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params)

    if overlap:
        step = build_zero_train_step(
            lambda p, b: loss_fn(p, b, model_config), optimizer, mesh,
            axis_name="data", collective=collective, overlap=True,
            n_chunks=n_chunks)
        state = create_zero_state(params, optimizer, mesh)
    else:
        step = build_train_step(
            lambda p, b: loss_fn(p, b, model_config), optimizer, mesh,
            shardings, bsh, weight_update=weight_update,
            params_shape=params_shape)
        state = create_train_state(shard_params(params, shardings),
                                   optimizer)

    # Batch must divide evenly over the data-like axes.
    data_shards = 1
    for ax in ("data", "fsdp"):
        if ax in mesh.axis_names:
            data_shards *= mesh.shape[ax]
    if batch_size is None:
        batch_size = max(8, n_devices)
    if batch_size % data_shards:
        batch_size = ((batch_size + data_shards - 1)
                      // data_shards) * data_shards
    instrument = bool(GlobalConfig.train_goodput_instrumentation)
    worker_label = f"train-{jax.process_index()}"
    ledger = GoodputLedger(worker=worker_label) if instrument else None
    if ledger is not None:
        set_active_ledger(ledger)

    rng = np.random.RandomState(seed)
    host_tokens = rng.randint(0, model_config.vocab_size,
                              (batch_size, seq_len)).astype("int32")
    t_h2d = time.perf_counter()
    batch = {"tokens": jax.device_put(host_tokens, bsh)}
    if ledger is not None:
        # One-off input transfer: an h2d histogram sample + stalled
        # ledger time (a real input pipeline pays this per step).
        h2d_s = time.perf_counter() - t_h2d
        goodput_metrics().step_phase_seconds.observe(
            h2d_s, {"phase": "h2d"})
        ledger.book_phases({"h2d": h2d_s})
    tokens_per_step = batch_size * (seq_len - 1)  # next-token targets

    t_compile = time.perf_counter()
    state, metrics = step(state, batch)  # compile + warmup
    jax.block_until_ready(metrics["loss"])
    if ledger is not None:
        # The compile+warmup step is wall time the pod spent not
        # training — exactly what a preemption/resume re-pays.
        ledger.lose("recompiling", time.perf_counter() - t_compile)

    step_rows = []
    t0 = time.perf_counter()
    for i in range(steps):
        if ledger is not None:
            sp = StepPhases(step=i, worker=worker_label, ledger=ledger)
            with sp.phase("compute"):
                state, metrics = step(state, batch)
                # Phase attribution needs the step's device work fenced
                # inside its timed section (dispatch alone is ~free).
                jax.block_until_ready(metrics["loss"])
            if report is not None:
                with sp.phase("weight_publish"):
                    report({"loss": float(metrics["loss"]),
                            "step": int(metrics["step"])})
            step_rows.append(sp.finish())
        else:
            state, metrics = step(state, batch)
            if report is not None:
                report({"loss": float(metrics["loss"]),
                        "step": int(metrics["step"])})
    jax.block_until_ready(metrics["loss"])
    elapsed = time.perf_counter() - t0
    loss = float(metrics["loss"])
    tokens_per_sec = tokens_per_step * steps / max(elapsed, 1e-9)
    extra = {}
    if ledger is not None:
        phase_seconds: dict = {}
        for row in step_rows:
            for phase, dur in row["phases"].items():
                phase_seconds[phase] = phase_seconds.get(phase, 0.0) + dur
        extra = {"goodput": ledger.snapshot(),
                 "phase_seconds": phase_seconds,
                 "step_walls": [row["wall_s"] for row in step_rows]}
        set_active_ledger(None)
        publish_train_done(worker_label)
    return {
        **extra,
        "n_devices": n_devices,
        "mesh": {name: int(size) for name, size
                 in zip(mesh.axis_names, mesh.devices.shape)},
        "weight_update": weight_update,
        "overlap": overlap,
        "steps": steps,
        "batch_size": batch_size,
        "seq_len": seq_len,
        "loss": loss,
        "train_seconds": elapsed,
        "tokens_per_sec": tokens_per_sec,
        "tokens_per_sec_per_chip": tokens_per_sec / max(n_devices, 1),
    }


def pod_train_loop(config: Optional[dict] = None) -> None:
    """`train_loop_per_worker` for `JaxTrainer`: pod-scale sharded Llama
    training over the multi-host mesh, reporting throughput per step.

    Config keys (all optional): ``mesh_axes``, ``weight_update``,
    ``steps``, ``batch_size``, ``seq_len``, ``learning_rate``, ``seed``,
    ``model_config`` (a LlamaConfig).  Mesh/weight-update defaults come
    from the backend's `JaxConfig` when driven through `JaxTrainer`.
    """
    from ray_tpu import train

    config = dict(config or {})
    summary = run_pod_training(
        model_config=config.get("model_config"),
        mesh_axes=config.get("mesh_axes"),
        steps=int(config.get("steps", 4)),
        batch_size=config.get("batch_size"),
        seq_len=int(config.get("seq_len", 33)),
        weight_update=config.get("weight_update", "replicated"),
        learning_rate=float(config.get("learning_rate", 1e-3)),
        seed=int(config.get("seed", 0)),
        overlap=bool(config.get("overlap", False)),
        n_chunks=int(config.get("n_chunks", 4)),
        collective=config.get("collective", "auto"),
        report=None,
    )
    train.report(summary)
