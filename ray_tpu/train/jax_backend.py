"""JaxBackend — the TPU-native Train backend.

This is the component BASELINE.json's north star names: the analogue of the
reference's TorchBackend/TorchConfig (`train/torch/config.py:146` — pick
process-group backend, broadcast rank-0 address, `dist.init_process_group`
at `:108`), re-designed for jax:

- on_start: rank 0 picks a coordinator port; every worker calls
  `jax.distributed.initialize(coordinator, num_processes, process_id)`.
  After that, `jax.devices()` on any worker sees the GLOBAL device set —
  on a TPU pod slice, collectives between them ride ICI, and the SPMD
  mesh spans the slice.
- Workers then build meshes via `ray_tpu.train.jax_utils` / collective
  `get_group_mesh` and run pjit'd steps; there is no DDP wrapper — data/
  model parallelism are sharding annotations, not engines.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ray_tpu.train.backend import Backend, BackendConfig


@dataclass
class JaxConfig(BackendConfig):
    # "tpu" on real hardware; "cpu" for the fake-mesh test tier
    # (the moral equivalent of the reference's _fake_gpus/gloo tiers).
    platform: Optional[str] = None
    # CPU tier only: per-process virtual device count
    # (jax.config jax_num_cpu_devices).
    num_cpu_devices: Optional[int] = None

    @property
    def backend_cls(self):
        return JaxBackend


def _setup_jax_distributed(coordinator: Optional[str], world_size: int,
                           rank: int, platform: Optional[str],
                           num_cpu_devices: Optional[int]) -> int:
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    if num_cpu_devices and (platform == "cpu"):
        jax.config.update("jax_num_cpu_devices", num_cpu_devices)
    if world_size > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=world_size,
            process_id=rank,
        )
    return jax.device_count()


def _shutdown_jax_distributed() -> None:
    import jax

    try:
        jax.distributed.shutdown()
    except Exception:
        pass


class JaxBackend(Backend):
    def on_start(self, worker_group, backend_config: JaxConfig) -> None:
        import ray_tpu

        world_size = worker_group.num_workers
        coordinator = None
        if world_size > 1:
            meta0 = worker_group.metadata()[0]
            port = worker_group.execute_single(0, _free_port_on_worker)
            coordinator = f"{meta0['ip']}:{port}"
        device_counts = ray_tpu.get([
            w.execute.remote(_setup_jax_distributed, coordinator, world_size,
                             rank, backend_config.platform,
                             backend_config.num_cpu_devices)
            for rank, w in enumerate(worker_group.workers)
        ], timeout=600)
        # All workers must agree on the global device count — a mismatch
        # means a partial gang (some host failed to join its slice).
        if len(set(device_counts)) != 1:
            raise RuntimeError(
                f"inconsistent global device count across workers: "
                f"{device_counts}")

    def on_shutdown(self, worker_group, backend_config: JaxConfig) -> None:
        try:
            worker_group.execute(_shutdown_jax_distributed)
        except Exception:
            pass


def _free_port_on_worker() -> int:
    import socket

    s = socket.socket()
    s.bind(("0.0.0.0", 0))
    port = s.getsockname()[1]
    s.close()
    return port
