"""Worker-side training session.

Reference: `train/_internal/session.py:109` (`_TrainSession`) — the user's
``train_loop_per_worker`` runs in a dedicated thread; ``report(metrics,
checkpoint)`` passes results through a bounded queue (`session.py:202`) back
to the driver poll loop; checkpoints persist to experiment storage before the
metrics that reference them are released.
"""

from __future__ import annotations

import os
import queue
import threading
import time
import traceback
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint

_session: Optional["_TrainSession"] = None


FINISHED = "__finished__"
ERRORED = "__errored__"
REPORT = "__report__"


@dataclass
class TrainContext:
    world_rank: int
    world_size: int
    local_rank: int
    local_world_size: int
    node_rank: int
    experiment_name: str
    storage_dir: str

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_local_world_size(self) -> int:
        return self.local_world_size

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_experiment_name(self) -> str:
        return self.experiment_name

    def get_checkpoint(self) -> Optional[Checkpoint]:
        s = get_session()
        return s.latest_checkpoint if s else None

    def get_trial_dir(self) -> str:
        return self.storage_dir


class _TrainSession:
    def __init__(self, train_fn: Callable, config: Dict[str, Any],
                 context: TrainContext,
                 latest_checkpoint: Optional[Checkpoint]):
        self.context = context
        self.latest_checkpoint = latest_checkpoint
        self._result_queue: "queue.Queue" = queue.Queue(maxsize=8)
        self._train_fn = train_fn
        config = dict(config or {})
        # Dataset shards ride alongside user config (trainer `datasets=`);
        # exposed via train.get_dataset_shard, not the config dict.
        shards = config.pop("__datasets__", {})
        self.dataset_shards = {
            name: per_rank[context.world_rank]
            for name, per_rank in shards.items()
            if context.world_rank < len(per_rank)
        }
        self._config = config
        self._thread: Optional[threading.Thread] = None
        self._report_counter = 0
        self._last_report_ts: Optional[float] = None

    def start(self):
        def _run():
            global _session
            _session = self
            try:
                if self._takes_config():
                    self._train_fn(self._config)
                else:
                    self._train_fn()
                self._result_queue.put((FINISHED, None, None))
            except BaseException as e:  # noqa: BLE001
                self._result_queue.put(
                    (ERRORED, f"{type(e).__name__}: {e}\n"
                     f"{traceback.format_exc()}", None))

        self._thread = threading.Thread(target=_run, daemon=True,
                                        name="train-loop")
        self._thread.start()

    def _takes_config(self) -> bool:
        import inspect

        try:
            sig = inspect.signature(self._train_fn)
            return len(sig.parameters) >= 1
        except (TypeError, ValueError):
            return False

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None):
        self._record_telemetry(metrics)
        ckpt_path = None
        if checkpoint is not None:
            # Name by a session-side monotonic counter, never user metrics:
            # duplicate names would alias directories and break driver-side
            # top-k retention (reference names checkpoints driver-side with
            # a monotonic index for the same reason).
            from ray_tpu.util.tracing import span

            t0 = time.perf_counter()
            with span("train.checkpoint_persist",
                      attrs={"rank": self.context.world_rank}):
                persisted = checkpoint.persist(
                    self.context.storage_dir,
                    name=f"checkpoint_{self._report_counter:06d}"
                         f"_rank{self.context.world_rank}")
            try:
                from ray_tpu.observability.goodput import record_checkpoint

                record_checkpoint(time.perf_counter() - t0)
            except Exception:
                pass  # telemetry must never fail a training step
            self._report_counter += 1
            self.latest_checkpoint = persisted
            ckpt_path = persisted.path
        # Blocks when the driver falls behind (backpressure, reference
        # bounded-queue behavior).
        self._result_queue.put((REPORT, metrics, ckpt_path))

    def _record_telemetry(self, metrics: Dict[str, Any]) -> None:
        """One training step per report(): step duration is the wall
        time since the previous report, loss/throughput are lifted from
        the user's metrics dict when recognizably named."""
        try:
            from ray_tpu.observability import train_metrics

            from ray_tpu.observability.train import record_report_step

            tm = train_metrics()
            now = time.monotonic()
            tm.reports.inc()
            if self._last_report_ts is not None:
                step_s = now - self._last_report_ts
                tm.step_seconds.observe(step_s)
            else:
                step_s = None
            self._last_report_ts = now
            self._telemetry_steps = getattr(
                self, "_telemetry_steps", 0) + 1
            record_report_step(self.context.world_rank,
                               self._telemetry_steps, step_s)
            if isinstance(metrics, dict):
                for key in ("loss", "total_loss", "train_loss"):
                    if isinstance(metrics.get(key), (int, float)):
                        tm.loss.set(float(metrics[key]))
                        break
                for key in ("num_samples", "samples", "batch_size"):
                    n = metrics.get(key)
                    if isinstance(n, (int, float)) and step_s:
                        tm.samples_per_sec.set(float(n) / step_s)
                        break
        except Exception:
            pass  # telemetry must never fail a training step

    def next_result(self, timeout: Optional[float] = None):
        try:
            return self._result_queue.get(timeout=timeout)
        except queue.Empty:
            return None


def get_session() -> Optional[_TrainSession]:
    return _session


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """`ray_tpu.train.report` — from inside train_loop_per_worker."""
    s = get_session()
    if s is None:
        raise RuntimeError(
            "train.report() called outside a training session")
    s.report(metrics, checkpoint)


def get_context() -> TrainContext:
    s = get_session()
    if s is None:
        raise RuntimeError("no active training session")
    return s.context


def get_dataset_shard(name: str = "train"):
    """This worker's split of `JaxTrainer(datasets={name: ds})` — a
    DataIterator when the dataset supports streaming_split (reference
    `session.get_dataset_shard`)."""
    s = get_session()
    if s is None:
        raise RuntimeError(
            "train.get_dataset_shard() called outside a training session")
    return s.dataset_shards.get(name)
