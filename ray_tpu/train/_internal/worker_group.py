"""WorkerGroup — the fleet of training worker actors.

Reference: `train/_internal/worker_group.py:102`. Each worker is a plain
actor hosting (a) an ``execute`` escape hatch for backend setup and (b) the
training session protocol (init/start/poll).
"""

from __future__ import annotations

import os
import socket
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train._internal.session import (
    ERRORED, FINISHED, REPORT, TrainContext, _TrainSession,
)
from ray_tpu.train.checkpoint import Checkpoint


@ray_tpu.remote
class TrainWorker:
    def __init__(self, world_rank: int):
        self.world_rank = world_rank
        self.session: Optional[_TrainSession] = None

    # -- generic escape hatch (backends run arbitrary setup through this) ---
    def execute(self, fn: Callable, *args, **kwargs):
        return fn(*args, **kwargs)

    # -- metadata -----------------------------------------------------------
    def get_metadata(self) -> Dict[str, Any]:
        ctx = ray_tpu.get_runtime_context()
        return {
            "node_id": ctx.get_node_id(),
            "hostname": socket.gethostname(),
            "ip": os.environ.get("RAY_TPU_NODE_IP", "127.0.0.1"),
            "pid": os.getpid(),
            "tpu_ids": ctx.get_tpu_ids(),
        }

    def find_free_port(self) -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    # -- session protocol ---------------------------------------------------
    def start_session(self, train_fn: Callable, config: Dict[str, Any],
                      context: TrainContext,
                      latest_checkpoint_path: Optional[str]) -> bool:
        ckpt = (Checkpoint(latest_checkpoint_path)
                if latest_checkpoint_path else None)
        self.session = _TrainSession(train_fn, config, context, ckpt)
        self.session.start()
        return True

    def next_result(self):
        """Blocks until the session produces the next report/final event."""
        assert self.session is not None, "session not started"
        item = self.session.next_result(timeout=3600)
        return item

    def shutdown_session(self):
        self.session = None
        return True


class WorkerGroup:
    def __init__(self, num_workers: int,
                 resources_per_worker: Dict[str, float],
                 placement_group=None):
        from ray_tpu.util.scheduling_strategies import (
            PlacementGroupSchedulingStrategy,
        )

        self.num_workers = num_workers
        self.workers: List[Any] = []
        for rank in range(num_workers):
            options: Dict[str, Any] = {
                "num_cpus": resources_per_worker.get("CPU", 1),
                "resources": {k: v for k, v in resources_per_worker.items()
                              if k not in ("CPU", "TPU")},
            }
            if resources_per_worker.get("TPU"):
                options["num_tpus"] = resources_per_worker["TPU"]
            if placement_group is not None:
                options["scheduling_strategy"] = \
                    PlacementGroupSchedulingStrategy(
                        placement_group=placement_group,
                        placement_group_bundle_index=rank)
            self.workers.append(TrainWorker.options(**options).remote(rank))

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        """Run fn on every worker, return all results (ordered by rank)."""
        return ray_tpu.get(
            [w.execute.remote(fn, *args, **kwargs) for w in self.workers],
            timeout=600)

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs) -> Any:
        return ray_tpu.get(
            self.workers[rank].execute.remote(fn, *args, **kwargs),
            timeout=600)

    def metadata(self) -> List[Dict[str, Any]]:
        return ray_tpu.get([w.get_metadata.remote() for w in self.workers],
                           timeout=600)

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
