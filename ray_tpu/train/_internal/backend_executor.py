"""BackendExecutor — owns the worker group + backend lifecycle.

Reference: `train/_internal/backend_executor.py:65,121,427,690`: create a
placement group, start the WorkerGroup inside it, run backend hooks, fan the
training function out, poll per-round results, and restart the whole group
from the latest checkpoint on worker failure.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.train._internal.session import (
    ERRORED, FINISHED, REPORT, TrainContext,
)
from ray_tpu.train._internal.worker_group import WorkerGroup
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.util.placement_group import (
    placement_group, remove_placement_group,
)


class TrainingFailedError(exc.RayTpuError):
    pass


class BackendExecutor:
    def __init__(self, backend_config: BackendConfig,
                 scaling_config: ScalingConfig, run_config: RunConfig,
                 experiment_dir: str):
        self._backend_config = backend_config
        self._backend = backend_config.backend_cls()
        self._scaling = scaling_config
        self._run_config = run_config
        self._experiment_dir = experiment_dir
        self.worker_group: Optional[WorkerGroup] = None
        self._pg = None
        self._finished: List[bool] = []

    # ------------------------------------------------------------------ start
    def start(self) -> None:
        n = self._scaling.num_workers
        if self._scaling.topology:
            # Claim a whole pod slice through its gang head resource, then
            # spread one worker per slice host (reference tpu.py:335 pattern
            # promoted into the trainer).
            from ray_tpu.accelerators.tpu import pod_head_resource

            bundles = [dict(self._scaling.bundle(),
                            **pod_head_resource(self._scaling.topology))]
            bundles += [self._scaling.bundle() for _ in range(n - 1)]
            self._pg = placement_group(bundles, strategy="STRICT_SPREAD")
        elif n > 1:
            self._pg = placement_group(
                [self._scaling.bundle() for _ in range(n)],
                strategy=self._scaling.placement_strategy)
        if self._pg is not None and not self._pg.wait(120):
            raise TrainingFailedError(
                f"placement group for {n} workers with bundles "
                f"{self._scaling.bundle()} could not be scheduled")
        self.worker_group = WorkerGroup(n, self._scaling.worker_resources(),
                                        self._pg)
        self._backend.on_start(self.worker_group, self._backend_config)

    # --------------------------------------------------------------- training
    def start_training(self, train_fn: Callable, config: Dict[str, Any],
                       latest_checkpoint_path: Optional[str]) -> None:
        self._backend.on_training_start(self.worker_group,
                                        self._backend_config)
        n = self.worker_group.num_workers
        node_ids = [m["node_id"] for m in self.worker_group.metadata()]
        local_ranks: Dict[str, int] = {}
        contexts = []
        node_rank_map: Dict[str, int] = {}
        for rank in range(n):
            node = node_ids[rank]
            local_rank = local_ranks.get(node, 0)
            local_ranks[node] = local_rank + 1
            if node not in node_rank_map:
                node_rank_map[node] = len(node_rank_map)
            contexts.append(TrainContext(
                world_rank=rank, world_size=n, local_rank=local_rank,
                local_world_size=0, node_rank=node_rank_map[node],
                experiment_name=os.path.basename(self._experiment_dir),
                storage_dir=self._experiment_dir))
        for ctx in contexts:
            ctx.local_world_size = local_ranks[node_ids[ctx.world_rank]]
        ray_tpu.get([
            w.start_session.remote(train_fn, config, contexts[rank],
                                   latest_checkpoint_path)
            for rank, w in enumerate(self.worker_group.workers)
        ], timeout=600)
        self._finished = [False] * n

    def get_next_results(self, timeout: float = 3600.0
                         ) -> Optional[List[tuple]]:
        """One lockstep round: every unfinished worker's next event.
        Returns None when all workers have finished. Raises on worker crash
        or training-function error."""
        if all(self._finished):
            return None
        refs = [
            w.next_result.remote()
            for w, done in zip(self.worker_group.workers, self._finished)
            if not done
        ]
        items = ray_tpu.get(refs, timeout=timeout)  # raises on actor death
        results = []
        idx = 0
        for rank in range(self.worker_group.num_workers):
            if self._finished[rank]:
                continue
            kind, payload, ckpt = items[idx]
            idx += 1
            if kind == ERRORED:
                raise TrainingFailedError(
                    f"training function failed on worker {rank}:\n{payload}")
            if kind == FINISHED:
                self._finished[rank] = True
            else:
                results.append((rank, payload, ckpt))
        if not results and all(self._finished):
            return None
        return results

    # ---------------------------------------------------------------- restart
    def restart(self) -> None:
        """Tear down and rebuild the gang (reference `_restart` at
        backend_executor.py:690). On TPU this is the failure-containment
        path: a dead host hangs the whole pjit gang, so the executor kills
        and re-creates ALL workers, then training resumes from the latest
        checkpoint."""
        self.shutdown(remove_pg=False)
        self.worker_group = WorkerGroup(
            self._scaling.num_workers, self._scaling.worker_resources(),
            self._pg)
        self._backend.on_start(self.worker_group, self._backend_config)

    def shutdown(self, remove_pg: bool = True) -> None:
        if self.worker_group is not None:
            try:
                self._backend.on_shutdown(self.worker_group,
                                          self._backend_config)
            except Exception:
                pass
            self.worker_group.shutdown()
            self.worker_group = None
        if remove_pg and self._pg is not None:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None
