"""Sharded (multi-host) checkpointing with resharding on restore.

TPU-native gap the reference's `train/_internal/storage.py` never had to
solve: a pjit-sharded train state lives distributed over a device mesh —
each host holds only its addressable shards, and a checkpoint written on
one mesh shape (say dp2 x tp4) must restore onto another (dp1 x tp8) when
the pod topology changes.

Format (orbax-style, content kept dependency-free):

    <dir>/meta.pkl             treedef + per-leaf global shape/dtype
    <dir>/shards-p{K}.npz      host K's pieces: key "leaf{i}.s{j}" -> array
    <dir>/index-p{K}.pkl       key -> (leaf index, global slice tuple)

Save: every host writes exactly its addressable shards (no gather, no
replicated duplication — piece lists are deduped by slice). Restore:
`jax.make_array_from_callback` asks each device for its slice under the
NEW sharding; the assembler cuts that slice out of whatever saved pieces
overlap it, so any source mesh reshards onto any target mesh.
"""

from __future__ import annotations

import glob
import os
import pickle
from typing import Any, Dict, Optional, Tuple

import numpy as np


def _norm_index(index, shape) -> Tuple[Tuple[int, int], ...]:
    """An addressable-shard index (tuple of slices) -> ((start, stop), ...)."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else sl.start
        stop = dim if sl.stop is None else sl.stop
        out.append((int(start), int(stop)))
    return tuple(out)


def save_sharded(tree: Any, ckpt_dir: str,
                 process_index: Optional[int] = None,
                 extra_meta: Optional[Dict[str, Any]] = None) -> None:
    """Write this host's pieces of a (possibly sharded) pytree.

    Call from EVERY host of the mesh (each writes its own shard file into
    the shared directory); single-host callers just write everything.
    """
    import jax

    proc = jax.process_index() if process_index is None else process_index
    os.makedirs(ckpt_dir, exist_ok=True)
    leaves, treedef = jax.tree.flatten(tree)

    pieces: Dict[str, np.ndarray] = {}
    index: Dict[str, Tuple[int, Tuple[Tuple[int, int], ...]]] = {}
    meta_leaves = []
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, jax.Array):
            shape, dtype = tuple(leaf.shape), np.dtype(leaf.dtype)
            seen = set()
            j = 0
            for shard in leaf.addressable_shards:
                span = _norm_index(shard.index, shape)
                if span in seen:
                    continue  # replicated copy; one write is enough
                seen.add(span)
                key = f"leaf{i}.s{j}"
                pieces[key] = np.asarray(shard.data)
                index[key] = (i, span)
                j += 1
        else:
            arr = np.asarray(leaf)
            shape, dtype = tuple(arr.shape), arr.dtype
            if proc == 0:
                key = f"leaf{i}.s0"
                pieces[key] = arr
                index[key] = (i, tuple((0, d) for d in shape))
        meta_leaves.append({"shape": shape, "dtype": dtype})

    np.savez(os.path.join(ckpt_dir, f"shards-p{proc}.npz"), **pieces)
    with open(os.path.join(ckpt_dir, f"index-p{proc}.pkl"), "wb") as f:
        pickle.dump(index, f)
    if proc == 0:
        with open(os.path.join(ckpt_dir, "meta.pkl"), "wb") as f:
            pickle.dump({"treedef": treedef, "leaves": meta_leaves,
                         "extra": extra_meta or {}}, f)


def load_meta(ckpt_dir: str) -> Dict[str, Any]:
    with open(os.path.join(ckpt_dir, "meta.pkl"), "rb") as f:
        return pickle.load(f)


class _PieceReader:
    """All saved pieces of one checkpoint, lazily opened per process."""

    def __init__(self, ckpt_dir: str):
        self._stores = []
        for idx_path in sorted(glob.glob(
                os.path.join(ckpt_dir, "index-p*.pkl"))):
            proc = os.path.basename(idx_path)[len("index-p"):-len(".pkl")]
            with open(idx_path, "rb") as f:
                index = pickle.load(f)
            npz = np.load(os.path.join(ckpt_dir, f"shards-p{proc}.npz"),
                          mmap_mode=None)
            self._stores.append((index, npz))
        # leaf -> [(span, store, key)]
        self._by_leaf: Dict[int, list] = {}
        for index, npz in self._stores:
            for key, (leaf_i, span) in index.items():
                self._by_leaf.setdefault(leaf_i, []).append((span, npz, key))

    def read_slice(self, leaf_i: int, span: Tuple[Tuple[int, int], ...],
                   shape, dtype) -> np.ndarray:
        """Assemble the requested global slice from overlapping pieces."""
        out = np.empty([b - a for a, b in span], dtype=dtype)
        filled = 0
        for piece_span, npz, key in self._by_leaf.get(leaf_i, []):
            inter = []
            for (ra, rb), (pa, pb) in zip(span, piece_span):
                a, b = max(ra, pa), min(rb, pb)
                if a >= b:
                    inter = None
                    break
                inter.append((a, b))
            if inter is None:
                continue
            data = npz[key]
            src = tuple(slice(a - pa, b - pa)
                        for (a, b), (pa, _pb) in zip(inter, piece_span))
            dst = tuple(slice(a - ra, b - ra)
                        for (a, b), (ra, _rb) in zip(inter, span))
            out[dst] = data[src]
            filled += int(np.prod([b - a for a, b in inter]))
        if filled < out.size:
            raise ValueError(
                f"checkpoint is missing data for leaf {leaf_i} slice {span} "
                f"({filled}/{out.size} elements found) — were all hosts' "
                "shard files written into the checkpoint directory?")
        return out


def load_sharded(ckpt_dir: str, shardings: Any = None) -> Any:
    """Restore a pytree saved by `save_sharded` onto NEW shardings.

    `shardings`: a pytree (matching the saved structure) of
    `jax.sharding.Sharding` for device placement — or None for host numpy
    arrays. Any source/target mesh combination works: each device's slice
    under the target sharding is cut from the saved pieces.
    """
    import jax

    meta = load_meta(ckpt_dir)
    reader = _PieceReader(ckpt_dir)
    treedef = meta["treedef"]
    n = len(meta["leaves"])

    # None marks "restore as host numpy" — keep it as a leaf (default
    # flattening treats None as an empty subtree and drops it).
    shard_leaves = (None if shardings is None
                    else jax.tree.flatten(
                        shardings, is_leaf=lambda x: x is None)[0])
    if shard_leaves is not None and len(shard_leaves) != n:
        raise ValueError(
            f"shardings tree has {len(shard_leaves)} leaves; checkpoint "
            f"has {n}")

    out_leaves = []
    for i in range(n):
        info = meta["leaves"][i]
        shape, dtype = info["shape"], info["dtype"]
        if shard_leaves is None or shard_leaves[i] is None:
            out_leaves.append(
                reader.read_slice(i, tuple((0, d) for d in shape),
                                  shape, dtype))
            continue
        sharding = shard_leaves[i]

        def cb(index, _i=i, _shape=shape, _dtype=dtype):
            span = _norm_index(index, _shape)
            return reader.read_slice(_i, span, _shape, _dtype)

        out_leaves.append(
            jax.make_array_from_callback(shape, sharding, cb))
    return jax.tree.unflatten(treedef, out_leaves)
