"""Trainers.

Reference: `train/base_trainer.py:567` (`BaseTrainer.fit`),
`train/data_parallel_trainer.py` (`DataParallelTrainer`). The TPU-native
`JaxTrainer` = DataParallelTrainer + JaxConfig: N worker processes, one per
TPU host, forming a single jax.distributed gang; the training loop runs
pjit'd SPMD steps over the pod's global mesh.

`fit()` runs the trial inline (the Tune-equivalent's Tuner can also wrap any
trainer via `as_trainable()` — see ray_tpu.tune).
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import time
import uuid
from typing import Any, Callable, Dict, Optional

from ray_tpu.train._internal.backend_executor import (
    BackendExecutor, TrainingFailedError,
)
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import (
    CheckpointConfig, FailureConfig, Result, RunConfig, ScalingConfig,
)


class DataParallelTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        backend_config: Optional[BackendConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self._train_fn = train_loop_per_worker
        self._config = train_loop_config or {}
        self._backend_config = backend_config or BackendConfig()
        self._scaling = scaling_config or ScalingConfig()
        self._run_config = run_config or RunConfig()
        self._datasets = datasets or {}
        self._resume_checkpoint = resume_from_checkpoint

    # ------------------------------------------------------------------- fit
    def fit(self) -> Result:
        """Run as a single-trial Tune experiment (reference
        `base_trainer.py:567`: every Trainer.fit wraps itself in a Tuner)."""
        from ray_tpu.tune.tuner import TuneConfig, Tuner

        name = self._run_config.name or f"train_{uuid.uuid4().hex[:8]}"
        run_config = dataclasses.replace(self._run_config, name=name)
        tuner = Tuner(
            self,
            tune_config=TuneConfig(num_samples=1, max_concurrent_trials=1),
            run_config=run_config)
        result = tuner.fit()[0]
        if result.error:
            raise TrainingFailedError(str(result.error))
        if (run_config.is_remote_storage() and result.checkpoint is not None
                and result.checkpoint.uri is None):
            # Tune rebuilds results from trial state with bare local
            # paths; rehydrate the storage URI from the mirrored layout.
            from ray_tpu.train.storage import StorageContext

            staging_root = run_config.resolved_storage_path()
            rel = os.path.relpath(result.checkpoint.path, staging_root)
            storage = StorageContext(
                run_config.storage_path or "",
                filesystem=run_config.storage_filesystem)
            result.checkpoint.uri = storage.uri_for(*rel.split(os.sep))
            result.checkpoint._fs = storage.fs
        return result

    def _run_training(self, experiment_dir: str,
                      on_report=None) -> Result:
        """The training orchestration loop (runs inside the trial)."""
        os.makedirs(experiment_dir, exist_ok=True)
        # Remote storage: checkpoints stage locally under experiment_dir
        # and sync to the pyarrow filesystem after each report.
        self._storage = None
        if self._run_config.is_remote_storage():
            from ray_tpu.train.storage import StorageContext

            # Mirror the local staging layout (<name>/<trial>/...) so a
            # checkpoint's URI is derivable from its staging path.
            rel = os.path.relpath(
                experiment_dir, self._run_config.resolved_storage_path())
            self._storage = StorageContext(
                self._run_config.storage_path or "",
                "/".join(rel.split(os.sep)) if rel != "." else "",
                filesystem=self._run_config.storage_filesystem)
            self._storage.makedirs()

        executor = BackendExecutor(self._backend_config, self._scaling,
                                   self._run_config, experiment_dir)
        failures = 0
        max_failures = self._run_config.failure_config.max_failures
        latest_ckpt_path = (self._resume_checkpoint.path
                            if self._resume_checkpoint else None)
        history: list = []
        checkpoints: list = []  # (score, path) for top-k retention
        last_metrics: Dict[str, Any] = {}
        error: Optional[BaseException] = None

        executor.start()
        try:
            while True:
                try:
                    self._start_and_poll(executor, latest_ckpt_path, history,
                                         checkpoints, on_report)
                    break  # finished cleanly
                except (TrainingFailedError, Exception) as e:  # noqa: BLE001
                    if history:
                        last_metrics = history[-1]
                    if checkpoints:
                        latest_ckpt_path = checkpoints[-1][1]
                    failures += 1
                    if max_failures >= 0 and failures > max_failures:
                        error = e
                        break
                    executor.restart()
        finally:
            executor.shutdown()

        if history:
            last_metrics = history[-1]
        latest = None
        if checkpoints:
            local = checkpoints[-1][1]
            latest = Checkpoint(local)
            if self._storage is not None:
                latest.uri = self._storage.uri_for(os.path.basename(local))
                latest._fs = self._storage.fs
        elif latest_ckpt_path:
            latest = Checkpoint(latest_ckpt_path)
        if error is not None:
            raise TrainingFailedError(
                f"training failed after {failures} failure(s); "
                f"last metrics {last_metrics}") from error
        return Result(metrics=last_metrics, checkpoint=latest,
                      path=experiment_dir, metrics_dataframe=history)

    def _start_and_poll(self, executor: BackendExecutor,
                        latest_ckpt_path: Optional[str], history: list,
                        checkpoints: list, on_report=None) -> None:
        config = dict(self._config)
        if self._datasets:
            config["__datasets__"] = self._shard_datasets(executor)
        executor.start_training(self._train_fn, config, latest_ckpt_path)
        ckpt_cfg = self._run_config.checkpoint_config
        while True:
            results = executor.get_next_results()
            if results is None:
                return
            reports = {rank: (metrics, ckpt)
                       for rank, metrics, ckpt in results}
            if not reports:
                continue
            # Rank 0's metrics are authoritative (reference semantics).
            rank0 = min(reports)
            metrics, _ = reports[rank0]
            if metrics is not None:
                metrics = dict(metrics)
                metrics.setdefault("training_iteration", len(history) + 1)
                metrics["timestamp"] = time.time()
                history.append(metrics)
            new_ckpt = None
            for rank, (_, ckpt_path) in sorted(reports.items()):
                if ckpt_path is not None:
                    score = None
                    if ckpt_cfg.checkpoint_score_attribute and metrics:
                        score = metrics.get(
                            ckpt_cfg.checkpoint_score_attribute)
                    checkpoints.append((score, ckpt_path))
                    new_ckpt = ckpt_path
                    if self._storage is not None:
                        self._storage.upload_dir(
                            ckpt_path, os.path.basename(ckpt_path))
            # Report before retention: score-based keep-k may evict the
            # checkpoint that was just created, and the consumer must never
            # receive an already-deleted path.
            if on_report is not None and metrics is not None:
                on_report(metrics, new_ckpt)
            self._enforce_keep_k(checkpoints)

    def _enforce_keep_k(self, checkpoints: list) -> None:
        keep = self._run_config.checkpoint_config.num_to_keep
        if keep is None or len(checkpoints) <= keep:
            return
        attr = self._run_config.checkpoint_config.checkpoint_score_attribute
        if attr:
            order = self._run_config.checkpoint_config.checkpoint_score_order
            ranked = sorted(
                checkpoints,
                key=lambda sc: (sc[0] is None,
                                -sc[0] if order == "max" and sc[0] is not None
                                else sc[0] if sc[0] is not None else 0))
            doomed = ranked[keep:]
        else:
            doomed = checkpoints[:-keep]
        for item in doomed:
            if item in checkpoints and len(checkpoints) > keep:
                checkpoints.remove(item)
                # A path may legitimately appear under several retention
                # entries; only delete from disk once no kept entry
                # references it.
                if all(path != item[1] for _, path in checkpoints):
                    shutil.rmtree(item[1], ignore_errors=True)
                    if getattr(self, "_storage", None) is not None:
                        self._storage.delete(os.path.basename(item[1]))

    def _shard_datasets(self, executor: BackendExecutor) -> Dict[str, Any]:
        """Split datasets across workers via streaming_split (Train<->Data
        ingestion, reference `train/_internal/data_config.py:61`)."""
        out = {}
        n = self._scaling.num_workers
        for key, ds in self._datasets.items():
            if hasattr(ds, "streaming_split"):
                out[key] = ds.streaming_split(n)
            else:
                out[key] = [ds] * n
        return out

    def as_trainable(self):
        """Wrap into a Tune-compatible trainable (reference
        base_trainer.py:724): the trial runs this trainer's orchestration
        loop, streaming each worker report to the Tune session so schedulers
        see intermediate results and checkpoints survive trial restarts."""
        trainer = self

        def _trainable(config: Dict[str, Any]):
            import copy

            from ray_tpu import tune
            from ray_tpu.tune import _session as tsession

            t = copy.copy(trainer)
            merged = dict(trainer._config)
            merged.update(config.get("train_loop_config", config))
            t._config = merged

            session = tsession.get_session()
            trial_dir = session.trial_dir if session else os.path.join(
                trainer._run_config.resolved_storage_path(),
                f"train_{uuid.uuid4().hex[:8]}")
            resume = tune.get_checkpoint() if session else None
            if resume is not None:
                t._resume_checkpoint = resume

            def on_report(metrics, ckpt_path):
                if tsession.get_session() is None:
                    return  # running outside a trial: nothing to stream to
                tune.report(metrics,
                            checkpoint=(Checkpoint(ckpt_path)
                                        if ckpt_path else None))

            t._run_training(trial_dir, on_report=on_report)

        _trainable.__name__ = f"{type(self).__name__}_trainable"
        return _trainable


class JaxTrainer(DataParallelTrainer):
    """The flagship TPU trainer (north star: `JaxTrainer`/`JaxBackend`).

    Usage::

        def train_loop(config):
            import jax
            mesh = jax.make_mesh((jax.device_count(),), ("data",))
            ...pjit'd SPMD training; ray_tpu.train.report(...) per epoch...

        trainer = JaxTrainer(
            train_loop,
            scaling_config=ScalingConfig(num_workers=4, use_tpu=True,
                                         chips_per_worker=4),
            jax_config=JaxConfig(),  # platform autodetected
        )
        result = trainer.fit()
    """

    def __init__(self, train_loop_per_worker: Callable, *,
                 jax_config: Optional["Any"] = None, **kwargs):
        from ray_tpu.train.jax_backend import JaxConfig

        backend_config = jax_config or JaxConfig()
        super().__init__(train_loop_per_worker,
                         backend_config=backend_config, **kwargs)


class TorchTrainer(DataParallelTrainer):
    """CPU-torch data-parallel trainer over the same worker-group
    machinery as JaxTrainer (reference: `train/torch/torch_trainer.py`;
    gloo process groups — see `train/torch_backend.py` for why NCCL has
    no role on a TPU stack).

    Usage::

        def train_loop(config):
            import torch.distributed as dist
            model = torch.nn.parallel.DistributedDataParallel(model)
            ...ray_tpu.train.report(...) per epoch...

        TorchTrainer(train_loop,
                     scaling_config=ScalingConfig(num_workers=2)).fit()
    """

    def __init__(self, train_loop_per_worker: Callable, *,
                 torch_config: Optional["Any"] = None, **kwargs):
        from ray_tpu.train.torch_backend import TorchConfig

        backend_config = torch_config or TorchConfig()
        super().__init__(train_loop_per_worker,
                         backend_config=backend_config, **kwargs)
