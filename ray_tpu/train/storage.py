"""Checkpoint storage over pyarrow filesystems.

Reference: `train/_internal/storage.py` (StorageContext) — experiment
artifacts live on a pyarrow `FileSystem`, so the same trainer code
persists to local disk, NFS, or object stores (`s3://`, `gs://`) without
path-specific branches. Multi-host TPU slices need this: every host
writes its checkpoint shard to one shared location.

URIs resolve via `pyarrow.fs.FileSystem.from_uri`; a plain path means the
local filesystem. An explicit `filesystem` argument (e.g. a mock or
fsspec-wrapped one) overrides URI inference — that is also how tests
exercise the remote path without real cloud credentials.
"""

from __future__ import annotations

import os
import posixpath
import shutil
from typing import Optional, Tuple


def resolve(path: str, filesystem=None) -> Tuple[object, str]:
    """(filesystem, fs_path) for a path/URI."""
    import pyarrow.fs as pafs

    if filesystem is not None:
        return filesystem, path
    if "://" in path:
        return pafs.FileSystem.from_uri(path)
    return pafs.LocalFileSystem(), os.path.abspath(path)


def is_uri(path: Optional[str]) -> bool:
    return bool(path) and "://" in path


class StorageContext:
    """One experiment's storage root on a pyarrow filesystem."""

    def __init__(self, storage_path: str, experiment_name: str = "",
                 filesystem=None):
        self.fs, root = resolve(storage_path, filesystem)
        self.root = (posixpath.join(root, experiment_name)
                     if experiment_name else root)
        # FileSystem.from_uri strips the scheme; keep the original URI so
        # checkpoint URIs stay restorable via Checkpoint.from_uri alone.
        base = storage_path if is_uri(storage_path) else None
        self._uri_root = (f"{base.rstrip('/')}/{experiment_name}"
                          if base and experiment_name else base)

    def uri_for(self, *parts: str) -> str:
        """Full URI (scheme included when one exists) for a storage
        entry; falls back to the fs path for explicit-filesystem use."""
        root = self._uri_root if self._uri_root else self.root
        return "/".join([root.rstrip("/"), *parts]) if parts else root

    # ----------------------------------------------------------------- paths
    def join(self, *parts: str) -> str:
        return posixpath.join(self.root, *parts)

    def makedirs(self, rel: str = "") -> None:
        self.fs.create_dir(self.join(rel) if rel else self.root,
                           recursive=True)

    def exists(self, rel: str) -> bool:
        import pyarrow.fs as pafs

        return self.fs.get_file_info(self.join(rel)).type \
            != pafs.FileType.NotFound

    def delete(self, rel: str) -> None:
        try:
            self.fs.delete_dir(self.join(rel))
        except (FileNotFoundError, OSError):
            pass

    # ------------------------------------------------------------- transfer
    def upload_dir(self, local_dir: str, rel: str) -> str:
        """Recursively copy a local directory into storage; returns the
        destination fs path."""
        dest_root = self.join(rel)
        self.fs.create_dir(dest_root, recursive=True)
        for dirpath, _dirnames, filenames in os.walk(local_dir):
            rel_dir = os.path.relpath(dirpath, local_dir)
            fs_dir = (dest_root if rel_dir == "."
                      else posixpath.join(dest_root, *rel_dir.split(os.sep)))
            if rel_dir != ".":
                self.fs.create_dir(fs_dir, recursive=True)
            for name in filenames:
                with open(os.path.join(dirpath, name), "rb") as src, \
                        self.fs.open_output_stream(
                            posixpath.join(fs_dir, name)) as dst:
                    shutil.copyfileobj(src, dst, 1 << 20)
        return dest_root


def download_dir(fs, fs_path: str, local_dir: str) -> str:
    """Recursively copy a storage directory to a local one."""
    import pyarrow.fs as pafs

    os.makedirs(local_dir, exist_ok=True)
    selector = pafs.FileSelector(fs_path, recursive=True)
    for info in fs.get_file_info(selector):
        rel = posixpath.relpath(info.path, fs_path)
        local = os.path.join(local_dir, *rel.split("/"))
        if info.type == pafs.FileType.Directory:
            os.makedirs(local, exist_ok=True)
        else:
            os.makedirs(os.path.dirname(local), exist_ok=True)
            with fs.open_input_stream(info.path) as src, \
                    open(local, "wb") as dst:
                shutil.copyfileobj(src, dst, 1 << 20)
    return local_dir
