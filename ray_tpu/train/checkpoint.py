"""Checkpoint — a directory of files, with jax-pytree conveniences.

Reference: `train/_checkpoint.py:56` (a directory on a pyarrow filesystem).
Here: a local/NFS/gcsfuse directory path. Pytree save/restore uses
orbax-style flat numpy ``.npz`` plus pickled structure — simple, portable,
and jax-native (no torch state_dicts).
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import uuid
from typing import Any, Dict, Optional


class Checkpoint:
    def __init__(self, path: Optional[str] = None, *,
                 uri: Optional[str] = None, filesystem=None):
        if path is None and uri is None:
            raise ValueError("Checkpoint needs a path or a uri")
        self._local_path = os.path.abspath(path) if path else None
        self.uri = uri
        self._fs = filesystem

    @property
    def path(self) -> str:
        """Local directory (lazily downloaded from storage when this
        checkpoint lives on a remote pyarrow filesystem)."""
        if self._local_path is None:
            from ray_tpu.train.storage import download_dir, resolve

            fs, fs_path = resolve(self.uri, self._fs)
            local = tempfile.mkdtemp(prefix="rtpu-ckpt-dl-")
            download_dir(fs, fs_path, local)
            self._local_path = local
        return self._local_path

    # -- construction -------------------------------------------------------
    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_uri(cls, uri: str, filesystem=None) -> "Checkpoint":
        """A checkpoint stored on a (possibly remote) pyarrow filesystem
        (reference: `Checkpoint.from_uri`)."""
        return cls(uri=uri, filesystem=filesystem)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        d = tempfile.mkdtemp(prefix="rtpu-ckpt-")
        with open(os.path.join(d, "data.pkl"), "wb") as f:
            pickle.dump(data, f)
        return cls(d)

    @classmethod
    def from_pytree(cls, tree: Any) -> "Checkpoint":
        """Save a jax pytree (params/opt state) as npz + structure."""
        import jax
        import numpy as np

        d = tempfile.mkdtemp(prefix="rtpu-ckpt-")
        leaves, treedef = jax.tree.flatten(tree)
        np.savez(os.path.join(d, "arrays.npz"),
                 **{str(i): np.asarray(leaf) for i, leaf in enumerate(leaves)})
        with open(os.path.join(d, "treedef.pkl"), "wb") as f:
            pickle.dump(treedef, f)
        return cls(d)

    # -- reading ------------------------------------------------------------
    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None:
            return self.path
        shutil.copytree(self.path, path, dirs_exist_ok=True)
        return path

    def as_directory(self):
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            yield self.path

        return _ctx()

    def to_dict(self) -> Dict[str, Any]:
        with open(os.path.join(self.path, "data.pkl"), "rb") as f:
            return pickle.load(f)

    def to_pytree(self) -> Any:
        import jax
        import numpy as np

        data = np.load(os.path.join(self.path, "arrays.npz"))
        leaves = [data[str(i)] for i in range(len(data.files))]
        with open(os.path.join(self.path, "treedef.pkl"), "rb") as f:
            treedef = pickle.load(f)
        return jax.tree.unflatten(treedef, leaves)

    # -- persistence --------------------------------------------------------
    def persist(self, storage_dir: str, name: Optional[str] = None) -> "Checkpoint":
        """Copy into experiment storage; returns the persisted checkpoint.

        Atomic: stage into a dot-prefixed tmp dir + rename, so a process
        killed mid-copy never leaves a torn `checkpoint_*` directory for
        crash recovery to pick up."""
        os.makedirs(storage_dir, exist_ok=True)
        dest = os.path.join(storage_dir,
                            name or f"checkpoint_{uuid.uuid4().hex[:8]}")
        if os.path.abspath(dest) == self.path:
            return Checkpoint(dest)
        tmp = os.path.join(storage_dir,
                           f".tmp_{os.path.basename(dest)}_{os.getpid()}")
        shutil.rmtree(tmp, ignore_errors=True)
        shutil.copytree(self.path, tmp)
        shutil.rmtree(dest, ignore_errors=True)  # relaunch overwrote name
        os.rename(tmp, dest)
        return Checkpoint(dest)

    def to_uri(self, uri: str, filesystem=None) -> "Checkpoint":
        """Upload into storage; returns the storage-backed checkpoint."""
        from ray_tpu.train.storage import StorageContext

        storage = StorageContext(uri, filesystem=filesystem)
        storage.makedirs()
        storage.upload_dir(self.path, "")
        return Checkpoint(uri=uri, filesystem=filesystem)

    def __repr__(self):
        if self._local_path is None:
            return f"Checkpoint(uri={self.uri})"
        return f"Checkpoint({self._local_path})"
