"""Public exception types (reference: `python/ray/exceptions.py`)."""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all ray_tpu errors."""


class RayTaskError(RayTpuError):
    """Wraps an exception raised inside a remote task/actor method.

    Re-raised on `get()` at the caller, carrying the remote traceback.
    """

    def __init__(self, cause: BaseException, remote_traceback: str = "",
                 task_name: str = ""):
        self.cause = cause
        self.remote_traceback = remote_traceback
        self.task_name = task_name
        super().__init__(
            f"task {task_name or '<unknown>'} failed: "
            f"{type(cause).__name__}: {cause}\n"
            f"--- remote traceback ---\n{remote_traceback}"
        )

    def as_instanceof_cause(self) -> BaseException:
        """Return an exception that isinstance-matches the original cause but
        still carries the remote traceback when displayed."""
        cause = self.cause
        if isinstance(cause, RayTaskError):
            return cause
        try:
            cls = type(cause)
            new = RayTaskError.__new__(RayTaskError)
            # Dynamic subclass so `except OriginalError` works at the caller.
            derived = type(
                "RayTaskError(" + cls.__name__ + ")", (RayTaskError, cls), {})
            new.__class__ = derived
            new.cause = cause
            new.remote_traceback = self.remote_traceback
            new.task_name = self.task_name
            new.args = (str(self),)
            return new
        except TypeError:
            return self


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ActorDiedError(RayTpuError):
    """The actor is dead; calls can never succeed."""


class ActorUnavailableError(RayTpuError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """`get()` exceeded its timeout."""


class ObjectLostError(RayTpuError):
    """The object's value was lost from every node and cannot be recovered."""


class OwnerDiedError(ObjectLostError):
    """The object's owner died, poisoning the object (reference semantics:
    owner failure fails all objects it owns)."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled via `cancel()`."""


class ObjectStoreFullError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class RaySystemError(RayTpuError):
    pass


class OutOfMemoryError(WorkerCrashedError):
    """Raised when a worker was OOM-killed by the raylet memory monitor
    (reference: `ray.exceptions.OutOfMemoryError`). Subclasses
    WorkerCrashedError so existing retry/except paths keep working."""
