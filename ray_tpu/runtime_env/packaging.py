"""Content-addressed code packages for runtime_env distribution.

Role-equivalent to the reference's `_private/runtime_env/packaging.py`:
a local ``working_dir`` / ``py_modules`` directory is zipped
deterministically, named by its content hash (``gcs://_rtpu_pkg_<sha>.zip``),
uploaded once to the GCS KV store, and downloaded + unpacked into each
node's cache on demand. Identical directory contents on any driver yield
the same URI, so re-submission reuses the cached package cluster-wide.
"""

from __future__ import annotations

import hashlib
import io
import os
import zipfile
from typing import List, Optional

# Reference parity: packaging.py caps packages to protect the GCS
# (GCS_STORAGE_MAX_SIZE); ours rides the RPC frame, same concern.
MAX_PACKAGE_BYTES = 100 * 1024 * 1024

_KV_NAMESPACE = "runtime_env_pkg"

_DEFAULT_EXCLUDES = {"__pycache__", ".git", ".venv", "node_modules"}


def _iter_files(root: str, excludes: Optional[List[str]] = None):
    ex = _DEFAULT_EXCLUDES | set(excludes or [])
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in ex)
        for name in sorted(filenames):
            if name in ex or name.endswith(".pyc"):
                continue
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, root)
            yield full, rel


def package_dir(root: str, excludes: Optional[List[str]] = None,
                include_root_name: bool = False) -> tuple:
    """Zip a directory deterministically; returns (uri, zip_bytes).

    ``include_root_name`` puts entries under ``<basename(root)>/...`` —
    used for py_modules so the unpacked tree is importable by its name
    (working_dir packages the contents directly, cwd IS the dir).
    """
    root = os.path.abspath(root)
    prefix = os.path.basename(root.rstrip(os.sep)) + "/" \
        if include_root_name else ""
    buf = io.BytesIO()
    hasher = hashlib.sha256()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for full, rel in _iter_files(root, excludes):
            with open(full, "rb") as f:
                data = f.read()
            hasher.update((prefix + rel).encode())
            hasher.update(data)
            # Fixed timestamp => byte-stable zip for identical content.
            info = zipfile.ZipInfo(prefix + rel,
                                   date_time=(2020, 1, 1, 0, 0, 0))
            info.external_attr = (os.stat(full).st_mode & 0xFFFF) << 16
            zf.writestr(info, data)
    payload = buf.getvalue()
    if len(payload) > MAX_PACKAGE_BYTES:
        raise ValueError(
            f"runtime_env package for {root} is {len(payload)} bytes; "
            f"limit is {MAX_PACKAGE_BYTES} (use excludes or py_modules)")
    uri = f"gcs://_rtpu_pkg_{hasher.hexdigest()[:32]}.zip"
    return uri, payload


def package_wheel(path: str) -> tuple:
    """Content-address a single .whl file; returns (uri, bytes)."""
    with open(path, "rb") as f:
        payload = f.read()
    sha = hashlib.sha256(payload).hexdigest()[:32]
    uri = f"gcs://_rtpu_whl_{sha}_{os.path.basename(path)}"
    return uri, payload


def upload_package(gcs_client, uri: str, payload: bytes) -> None:
    """Idempotent upload into the GCS KV (driver side)."""
    if not gcs_client.call("kv_exists", namespace=_KV_NAMESPACE, key=uri,
                           timeout=30):
        gcs_client.call("kv_put", namespace=_KV_NAMESPACE, key=uri,
                        value=payload, overwrite=False, timeout=60)


async def download_package(gcs_aclient, uri: str) -> bytes:
    payload = await gcs_aclient.acall("kv_get", namespace=_KV_NAMESPACE,
                                      key=uri, timeout=60)
    if payload is None:
        raise FileNotFoundError(f"runtime_env package {uri} not in GCS")
    return payload


def unpack_package(payload: bytes, dest: str) -> str:
    """Extract a package zip into dest (idempotent via done-marker)."""
    marker = os.path.join(dest, ".rtpu_pkg_ready")
    if os.path.exists(marker):
        return dest
    os.makedirs(dest, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(payload)) as zf:
        zf.extractall(dest)
    with open(marker, "w") as f:
        f.write("ok")
    return dest


def is_package_uri(s: str) -> bool:
    return isinstance(s, str) and s.startswith("gcs://")
