"""Runtime environments — per-task/actor/job Python environments.

Role-equivalent to the reference's `python/ray/runtime_env/` +
`python/ray/_private/runtime_env/` (see its ARCHITECTURE.md): a runtime_env
is a declarative dict attached to a job, actor, or task; the raylet
materializes it on worker-pool miss (venvs, unpacked code packages) and
spawns the worker inside it. Environments are content-addressed (URIs), so
identical specs share one materialization, and unreferenced URIs are
garbage-collected from the node cache.

Supported fields (reference parity: `runtime_env.py` schema):

- ``env_vars``: {str: str} exported into the worker process.
- ``working_dir``: local directory (packaged + uploaded to the GCS so
  remote nodes can download it) or an existing ``gcs://`` package URI;
  workers start with cwd inside the unpacked copy.
- ``py_modules``: list of local module directories / ``.whl`` files /
  ``gcs://`` URIs, prepended to the worker's PYTHONPATH.
- ``pip``: list of requirement strings (or {"packages": [...]} dict, or a
  path to a requirements.txt). Materialized as a virtualenv keyed by the
  content hash; the worker runs under its interpreter. Built with
  ``--system-site-packages`` so the host's preinstalled stack stays
  importable (and creation works offline for local wheel paths).
- ``conda``: not supported in this image (no conda binary) — raises at
  validation, matching the fail-fast behavior of the reference when the
  backing tool is missing.
- ``container``: {"image": ..., "run_options": [...]} — worker is spawned
  through the runtime named by RAY_TPU_CONTAINER_RUNTIME (podman/docker).
  Validation fails fast when no runtime is configured.
- ``config``: {"setup_timeout_seconds": int, "eager_install": bool}.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

_SUPPORTED = {"env_vars", "working_dir", "py_modules", "pip", "conda",
              "container", "config", "excludes"}


class RuntimeEnvValidationError(ValueError):
    pass


def validate_runtime_env(env: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Normalize + validate a runtime_env dict (reference:
    `runtime_env.py` __init__ validation). Returns the normalized dict."""
    if not env:
        return {}
    if isinstance(env, RuntimeEnv):
        env = dict(env)
    if not isinstance(env, dict):
        raise RuntimeEnvValidationError(
            f"runtime_env must be a dict, got {type(env).__name__}")
    unknown = set(env) - _SUPPORTED
    if unknown:
        raise RuntimeEnvValidationError(
            f"unsupported runtime_env field(s) {sorted(unknown)}; "
            f"supported: {sorted(_SUPPORTED)}")
    out: Dict[str, Any] = {}
    if env.get("env_vars"):
        ev = env["env_vars"]
        if not isinstance(ev, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in ev.items()):
            raise RuntimeEnvValidationError(
                "env_vars must be a Dict[str, str]")
        out["env_vars"] = dict(ev)
    if env.get("working_dir") is not None:
        wd = env["working_dir"]
        if not isinstance(wd, str):
            raise RuntimeEnvValidationError("working_dir must be a str")
        if not wd.startswith("gcs://") and not os.path.isdir(wd):
            raise RuntimeEnvValidationError(
                f"working_dir {wd!r} is not a directory or gcs:// URI")
        out["working_dir"] = wd
    if env.get("py_modules") is not None:
        mods = env["py_modules"]
        if not isinstance(mods, (list, tuple)):
            raise RuntimeEnvValidationError("py_modules must be a list")
        for m in mods:
            if not isinstance(m, str):
                raise RuntimeEnvValidationError(
                    "py_modules entries must be str paths or gcs:// URIs")
            if (not m.startswith("gcs://") and not os.path.isdir(m)
                    and not (os.path.isfile(m) and m.endswith(".whl"))):
                raise RuntimeEnvValidationError(
                    f"py_modules entry {m!r} is not a module directory, "
                    ".whl file, or gcs:// URI")
        out["py_modules"] = list(mods)
    if env.get("pip") is not None:
        out["pip"] = _normalize_pip(env["pip"])
    if env.get("conda") is not None:
        raise RuntimeEnvValidationError(
            "runtime_env 'conda' is not supported in this build (no conda "
            "binary in the image); use 'pip' with wheel paths instead")
    if env.get("container") is not None:
        c = env["container"]
        if not isinstance(c, dict) or "image" not in c:
            raise RuntimeEnvValidationError(
                "container must be a dict with an 'image' key")
        if not os.environ.get("RAY_TPU_CONTAINER_RUNTIME"):
            raise RuntimeEnvValidationError(
                "runtime_env 'container' requires RAY_TPU_CONTAINER_RUNTIME "
                "to name a container runtime (e.g. podman) on every node")
        out["container"] = dict(c)
    if env.get("config"):
        out["config"] = dict(env["config"])
    if env.get("excludes"):
        out["excludes"] = list(env["excludes"])
    return out


def _normalize_pip(pip: Any) -> Dict[str, Any]:
    if isinstance(pip, str):
        # Path to a requirements.txt.
        if not os.path.isfile(pip):
            raise RuntimeEnvValidationError(
                f"pip requirements file {pip!r} not found")
        with open(pip) as f:
            packages = [line.strip() for line in f
                        if line.strip() and not line.startswith("#")]
        return {"packages": packages}
    if isinstance(pip, (list, tuple)):
        if not all(isinstance(p, str) for p in pip):
            raise RuntimeEnvValidationError("pip list entries must be str")
        return {"packages": list(pip)}
    if isinstance(pip, dict):
        if "packages" not in pip:
            raise RuntimeEnvValidationError(
                "pip dict form requires a 'packages' key")
        return {"packages": list(pip["packages"]),
                **{k: v for k, v in pip.items() if k != "packages"}}
    raise RuntimeEnvValidationError(
        f"pip must be a list, dict, or requirements path; got {type(pip)}")


class RuntimeEnv(dict):
    """Typed wrapper (reference: `ray.runtime_env.RuntimeEnv`). Behaves as
    the validated dict; construction validates eagerly."""

    def __init__(self, **kwargs):
        super().__init__(validate_runtime_env(kwargs))

    def to_dict(self) -> Dict[str, Any]:
        return dict(self)


__all__ = ["RuntimeEnv", "RuntimeEnvValidationError", "validate_runtime_env"]
