"""Node-side runtime_env materialization + URI cache with GC.

Role-equivalent to the reference's runtime_env agent
(`_private/runtime_env/agent/runtime_env_agent.py` + plugins `pip.py`,
`working_dir.py`, `py_modules.py`, `container.py`): the raylet asks this
manager to materialize a validated runtime_env before spawning a worker
into it. Each resource is content-addressed:

- pip venvs live under ``<base>/pip/<hash-of-packages>``
- packages (working_dir / py_modules) under ``<base>/pkg/<uri-hash>``

Reference counts track which URIs live workers use; unreferenced entries
are deleted once the cache exceeds its size budget (reference:
`runtime_env/agent` URI cache GC, RAY_RUNTIME_ENV_*_CACHE_SIZE_GB).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import shutil
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from ray_tpu.runtime_env import packaging


class RuntimeEnvSetupError(RuntimeError):
    pass


class RuntimeEnvContext:
    """What the raylet needs to spawn a worker inside the env."""

    __slots__ = ("env_vars", "py_executable", "pythonpath", "working_dir",
                 "command_prefix", "uris")

    def __init__(self):
        self.env_vars: Dict[str, str] = {}
        self.py_executable: Optional[str] = None
        self.pythonpath: List[str] = []
        self.working_dir: Optional[str] = None
        self.command_prefix: List[str] = []
        self.uris: List[str] = []   # cache keys this context references


class RuntimeEnvManager:
    def __init__(self, base_dir: str, gcs_client,
                 cache_size_bytes: int = 10 * 1024 * 1024 * 1024):
        self._base = base_dir
        self._gcs = gcs_client
        self._cache_cap = cache_size_bytes
        self._locks: Dict[str, asyncio.Lock] = {}
        self._refs: Dict[str, int] = {}       # uri -> live worker count
        self._last_used: Dict[str, float] = {}
        self._sizes: Dict[str, int] = {}
        self.creations = 0                    # observability: cache misses
        os.makedirs(os.path.join(base_dir, "pip"), exist_ok=True)
        os.makedirs(os.path.join(base_dir, "pkg"), exist_ok=True)

    # ---- public -----------------------------------------------------------
    async def setup(self, runtime_env: Dict[str, Any]) -> RuntimeEnvContext:
        """Materialize every resource of a validated runtime_env. Safe to
        call concurrently; each URI is created once (per-URI lock)."""
        from ray_tpu.runtime_env import validate_runtime_env

        # Validation reads requirements files off disk: keep it (and the
        # packaging below) off the event loop.
        runtime_env = await asyncio.get_running_loop().run_in_executor(
            None, validate_runtime_env, runtime_env)
        ctx = RuntimeEnvContext()
        timeout = (runtime_env.get("config") or {}).get(
            "setup_timeout_seconds", 600)
        try:
            await asyncio.wait_for(self._setup_inner(runtime_env, ctx),
                                   timeout)
        except asyncio.TimeoutError:
            raise RuntimeEnvSetupError(
                f"runtime_env setup exceeded {timeout}s") from None
        # The bare ref/recency writes here and in release()/_maybe_gc()
        # are safe: everything runs on the raylet's one event loop with
        # no await between read and write. The per-URI asyncio.Lock in
        # _ensure_package dedups *creation work*, it is not a data lock.
        for uri in ctx.uris:
            self._refs[uri] = self._refs.get(uri, 0) + 1
            self._last_used[uri] = time.monotonic()  # graftlint: disable=lockset-consistency (single event loop; see above)
        return ctx

    def release(self, uris: List[str]) -> None:
        """A worker using these URIs exited."""
        for uri in uris:
            self._refs[uri] = max(0, self._refs.get(uri, 0) - 1)
            self._last_used[uri] = time.monotonic()  # graftlint: disable=lockset-consistency (single event loop; see setup)
        self._maybe_gc()

    def stats(self) -> Dict[str, Any]:
        return {"creations": self.creations,
                "cached_uris": sorted(self._refs),
                "refs": dict(self._refs),
                "cache_bytes": sum(self._sizes.values())}

    # ---- internals --------------------------------------------------------
    def _lock(self, key: str) -> asyncio.Lock:
        lock = self._locks.get(key)
        if lock is None:
            lock = self._locks[key] = asyncio.Lock()
        return lock

    async def _setup_inner(self, runtime_env: Dict[str, Any],
                           ctx: RuntimeEnvContext) -> None:
        ctx.env_vars.update(runtime_env.get("env_vars") or {})

        wd = runtime_env.get("working_dir")
        if wd:
            if packaging.is_package_uri(wd):
                ctx.working_dir = await self._ensure_package(wd)
            else:
                # Same-node fast path: the driver's local dir is directly
                # visible; remote nodes receive the packaged URI instead
                # (rewritten at submission, see prepare_runtime_env).
                ctx.working_dir = os.path.abspath(wd)
            if ctx.working_dir:
                ctx.uris.append(f"wd:{ctx.working_dir}")
                ctx.pythonpath.append(ctx.working_dir)

        for mod in runtime_env.get("py_modules") or []:
            if packaging.is_package_uri(mod):
                path = await self._ensure_package(mod)
                ctx.pythonpath.append(path)
                ctx.uris.append(mod)
            elif os.path.isdir(mod):
                # Prepend the PARENT so `import <dirname>` works.
                ctx.pythonpath.append(os.path.dirname(os.path.abspath(mod)))
            elif mod.endswith(".whl"):
                path = await self._ensure_wheel_unpacked(mod)
                ctx.pythonpath.append(path)

        pip = runtime_env.get("pip")
        if pip:
            venv = await self._ensure_pip_env(pip)
            ctx.py_executable = os.path.join(venv, "bin", "python")
            ctx.uris.append(f"pip:{os.path.basename(venv)}")

        container = runtime_env.get("container")
        if container:
            runtime = os.environ.get("RAY_TPU_CONTAINER_RUNTIME")
            if not runtime:
                raise RuntimeEnvSetupError(
                    "container runtime_env needs RAY_TPU_CONTAINER_RUNTIME")
            ctx.command_prefix = (
                [runtime, "run", "--rm", "--network=host",
                 "-v", "/tmp:/tmp"]
                + list(container.get("run_options") or [])
                + [container["image"]])

    async def _ensure_package(self, uri: str) -> str:
        key = hashlib.sha256(uri.encode()).hexdigest()[:24]
        dest = os.path.join(self._base, "pkg", key)
        async with self._lock(uri):
            marker = os.path.join(dest, ".rtpu_pkg_ready")
            if os.path.exists(marker):
                self._last_used[uri] = time.monotonic()
                return self._package_root(dest)
            payload = await packaging.download_package(self._gcs, uri)
            loop = asyncio.get_running_loop()
            if uri.endswith(".whl") or "_whl_" in uri:
                await loop.run_in_executor(
                    None, self._unpack_wheel_bytes, payload, dest)
            else:
                await loop.run_in_executor(
                    None, packaging.unpack_package, payload, dest)
            self.creations += 1
            self._sizes[uri] = len(payload)
            # Stamp recency at creation. Without this a just-built
            # package has no _last_used entry, sorts as oldest in the
            # LRU, and _maybe_gc can delete it during the awaits between
            # here and setup() taking the ref.
            self._last_used[uri] = time.monotonic()
            return self._package_root(dest)

    @staticmethod
    def _package_root(dest: str) -> str:
        return dest

    @staticmethod
    def _unpack_wheel_bytes(payload: bytes, dest: str) -> None:
        import io
        import zipfile

        os.makedirs(dest, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(payload)) as zf:
            zf.extractall(dest)
        with open(os.path.join(dest, ".rtpu_pkg_ready"), "w") as f:
            f.write("ok")

    async def _ensure_wheel_unpacked(self, path: str) -> str:
        """Local .whl in py_modules: unpack (wheels are importable trees)."""
        loop = asyncio.get_running_loop()
        uri, payload = await loop.run_in_executor(
            None, packaging.package_wheel, path)
        key = hashlib.sha256(uri.encode()).hexdigest()[:24]
        dest = os.path.join(self._base, "pkg", key)
        async with self._lock(uri):
            if not os.path.exists(os.path.join(dest, ".rtpu_pkg_ready")):
                await loop.run_in_executor(
                    None, self._unpack_wheel_bytes, payload, dest)
                self.creations += 1
                self._sizes[uri] = len(payload)
        return dest

    async def _ensure_pip_env(self, pip: Dict[str, Any]) -> str:
        packages = pip["packages"]
        spec = json.dumps(packages, sort_keys=True)
        key = hashlib.sha256(spec.encode()).hexdigest()[:24]
        venv_dir = os.path.join(self._base, "pip", key)
        async with self._lock(f"pip:{key}"):
            marker = os.path.join(venv_dir, ".rtpu_env_ready")
            if os.path.exists(marker):
                return venv_dir
            loop = asyncio.get_running_loop()
            try:
                await loop.run_in_executor(
                    None, self._create_venv, venv_dir, packages)
            except Exception:
                shutil.rmtree(venv_dir, ignore_errors=True)
                raise

            def _finish():
                # Marker write + recursive size walk are sync disk I/O:
                # keep them in the executor with the venv build, not on
                # the event loop this setup shares with the raylet.
                with open(marker, "w") as f:
                    f.write(spec)
                return _du(venv_dir)

            size = await loop.run_in_executor(None, _finish)
            self.creations += 1
            self._sizes[f"pip:{key}"] = size
            return venv_dir

    def _create_venv(self, venv_dir: str, packages: List[str]) -> None:
        """venv inheriting the creating interpreter's site-packages: the
        host's preinstalled stack (jax, numpy, cloudpickle) stays
        importable and only the delta installs (reference: pip.py uses
        virtualenv the same way). --system-site-packages alone is not
        enough when the host python is itself a venv (/opt/venv): the new
        venv would inherit the BASE interpreter's site-packages, so the
        current environment's paths are grafted in with a .pth file."""
        import glob as _glob

        subprocess.run(
            [sys.executable, "-m", "venv", "--system-site-packages",
             venv_dir],
            check=True, capture_output=True, timeout=300)
        parent_sites = [p for p in sys.path
                        if p.endswith("site-packages") and os.path.isdir(p)]
        for venv_site in _glob.glob(
                os.path.join(venv_dir, "lib", "python*", "site-packages")):
            with open(os.path.join(venv_site, "_rtpu_inherit.pth"),
                      "w") as f:
                f.write("\n".join(parent_sites) + "\n")
        pip_exe = os.path.join(venv_dir, "bin", "pip")
        cmd = [pip_exe, "install", "--no-input"]
        if all(os.path.exists(p.split("[")[0]) for p in packages):
            # Pure local wheels/dirs: never touch the network.
            cmd.append("--no-index")
        result = subprocess.run(cmd + list(packages),
                                capture_output=True, timeout=600)
        if result.returncode != 0:
            raise RuntimeEnvSetupError(
                f"pip install failed: {result.stderr.decode()[-2000:]}")

    # ---- GC ---------------------------------------------------------------
    def _maybe_gc(self) -> None:
        total = sum(self._sizes.values())
        if total <= self._cache_cap:
            return
        # Evict least-recently-used unreferenced entries. A URI whose
        # creation lock is held is mid-_ensure_package: its files are
        # about to be returned to a worker, so it is not a candidate
        # even though no ref exists yet.
        victims = sorted(
            (u for u in self._sizes
             if self._refs.get(u, 0) == 0
             and not self._creation_in_flight(u)),
            key=lambda u: self._last_used.get(u, 0))
        for uri in victims:
            if total <= self._cache_cap:
                break
            total -= self._sizes.pop(uri, 0)  # graftlint: disable=lockset-consistency (single event loop; see setup)
            self._refs.pop(uri, None)
            self._last_used.pop(uri, None)  # graftlint: disable=lockset-consistency (single event loop; see setup)
            self._delete_entry(uri)

    def _creation_in_flight(self, uri: str) -> bool:
        lock = self._locks.get(uri)
        return lock is not None and lock.locked()

    def _delete_entry(self, uri: str) -> None:
        if uri.startswith("pip:"):
            path = os.path.join(self._base, "pip", uri.split(":", 1)[1])
        elif uri.startswith("wd:"):
            return  # plain local dir — not ours to delete
        else:
            key = hashlib.sha256(uri.encode()).hexdigest()[:24]
            path = os.path.join(self._base, "pkg", key)
        shutil.rmtree(path, ignore_errors=True)


def _du(path: str) -> int:
    total = 0
    for dirpath, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(dirpath, f))
            except OSError:
                pass
    return total


def prepare_runtime_env(runtime_env: Optional[Dict[str, Any]],
                        gcs_client) -> Optional[Dict[str, Any]]:
    """Driver-side submission rewrite (reference:
    `runtime_env.py` upload_*_if_needed): package local working_dir /
    py_modules dirs and replace them with gcs:// URIs so every node can
    materialize them."""
    from ray_tpu.runtime_env import validate_runtime_env

    env = validate_runtime_env(runtime_env)
    if not env:
        return None
    wd = env.get("working_dir")
    if wd and not packaging.is_package_uri(wd):
        uri, payload = packaging.package_dir(wd, env.get("excludes"))
        packaging.upload_package(gcs_client, uri, payload)
        env["working_dir"] = uri
    mods = env.get("py_modules")
    if mods:
        out = []
        for m in mods:
            if packaging.is_package_uri(m):
                out.append(m)
            elif os.path.isdir(m):
                uri, payload = packaging.package_dir(
                    m, env.get("excludes"), include_root_name=True)
                packaging.upload_package(gcs_client, uri, payload)
                out.append(uri)
            elif m.endswith(".whl"):
                uri, payload = packaging.package_wheel(m)
                packaging.upload_package(gcs_client, uri, payload)
                out.append(uri)
        env["py_modules"] = out
    return env
