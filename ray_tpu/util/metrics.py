"""User-defined application metrics (reference: `python/ray/util/metrics.py`,
exported through the node MetricsAgent -> Prometheus in the reference;
here pushed to the GCS metrics registry and served from the GCS
``/metrics`` scrape endpoint alongside the system gauges).

Usage, mirroring the reference API::

    from ray_tpu.util.metrics import Counter, Gauge, Histogram

    requests = Counter("num_requests", description="...",
                       tag_keys=("route",))
    requests.inc(1.0, tags={"route": "/predict"})

Metrics are process-local and flushed to the GCS every
``GlobalConfig.metrics_report_interval_s`` seconds by a daemon thread
(the reference's C++ registry flushes to the metrics agent on the same
cadence). Aggregation on the scrape side: counters and histograms are
summed across processes; gauges are exported per-process with a
``pid`` label (summing gauges would be wrong).
"""

from __future__ import annotations

import os
import re
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

_registry_lock = threading.Lock()
_registry: Dict[str, "Metric"] = {}  # name -> canonical instance
_flusher_started = False

DEFAULT_BOUNDARIES = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _valid_name(name: str) -> str:
    out = "".join(c if (c.isalnum() or c == "_") else "_" for c in name)
    if not out or out[0].isdigit():
        raise ValueError(f"invalid metric name {name!r}")
    return out


class Metric:
    """Base class; do not instantiate directly."""

    _type = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if tag_keys is not None and not all(
                isinstance(k, str) for k in tag_keys):
            raise TypeError("tag_keys must be strings")
        self._name = _valid_name(name)
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        # tag-value tuple (aligned with _tag_keys) -> float / bucket list
        self._data: Dict[Tuple[str, ...], object] = {}
        # tag-value tuple -> {"trace_id", "value", "ts"}: the max-valued
        # exemplar per label set (histograms only; see Histogram.observe).
        self._exemplars: Dict[Tuple[str, ...], Dict[str, Any]] = {}
        # Re-creating a metric with the same name (e.g. inside a task body
        # run many times on one worker) aliases the canonical instance's
        # storage instead of growing the registry without bound.
        with _registry_lock:
            prior = _registry.get(self._name)
            if prior is not None:
                if (prior._type != self._type
                        or prior._tag_keys != self._tag_keys
                        or getattr(prior, "boundaries", None)
                        != getattr(self, "boundaries", None)):
                    raise ValueError(
                        f"metric {self._name!r} already registered with a "
                        f"different type/tag_keys/boundaries")
                self._data = prior._data
                self._lock = prior._lock
                if not hasattr(prior, "_exemplars"):
                    prior._exemplars = {}
                self._exemplars = prior._exemplars
            else:
                _registry[self._name] = self
        _ensure_flusher()

    # Reference parity: metric.set_default_tags({...}) returns self.
    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    @property
    def info(self) -> Dict[str, object]:
        return {"name": self._name, "type": self._type,
                "description": self._description,
                "tag_keys": self._tag_keys,
                "default_tags": dict(self._default_tags)}

    def _tag_tuple(self, tags: Optional[Dict[str, str]]) -> Tuple[str, ...]:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        extra = set(merged) - set(self._tag_keys)
        if extra:
            raise ValueError(
                f"unknown tag(s) {sorted(extra)} for metric {self._name!r}; "
                f"declared tag_keys={self._tag_keys}")
        vals = tuple(str(merged.get(k, "")) for k in self._tag_keys)
        if any("," in v for v in vals):
            raise ValueError("tag values must not contain ','")
        return vals

    def _snapshot(self) -> Dict[str, object]:
        with self._lock:
            data = {",".join(k): v if not isinstance(v, list) else list(v)
                    for k, v in self._data.items()}
            exemplars = {",".join(k): dict(v)
                         for k, v in self._exemplars.items()}
        snap = {**self.info, "data": data}
        if exemplars:
            snap["exemplars"] = exemplars
        return snap


class Counter(Metric):
    """Monotonically increasing counter (summed across processes)."""

    _type = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("Counter.inc() requires value >= 0")
        key = self._tag_tuple(tags)
        with self._lock:
            self._data[key] = float(self._data.get(key, 0.0)) + value


class Gauge(Metric):
    """Last-write-wins value (exported per-process)."""

    _type = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._data[self._tag_tuple(tags)] = float(value)


class Histogram(Metric):
    """Cumulative-bucket histogram, Prometheus exposition semantics."""

    _type = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        self.boundaries = tuple(
            sorted(boundaries if boundaries else DEFAULT_BOUNDARIES))
        if any(b <= 0 for b in self.boundaries):
            raise ValueError("histogram boundaries must be > 0")
        super().__init__(name, description, tag_keys)

    @property
    def info(self) -> Dict[str, object]:
        out = super().info
        out["boundaries"] = self.boundaries
        return out

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None, *,
                trace_id: Optional[str] = None) -> None:
        """Record one observation. ``trace_id`` optionally links an
        exemplar: per label set, the max-valued observation's trace_id
        is kept (replaced when a new value >= the stored one), so a
        latency histogram points straight at the slowest request's
        retrievable trace. The exemplar rides a dedicated kwarg — it
        never widens the declared tag_keys / label set."""
        key = self._tag_tuple(tags)
        with self._lock:
            cell = self._data.get(key)
            if cell is None:
                # [bucket_0..bucket_n-1, +inf, sum, count]
                cell = [0.0] * (len(self.boundaries) + 3)
                self._data[key] = cell
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    cell[i] += 1
            cell[len(self.boundaries)] += 1          # +inf bucket
            cell[len(self.boundaries) + 1] += value  # sum
            cell[len(self.boundaries) + 2] += 1      # count
            if trace_id is not None:
                prior = self._exemplars.get(key)
                if prior is None or float(value) >= prior["value"]:
                    self._exemplars[key] = {
                        "trace_id": str(trace_id),
                        "value": float(value), "ts": time.time()}


# --------------------------------------------------------------------- flush

_flush_samplers: List = []


def register_flush_sampler(fn) -> None:
    """Register a callable invoked right before every metrics flush —
    the hook for sampled gauges (device HBM, engine queue depth) that
    must be fresh at export time without their own timer threads."""
    with _registry_lock:
        if fn not in _flush_samplers:
            _flush_samplers.append(fn)
    _ensure_flusher()


def _run_samplers() -> None:
    for fn in list(_flush_samplers):
        try:
            fn()
        except Exception:
            pass  # a broken sampler must not stop the flush


def snapshot_records() -> List[Dict[str, object]]:
    """Serializable snapshots of every registered metric (for async push
    paths that cannot use the sync GCS client, e.g. worker kill)."""
    _run_samplers()
    with _registry_lock:
        return [m._snapshot() for m in _registry.values()]


def _flush_once() -> bool:
    """Push one snapshot of every registered metric to the GCS."""
    from ray_tpu._private.worker import global_worker_or_none

    w = global_worker_or_none()
    if w is None or getattr(w, "_dead", False):
        return False
    _run_samplers()
    with _registry_lock:
        snaps = [m._snapshot() for m in _registry.values()]
    if not snaps:
        return True
    try:
        w.gcs.call("push_metrics", source=metric_source(w),
                   records=snaps, timeout=5)
        return True
    except Exception:
        return False


def metric_source(worker) -> str:
    """Cluster-unique push key: bare pid collides across nodes."""
    wid = getattr(worker, "worker_id", None)
    suffix = wid.binary().hex()[:8] if wid is not None else "local"
    return f"{os.getpid()}@{suffix}"


def _ensure_flusher() -> None:
    global _flusher_started
    with _registry_lock:
        if _flusher_started:
            return
        _flusher_started = True

    def _loop():
        from ray_tpu._private.config import GlobalConfig
        while True:
            time.sleep(GlobalConfig.metrics_report_interval_s)
            _flush_once()

    threading.Thread(target=_loop, daemon=True,
                     name="rtpu-metrics-flusher").start()


def flush() -> bool:
    """Force an immediate push (also called at worker shutdown/kill;
    SIGKILL'd workers lose at most one flush interval of updates)."""
    return _flush_once()


# ----------------------------------------------------------------- MetricsHub
#
# The query surface the control plane reads (serve autoscaler, data
# backpressure tuner, raylet memory preemption). One substrate: the GCS
# ``user_metrics_summary`` aggregate, polled into bounded time-windowed
# series with *explicit* staleness — a controller can always tell "the
# gauge is low" apart from "the gauge stopped updating", and must hold
# rather than act on the latter.

_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_labels(label_str: str) -> Dict[str, str]:
    """``k="v",pid="123@ab"`` -> dict (the GCS summary data-key format)."""
    return {m.group(1): m.group(2).replace('\\"', '"').replace("\\\\", "\\")
            for m in _LABEL_RE.finditer(label_str or "")}


def _merge_hist(acc: Optional[Dict[str, Any]],
                cell: Dict[str, Any]) -> Dict[str, Any]:
    if acc is None:
        return {"count": float(cell.get("count", 0.0)),
                "sum": float(cell.get("sum", 0.0)),
                "buckets": {k: float(v)
                            for k, v in cell.get("buckets", {}).items()}}
    acc["count"] += float(cell.get("count", 0.0))
    acc["sum"] += float(cell.get("sum", 0.0))
    for k, v in cell.get("buckets", {}).items():
        acc["buckets"][k] = acc["buckets"].get(k, 0.0) + float(v)
    return acc


def _hist_sub(new: Dict[str, Any], old: Dict[str, Any]) -> Dict[str, Any]:
    """Windowed delta of two cumulative histogram snapshots. A negative
    count means the series reset (sources exited faster than tombstones
    accrued); fall back to the newest cumulative state."""
    delta = {"count": new["count"] - old["count"],
             "sum": new["sum"] - old["sum"],
             "buckets": {k: v - old["buckets"].get(k, 0.0)
                         for k, v in new["buckets"].items()}}
    if delta["count"] <= 0 or any(v < 0 for v in delta["buckets"].values()):
        return new
    return delta


class MetricSeries:
    """One queried metric: samples ``[(ts, value), ...]`` inside the
    window (newest last) plus explicit staleness. Gauge/counter values
    are floats; histogram values are ``{count, sum, buckets}`` dicts of
    cumulative state."""

    def __init__(self, name: str, mtype: Optional[str],
                 samples: List[Tuple[float, Any]],
                 age_s: Optional[float], n_series: int = 0):
        self.name = name
        self.type = mtype
        self.samples = samples
        #: Seconds since the freshest *source push* contributing to the
        #: newest sample (GCS-side age + time since the hub last fetched).
        #: ``None`` when the metric has never been observed.
        self.age_s = age_s
        #: How many label-sets were aggregated into each sample.
        self.n_series = n_series

    def __bool__(self) -> bool:
        return bool(self.samples)

    @property
    def latest(self):
        return self.samples[-1][1] if self.samples else None

    def stale(self, ttl: Optional[float] = None) -> bool:
        """True when the newest contributing push is older than ``ttl``
        (default ``GlobalConfig.ctrl_metrics_staleness_s``). A series
        with no samples at all is *absent*, not stale — test with
        ``bool(series)`` first; controllers treat absent as "signal not
        wired" and stale as "signal broken, hold"."""
        if not self.samples:
            return False
        if ttl is None:
            from ray_tpu._private.config import GlobalConfig
            ttl = GlobalConfig.ctrl_metrics_staleness_s
        return self.age_s is None or self.age_s > ttl

    def mean(self) -> Optional[float]:
        """Mean gauge/counter value over the window (histograms: mean
        observation of the newest cumulative snapshot)."""
        if not self.samples:
            return None
        if self.type == "histogram":
            cell = self.samples[-1][1]
            return cell["sum"] / cell["count"] if cell["count"] else 0.0
        vals = [v for _, v in self.samples]
        return sum(vals) / len(vals)

    def delta(self) -> Optional[float]:
        """Increase across the window (counters / histogram counts)."""
        if not self.samples:
            return None
        new, old = self.samples[-1][1], self.samples[0][1]
        if self.type == "histogram":
            return max(0.0, new["count"] - old["count"])
        return max(0.0, float(new) - float(old))

    def rate(self) -> Optional[float]:
        """delta() / window span; None with fewer than two samples."""
        if len(self.samples) < 2:
            return None
        span = self.samples[-1][0] - self.samples[0][0]
        d = self.delta()
        return (d / span) if span > 0 and d is not None else None

    def quantile(self, q: float) -> Optional[float]:
        """Histogram quantile over the window (delta of the oldest vs
        newest cumulative snapshot; single-sample series use lifetime
        state). Returns the smallest bucket boundary covering ``q`` of
        observations — the Prometheus ``histogram_quantile`` estimate
        without interpolation, which is all hysteresis needs."""
        if self.type != "histogram" or not self.samples:
            return None
        cell = self.samples[-1][1]
        if len(self.samples) > 1:
            cell = _hist_sub(cell, self.samples[0][1])
        count = cell["count"]
        if not count:
            return None
        target = q * count
        for bound, cum in sorted(cell["buckets"].items(),
                                 key=lambda kv: float(kv[0])):
            if cum >= target:
                return float(bound)
        # Beyond the last boundary (+inf bucket): the largest finite
        # boundary is the best lower bound we can report.
        bounds = [float(b) for b in cell["buckets"]]
        return max(bounds) if bounds else None


class MetricsHub:
    """Windowed, staleness-aware client over the cluster metrics plane.

    ``fetch(prefixes)`` returns a ``user_metrics_summary``-shaped dict
    (default: the GCS RPC through the global worker; the data
    backpressure tuner plugs in :func:`local_summary` to read its own
    process registry with zero RPCs). ``refresh()`` is rate-limited, so
    controllers may call it every tick; samples are pruned beyond
    ``history_s``."""

    def __init__(self, fetch=None, history_s: float = 600.0,
                 min_refresh_s: float = 0.5):
        self._fetch = fetch or _gcs_summary
        self._history_s = history_s
        self._min_refresh_s = min_refresh_s
        self._lock = threading.Lock()
        # (name, label_str) -> deque[(ts, value)]
        self._series: Dict[Tuple[str, str], deque] = {}
        self._meta: Dict[str, Dict[str, Any]] = {}
        self._server_age: Dict[str, Optional[float]] = {}
        self._last_refresh = 0.0

    def refresh(self, prefixes: Optional[Sequence[str]] = None,
                force: bool = False) -> bool:
        now = time.time()
        with self._lock:
            if not force and now - self._last_refresh < self._min_refresh_s:
                return True
            self._last_refresh = now
        try:
            summary = self._fetch(list(prefixes) if prefixes else None)
        except Exception:
            return False
        if summary is None:
            return False
        self.ingest(summary, ts=now)
        return True

    def ingest(self, summary: Dict[str, Any],
               ts: Optional[float] = None) -> None:
        """Append one summary snapshot (also the unit-test entry point:
        feed synthetic snapshots, no cluster required)."""
        ts = time.time() if ts is None else ts
        horizon = ts - self._history_s
        with self._lock:
            for name, entry in summary.items():
                self._meta[name] = {
                    "type": entry.get("type"),
                    "boundaries": entry.get("boundaries")}
                self._server_age[name] = entry.get("age_s")
                for label_str, cell in (entry.get("data") or {}).items():
                    dq = self._series.setdefault((name, label_str), deque())
                    dq.append((ts, cell))
                    while dq and dq[0][0] < horizon:
                        dq.popleft()

    def query(self, name: str, window: Optional[float] = None,
              labels: Optional[Dict[str, str]] = None) -> MetricSeries:
        """Aggregate every stored label-set of ``name`` whose labels are
        a superset of ``labels`` into one windowed series. Counters and
        histograms sum across label-sets; gauges sum too (the per-pid
        gauge split means "sum over processes" is the cluster total —
        pass ``labels={"pid": ...}`` for a single process). ``name``
        accepts the exported ``rtpu_`` prefix."""
        if name.startswith("rtpu_"):
            name = name[len("rtpu_"):]
        now = time.time()
        cutoff = (now - window) if window else None
        with self._lock:
            meta = self._meta.get(name)
            mtype = meta["type"] if meta else None
            merged: Dict[float, Any] = {}
            n_series = 0
            for (sname, label_str), dq in self._series.items():
                if sname != name:
                    continue
                if labels:
                    parsed = parse_labels(label_str)
                    if any(parsed.get(k) != str(v)
                           for k, v in labels.items()):
                        continue
                n_series += 1
                for sts, cell in dq:
                    if cutoff is not None and sts < cutoff:
                        continue
                    if mtype == "histogram":
                        merged[sts] = _merge_hist(merged.get(sts), cell)
                    else:
                        merged[sts] = merged.get(sts, 0.0) + float(cell)
            server_age = self._server_age.get(name)
            fetched = self._last_refresh
        samples = sorted(merged.items())
        age = None
        if samples:
            age = max(0.0, now - fetched) + (server_age or 0.0)
        return MetricSeries(name, mtype, samples, age, n_series)


def _gcs_summary(prefixes: Optional[List[str]]):
    """Default hub fetch: the GCS aggregate through the global worker."""
    from ray_tpu._private.worker import global_worker_or_none

    w = global_worker_or_none()
    if w is None or getattr(w, "_dead", False):
        return None
    return w.gcs.call("user_metrics_summary", prefixes=prefixes, timeout=5)


def local_summary(prefixes: Optional[List[str]] = None) -> Dict[str, Any]:
    """This process's registry in ``user_metrics_summary`` shape — the
    zero-RPC hub fetch for in-process controllers (the data executors
    tune against gauges *they* set; a GCS round-trip would only add the
    flush interval as control latency). ``age_s`` is 0: local reads are
    fresh by construction."""
    out: Dict[str, Any] = {}
    for rec in snapshot_records():
        name, typ = rec["name"], rec["type"]
        if prefixes and not any(name.startswith(p) for p in prefixes):
            continue
        keys = rec.get("tag_keys", ())
        data: Dict[str, Any] = {}
        for tagvals, cell in rec.get("data", {}).items():
            label_str = ",".join(
                f'{k}="{v}"' for k, v in
                zip(keys, tagvals.split(",") if keys else ()))
            if typ == "histogram":
                bounds = tuple(rec.get("boundaries", ()))
                if len(cell) != len(bounds) + 3:
                    continue
                count = cell[len(bounds) + 2]
                total = cell[len(bounds) + 1]
                data[label_str] = {
                    "count": count, "sum": total,
                    "mean": (total / count) if count else 0.0,
                    "buckets": {str(b): cell[i]
                                for i, b in enumerate(bounds)}}
            else:
                data[label_str] = float(cell)
        entry: Dict[str, Any] = {"type": typ,
                                 "description": rec.get("description", ""),
                                 "age_s": 0.0, "data": data}
        if typ == "histogram":
            entry["boundaries"] = list(rec.get("boundaries", ()))
        out[name] = entry
    return out


_global_hub: Optional[MetricsHub] = None


def global_hub() -> MetricsHub:
    global _global_hub
    with _registry_lock:
        if _global_hub is None:
            _global_hub = MetricsHub()
        return _global_hub


def query(name: str, window: Optional[float] = None,
          labels: Optional[Dict[str, str]] = None) -> MetricSeries:
    """Query the cluster metrics plane: ``query("serve_queue_wait_seconds",
    window=30).quantile(0.95)``. Refreshes the process-global hub from
    the GCS (rate-limited) and returns a windowed, staleness-aware
    series — the controllers' one shared read path."""
    hub = global_hub()
    hub.refresh()
    return hub.query(name, window=window, labels=labels)
