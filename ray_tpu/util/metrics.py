"""User-defined application metrics (reference: `python/ray/util/metrics.py`,
exported through the node MetricsAgent -> Prometheus in the reference;
here pushed to the GCS metrics registry and served from the GCS
``/metrics`` scrape endpoint alongside the system gauges).

Usage, mirroring the reference API::

    from ray_tpu.util.metrics import Counter, Gauge, Histogram

    requests = Counter("num_requests", description="...",
                       tag_keys=("route",))
    requests.inc(1.0, tags={"route": "/predict"})

Metrics are process-local and flushed to the GCS every
``GlobalConfig.metrics_report_interval_s`` seconds by a daemon thread
(the reference's C++ registry flushes to the metrics agent on the same
cadence). Aggregation on the scrape side: counters and histograms are
summed across processes; gauges are exported per-process with a
``pid`` label (summing gauges would be wrong).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

_registry_lock = threading.Lock()
_registry: Dict[str, "Metric"] = {}  # name -> canonical instance
_flusher_started = False

DEFAULT_BOUNDARIES = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _valid_name(name: str) -> str:
    out = "".join(c if (c.isalnum() or c == "_") else "_" for c in name)
    if not out or out[0].isdigit():
        raise ValueError(f"invalid metric name {name!r}")
    return out


class Metric:
    """Base class; do not instantiate directly."""

    _type = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if tag_keys is not None and not all(
                isinstance(k, str) for k in tag_keys):
            raise TypeError("tag_keys must be strings")
        self._name = _valid_name(name)
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        # tag-value tuple (aligned with _tag_keys) -> float / bucket list
        self._data: Dict[Tuple[str, ...], object] = {}
        # Re-creating a metric with the same name (e.g. inside a task body
        # run many times on one worker) aliases the canonical instance's
        # storage instead of growing the registry without bound.
        with _registry_lock:
            prior = _registry.get(self._name)
            if prior is not None:
                if (prior._type != self._type
                        or prior._tag_keys != self._tag_keys
                        or getattr(prior, "boundaries", None)
                        != getattr(self, "boundaries", None)):
                    raise ValueError(
                        f"metric {self._name!r} already registered with a "
                        f"different type/tag_keys/boundaries")
                self._data = prior._data
                self._lock = prior._lock
            else:
                _registry[self._name] = self
        _ensure_flusher()

    # Reference parity: metric.set_default_tags({...}) returns self.
    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    @property
    def info(self) -> Dict[str, object]:
        return {"name": self._name, "type": self._type,
                "description": self._description,
                "tag_keys": self._tag_keys,
                "default_tags": dict(self._default_tags)}

    def _tag_tuple(self, tags: Optional[Dict[str, str]]) -> Tuple[str, ...]:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        extra = set(merged) - set(self._tag_keys)
        if extra:
            raise ValueError(
                f"unknown tag(s) {sorted(extra)} for metric {self._name!r}; "
                f"declared tag_keys={self._tag_keys}")
        vals = tuple(str(merged.get(k, "")) for k in self._tag_keys)
        if any("," in v for v in vals):
            raise ValueError("tag values must not contain ','")
        return vals

    def _snapshot(self) -> Dict[str, object]:
        with self._lock:
            data = {",".join(k): v if not isinstance(v, list) else list(v)
                    for k, v in self._data.items()}
        return {**self.info, "data": data}


class Counter(Metric):
    """Monotonically increasing counter (summed across processes)."""

    _type = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("Counter.inc() requires value >= 0")
        key = self._tag_tuple(tags)
        with self._lock:
            self._data[key] = float(self._data.get(key, 0.0)) + value


class Gauge(Metric):
    """Last-write-wins value (exported per-process)."""

    _type = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._data[self._tag_tuple(tags)] = float(value)


class Histogram(Metric):
    """Cumulative-bucket histogram, Prometheus exposition semantics."""

    _type = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        self.boundaries = tuple(
            sorted(boundaries if boundaries else DEFAULT_BOUNDARIES))
        if any(b <= 0 for b in self.boundaries):
            raise ValueError("histogram boundaries must be > 0")
        super().__init__(name, description, tag_keys)

    @property
    def info(self) -> Dict[str, object]:
        out = super().info
        out["boundaries"] = self.boundaries
        return out

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        key = self._tag_tuple(tags)
        with self._lock:
            cell = self._data.get(key)
            if cell is None:
                # [bucket_0..bucket_n-1, +inf, sum, count]
                cell = [0.0] * (len(self.boundaries) + 3)
                self._data[key] = cell
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    cell[i] += 1
            cell[len(self.boundaries)] += 1          # +inf bucket
            cell[len(self.boundaries) + 1] += value  # sum
            cell[len(self.boundaries) + 2] += 1      # count


# --------------------------------------------------------------------- flush

_flush_samplers: List = []


def register_flush_sampler(fn) -> None:
    """Register a callable invoked right before every metrics flush —
    the hook for sampled gauges (device HBM, engine queue depth) that
    must be fresh at export time without their own timer threads."""
    with _registry_lock:
        if fn not in _flush_samplers:
            _flush_samplers.append(fn)
    _ensure_flusher()


def _run_samplers() -> None:
    for fn in list(_flush_samplers):
        try:
            fn()
        except Exception:
            pass  # a broken sampler must not stop the flush


def snapshot_records() -> List[Dict[str, object]]:
    """Serializable snapshots of every registered metric (for async push
    paths that cannot use the sync GCS client, e.g. worker kill)."""
    _run_samplers()
    with _registry_lock:
        return [m._snapshot() for m in _registry.values()]


def _flush_once() -> bool:
    """Push one snapshot of every registered metric to the GCS."""
    from ray_tpu._private.worker import global_worker_or_none

    w = global_worker_or_none()
    if w is None or getattr(w, "_dead", False):
        return False
    _run_samplers()
    with _registry_lock:
        snaps = [m._snapshot() for m in _registry.values()]
    if not snaps:
        return True
    try:
        w.gcs.call("push_metrics", source=metric_source(w),
                   records=snaps, timeout=5)
        return True
    except Exception:
        return False


def metric_source(worker) -> str:
    """Cluster-unique push key: bare pid collides across nodes."""
    wid = getattr(worker, "worker_id", None)
    suffix = wid.binary().hex()[:8] if wid is not None else "local"
    return f"{os.getpid()}@{suffix}"


def _ensure_flusher() -> None:
    global _flusher_started
    with _registry_lock:
        if _flusher_started:
            return
        _flusher_started = True

    def _loop():
        from ray_tpu._private.config import GlobalConfig
        while True:
            time.sleep(GlobalConfig.metrics_report_interval_s)
            _flush_once()

    threading.Thread(target=_loop, daemon=True,
                     name="rtpu-metrics-flusher").start()


def flush() -> bool:
    """Force an immediate push (also called at worker shutdown/kill;
    SIGKILL'd workers lose at most one flush interval of updates)."""
    return _flush_once()
