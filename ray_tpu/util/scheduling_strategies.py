"""Per-task scheduling strategies
(reference: `python/ray/util/scheduling_strategies.py`)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: "object"  # ray_tpu.util.placement_group.PlacementGroup
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: bytes
    soft: bool = False

    def __post_init__(self):
        if isinstance(self.node_id, str):
            self.node_id = bytes.fromhex(self.node_id)


@dataclass
class NodeLabelSchedulingStrategy:
    hard: Optional[Dict[str, List[str]]] = None
    soft: Optional[Dict[str, List[str]]] = None
