"""multiprocessing.Pool API over ray_tpu tasks (reference:
`python/ray/util/multiprocessing/pool.py` — drop-in Pool whose workers
are actors; here map-style calls fan out as tasks and `imap` streams
results in completion order or submission order).

    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=8) as p:
        print(p.map(f, range(100)))
"""

from __future__ import annotations

import itertools
import uuid
from typing import Any, Callable, Iterable, List, Optional, Set

import ray_tpu

# Worker-process-side record of which pools already ran their initializer
# there — stdlib Pool contract: initializer runs once per worker process,
# not once per task.
_WORKER_INITED: Set[str] = set()


class AsyncResult:
    def __init__(self, refs: List[Any], single: bool = False,
                 callback: Optional[Callable] = None,
                 error_callback: Optional[Callable] = None):
        self._refs = refs
        self._single = single
        if callback is not None or error_callback is not None:
            import threading

            def _notify():
                try:
                    result = self.get()
                except Exception as e:  # noqa: BLE001
                    if error_callback is not None:
                        error_callback(e)
                    return
                if callback is not None:
                    callback(result)

            threading.Thread(target=_notify, daemon=True).start()

    def get(self, timeout: Optional[float] = None):
        out = ray_tpu.get(self._refs, timeout=timeout)
        return out[0] if self._single else out

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        done, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                               timeout=0)
        return len(done) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        try:
            ray_tpu.get(self._refs, timeout=0)
            return True
        except Exception:
            return False


class Pool:
    """Task-backed process pool. `processes` caps in-flight tasks (the
    cluster scheduler does the real placement)."""

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = ()):
        if processes is not None and processes < 1:
            raise ValueError("processes must be >= 1")
        self._processes = processes or 8
        self._initializer = initializer
        self._initargs = initargs
        self._closed = False
        self._pool_id = uuid.uuid4().hex
        # One exported remote function per (func, kind) — re-exporting a
        # fresh closure per call would grow cluster function state without
        # bound on long-lived drivers.
        self._task_cache: dict = {}

    # ------------------------------------------------------------ internal
    def _task(self, func: Callable, kind: str = "item"):
        key = (func, kind)
        cached = self._task_cache.get(key)
        if cached is not None:
            return cached
        init, initargs, pool_id = (self._initializer, self._initargs,
                                   self._pool_id)

        def _ensure_init():
            if init is None:
                return
            from ray_tpu.util import multiprocessing as _mp

            if pool_id not in _mp._WORKER_INITED:
                _mp._WORKER_INITED.add(pool_id)
                init(*initargs)

        if kind == "item":
            @ray_tpu.remote
            def _call(*args, **kwargs):
                _ensure_init()
                return func(*args, **kwargs)
        elif kind == "chunk":
            @ray_tpu.remote
            def _call(xs):
                _ensure_init()
                return [func(x) for x in xs]
        else:  # starchunk
            @ray_tpu.remote
            def _call(xs):
                _ensure_init()
                return [func(*x) for x in xs]

        self._task_cache[key] = _call
        return _call

    def _check_open(self) -> None:
        if self._closed:
            raise ValueError("Pool not running")

    @staticmethod
    def _star(args: Any) -> tuple:
        return tuple(args) if isinstance(args, (tuple, list)) else (args,)

    # ----------------------------------------------------------------- api
    def apply(self, func, args=(), kwds=None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func, args=(), kwds=None, callback=None,
                    error_callback=None) -> AsyncResult:
        self._check_open()
        task = self._task(func, "item")
        return AsyncResult([task.remote(*args, **(kwds or {}))],
                           single=True, callback=callback,
                           error_callback=error_callback)

    def map(self, func, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(func, iterable, chunksize).get()

    def map_async(self, func, iterable: Iterable,
                  chunksize: Optional[int] = None, callback=None,
                  error_callback=None) -> AsyncResult:
        self._check_open()
        items = list(iterable)
        chunk = chunksize or max(1, len(items) // (self._processes * 4) or 1)
        task = self._task(func, "chunk")
        refs = [task.remote(items[i:i + chunk])
                for i in range(0, len(items), chunk)]
        return _FlatteningResult(refs, callback=callback,
                                 error_callback=error_callback)

    def starmap(self, func, iterable: Iterable[tuple],
                chunksize: Optional[int] = None) -> List[Any]:
        return self.starmap_async(func, iterable, chunksize).get()

    def starmap_async(self, func, iterable: Iterable[tuple],
                      chunksize: Optional[int] = None, callback=None,
                      error_callback=None) -> AsyncResult:
        self._check_open()
        items = [self._star(a) for a in iterable]
        chunk = chunksize or max(1, len(items) // (self._processes * 4) or 1)
        task = self._task(func, "starchunk")
        refs = [task.remote(items[i:i + chunk])
                for i in range(0, len(items), chunk)]
        return _FlatteningResult(refs, callback=callback,
                                 error_callback=error_callback)

    def imap(self, func, iterable: Iterable,
             chunksize: int = 1) -> Iterable[Any]:
        """Submission-order streaming, bounded in-flight window. Like
        stdlib Pool.imap, blocks without timeout on each item."""
        self._check_open()
        task = self._task(func, "item")
        window = self._processes * 2
        it = iter(iterable)
        pending: List[Any] = [task.remote(x)
                              for x in itertools.islice(it, window)]
        while pending:
            yield ray_tpu.get(pending.pop(0))
            for x in itertools.islice(it, 1):
                pending.append(task.remote(x))

    def imap_unordered(self, func, iterable: Iterable,
                       chunksize: int = 1) -> Iterable[Any]:
        """Completion-order streaming."""
        self._check_open()
        task = self._task(func, "item")
        window = self._processes * 2
        it = iter(iterable)
        pending = [task.remote(x) for x in itertools.islice(it, window)]
        while pending:
            done, pending = ray_tpu.wait(pending, num_returns=1)
            yield ray_tpu.get(done[0])
            for x in itertools.islice(it, 1):
                pending.append(task.remote(x))

    # ------------------------------------------------------------ lifecycle
    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()


class _FlatteningResult(AsyncResult):
    def get(self, timeout: Optional[float] = None):
        chunks = ray_tpu.get(self._refs, timeout=timeout)
        return [x for c in chunks for x in c]
