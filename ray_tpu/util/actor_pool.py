"""ActorPool — round-robin work distribution over a fixed set of actors.

Reference: `python/ray/util/actor_pool.py` (submit/get_next/
get_next_unordered/map/map_unordered/has_next/has_free/push/pop_idle).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Optional

import ray_tpu


class ActorPool:
    def __init__(self, actors: Iterable[Any]):
        self._idle: List[Any] = list(actors)
        self._future_to_actor = {}   # ref -> (index, actor)
        self._index_to_future = {}   # submission index -> ref
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: List[tuple] = []

    # -------------------------------------------------------------- submit
    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """fn(actor, value) -> ObjectRef; queues if no actor is free."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            i = self._next_task_index
            self._next_task_index += 1
            self._future_to_actor[ref] = (i, actor)
            self._index_to_future[i] = ref
        else:
            self._pending_submits.append((fn, value))

    def has_free(self) -> bool:
        return bool(self._idle) and not self._pending_submits

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending_submits)

    # --------------------------------------------------------------- fetch
    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Next result in submission order. An application error from the
        task is re-raised once — the actor returns to the pool and the pool
        advances past the failed index (a timeout leaves state untouched so
        the caller can retry)."""
        if not self.has_next():
            raise StopIteration("no more results")
        i = self._next_return_index
        ref = self._index_to_future[i]
        try:
            value = ray_tpu.get(ref, timeout=timeout or 600)
        except (ray_tpu.exceptions.GetTimeoutError, TimeoutError):
            raise
        except Exception:
            self._next_return_index += 1
            self._index_to_future.pop(i)
            self._return_actor(ref)
            raise
        self._next_return_index += 1
        self._index_to_future.pop(i)
        self._return_actor(ref)
        return value

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        """Next result in completion order."""
        if not self.has_next():
            raise StopIteration("no more results")
        ready, _ = ray_tpu.wait(list(self._future_to_actor),
                                num_returns=1, timeout=timeout or 600)
        if not ready:
            raise TimeoutError("no result within timeout")
        ref = ready[0]
        i, _ = self._future_to_actor[ref]
        self._index_to_future.pop(i, None)
        try:
            value = ray_tpu.get(ref, timeout=60)
        except Exception:
            self._return_actor(ref)
            raise
        self._return_actor(ref)
        return value

    def _return_actor(self, ref) -> None:
        _, actor = self._future_to_actor.pop(ref)
        self._idle.append(actor)
        while self._pending_submits and self._idle:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    # ----------------------------------------------------------------- map
    def map(self, fn: Callable[[Any, Any], Any],
            values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # ------------------------------------------------------------ mutation
    def push(self, actor: Any) -> None:
        self._idle.append(actor)
        while self._pending_submits and self._idle:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    def pop_idle(self) -> Optional[Any]:
        return self._idle.pop() if self._idle else None
