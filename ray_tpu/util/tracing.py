"""Request-scoped distributed tracing (reference:
`python/ray/util/tracing/tracing_helper.py`, which wraps every remote
call/execution in OpenTelemetry spans and propagates context in task specs).

Two planes live here:

* **Task lineage** — the runtime already propagates
  ``parent_task_id``/``depth`` on every ``TaskSpec`` and records
  PENDING/RUNNING/FINISHED lifecycle events into the head's task-event
  ring; ``span_tree()`` reconstructs that cross-task call tree.

* **Request-scoped traces** — a ``TraceContext`` (trace_id / span_id /
  parent_span_id / baggage) rides a contextvar inside a process and a
  compact wire dict (``{"t", "s", "b"}``) across ``.remote()`` calls:
  the submitting worker stamps ``current_trace().to_wire()`` onto the
  TaskSpec, the executing worker restores it around the task body, so
  spans recorded anywhere downstream parent under the span that was
  active at submit time. Trace-tagged SPAN events ride the same
  ``push_task_events`` channel and land in the GCS's tail-sampled
  ``TraceStore`` (ray_tpu/observability/traces.py); read them back with
  ``util.state.get_trace()`` / ``list_traces()`` /
  ``trace_critical_path()``.

Typical use::

    from ray_tpu.util import tracing

    with tracing.trace_root("serve.request") as tc:
        with tracing.span("route"):
            ref = replica.handle.remote(req)      # context rides along
        out = ray_tpu.get(ref)
    print(tc.trace_id)                            # retrievable trace

The wire format deliberately drops ``parent_span_id``: the receiver
parents to the *sender's* span, so the sender's own parent link never
travels.
"""

from __future__ import annotations

import contextvars
import os
import time
import uuid
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

# ------------------------------------------------------------- context


def new_trace_id() -> str:
    return uuid.uuid4().hex


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclass
class TraceContext:
    """One hop of a request-scoped trace. ``span_id`` is the identity of
    the currently-active span; anything recorded beneath it parents
    there. ``baggage`` is small propagated metadata (e.g. SLO lane) —
    copied, never merged, on each hop."""

    trace_id: str
    span_id: str
    parent_span_id: Optional[str] = None
    baggage: Dict[str, Any] = field(default_factory=dict)

    def to_wire(self) -> Dict[str, Any]:
        """Compact dict for the TaskSpec. The parent link never travels:
        the receiver parents to the sender's span itself."""
        return {"t": self.trace_id, "s": self.span_id,
                "b": dict(self.baggage)}

    @classmethod
    def from_wire(cls, wire: Optional[Dict[str, Any]]
                  ) -> Optional["TraceContext"]:
        if not wire:
            return None
        return cls(trace_id=wire["t"], span_id=wire["s"],
                   parent_span_id=None,
                   baggage=dict(wire.get("b") or {}))


_CURRENT: contextvars.ContextVar[Optional[TraceContext]] = \
    contextvars.ContextVar("ray_tpu_trace_context", default=None)


def current_trace() -> Optional[TraceContext]:
    """The TraceContext active on this thread/coroutine, or None."""
    return _CURRENT.get()


def child_context() -> Optional[TraceContext]:
    """A fresh context parented under the active span (same trace, new
    span_id, baggage copied), or None when no trace is active."""
    tc = _CURRENT.get()
    if tc is None:
        return None
    return TraceContext(trace_id=tc.trace_id, span_id=new_span_id(),
                        parent_span_id=tc.span_id,
                        baggage=dict(tc.baggage))


def current_wire_context() -> Optional[Dict[str, Any]]:
    """``current_trace().to_wire()`` or None — what ``.remote()`` stamps
    onto the TaskSpec."""
    tc = _CURRENT.get()
    return tc.to_wire() if tc is not None else None


def activate_wire_context(wire: Optional[Dict[str, Any]]
                          ) -> Optional[contextvars.Token]:
    """Executing-worker side: restore the caller's context around a task
    body. Returns a token for ``deactivate_context`` (None when there
    was nothing to restore — pass it back unconditionally)."""
    tc = TraceContext.from_wire(wire)
    if tc is None:
        return None
    return _CURRENT.set(tc)


def deactivate_context(token: Optional[contextvars.Token]) -> None:
    if token is not None:
        _CURRENT.reset(token)


@contextmanager
def trace_root(name: str, attrs: Optional[Dict[str, Any]] = None,
               baggage: Optional[Dict[str, Any]] = None
               ) -> Iterator[TraceContext]:
    """Open a new trace: fresh trace_id, root span active for the block.
    The recorded root span is tagged ``attrs["trace_root"]`` — the
    signal the GCS TraceStore completes (and tail-samples) a trace on."""
    tc = TraceContext(trace_id=new_trace_id(), span_id=new_span_id(),
                      parent_span_id=None, baggage=dict(baggage or {}))
    token = _CURRENT.set(tc)
    start = time.time()
    attrs = dict(attrs) if attrs else {}
    attrs["trace_root"] = True
    try:
        yield tc
    except BaseException as e:
        attrs["error"] = type(e).__name__
        raise
    finally:
        _CURRENT.reset(token)
        record_span(name, start, time.time() - start, attrs,
                    trace={"trace_id": tc.trace_id,
                           "span_id": tc.span_id,
                           "parent_span_id": None})


def record_span(name: str, start: float, dur: float,
                attrs: Optional[Dict[str, Any]] = None, *,
                trace: Optional[Dict[str, Any]] = None) -> None:
    """Record a span with explicit wall-clock start/duration — for
    callers that reconstruct lifecycle phases after the fact (the LLM
    engine's queued/prefill/decode phases, jit-compile events).

    Trace fields are stamped exactly once: an explicit ``trace`` dict
    (``trace_id``/``span_id``/``parent_span_id``) wins outright;
    otherwise the ambient context, if any, contributes the trace_id and
    parents a *fresh* span id under the active span. ``span()`` and
    ``trace_root()`` always pass ``trace=`` explicitly, so a span is
    never double-tagged by its own ambient push."""
    from ray_tpu._private.worker import global_worker_or_none

    w = global_worker_or_none()
    # Thin-client drivers (ray_tpu://) have no local event buffer;
    # spans there are a no-op rather than an AttributeError.
    if (w is not None and not getattr(w, "_dead", False)
            and hasattr(w, "_task_events_lock")):
        tid = w.current_task_id()
        event = {
            "task_id": tid.binary() if tid else b"driver",
            "name": name, "job_id": b"", "state": "SPAN",
            "ts": start, "dur": dur,
            "owner_pid": os.getpid(),
            "attrs": attrs or {},
        }
        if trace is None:
            tc = _CURRENT.get()
            if tc is not None:
                trace = {"trace_id": tc.trace_id,
                         "span_id": new_span_id(),
                         "parent_span_id": tc.span_id}
        if trace is not None and trace.get("trace_id"):
            event["trace_id"] = trace["trace_id"]
            event["span_id"] = trace.get("span_id")
            event["parent_span_id"] = trace.get("parent_span_id")
        with w._task_events_lock:
            w._task_events.append(event)
        if event.get("trace_id"):
            # Traced spans feed the GCS TraceStore; nudge the debounced
            # flush so traces assemble on a sub-second cadence instead
            # of waiting for the 100-event batch threshold.
            flush = getattr(w, "flush_task_events_soon", None)
            if flush is not None:
                flush()


@contextmanager
def span(name: str, attrs: Optional[Dict[str, Any]] = None) -> Iterator[None]:
    """Record a named span inside the current task/driver. When a trace
    is active, the block runs under a child context (so nested spans and
    ``.remote()`` calls parent here) and the recorded SPAN event carries
    the trace fields. A raising body still records the span, tagged
    ``attrs["error"]`` with the exception type so timelines distinguish
    failures from successes."""
    child = child_context()
    token = _CURRENT.set(child) if child is not None else None
    start = time.time()
    attrs = dict(attrs) if attrs else {}
    try:
        yield
    except BaseException as e:
        attrs["error"] = type(e).__name__
        raise
    finally:
        if token is not None:
            _CURRENT.reset(token)
        record_span(name, start, time.time() - start, attrs,
                    trace=({"trace_id": child.trace_id,
                            "span_id": child.span_id,
                            "parent_span_id": child.parent_span_id}
                           if child is not None else {}))


# ----------------------------------------------------- tree / analysis


def build_trace_tree(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Assemble normalized span dicts (trace_id/span_id/parent_span_id/
    name/ts/dur/attrs) into one causal tree. Never drops anything:
    spans whose parent did not arrive (a crashed or late hop) surface
    in ``orphans``; extra parentless spans beyond the root do too."""
    nodes: Dict[str, Dict[str, Any]] = {}
    for s in spans:
        sid = s.get("span_id")
        if sid is None or sid in nodes:
            continue
        nodes[sid] = {
            "span_id": sid,
            "parent_span_id": s.get("parent_span_id"),
            "name": s.get("name"),
            "ts": s.get("ts"), "dur": s.get("dur", 0.0),
            "attrs": dict(s.get("attrs") or {}),
            "children": [],
        }
    rootless: List[Dict[str, Any]] = []
    orphans: List[Dict[str, Any]] = []
    for node in nodes.values():
        parent = node["parent_span_id"]
        if parent is None:
            rootless.append(node)
        elif parent in nodes:
            nodes[parent]["children"].append(node)
        else:
            orphans.append(node)
    for node in nodes.values():
        node["children"].sort(key=lambda c: c["ts"] or 0.0)
    rootless.sort(key=lambda n: n["ts"] or 0.0)
    root = next((n for n in rootless if n["attrs"].get("trace_root")),
                rootless[0] if rootless else None)
    orphans.extend(n for n in rootless if n is not root)
    return {"num_spans": len(spans), "root": root, "orphans": orphans}


def critical_path(tree: Dict[str, Any]) -> Dict[str, Any]:
    """Walk the tree root-down, always descending into the
    longest-duration child: the hops a request's latency actually
    flowed through. Each hop's ``self_s`` is its duration minus its
    children's (time spent *in* that hop, not waiting below it); the
    ``dominant`` hop is where the request's time went."""
    root = tree.get("root") if "root" in tree else tree
    if not root:
        return {"path": [], "dominant": None,
                "dominant_self_s": 0.0, "total_s": 0.0}
    path = []
    node = root
    while node is not None:
        kids = node.get("children") or []
        dur = node.get("dur") or 0.0
        self_s = max(0.0, dur - sum(c.get("dur") or 0.0 for c in kids))
        path.append({"name": node.get("name"),
                     "span_id": node.get("span_id"),
                     "dur": dur, "self_s": self_s})
        node = (max(kids, key=lambda c: c.get("dur") or 0.0)
                if kids else None)
    dominant = max(path, key=lambda h: h["self_s"])
    return {"path": path, "dominant": dominant["name"],
            "dominant_self_s": dominant["self_s"],
            "total_s": root.get("dur") or 0.0}


def span_tree() -> List[Dict[str, Any]]:
    """The cross-task call tree: each node is a task with its lifecycle
    timestamps, user spans, and children (tasks it submitted). SPAN
    events whose task node fell out of the lifecycle ring are surfaced
    under a synthetic ``(orphaned-spans)`` root, never dropped."""
    import ray_tpu

    events = ray_tpu.task_events()
    nodes: Dict[bytes, Dict[str, Any]] = {}
    spans: Dict[bytes, List[Dict[str, Any]]] = {}
    for e in events:
        if e["state"] == "SPAN":
            spans.setdefault(e["task_id"], []).append(
                {"name": e["name"], "ts": e["ts"], "dur": e.get("dur", 0),
                 "attrs": e.get("attrs", {})})
            continue
        node = nodes.setdefault(e["task_id"], {
            "task_id": e["task_id"].hex(), "name": e["name"],
            "states": {}, "children": [], "spans": [],
            "parent_task_id": None})
        node["states"][e["state"]] = e["ts"]
        if e.get("parent_task_id"):
            node["parent_task_id"] = e["parent_task_id"]
    lost: List[Dict[str, Any]] = []
    for tid, sp in spans.items():
        if tid in nodes:
            nodes[tid]["spans"] = sorted(sp, key=lambda s: s["ts"])
        else:
            for s in sp:
                s = dict(s)
                s["attrs"] = dict(s["attrs"]) | {"orphan": True}
                lost.append(s)
    roots = []
    for node in nodes.values():
        parent = node.pop("parent_task_id", None)
        pnode = nodes.get(parent) if parent else None
        if pnode is not None and pnode is not node:
            pnode["children"].append(node)
        else:
            roots.append(node)
    if lost:
        roots.append({"task_id": None, "name": "(orphaned-spans)",
                      "orphan": True, "states": {}, "children": [],
                      "spans": sorted(lost, key=lambda s: s["ts"])})
    return roots
