"""Lightweight task tracing (reference:
`python/ray/util/tracing/tracing_helper.py`, which wraps every remote
call/execution in OpenTelemetry spans and propagates context in task specs).

Here the runtime *already* propagates trace lineage natively: every
``TaskSpec`` carries ``parent_task_id``/``depth``, and the worker records
PENDING/RUNNING/FINISHED lifecycle events into the head's task-event ring
buffer. This module adds the user-facing span API on top:

    from ray_tpu.util import tracing

    @ray_tpu.remote
    def step():
        with tracing.span("load"):
            ...
        with tracing.span("compute", attrs={"n": 4}):
            ...

Spans attach to the current task (or the driver) and export through the
same GCS ring buffer; ``ray_tpu.timeline()`` renders them as nested rows
and ``span_tree()`` reconstructs the cross-task call tree from
``parent_task_id`` links — the role OpenTelemetry context propagation
plays in the reference.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional


def record_span(name: str, start: float, dur: float,
                attrs: Optional[Dict[str, Any]] = None) -> None:
    """Record a span with explicit wall-clock start/duration — for
    callers that reconstruct lifecycle phases after the fact (the LLM
    engine's queued/prefill/decode phases, jit-compile events)."""
    from ray_tpu._private.worker import global_worker_or_none

    w = global_worker_or_none()
    # Thin-client drivers (ray_tpu://) have no local event buffer;
    # spans there are a no-op rather than an AttributeError.
    if (w is not None and not getattr(w, "_dead", False)
            and hasattr(w, "_task_events_lock")):
        tid = w.current_task_id()
        event = {
            "task_id": tid.binary() if tid else b"driver",
            "name": name, "job_id": b"", "state": "SPAN",
            "ts": start, "dur": dur,
            "owner_pid": __import__("os").getpid(),
            "attrs": attrs or {},
        }
        with w._task_events_lock:
            w._task_events.append(event)


@contextmanager
def span(name: str, attrs: Optional[Dict[str, Any]] = None) -> Iterator[None]:
    """Record a named span inside the current task/driver. A raising
    body still records the span, tagged ``attrs["error"]`` with the
    exception type so timelines distinguish failures from successes."""
    start = time.time()
    attrs = dict(attrs) if attrs else {}
    try:
        yield
    except BaseException as e:
        attrs["error"] = type(e).__name__
        raise
    finally:
        record_span(name, start, time.time() - start, attrs)


def span_tree() -> List[Dict[str, Any]]:
    """The cross-task call tree: each node is a task with its lifecycle
    timestamps, user spans, and children (tasks it submitted)."""
    import ray_tpu

    events = ray_tpu.task_events()
    nodes: Dict[bytes, Dict[str, Any]] = {}
    spans: Dict[bytes, List[Dict[str, Any]]] = {}
    for e in events:
        if e["state"] == "SPAN":
            spans.setdefault(e["task_id"], []).append(
                {"name": e["name"], "ts": e["ts"], "dur": e.get("dur", 0),
                 "attrs": e.get("attrs", {})})
            continue
        node = nodes.setdefault(e["task_id"], {
            "task_id": e["task_id"].hex(), "name": e["name"],
            "states": {}, "children": [], "spans": [],
            "parent_task_id": None})
        node["states"][e["state"]] = e["ts"]
        if e.get("parent_task_id"):
            node["parent_task_id"] = e["parent_task_id"]
    for tid, sp in spans.items():
        if tid in nodes:
            nodes[tid]["spans"] = sorted(sp, key=lambda s: s["ts"])
    roots = []
    for node in nodes.values():
        parent = node.pop("parent_task_id", None)
        pnode = nodes.get(parent) if parent else None
        if pnode is not None and pnode is not node:
            pnode["children"].append(node)
        else:
            roots.append(node)
    return roots
