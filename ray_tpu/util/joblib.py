"""joblib backend over ray_tpu tasks (reference:
`python/ray/util/joblib/` — `register_ray()` + a backend that fans
scikit-learn/joblib work out as tasks).

    import joblib
    from ray_tpu.util.joblib import register_ray_tpu

    register_ray_tpu()
    with joblib.parallel_backend("ray_tpu"):
        joblib.Parallel()(joblib.delayed(f)(i) for i in range(100))
"""

from __future__ import annotations

from typing import Optional


def register_ray_tpu() -> None:
    import joblib

    joblib.register_parallel_backend("ray_tpu", RayTpuBackend)


def _make_backend_class():
    from joblib._parallel_backends import ParallelBackendBase

    class _RayTpuBackend(ParallelBackendBase):
        supports_timeout = True

        def __init__(self, *args, **kwargs):
            super().__init__(*args, **kwargs)
            self._pool = None

        def effective_n_jobs(self, n_jobs: Optional[int]) -> int:
            if n_jobs == 0:
                raise ValueError("n_jobs == 0 has no meaning")
            if n_jobs is None:
                return 1
            if n_jobs < 0:
                import ray_tpu

                try:
                    return max(1, int(
                        ray_tpu.cluster_resources().get("CPU", 1)))
                except Exception:
                    return 1
            return n_jobs

        def configure(self, n_jobs: int = 1, parallel=None, **kwargs):
            from ray_tpu.util.multiprocessing import Pool

            n_jobs = self.effective_n_jobs(n_jobs)
            self._pool = Pool(processes=n_jobs)
            self.parallel = parallel
            return n_jobs

        def apply_async(self, func, callback=None):
            return self._pool.apply_async(func, callback=callback)

        def terminate(self):
            if self._pool is not None:
                self._pool.terminate()
                self._pool = None

        def abort_everything(self, ensure_ready: bool = True):
            self.terminate()
            if ensure_ready:
                self.configure(n_jobs=self.parallel.n_jobs,
                               parallel=self.parallel)

    return _RayTpuBackend


class RayTpuBackend:
    """Lazy proxy: joblib internals import only when the backend is
    instantiated (keeps `ray_tpu.util` importable without joblib)."""

    def __new__(cls, *args, **kwargs):
        return _make_backend_class()(*args, **kwargs)
