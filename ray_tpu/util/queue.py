"""Distributed FIFO queue backed by a detached-capable actor.

Reference: `python/ray/util/queue.py` (Queue over an _QueueActor with
put/get/qsize/empty/full + *_nowait + batch variants).
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray_tpu.remote(num_cpus=0.5)
class _QueueActor:
    def __init__(self, maxsize: int):
        self._q: asyncio.Queue = asyncio.Queue(maxsize)

    async def put(self, item, timeout: Optional[float] = None) -> bool:
        try:
            await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout: Optional[float] = None):
        try:
            return True, await asyncio.wait_for(self._q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    def put_nowait(self, item) -> bool:
        try:
            self._q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    def get_nowait(self):
        try:
            return True, self._q.get_nowait()
        except asyncio.QueueEmpty:
            return False, None

    def put_nowait_batch(self, items: List[Any]) -> int:
        n = 0
        for item in items:
            if not self.put_nowait(item):
                break
            n += 1
        return n

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        out = []
        for _ in range(num_items):
            ok, item = self.get_nowait()
            if not ok:
                break
            out.append(item)
        return out

    def qsize(self) -> int:
        return self._q.qsize()


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        self.maxsize = maxsize
        self.actor = _QueueActor.options(**(actor_options or {})).remote(
            maxsize)

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            if not ray_tpu.get(self.actor.put_nowait.remote(item),
                               timeout=30):
                raise Full()
            return
        ok = ray_tpu.get(self.actor.put.remote(item, timeout),
                         timeout=(timeout or 3600) + 30)
        if not ok:
            raise Full()

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        if not block:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote(),
                                   timeout=30)
        else:
            ok, item = ray_tpu.get(self.actor.get.remote(timeout),
                                   timeout=(timeout or 3600) + 30)
        if not ok:
            raise Empty()
        return item

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        n = ray_tpu.get(self.actor.put_nowait_batch.remote(list(items)),
                        timeout=60)
        if n < len(items):
            raise Full(f"only {n}/{len(items)} items fit")

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        return ray_tpu.get(self.actor.get_nowait_batch.remote(num_items),
                           timeout=60)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote(), timeout=30)

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return self.maxsize > 0 and self.qsize() >= self.maxsize

    def shutdown(self) -> None:
        ray_tpu.kill(self.actor)
