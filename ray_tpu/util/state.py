"""State API SDK — programmatic cluster introspection.

Reference: `python/ray/util/state/api.py` (`ray.util.state.list_actors`
etc. over the GCS + per-raylet state RPCs,
`node_manager.proto:420-422`).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import ray_tpu


def _gcs():
    from ray_tpu._private.worker import global_worker

    return global_worker().gcs


def list_nodes() -> List[Dict[str, Any]]:
    return ray_tpu.nodes()


def list_actors(detail: bool = False) -> List[Dict[str, Any]]:
    out = []
    for info in _gcs().call("list_actors", timeout=30):
        row = {
            "actor_id": info["actor_id"].hex(),
            "class_name": info.get("class_name", ""),
            "state": info.get("state"),
            "name": info.get("name", ""),
            "node_id": (info.get("node_id") or b"").hex(),
            "worker_id": (info.get("worker_id") or b"").hex(),
        }
        if detail:
            row["death_cause"] = info.get("death_cause")
            row["num_restarts"] = info.get("restarts_used", 0)
        out.append(row)
    return out


def list_workers() -> List[Dict[str, Any]]:
    return [{
        "worker_id": w["worker_id"].hex(),
        "node_id": w["node_id"].hex(),
        "mode": w.get("mode"),
        "pid": w.get("pid"),
    } for w in _gcs().call("list_workers", timeout=30)]


def list_jobs() -> List[Dict[str, Any]]:
    return [{
        "job_id": j["job_id"].hex(),
        "state": j.get("state"),
        "metadata": j.get("metadata") or {},
    } for j in _gcs().call("list_jobs", timeout=30)]


def list_placement_groups() -> List[Dict[str, Any]]:
    return [{
        "placement_group_id": p["pg_id"].hex(),
        "state": p.get("state"),
        "strategy": p.get("strategy"),
        "bundles": p.get("bundles"),
        "name": p.get("name", ""),
    } for p in _gcs().call("list_placement_groups", timeout=30)]


def list_tasks(job_id: Optional[bytes] = None,
               limit: int = 1000) -> List[Dict[str, Any]]:
    """Latest lifecycle state per task from the GCS task-event table."""
    events = _gcs().call("get_task_events", job_id=job_id, limit=limit * 4,
                         timeout=30)
    latest: Dict[bytes, Dict[str, Any]] = {}
    for e in events:
        latest[e["task_id"]] = e
    out = []
    for e in list(latest.values())[-limit:]:
        out.append({
            "task_id": e["task_id"].hex(),
            "name": e.get("name"),
            "state": e.get("state"),
            "job_id": e["job_id"].hex() if e.get("job_id") else None,
            "ts": e.get("ts"),
        })
    return out


def list_objects() -> List[Dict[str, Any]]:
    """Per-node shared-memory store summaries (via raylet node_stats)."""
    from ray_tpu._private.worker import global_worker

    w = global_worker()
    out = []
    for node in _gcs().call("get_all_nodes", timeout=30):
        if node.get("state") != "ALIVE":
            continue
        client = w._raylet_for_node(node["node_id"])
        if client is None:
            continue
        try:
            stats = client.call("node_stats", timeout=15)
        except Exception:
            continue
        row = dict(stats.get("store") or {})
        row["node_id"] = node["node_id"].hex()
        row["num_workers"] = stats.get("num_workers")
        out.append(row)
    return out


def memory_summary(top_n: int = 10) -> Dict[str, Any]:
    """Cluster object-store memory introspection (reference: `ray memory`
    / `ray_private.internal_api.memory_summary`).

    Returns::

        {"nodes":   [per-node store stats: used/capacity/num_objects/
                     pinned_bytes/spilled_bytes, spill/restore/eviction
                     counters + cumulative spill/restore wall time],
         "totals":  the same counters summed across nodes,
         "top_objects": top-N objects cluster-wide by size, each with
                     node, size, sealed/pinned/spilled state, idle age,
                     and this driver's reference-count view (owned /
                     borrowed / untracked),
         "hints":   reference-leak heuristics — pinned primaries this
                     driver no longer tracks, long-idle pinned objects}
    """
    from ray_tpu._private.worker import global_worker

    w = global_worker()
    nodes: List[Dict[str, Any]] = []
    objects: List[Dict[str, Any]] = []
    for node in _gcs().call("get_all_nodes", timeout=30):
        if node.get("state") != "ALIVE":
            continue
        client = w._raylet_for_node(node["node_id"])
        if client is None:
            continue
        try:
            stats = client.call("memory_stats", top_n=max(top_n, 0),
                                timeout=15)
        except Exception:
            continue
        node_hex = node["node_id"].hex()
        row = dict(stats.get("store") or {})
        row["node_id"] = node_hex
        nodes.append(row)
        for obj in stats.get("objects") or []:
            obj = dict(obj)
            obj["node_id"] = node_hex
            objects.append(obj)

    totals: Dict[str, float] = {}
    for row in nodes:
        for k, v in row.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                totals[k] = totals.get(k, 0) + v

    objects.sort(key=lambda o: o.get("size", 0), reverse=True)
    top = objects[:top_n] if top_n else objects
    hints: List[str] = []
    for obj in top:
        try:
            oid = bytes.fromhex(obj["object_id"])
        except (KeyError, ValueError):
            oid = None
        snap = w.reference_counter.snapshot(oid) if oid is not None else None
        if snap is None:
            obj["reference"] = "untracked"
        elif snap["freed"]:
            obj["reference"] = "freed"
        else:
            obj["reference"] = ("owned" if snap["is_owned_by_us"]
                                else "borrowed")
        if obj.get("pinned") and obj["reference"] == "untracked":
            hints.append(
                f"object {obj['object_id'][:12]} ({obj['size']} B, node "
                f"{obj['node_id'][:12]}) is pinned but this driver holds "
                f"no reference — possible leaked primary (owner exited "
                f"without cleanup?)")
        elif obj.get("pinned") and obj.get("idle_s", 0) > 600:
            hints.append(
                f"object {obj['object_id'][:12]} ({obj['size']} B) pinned "
                f"and idle {obj['idle_s']:.0f}s — check for a retained "
                f"ObjectRef that is no longer needed")
    return {"nodes": nodes, "totals": totals, "top_objects": top,
            "hints": hints,
            "num_tracked_refs": w.reference_counter.num_tracked()}


def cluster_resources() -> Dict[str, float]:
    total: Dict[str, float] = {}
    for node in _gcs().call("get_all_nodes", timeout=30):
        if node.get("state") != "ALIVE":
            continue
        for k, v in (node.get("total") or {}).items():
            total[k] = total.get(k, 0) + v
    return total


def available_resources() -> Dict[str, float]:
    avail: Dict[str, float] = {}
    for node in _gcs().call("get_all_nodes", timeout=30):
        if node.get("state") != "ALIVE":
            continue
        for k, v in (node.get("available") or {}).items():
            avail[k] = avail.get(k, 0) + v
    return avail


def summary() -> Dict[str, Any]:
    nodes = ray_tpu.nodes()
    return {
        "nodes_alive": sum(1 for n in nodes if n["Alive"]),
        "nodes_dead": sum(1 for n in nodes if not n["Alive"]),
        "actors": len(list_actors()),
        "workers": len(list_workers()),
        "cluster_resources": cluster_resources(),
        "available_resources": available_resources(),
    }


def summary_tasks() -> List[Dict[str, Any]]:
    """Per-function-name rollup of task lifecycle states (reference:
    `ray summary tasks` / `util/state/summary.py`)."""
    from collections import defaultdict

    agg: Dict[str, Dict[str, int]] = defaultdict(lambda: defaultdict(int))
    for e in ray_tpu.task_events():
        if e.get("state") == "SPAN":
            continue
        agg[e["name"]][e["state"]] += 1
    out = []
    for name, states in sorted(agg.items()):
        # PENDING/RUNNING counts are event totals; net in-flight =
        # submitted minus finished/failed.
        out.append({"name": name, **dict(states),
                    "total": states.get("PENDING", 0)})
    return out


def list_cluster_events(event_type: Optional[str] = None,
                        severity: Optional[str] = None,
                        node_id: Optional[str] = None,
                        limit: int = 100) -> List[Dict[str, Any]]:
    """Typed failure-forensics events from the GCS ClusterEventLog
    (reference: `ray list cluster-events` / gcs event export). Filters:
    ``event_type`` (see ray_tpu.observability.EVENT_TYPES), ``severity``
    (INFO/WARNING/ERROR), ``node_id`` hex prefix."""
    return _gcs().call("list_cluster_events", event_type=event_type,
                       severity=severity, node_id=node_id, limit=limit,
                       timeout=30)


def summary_events() -> Dict[str, Any]:
    """Rollup of the ClusterEventLog: total recorded, currently
    buffered, and a type -> severity -> count table."""
    return _gcs().call("summary_cluster_events", timeout=30)


def get_log(task_id: Optional[str] = None, actor_id: Optional[str] = None,
            worker_id: Optional[str] = None,
            tail: int = 100) -> List[str]:
    """Retrieve log lines for one task, actor, or worker (reference:
    `ray.util.state.get_log`). Exactly one selector is required; IDs are
    hex strings (as returned by the list_* APIs / ``ref.task_id().hex()``).
    Task logs are sliced out of the owning worker's log file via the
    per-task attribution markers, so a pooled worker that ran many tasks
    returns only the requested task's lines. Served by the raylet from
    the on-disk log files, so logs of dead workers remain retrievable."""
    from ray_tpu._private.worker import global_worker

    selectors = [s for s in (task_id, actor_id, worker_id) if s]
    if len(selectors) != 1:
        raise ValueError(
            "get_log requires exactly one of task_id=, actor_id=, "
            "worker_id=")
    w = global_worker()
    gcs = _gcs()
    if actor_id is not None:
        # Resolve the actor to its current worker; the worker branch
        # below then finds the node.
        info = gcs.call("get_actor_info",
                        actor_id=bytes.fromhex(actor_id), timeout=30)
        if not info or not info.get("worker_id"):
            raise ValueError(f"actor {actor_id} not found or has no "
                             "worker")
        worker_id = info["worker_id"].hex()
    if worker_id is not None:
        node_hex = None
        for row in gcs.call("list_workers", timeout=30):
            if row["worker_id"].hex() == worker_id:
                node_hex = row["node_id"].hex()
                break
        if node_hex is None:
            raise ValueError(f"worker {worker_id} not found")
        client = w._raylet_for_node(bytes.fromhex(node_hex))
        if client is None:
            raise ValueError(f"node {node_hex[:12]} hosting worker "
                             f"{worker_id[:12]} is unreachable")
        reply = client.call("get_log",
                            worker_id=bytes.fromhex(worker_id),
                            tail=tail, timeout=30)
        return reply.get("lines", [])
    # task_id: the owning worker isn't tracked after the fact — fan out
    # to every alive node; the markers make non-owners return nothing.
    lines: List[str] = []
    for node in gcs.call("get_all_nodes", timeout=30):
        if node.get("state") != "ALIVE":
            continue
        client = w._raylet_for_node(node["node_id"])
        if client is None:
            continue
        try:
            reply = client.call("get_log", task_id=task_id, tail=tail,
                                timeout=30)
        except Exception:
            continue
        lines.extend(reply.get("lines", []))
    if tail:
        lines = lines[-int(tail):]
    return lines


def summary_actors() -> List[Dict[str, Any]]:
    """Per-class rollup of actor states (reference: `ray summary
    actors`)."""
    from collections import defaultdict

    agg: Dict[str, Dict[str, int]] = defaultdict(lambda: defaultdict(int))
    for a in list_actors():
        cls = a.get("class_name") or a.get("name") or "<anonymous>"
        agg[cls][a.get("state", "UNKNOWN")] += 1
    return [{"class": cls, **dict(states)}
            for cls, states in sorted(agg.items())]
