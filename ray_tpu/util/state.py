"""State API SDK — programmatic cluster introspection.

Reference: `python/ray/util/state/api.py` (`ray.util.state.list_actors`
etc. over the GCS + per-raylet state RPCs,
`node_manager.proto:420-422`).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import ray_tpu


def _gcs():
    from ray_tpu._private.worker import global_worker

    return global_worker().gcs


def list_nodes() -> List[Dict[str, Any]]:
    return ray_tpu.nodes()


def list_actors(detail: bool = False) -> List[Dict[str, Any]]:
    out = []
    for info in _gcs().call("list_actors", timeout=30):
        row = {
            "actor_id": info["actor_id"].hex(),
            "class_name": info.get("class_name", ""),
            "state": info.get("state"),
            "name": info.get("name", ""),
            "node_id": (info.get("node_id") or b"").hex(),
            "worker_id": (info.get("worker_id") or b"").hex(),
        }
        if detail:
            row["death_cause"] = info.get("death_cause")
            row["num_restarts"] = info.get("restarts_used", 0)
        out.append(row)
    return out


def list_workers() -> List[Dict[str, Any]]:
    return [{
        "worker_id": w["worker_id"].hex(),
        "node_id": w["node_id"].hex(),
        "mode": w.get("mode"),
        "pid": w.get("pid"),
    } for w in _gcs().call("list_workers", timeout=30)]


def list_jobs() -> List[Dict[str, Any]]:
    return [{
        "job_id": j["job_id"].hex(),
        "state": j.get("state"),
        "metadata": j.get("metadata") or {},
    } for j in _gcs().call("list_jobs", timeout=30)]


def list_placement_groups() -> List[Dict[str, Any]]:
    return [{
        "placement_group_id": p["pg_id"].hex(),
        "state": p.get("state"),
        "strategy": p.get("strategy"),
        "bundles": p.get("bundles"),
        "name": p.get("name", ""),
    } for p in _gcs().call("list_placement_groups", timeout=30)]


def list_tasks(job_id: Optional[bytes] = None,
               limit: int = 1000) -> List[Dict[str, Any]]:
    """Latest lifecycle state per task from the GCS task-event table."""
    events = _gcs().call("get_task_events", job_id=job_id, limit=limit * 4,
                         timeout=30)
    latest: Dict[bytes, Dict[str, Any]] = {}
    for e in events:
        latest[e["task_id"]] = e
    out = []
    for e in list(latest.values())[-limit:]:
        out.append({
            "task_id": e["task_id"].hex(),
            "name": e.get("name"),
            "state": e.get("state"),
            "job_id": e["job_id"].hex() if e.get("job_id") else None,
            "ts": e.get("ts"),
        })
    return out


def list_objects() -> List[Dict[str, Any]]:
    """Per-node shared-memory store summaries (via raylet node_stats)."""
    from ray_tpu._private.worker import global_worker

    w = global_worker()
    out = []
    for node in _gcs().call("get_all_nodes", timeout=30):
        if node.get("state") != "ALIVE":
            continue
        client = w._raylet_for_node(node["node_id"])
        if client is None:
            continue
        try:
            stats = client.call("node_stats", timeout=15)
        except Exception:
            continue
        row = dict(stats.get("store") or {})
        row["node_id"] = node["node_id"].hex()
        row["num_workers"] = stats.get("num_workers")
        out.append(row)
    return out


def memory_summary(top_n: int = 10) -> Dict[str, Any]:
    """Cluster object-store memory introspection (reference: `ray memory`
    / `ray_private.internal_api.memory_summary`).

    Returns::

        {"nodes":   [per-node store stats: used/capacity/num_objects/
                     pinned_bytes/spilled_bytes, spill/restore/eviction
                     counters + cumulative spill/restore wall time],
         "totals":  the same counters summed across nodes,
         "top_objects": top-N objects cluster-wide by size, each with
                     node, size, sealed/pinned/spilled state, idle age,
                     and this driver's reference-count view (owned /
                     borrowed / untracked),
         "hints":   reference-leak heuristics — pinned primaries this
                     driver no longer tracks, long-idle pinned objects}
    """
    from ray_tpu._private.worker import global_worker

    w = global_worker()
    nodes: List[Dict[str, Any]] = []
    objects: List[Dict[str, Any]] = []
    for node in _gcs().call("get_all_nodes", timeout=30):
        if node.get("state") != "ALIVE":
            continue
        client = w._raylet_for_node(node["node_id"])
        if client is None:
            continue
        try:
            stats = client.call("memory_stats", top_n=max(top_n, 0),
                                timeout=15)
        except Exception:
            continue
        node_hex = node["node_id"].hex()
        row = dict(stats.get("store") or {})
        row["node_id"] = node_hex
        nodes.append(row)
        for obj in stats.get("objects") or []:
            obj = dict(obj)
            obj["node_id"] = node_hex
            objects.append(obj)

    totals: Dict[str, float] = {}
    for row in nodes:
        for k, v in row.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                totals[k] = totals.get(k, 0) + v

    objects.sort(key=lambda o: o.get("size", 0), reverse=True)
    top = objects[:top_n] if top_n else objects
    hints: List[str] = []
    for obj in top:
        try:
            oid = bytes.fromhex(obj["object_id"])
        except (KeyError, ValueError):
            oid = None
        snap = w.reference_counter.snapshot(oid) if oid is not None else None
        if snap is None:
            obj["reference"] = "untracked"
        elif snap["freed"]:
            obj["reference"] = "freed"
        else:
            obj["reference"] = ("owned" if snap["is_owned_by_us"]
                                else "borrowed")
        if obj.get("pinned") and obj["reference"] == "untracked":
            hints.append(
                f"object {obj['object_id'][:12]} ({obj['size']} B, node "
                f"{obj['node_id'][:12]}) is pinned but this driver holds "
                f"no reference — possible leaked primary (owner exited "
                f"without cleanup?)")
        elif obj.get("pinned") and obj.get("idle_s", 0) > 600:
            hints.append(
                f"object {obj['object_id'][:12]} ({obj['size']} B) pinned "
                f"and idle {obj['idle_s']:.0f}s — check for a retained "
                f"ObjectRef that is no longer needed")
    return {"nodes": nodes, "totals": totals, "top_objects": top,
            "hints": hints,
            "num_tracked_refs": w.reference_counter.num_tracked()}


def cluster_resources() -> Dict[str, float]:
    total: Dict[str, float] = {}
    for node in _gcs().call("get_all_nodes", timeout=30):
        if node.get("state") != "ALIVE":
            continue
        for k, v in (node.get("total") or {}).items():
            total[k] = total.get(k, 0) + v
    return total


def available_resources() -> Dict[str, float]:
    avail: Dict[str, float] = {}
    for node in _gcs().call("get_all_nodes", timeout=30):
        if node.get("state") != "ALIVE":
            continue
        for k, v in (node.get("available") or {}).items():
            avail[k] = avail.get(k, 0) + v
    return avail


def summary() -> Dict[str, Any]:
    nodes = ray_tpu.nodes()
    return {
        "nodes_alive": sum(1 for n in nodes if n["Alive"]),
        "nodes_dead": sum(1 for n in nodes if not n["Alive"]),
        "actors": len(list_actors()),
        "workers": len(list_workers()),
        "cluster_resources": cluster_resources(),
        "available_resources": available_resources(),
    }


def summary_tasks() -> List[Dict[str, Any]]:
    """Per-function-name rollup of task lifecycle states (reference:
    `ray summary tasks` / `util/state/summary.py`)."""
    from collections import defaultdict

    agg: Dict[str, Dict[str, int]] = defaultdict(lambda: defaultdict(int))
    for e in ray_tpu.task_events():
        if e.get("state") == "SPAN":
            continue
        agg[e["name"]][e["state"]] += 1
    out = []
    for name, states in sorted(agg.items()):
        # PENDING/RUNNING counts are event totals; net in-flight =
        # submitted minus finished/failed.
        out.append({"name": name, **dict(states),
                    "total": states.get("PENDING", 0)})
    return out


def list_cluster_events(event_type: Optional[str] = None,
                        severity: Optional[str] = None,
                        node_id: Optional[str] = None,
                        limit: int = 100) -> List[Dict[str, Any]]:
    """Typed failure-forensics events from the GCS ClusterEventLog
    (reference: `ray list cluster-events` / gcs event export). Filters:
    ``event_type`` (see ray_tpu.observability.EVENT_TYPES), ``severity``
    (INFO/WARNING/ERROR), ``node_id`` hex prefix."""
    return _gcs().call("list_cluster_events", event_type=event_type,
                       severity=severity, node_id=node_id, limit=limit,
                       timeout=30)


def summary_events() -> Dict[str, Any]:
    """Rollup of the ClusterEventLog: total recorded, currently
    buffered, and a type -> severity -> count table."""
    return _gcs().call("summary_cluster_events", timeout=30)


def train_summary() -> Dict[str, Any]:
    """The training goodput & straggler rollup from the GCS step
    matrix: per-worker step counts / mean step wall / stall and
    straggler flags, the cluster goodput ratio (productive seconds
    over accounted wall), lost seconds by cause
    (stalled/recompiling/restarting/checkpointing), per-phase mean
    seconds, and the recent TRAIN_STRAGGLER flags. Answers "which
    worker is slowing the pod, and in which phase?" without logs."""
    return _gcs().call("train_summary", timeout=30)


def list_train_steps(worker: Optional[str] = None,
                     limit: int = 200) -> List[Dict[str, Any]]:
    """Newest-last rows of the cross-worker train step matrix (worker,
    step, wall_s, per-phase seconds, goodput snapshot), optionally
    filtered by worker label (e.g. ``train-0``, ``learner-1``)."""
    return _gcs().call("list_train_steps", worker=worker, limit=limit,
                       timeout=30)


def serve_accounting(top_n: Optional[int] = None,
                     trace_id: Optional[str] = None) -> Dict[str, Any]:
    """The serve cost-accounting rollup from the GCS accounting ring:
    top-N tenants by chip-seconds (tokens, KV block-seconds, prefill
    computed/avoided per tenant — "which tenant is eating the
    fleet?"), per-lane SLO attainment and burn rates (fast/slow
    windows), and ring occupancy. Pass the ``x-trace-id`` a routed
    request returned as ``trace_id`` to also get that request's own
    cost row under ``"request"``."""
    return _gcs().call("serve_accounting_summary", top_n=top_n,
                       trace_id=trace_id, timeout=30)


def list_serve_accounting(tenant: Optional[str] = None,
                          lane: Optional[str] = None,
                          trace_id: Optional[str] = None,
                          limit: int = 200) -> List[Dict[str, Any]]:
    """Newest-last per-request cost rows from the GCS accounting ring
    (tenant, lane, trace_id, tokens, block-seconds, chip-seconds per
    phase, speculative counts, TTFT/TPOT), optionally filtered."""
    return _gcs().call("list_serve_accounting", tenant=tenant,
                       lane=lane, trace_id=trace_id, limit=limit,
                       timeout=30)


def xla_summary(top_n: int = 8) -> Dict[str, Any]:
    """The fleet's compiled-program cost rollup from the GCS XLA ring:
    the current program set (one row per tracked function × argument
    signature × process) ranked by cumulative FLOPs, peak HBM bytes,
    and lost-to-roofline headroom seconds, plus roofline-verdict and
    measurement counts. Answers "which program is eating the fleet,
    and is it compute-, memory-, or comm-bound?" — rows whose
    ``measurement`` is ``"cpu"`` carry nominal-spec ratios that prove
    the plumbing, not performance."""
    return _gcs().call("xla_summary", top_n=top_n, timeout=30)


def list_xla_programs(fn: Optional[str] = None,
                      verdict: Optional[str] = None,
                      limit: int = 200) -> List[Dict[str, Any]]:
    """Newest-last program cost rows from the GCS XLA ring (fn,
    signature, flops, bytes accessed, HBM breakdown, sampled wall,
    MFU/MBU, roofline verdict), optionally filtered by function name
    or verdict (``compute-bound`` / ``memory-bound`` / ``comm-bound``
    / ``unsampled`` / ``unknown``)."""
    return _gcs().call("list_xla_programs", fn=fn, verdict=verdict,
                       limit=limit, timeout=30)


def get_log(task_id: Optional[str] = None, actor_id: Optional[str] = None,
            worker_id: Optional[str] = None,
            tail: int = 100) -> List[str]:
    """Retrieve log lines for one task, actor, or worker (reference:
    `ray.util.state.get_log`). Exactly one selector is required; IDs are
    hex strings (as returned by the list_* APIs / ``ref.task_id().hex()``).
    Task logs are sliced out of the owning worker's log file via the
    per-task attribution markers, so a pooled worker that ran many tasks
    returns only the requested task's lines. Served by the raylet from
    the on-disk log files, so logs of dead workers remain retrievable."""
    from ray_tpu._private.worker import global_worker

    selectors = [s for s in (task_id, actor_id, worker_id) if s]
    if len(selectors) != 1:
        raise ValueError(
            "get_log requires exactly one of task_id=, actor_id=, "
            "worker_id=")
    w = global_worker()
    gcs = _gcs()
    if actor_id is not None:
        # Resolve the actor to its current worker; the worker branch
        # below then finds the node.
        info = gcs.call("get_actor_info",
                        actor_id=bytes.fromhex(actor_id), timeout=30)
        if not info or not info.get("worker_id"):
            raise ValueError(f"actor {actor_id} not found or has no "
                             "worker")
        worker_id = info["worker_id"].hex()
    if worker_id is not None:
        node_hex = None
        for row in gcs.call("list_workers", timeout=30):
            if row["worker_id"].hex() == worker_id:
                node_hex = row["node_id"].hex()
                break
        if node_hex is None:
            raise ValueError(f"worker {worker_id} not found")
        client = w._raylet_for_node(bytes.fromhex(node_hex))
        if client is None:
            raise ValueError(f"node {node_hex[:12]} hosting worker "
                             f"{worker_id[:12]} is unreachable")
        reply = client.call("get_log",
                            worker_id=bytes.fromhex(worker_id),
                            tail=tail, timeout=30)
        return reply.get("lines", [])
    # task_id: the owning worker isn't tracked after the fact — fan out
    # to every alive node; the markers make non-owners return nothing.
    lines: List[str] = []
    for node in gcs.call("get_all_nodes", timeout=30):
        if node.get("state") != "ALIVE":
            continue
        client = w._raylet_for_node(node["node_id"])
        if client is None:
            continue
        try:
            reply = client.call("get_log", task_id=task_id, tail=tail,
                                timeout=30)
        except Exception:
            continue
        lines.extend(reply.get("lines", []))
    if tail:
        lines = lines[-int(tail):]
    return lines


def _resolve_actor_worker(actor_id: str) -> str:
    """actor id hex -> its current worker id hex (via the GCS actor
    table); raises ValueError for unknown/worker-less actors."""
    info = _gcs().call("get_actor_info",
                       actor_id=bytes.fromhex(actor_id), timeout=30)
    if not info or not info.get("worker_id"):
        raise ValueError(f"actor {actor_id} not found or has no worker")
    return info["worker_id"].hex()


def _worker_row(worker_id: str) -> Dict[str, Any]:
    """GCS registration row (node_id, addr, pid) for one worker id hex."""
    for row in _gcs().call("list_workers", timeout=30):
        if row["worker_id"].hex() == worker_id:
            return row
    raise ValueError(f"worker {worker_id} not found")


def stack(node_id: Optional[str] = None, worker_id: Optional[str] = None,
          actor_id: Optional[str] = None) -> Dict[str, Dict[str, Any]]:
    """Live all-thread Python stacks across the cluster (the `ray stack`
    equivalent). Selectors narrow the fan-out: ``actor_id`` -> that
    actor's worker, ``worker_id`` -> that worker, ``node_id`` (hex
    prefix) -> every worker on that node; with none, every worker on
    every alive node. Returns ``{worker_id_hex: {"pid", "threads":
    [{"thread_name", "stack", ...}], "stacks": text}}`` — unreachable
    workers report ``{"error": ...}`` instead of failing the sweep."""
    from ray_tpu._private.worker import global_worker

    if sum(bool(s) for s in (node_id, worker_id, actor_id)) > 1:
        raise ValueError("stack() takes at most one of node_id=, "
                         "worker_id=, actor_id=")
    w = global_worker()
    gcs = _gcs()
    if actor_id is not None:
        worker_id = _resolve_actor_worker(actor_id)
    target_worker = bytes.fromhex(worker_id) if worker_id else None
    if worker_id is not None:
        node_id = _worker_row(worker_id)["node_id"].hex()
    out: Dict[str, Dict[str, Any]] = {}
    for node in gcs.call("get_all_nodes", timeout=30):
        if node.get("state") != "ALIVE":
            continue
        if node_id and not node["node_id"].hex().startswith(node_id):
            continue
        client = w._raylet_for_node(node["node_id"])
        if client is None:
            continue
        try:
            out.update(client.call("dump_stacks", worker_id=target_worker,
                                   timeout=30) or {})
        except Exception as e:  # noqa: BLE001
            out[f"node-{node['node_id'].hex()[:12]}"] = {"error": repr(e)}
    return out


def profile(actor_id: Optional[str] = None,
            worker_id: Optional[str] = None,
            duration: float = 1.0,
            hz: Optional[float] = None) -> Dict[str, Any]:
    """Wall-clock flamegraph of one actor's (or worker's) process:
    samples every thread at ``hz`` for ``duration`` seconds and merges
    them into a collapsed-stack (``folded``) + speedscope
    (``speedscope``) payload with per-thread attribution.

    The window is chunked into short worker-side RPCs, so a target that
    dies mid-profile yields the samples gathered so far instead of a
    hang: the reply is tagged ``partial=True`` with the raylet's PR-4
    exit classification under ``exit`` (exit_type / detail) explaining
    *why* the profile came back short."""
    from ray_tpu._private.worker import global_worker
    from ray_tpu.observability import profiling as _profiling

    if sum(bool(s) for s in (worker_id, actor_id)) != 1:
        raise ValueError("profile() requires exactly one of actor_id=, "
                         "worker_id=")
    if actor_id is not None:
        worker_id = _resolve_actor_worker(actor_id)
    row = _worker_row(worker_id)
    w = global_worker()
    client = w._client_for(tuple(row["addr"]))
    counts: Dict[str, Dict[str, int]] = {}
    samples = dropped = 0
    sampled_s = 0.0
    partial = False
    exit_info: Optional[Dict[str, Any]] = None
    remaining = max(float(duration), 0.05)
    chunk = min(0.5, remaining)
    while remaining > 1e-3:
        win = min(chunk, remaining)
        try:
            reply = client.call("profile", duration_s=win, hz=hz,
                                timeout=win + 15)
        except Exception:  # noqa: BLE001 — died mid-window
            partial = True
            exit_info = _classify_worker_exit(w, row, worker_id)
            break
        _profiling.merge_counts(counts, reply.get("counts") or {})
        samples += reply.get("samples", 0)
        dropped += reply.get("dropped", 0)
        sampled_s += reply.get("duration_s", win)
        hz = reply.get("hz", hz)
        remaining -= win
    label = f"{'actor ' + actor_id if actor_id else 'worker ' + worker_id}"
    return {
        "worker_id": worker_id, "pid": row.get("pid"),
        "duration_s": sampled_s, "hz": hz,
        "samples": samples, "dropped": dropped,
        "folded": _profiling.collapse(counts),
        "speedscope": _profiling.render_speedscope(
            counts, name=f"ray_tpu profile: {label}"),
        "partial": partial, "exit": exit_info,
    }


def _classify_worker_exit(w, row: Dict[str, Any],
                          worker_id: str) -> Dict[str, Any]:
    """Why did the profile target go away mid-window? Ask its lessor
    raylet for the PR-4 exit classification (one short retry — the
    reaper polls every 200ms, the profiler often notices first)."""
    from ray_tpu.observability import events as _events

    info: Dict[str, Any] = {}
    client = w._raylet_for_node(row["node_id"])
    if client is not None:
        for attempt in range(2):
            try:
                info = client.call(
                    "get_worker_exit_info",
                    worker_id=bytes.fromhex(worker_id), timeout=5) or {}
            except Exception:  # noqa: BLE001
                info = {}
            if info.get("exit_type"):
                break
            if attempt == 0:
                import time as _time

                _time.sleep(0.5)
    else:
        info = {"exit_type": "NODE_DEATH"}
    out = dict(info)
    out.setdefault("exit_type", "SYSTEM_ERROR")
    try:
        out["detail"] = _events.format_exit_detail(info, None)
    except Exception:  # noqa: BLE001
        out["detail"] = ""
    return out


def tpu_profile(actor_id: Optional[str] = None,
                worker_id: Optional[str] = None,
                duration: float = 1.0) -> Dict[str, Any]:
    """Capture a jax.profiler device trace on the target worker for
    ``duration`` seconds and return ``{"artifact": path}`` (a TensorBoard
    / xprof-loadable trace directory on the worker's host). On a
    process without a TPU backend this is a no-op with a ``skipped``
    reason — host flamegraphs (:func:`profile`) still work there."""
    from ray_tpu._private.worker import global_worker

    if sum(bool(s) for s in (worker_id, actor_id)) != 1:
        raise ValueError("tpu_profile() requires exactly one of "
                         "actor_id=, worker_id=")
    if actor_id is not None:
        worker_id = _resolve_actor_worker(actor_id)
    row = _worker_row(worker_id)
    w = global_worker()
    client = w._client_for(tuple(row["addr"]))
    reply = client.call("tpu_profile", duration_s=float(duration),
                        timeout=float(duration) + 60)
    return reply


def summary_actors() -> List[Dict[str, Any]]:
    """Per-class rollup of actor states (reference: `ray summary
    actors`)."""
    from collections import defaultdict

    agg: Dict[str, Dict[str, int]] = defaultdict(lambda: defaultdict(int))
    for a in list_actors():
        cls = a.get("class_name") or a.get("name") or "<anonymous>"
        agg[cls][a.get("state", "UNKNOWN")] += 1
    return [{"class": cls, **dict(states)}
            for cls, states in sorted(agg.items())]


# ------------------------------------------------------------------- traces


def get_trace(trace_id: str) -> Optional[Dict[str, Any]]:
    """One request's causal tree from the GCS trace store, or None.
    Returns the assembled tree (``root``/``orphans``/``num_spans`` from
    ``tracing.build_trace_tree``) plus the store's verdict: ``complete``
    (the root span arrived and tail-sampling kept it), ``dur`` (root
    duration), ``error`` and ``keep_reason``. An in-flight trace comes
    back partial with ``complete`` False — debugging never waits on
    sampling."""
    from ray_tpu.util.tracing import build_trace_tree

    rec = _gcs().call("get_trace", trace_id=trace_id, timeout=30)
    if rec is None:
        return None
    tree = build_trace_tree(rec.get("spans") or [])
    tree.update({
        "trace_id": trace_id,
        "complete": bool(rec.get("complete")),
        "dur": rec.get("dur"),
        "error": rec.get("error", False),
        "keep_reason": rec.get("keep_reason"),
    })
    return tree


def list_traces(limit: int = 100) -> List[Dict[str, Any]]:
    """Summaries of kept traces, newest first (trace_id, root_name, ts,
    dur, error, keep_reason, num_spans)."""
    return _gcs().call("list_traces", limit=limit, timeout=30)


def trace_critical_path(tree_or_id: Any) -> Dict[str, Any]:
    """Critical path of a trace: pass either a tree from
    :func:`get_trace` or a bare trace_id string. Answers "where did
    this request's time go" — the dominant hop is the one with the most
    self-time along the longest-duration root-to-leaf walk."""
    from ray_tpu.util.tracing import critical_path

    tree = tree_or_id
    if isinstance(tree_or_id, str):
        tree = get_trace(tree_or_id)
        if tree is None:
            raise ValueError(f"no trace {tree_or_id!r} in the store")
    return critical_path(tree)
