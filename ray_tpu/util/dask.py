"""Dask-on-ray_tpu scheduler shim.

Reference: `python/ray/util/dask/__init__.py` + `scheduler.py:1`
(`ray_dask_get`: a dask custom scheduler that submits each graph task as
a Ray task, wiring dependencies as ObjectRefs so dask collections
execute on the cluster). Redesigned dependency-free: a dask graph is a
plain dict {key: spec} where spec is `(callable, *args)` with args that
may be other keys or nested lists/tuples — the scheduler needs no dask
import, so it works (and is tested) even though dask is not baked into
this image. With dask installed, use it as
``dask.compute(x, scheduler=ray_dask_get)``.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List

import ray_tpu

__all__ = ["ray_dask_get", "enable_dask_on_ray_tpu"]


def _is_task(spec: Any) -> bool:
    """Dask task convention: a tuple whose head is callable."""
    return isinstance(spec, tuple) and bool(spec) and callable(spec[0])


def _identity(x):
    return x


@ray_tpu.remote
def _exec_task(fn, template, *resolved):
    """One graph node. Dependency refs ride as TOP-LEVEL task args (the
    runtime resolves them before the body runs — no blocking worker-side
    gets, no hold-a-slot-while-waiting deadlock); `template` is the arg
    structure with _Slot placeholders marking where each value goes."""
    return fn(*_fill(template, resolved))


class _Slot:
    """Placeholder for the i-th flattened dependency."""

    def __init__(self, i: int):
        self.i = i


def _fill(node, values):
    if isinstance(node, _Slot):
        return values[node.i]
    if isinstance(node, list):
        return [_fill(x, values) for x in node]
    if isinstance(node, tuple):
        return tuple(_fill(x, values) for x in node)
    return node


def _toposort(dsk: Dict[Hashable, Any]) -> List[Hashable]:
    seen: Dict[Hashable, int] = {}   # 0=visiting, 1=done
    order: List[Hashable] = []

    def deps(spec, out):
        try:
            if spec in dsk:                 # tuple keys before containers
                out.append(spec)
                return
        except TypeError:
            pass
        if _is_task(spec):
            for a in spec[1:]:
                deps(a, out)
        elif isinstance(spec, (list, tuple)):
            for a in spec:
                deps(a, out)

    # Iterative DFS — dask graphs routinely contain 1000+-deep linear
    # chains, which would blow Python's recursion limit. A node popped
    # un-expanded while marked "visiting" must be an ancestor still open
    # (its finalize sentinel is pushed immediately on first expansion, so
    # duplicate edges finalize before their extra entries pop) -> cycle.
    for root in dsk:
        stack = [(root, False)]
        while stack:
            key, expanded = stack.pop()
            state = seen.get(key)
            if expanded:
                seen[key] = 1
                order.append(key)
                continue
            if state == 1:
                continue
            if state == 0:
                raise ValueError(f"cycle in dask graph at {key!r}")
            seen[key] = 0
            stack.append((key, True))                 # finalize sentinel
            out: List[Hashable] = []
            deps(dsk[key], out)
            for d in out:
                if seen.get(d) != 1:
                    stack.append((d, False))
    return order


def ray_dask_get(dsk: Dict[Hashable, Any], keys, timeout: float = None,
                 **_ignored):
    """Execute a dask graph on the cluster; one ray task per graph task,
    dependencies passed as ObjectRefs (the scheduler never materializes
    intermediate results driver-side). `keys` may be a key, or an
    arbitrarily nested list of keys (dask collection convention); the
    result mirrors its shape. `timeout` bounds the final gather (default
    unbounded — a scheduler must not fail a long critical path)."""

    refs: Dict[Hashable, Any] = {}

    def templatize(arg, deps: List[Any]):
        """Replace keys/inline-tasks (at any nesting depth) by _Slot
        placeholders, appending the backing ref to `deps`."""
        # Key check FIRST: dask keys are commonly tuples like ("x", 0),
        # which must resolve as references, not be walked as containers.
        try:
            if arg in refs:
                deps.append(refs[arg])
                return _Slot(len(deps) - 1)
        except TypeError:
            pass                                      # unhashable spec
        if _is_task(arg):
            deps.append(_submit(arg))                 # inline nested task
            return _Slot(len(deps) - 1)
        if isinstance(arg, list):
            return [templatize(a, deps) for a in arg]
        if isinstance(arg, tuple):
            return tuple(templatize(a, deps) for a in arg)
        return arg

    def _submit(spec):
        deps: List[Any] = []
        template = [templatize(a, deps) for a in spec[1:]]
        return _exec_task.remote(spec[0], template, *deps)

    for key in _toposort(dsk):
        spec = dsk[key]
        if _is_task(spec):
            refs[key] = _submit(spec)
        elif isinstance(spec, (list, tuple)):
            # Collection-of-keys value: materialize as its own task.
            deps: List[Any] = []
            template = templatize(spec, deps)
            refs[key] = _exec_task.remote(_identity, [template], *deps)
        elif isinstance(spec, Hashable) and spec in refs:
            refs[key] = refs[spec]                    # alias key
        else:
            refs[key] = ray_tpu.put(spec)             # literal data

    def resolve(k):
        if isinstance(k, list):
            return [resolve(x) for x in k]
        return ray_tpu.get(refs[k], timeout=timeout)

    return resolve(keys)


def enable_dask_on_ray_tpu() -> None:
    """Install ray_dask_get as dask's default scheduler (requires dask)."""
    import dask

    dask.config.set(scheduler=ray_dask_get)
