"""ray_tpu.util — user-facing utilities (reference: `python/ray/util/`)."""

from ray_tpu.util.actor_pool import ActorPool

__all__ = ["ActorPool"]
