"""ray_tpu.util — user-facing utilities (reference: `python/ray/util/`)."""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util import metrics, tracing

__all__ = ["ActorPool", "metrics", "tracing"]
