"""Collective communication API.

Function-for-function parity with the reference's `util/collective/collective.py`
(`init_collective_group :40`, `create_collective_group :120`, `allreduce :258`,
`barrier :298`, `reduce :311`, `broadcast :373`, `allgather :423`,
`reducescatter :472`, `send :531`, `recv :594`), re-based on TPU-native
backends: ``xla`` (jax.distributed + XLA collectives over ICI/DCN) and
``shm`` (CPU host tensors via the coordinator hub).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ray_tpu.util.collective.types import Backend, ReduceOp


class GroupManager:
    """Per-process registry of collective groups (reference `GroupManager`)."""

    def __init__(self):
        self._groups: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def create_group(self, backend: str, world_size: int, rank: int,
                     group_name: str, **options):
        backend = Backend.validate(backend)
        with self._lock:
            if group_name in self._groups:
                raise RuntimeError(
                    f"collective group {group_name!r} already initialized in "
                    "this process")
        if backend == Backend.XLA:
            from ray_tpu.util.collective.collective_group.xla_collective_group \
                import XLAGroup

            group = XLAGroup(world_size, rank, group_name, **options)
        elif backend == Backend.PALLAS:
            from ray_tpu.util.collective.collective_group \
                .pallas_collective_group import PallasGroup

            group = PallasGroup(world_size, rank, group_name, **options)
        else:
            from ray_tpu.util.collective.collective_group.shm_collective_group \
                import SHMGroup

            group = SHMGroup(world_size, rank, group_name)
        with self._lock:
            self._groups[group_name] = group
        return group

    def get_group(self, group_name: str):
        group = self._groups.get(group_name)
        if group is None:
            raise RuntimeError(
                f"collective group {group_name!r} is not initialized in this "
                "process; call init_collective_group first")
        return group

    def is_group_initialized(self, group_name: str) -> bool:
        return group_name in self._groups

    def destroy_group(self, group_name: str):
        group = self._groups.pop(group_name, None)
        if group is not None:
            group.destroy()


_group_mgr = GroupManager()


def init_collective_group(world_size: int, rank: int,
                          backend: str = Backend.XLA,
                          group_name: str = "default", **options) -> None:
    """Initialize this process's membership in a collective group.

    Call from inside each participating actor/task (reference
    `collective.py:40`)."""
    if not (0 <= rank < world_size):
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    _group_mgr.create_group(backend, world_size, rank, group_name, **options)


def create_collective_group(actors: List[Any], world_size: int,
                            ranks: List[int], backend: str = Backend.XLA,
                            group_name: str = "default") -> None:
    """Driver-side declaration: make every actor join the group
    (reference `collective.py:120`). Blocks until all members are in."""
    import ray_tpu

    if len(actors) != world_size or sorted(ranks) != list(range(world_size)):
        raise ValueError("need exactly world_size actors with ranks 0..n-1")
    refs = [
        actor._init_collective.remote(world_size, rank, backend, group_name)
        for actor, rank in zip(actors, ranks)
    ]
    ray_tpu.get(refs, timeout=300)


def is_group_initialized(group_name: str = "default") -> bool:
    return _group_mgr.is_group_initialized(group_name)


def destroy_collective_group(group_name: str = "default") -> None:
    _group_mgr.destroy_group(group_name)


def get_rank(group_name: str = "default") -> int:
    return _group_mgr.get_group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group_mgr.get_group(group_name).world_size


def get_group_mesh(group_name: str = "default", axis_name: str = "x"):
    """TPU-native extension: the group's global `jax.sharding.Mesh` for
    writing pjit/shard_map programs whose collectives ride ICI."""
    group = _group_mgr.get_group(group_name)
    if not hasattr(group, "get_mesh"):
        raise RuntimeError(
            f"group {group_name!r} uses backend without a device mesh; use "
            "backend='xla'")
    return group.get_mesh(axis_name)


# ---------------------------------------------------------------------------
# Collective ops (value-returning: functional style fits jax; the reference
# mutates torch tensors in place, which has no jax analogue).  Every op is
# metered: rtpu_collective_{ops,bytes}_total{op,backend,dtype}, an
# op-latency histogram and a `collective:<op>` timeline span.
# ---------------------------------------------------------------------------

def _backend_name(group) -> str:
    return getattr(group, "backend_name", type(group).__name__.lower()
                   .replace("group", ""))


def _observed(op_name: str, group, tensor=None):
    from ray_tpu.observability.collective import observe_collective

    return observe_collective(op_name, _backend_name(group), tensor)


def allreduce(tensor, group_name: str = "default",
              op: ReduceOp = ReduceOp.SUM):
    group = _group_mgr.get_group(group_name)
    with _observed("allreduce", group, tensor):
        return group.allreduce(tensor, op)


def barrier(group_name: str = "default") -> None:
    group = _group_mgr.get_group(group_name)
    with _observed("barrier", group):
        group.barrier()


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: ReduceOp = ReduceOp.SUM):
    group = _group_mgr.get_group(group_name)
    with _observed("reduce", group, tensor):
        return group.reduce(tensor, dst_rank, op)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    group = _group_mgr.get_group(group_name)
    with _observed("broadcast", group, tensor):
        return group.broadcast(tensor, src_rank)


def allgather(tensor, group_name: str = "default") -> List[Any]:
    group = _group_mgr.get_group(group_name)
    with _observed("allgather", group, tensor):
        return group.allgather(tensor)


def reducescatter(tensor, group_name: str = "default",
                  op: ReduceOp = ReduceOp.SUM):
    group = _group_mgr.get_group(group_name)
    with _observed("reducescatter", group, tensor):
        return group.reducescatter(tensor, op)


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    group = _group_mgr.get_group(group_name)
    with _observed("send", group, tensor):
        group.send(tensor, dst_rank)


def recv(src_rank: int, group_name: str = "default"):
    group = _group_mgr.get_group(group_name)
    with _observed("recv", group):
        return group.recv(src_rank)
