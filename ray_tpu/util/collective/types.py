"""Collective types (reference: `python/ray/util/collective/types.py`)."""

from __future__ import annotations

import enum


class Backend:
    XLA = "xla"      # jax.distributed + XLA collectives over ICI/DCN (TPU path)
    PALLAS = "pallas"  # hand-written Pallas ring kernels over ICI RDMA
    SHM = "shm"      # hub-actor CPU backend (gloo-equivalent for host tensors)
    # Alias kept for API familiarity with the reference ("gloo" on CPU).
    GLOO = "shm"

    @staticmethod
    def validate(name: str) -> str:
        if name in (Backend.XLA,):
            return Backend.XLA
        if name in (Backend.PALLAS,):
            return Backend.PALLAS
        if name in ("shm", "gloo", "cpu"):
            return Backend.SHM
        raise ValueError(
            f"unknown collective backend {name!r}; ray_tpu supports 'xla' "
            "(TPU/ICI via jax), 'pallas' (Pallas ring kernels over ICI, "
            "lax fallback off-TPU) and 'shm'/'gloo' (CPU host tensors)")


class ReduceOp(enum.Enum):
    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3
    AVERAGE = 4
