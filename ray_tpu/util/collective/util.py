"""Rendezvous + hub actor for collective groups.

Role-equivalent to the reference's `NCCLUniqueIDStore` named actor
(`util/collective/util.py:9`, `nccl_collective_group.py:28,573`): group
members find each other through a named actor. Here the same actor also
implements the SHM backend's data plane (gather-reduce-scatter rounds) and
host-level send/recv mailboxes.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.util.collective.types import ReduceOp


def _reduce(arrays: List[np.ndarray], op: ReduceOp) -> np.ndarray:
    stack = np.stack([np.asarray(a) for a in arrays])
    if op == ReduceOp.SUM:
        return stack.sum(axis=0)
    if op == ReduceOp.PRODUCT:
        return stack.prod(axis=0)
    if op == ReduceOp.MIN:
        return stack.min(axis=0)
    if op == ReduceOp.MAX:
        return stack.max(axis=0)
    if op == ReduceOp.AVERAGE:
        return stack.mean(axis=0)
    raise ValueError(f"unsupported reduce op {op}")


@ray_tpu.remote(max_concurrency=256)
class CollectiveCoordinator:
    """Named async actor: rendezvous KV + SHM-backend collective hub.

    One instance per group, named ``collective_group:{group_name}``.
    """

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.kv: Dict[str, Any] = {}
        self.kv_events: Dict[str, asyncio.Event] = {}
        # op_uid -> {"data": {rank: payload}, "event": Event, "result": Any}
        self.rounds: Dict[str, Dict] = {}
        # (src, dst, tag) -> payload mailboxes for send/recv
        self.mailboxes: Dict[tuple, Any] = {}
        self.mail_events: Dict[tuple, asyncio.Event] = {}

    # ---- rendezvous KV ----------------------------------------------------
    async def put(self, key: str, value: Any):
        self.kv[key] = value
        self.kv_events.setdefault(key, asyncio.Event()).set()
        return True

    async def get(self, key: str, timeout: float = 60.0):
        ev = self.kv_events.setdefault(key, asyncio.Event())
        if key not in self.kv:
            try:
                await asyncio.wait_for(ev.wait(), timeout)
            except asyncio.TimeoutError:
                return None
        return self.kv.get(key)

    # ---- collective rounds (SHM backend data plane) -----------------------
    def _round(self, op_uid: str) -> Dict:
        if op_uid not in self.rounds:
            self.rounds[op_uid] = {"data": {}, "event": asyncio.Event(),
                                   "result": None}
        return self.rounds[op_uid]

    async def gather_round(self, op_uid: str, rank: int, payload: Any,
                           timeout: float = 300.0) -> Dict[int, Any]:
        """All ranks contribute; every caller gets the full {rank: payload}."""
        rnd = self._round(op_uid)
        rnd["data"][rank] = payload
        if len(rnd["data"]) == self.world_size:
            rnd["event"].set()
        else:
            await asyncio.wait_for(rnd["event"].wait(), timeout)
        data = rnd["data"]
        # Last rank to observe completion cleans up.
        rnd.setdefault("seen", set()).add(rank)
        if len(rnd["seen"]) == self.world_size:
            self.rounds.pop(op_uid, None)
        return data

    async def barrier(self, op_uid: str, rank: int, timeout: float = 300.0):
        await self.gather_round(op_uid, rank, None, timeout)
        return True

    # ---- send/recv mailboxes ---------------------------------------------
    async def send(self, src: int, dst: int, tag: str, payload: Any):
        key = (src, dst, tag)
        self.mailboxes[key] = payload
        self.mail_events.setdefault(key, asyncio.Event()).set()
        return True

    async def recv(self, src: int, dst: int, tag: str,
                   timeout: float = 300.0):
        key = (src, dst, tag)
        ev = self.mail_events.setdefault(key, asyncio.Event())
        if key not in self.mailboxes:
            await asyncio.wait_for(ev.wait(), timeout)
        payload = self.mailboxes.pop(key)
        self.mail_events.pop(key, None)
        return payload


def get_or_create_coordinator(group_name: str, world_size: int):
    """Named-actor rendezvous: first caller creates, others attach."""
    name = f"collective_group:{group_name}"
    return CollectiveCoordinator.options(
        name=name, get_if_exists=True, lifetime="detached",
        max_concurrency=256).remote(world_size)
