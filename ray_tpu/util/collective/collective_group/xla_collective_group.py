"""XLA collective backend — the TPU-native tensor plane.

This is the component the reference gains in the TPU build (SURVEY §2.3,
§5): the equivalent of `nccl_collective_group.py` where

- rendezvous = a named coordinator actor (exactly the `NCCLUniqueIDStore`
  pattern at `nccl_collective_group.py:28`): rank 0 publishes the
  `jax.distributed` coordinator address; every member calls
  `jax.distributed.initialize(coordinator, world_size, rank)`;
- the data plane = XLA collectives compiled over the global device mesh:
  over ICI within a pod slice, DCN across slices — never gRPC/sockets.

Two usage tiers:
1. Host-level API parity (`allreduce(numpy_tensor)` etc.): implemented with
   jitted psum/all_gather over the global 1-D process mesh. Convenient, pays
   host<->device transfer per call.
2. The REAL training path: get the group's `Mesh` via `get_mesh()` (or
   `device_mesh(axes=...)`) and write pjit/shard_map programs whose
   `jax.lax.psum/all_gather/ppermute` lower directly onto ICI. The Train
   JaxBackend does exactly this.
"""

from __future__ import annotations

import os
import socket
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

import ray_tpu
from ray_tpu.util.collective.collective_group.base_collective_group import (
    BaseGroup,
)
from ray_tpu.util.collective.types import ReduceOp
from ray_tpu.util.collective.util import get_or_create_coordinator


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class XLAGroup(BaseGroup):
    backend_name = "xla"

    def __init__(self, world_size: int, rank: int, group_name: str,
                 platform: Optional[str] = None,
                 local_device_count: Optional[int] = None):
        super().__init__(world_size, rank, group_name)
        self._hub = get_or_create_coordinator(group_name, world_size)
        self._init_jax_distributed(platform, local_device_count)
        import jax

        self._jax = jax
        self._mesh_cache: dict = {}

    # ------------------------------------------------------------ rendezvous
    def _init_jax_distributed(self, platform, local_device_count) -> None:
        import jax

        if platform:
            jax.config.update("jax_platforms", platform)
        if local_device_count and platform == "cpu":
            jax.config.update("jax_num_cpu_devices", local_device_count)

        if self.world_size == 1:
            return  # single-process: plain jax, no distributed runtime

        key = "jax_coordinator"
        if self.rank == 0:
            coordinator = f"127.0.0.1:{_free_port()}"
            host = os.environ.get("RAY_TPU_NODE_IP")
            if host:
                coordinator = f"{host}:{_free_port()}"
            ray_tpu.get(self._hub.put.remote(key, coordinator), timeout=60)
        else:
            coordinator = ray_tpu.get(self._hub.get.remote(key, 120.0),
                                      timeout=130)
            if coordinator is None:
                raise TimeoutError(
                    "rank 0 never published the jax coordinator address")
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=self.world_size,
            process_id=self.rank,
        )

    # ---------------------------------------------------------------- meshes
    def get_mesh(self, axis_name: str = "x"):
        """1-D mesh over every device in the group — the substrate for
        in-jit collectives over ICI."""
        return self.device_mesh((-1,), (axis_name,))

    def device_mesh(self, shape: Sequence[int], axis_names: Sequence[str]):
        """An N-D `jax.sharding.Mesh` over the group's global devices."""
        key = (tuple(shape), tuple(axis_names))
        if key not in self._mesh_cache:
            jax = self._jax
            devices = np.array(jax.devices())
            self._mesh_cache[key] = jax.sharding.Mesh(
                devices.reshape(shape), tuple(axis_names))
        return self._mesh_cache[key]

    # ---------------------------------------------------- host-level parity
    def _process_allgather(self, tensor) -> np.ndarray:
        from jax.experimental import multihost_utils

        return np.asarray(
            multihost_utils.process_allgather(np.asarray(tensor)))

    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        gathered = self._gather_stack(tensor)
        if op == ReduceOp.SUM:
            return gathered.sum(axis=0)
        if op == ReduceOp.PRODUCT:
            return gathered.prod(axis=0)
        if op == ReduceOp.MIN:
            return gathered.min(axis=0)
        if op == ReduceOp.MAX:
            return gathered.max(axis=0)
        if op == ReduceOp.AVERAGE:
            return gathered.mean(axis=0)
        raise ValueError(f"unsupported op {op}")

    def _gather_stack(self, tensor) -> np.ndarray:
        if self.world_size == 1:
            return np.asarray(tensor)[None]
        return self._process_allgather(tensor)

    def barrier(self):
        if self.world_size == 1:
            return
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(
            f"ray_tpu:{self.group_name}:barrier")

    def reduce(self, tensor, dst_rank: int = 0, op: ReduceOp = ReduceOp.SUM):
        out = self.allreduce(tensor, op)
        return out if self.rank == dst_rank else tensor

    def broadcast(self, tensor, src_rank: int = 0):
        gathered = self._gather_stack(tensor)
        return gathered[src_rank]

    def allgather(self, tensor) -> List[Any]:
        gathered = self._gather_stack(tensor)
        return [gathered[r] for r in range(self.world_size)]

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        full = self.allreduce(tensor, op)
        return np.array_split(full, self.world_size, axis=0)[self.rank]

    def send(self, tensor, dst_rank: int):
        # Point-to-point doesn't fit SPMD; route via the coordinator actor.
        ray_tpu.get(self._hub.send.remote(
            self.rank, dst_rank, "xla_p2p", np.asarray(tensor)), timeout=300)

    def recv(self, src_rank: int):
        return ray_tpu.get(self._hub.recv.remote(
            src_rank, self.rank, "xla_p2p"), timeout=300)

    def destroy(self):
        if self.world_size > 1:
            try:
                self._jax.distributed.shutdown()
            except Exception:
                pass
