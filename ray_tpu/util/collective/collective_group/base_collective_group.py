"""Backend interface (reference: `collective_group/base_collective_group.py`)."""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, List

from ray_tpu.util.collective.types import ReduceOp


class BaseGroup(ABC):
    def __init__(self, world_size: int, rank: int, group_name: str):
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name

    @abstractmethod
    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM): ...

    @abstractmethod
    def barrier(self): ...

    @abstractmethod
    def reduce(self, tensor, dst_rank: int = 0,
               op: ReduceOp = ReduceOp.SUM): ...

    @abstractmethod
    def broadcast(self, tensor, src_rank: int = 0): ...

    @abstractmethod
    def allgather(self, tensor) -> List[Any]: ...

    @abstractmethod
    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM): ...

    @abstractmethod
    def send(self, tensor, dst_rank: int): ...

    @abstractmethod
    def recv(self, src_rank: int): ...

    def destroy(self):
        pass
