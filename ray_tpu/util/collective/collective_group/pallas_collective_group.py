"""Pallas ICI collective backend.

The `pallas` backend shares the XLA group's control plane — rendezvous via
the named coordinator actor, `jax.distributed.initialize`, group meshes —
but routes the data plane through the hand-written ring kernels in
`ray_tpu.util.collective.pallas` (`pltpu.make_async_remote_copy`
double-buffered rings) instead of XLA's stock collectives.  That makes the
wire schedule ours to shape: the EQuARX-style int8 variant halves-to-
quarters allreduce bytes on bandwidth-bound links, something XLA's psum
cannot be told to do.

Implementation resolution per op (see `pallas.ring.select_impl`):
TPU backend → compiled Pallas kernels; CPU with
``RAY_TPU_PALLAS_INTERPRET=1`` → the same kernels under the Pallas
interpreter (what the tier-1 tests exercise); anything else → automatic
fallback to `jax.lax` collectives, so a `pallas` group degrades gracefully
off-TPU rather than failing.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.util.collective.collective_group.xla_collective_group import (
    XLAGroup,
)
from ray_tpu.util.collective.types import ReduceOp

_RING_OPS = {
    ReduceOp.SUM: "sum",
    ReduceOp.AVERAGE: "avg",
    ReduceOp.MIN: "min",
    ReduceOp.MAX: "max",
    ReduceOp.PRODUCT: "prod",
}


class PallasGroup(XLAGroup):
    """Collective group whose device-side ops are Pallas ring kernels.

    Host-level API parity ops accept numpy/jax arrays like `XLAGroup`; the
    real training path pulls `get_mesh()` / `ring_collective()` and runs
    the kernels inside its own jitted step.
    """

    backend_name = "pallas"

    def __init__(self, world_size: int, rank: int, group_name: str,
                 platform: Optional[str] = None,
                 local_device_count: Optional[int] = None,
                 quantized: bool = False):
        super().__init__(world_size, rank, group_name,
                         platform=platform,
                         local_device_count=local_device_count)
        self._quantized = quantized
        self._fn_cache: dict = {}

    # ------------------------------------------------------------ resolution
    def resolved_impl(self) -> str:
        from ray_tpu.util.collective.pallas import select_impl

        return select_impl("auto")

    def uses_pallas(self) -> bool:
        return self.resolved_impl() != "lax"

    # ------------------------------------------------------------- data plane
    def _ring_fn(self, kind: str, axis_name: str, op: str, shape_key):
        """jit(shard_map(ring kernel)) over the group's 1-D device mesh,
        cached per (kind, op, shape/dtype) to avoid retraces."""
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        import jax

        from ray_tpu.util.collective import pallas as rk

        key = (kind, axis_name, op, shape_key)
        if key in self._fn_cache:
            return self._fn_cache[key]
        mesh = self.get_mesh(axis_name)
        n = int(np.prod(mesh.devices.shape))

        if kind == "allreduce":
            def fn(x):
                return rk.ring_allreduce(x, axis_name, n=n, op=op)
        elif kind == "quantized_allreduce":
            def fn(x):
                return rk.quantized_ring_allreduce(x, axis_name, n=n, op=op)
        elif kind == "allgather":
            def fn(x):
                return rk.ring_allgather(x, axis_name, n=n)
        elif kind == "reducescatter":
            def fn(x):
                return rk.ring_reduce_scatter(x, axis_name, n=n, op=op)
        else:
            raise ValueError(kind)

        out_specs = P(None, axis_name) if kind == "allgather" \
            else P(axis_name)
        wrapped = jax.jit(shard_map(
            fn, mesh=mesh, in_specs=P(axis_name),
            out_specs=out_specs, check_rep=False))
        self._fn_cache[key] = wrapped
        return wrapped

    def _global_from_local(self, tensor, axis_name: str):
        """Stack the per-rank host tensor into a global device array
        sharded over the group axis (each process contributes its rank's
        slab)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.get_mesh(axis_name)
        local = np.asarray(tensor)
        sharding = NamedSharding(mesh, P(axis_name))
        n_devices = int(np.prod(mesh.devices.shape))
        global_shape = (n_devices * local.shape[0],) + local.shape[1:]
        local_devices = [d for d in mesh.devices.flat
                         if d.process_index == jax.process_index()]
        arrays = [jax.device_put(local, d) for d in local_devices]
        return jax.make_array_from_single_device_arrays(
            global_shape, sharding, arrays)

    def device_allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM,
                         axis_name: str = "x", quantized: bool = None):
        """Allreduce a per-rank tensor through the ring kernels (device
        path).  Returns this rank's (identical) copy as numpy."""
        if quantized is None:
            quantized = self._quantized
        local = np.asarray(tensor)
        kind = "quantized_allreduce" if quantized else "allreduce"
        fn = self._ring_fn(kind, axis_name, _RING_OPS[op],
                           (local.shape, str(local.dtype)))
        glob = self._global_from_local(local[None], axis_name)
        out = fn(glob)
        return np.asarray(out.addressable_data(0))[0]

    # Host-level parity ops ride the device ring when viable; XLAGroup's
    # process_allgather parity path stays as the multi-host host fallback.
    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        if self.uses_pallas() and op in _RING_OPS:
            try:
                return self.device_allreduce(tensor, op)
            except Exception:
                pass  # fall back to the host parity path below
        return super().allreduce(tensor, op)

    def destroy(self):
        self._fn_cache.clear()
        super().destroy()
