"""SHM (CPU) collective backend — the gloo-equivalent for host tensors.

Reference analogue: `collective_group/gloo_collective_group.py` (565 LoC,
rendezvous via a pluggable store). Data plane: every collective is a
gather round through the group's named coordinator actor; payloads ride the
object store (zero-copy shared memory intra-node). Correct and simple; the
high-bandwidth tensor path on TPU is the XLA backend, not this one.
"""

from __future__ import annotations

from typing import Any, List

import numpy as np

import ray_tpu
from ray_tpu.util.collective.collective_group.base_collective_group import (
    BaseGroup,
)
from ray_tpu.util.collective.types import ReduceOp
from ray_tpu.util.collective.util import _reduce, get_or_create_coordinator


class SHMGroup(BaseGroup):
    backend_name = "shm"

    def __init__(self, world_size: int, rank: int, group_name: str):
        super().__init__(world_size, rank, group_name)
        self._hub = get_or_create_coordinator(group_name, world_size)
        self._op_counter = 0
        # Point-to-point tags sequence per (src, dst) pair so a sender's Nth
        # send matches the receiver's Nth recv from that sender.
        self._p2p_counters: dict = {}

    def _next_uid(self, kind: str) -> str:
        # All ranks issue collectives in the same order (SPMD contract), so a
        # per-rank counter yields matching uids across the group.
        self._op_counter += 1
        return f"{kind}:{self._op_counter}"

    def _round(self, kind: str, payload) -> dict:
        uid = self._next_uid(kind)
        return ray_tpu.get(
            self._hub.gather_round.remote(uid, self.rank, payload),
            timeout=300)

    # ------------------------------------------------------------------ ops
    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        data = self._round("allreduce", np.asarray(tensor))
        return _reduce([data[r] for r in range(self.world_size)], op)

    def barrier(self):
        self._round("barrier", None)

    def reduce(self, tensor, dst_rank: int = 0, op: ReduceOp = ReduceOp.SUM):
        data = self._round("reduce", np.asarray(tensor))
        if self.rank == dst_rank:
            return _reduce([data[r] for r in range(self.world_size)], op)
        return tensor

    def broadcast(self, tensor, src_rank: int = 0):
        payload = np.asarray(tensor) if self.rank == src_rank else None
        data = self._round("broadcast", payload)
        return data[src_rank]

    def allgather(self, tensor) -> List[Any]:
        data = self._round("allgather", np.asarray(tensor))
        return [data[r] for r in range(self.world_size)]

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        data = self._round("reducescatter", np.asarray(tensor))
        full = _reduce([data[r] for r in range(self.world_size)], op)
        chunks = np.array_split(full, self.world_size, axis=0)
        return chunks[self.rank]

    def _p2p_tag(self, src: int, dst: int) -> str:
        n = self._p2p_counters.get((src, dst), 0) + 1
        self._p2p_counters[(src, dst)] = n
        return f"t{n}"

    def send(self, tensor, dst_rank: int):
        tag = self._p2p_tag(self.rank, dst_rank)
        ray_tpu.get(self._hub.send.remote(
            self.rank, dst_rank, tag, np.asarray(tensor)), timeout=300)

    def recv(self, src_rank: int):
        tag = self._p2p_tag(src_rank, self.rank)
        return ray_tpu.get(self._hub.recv.remote(
            src_rank, self.rank, tag), timeout=300)