from ray_tpu.util.collective.collective import (
    allgather, allreduce, barrier, broadcast, create_collective_group,
    destroy_collective_group, get_collective_group_size, get_group_mesh,
    get_rank, init_collective_group, is_group_initialized, recv, reduce,
    reducescatter, send,
)
from ray_tpu.util.collective.types import Backend, ReduceOp

__all__ = [
    "init_collective_group", "create_collective_group",
    "destroy_collective_group", "is_group_initialized", "get_rank",
    "get_collective_group_size", "get_group_mesh", "allreduce", "barrier",
    "reduce", "broadcast", "allgather", "reducescatter", "send", "recv",
    "Backend", "ReduceOp",
]
