"""EQuARX-style fused quantized ring allreduce.

PAPERS.md ("EQuARX: Efficient Quantized AllReduce in XLA") shows that on
slow links an int8 allreduce with per-block scales buys ~2x wire time for a
small accuracy cost.  This kernel fuses the whole thing: at every ring hop
the outgoing chunk (a running f32 partial sum) is re-quantized to int8 with
one f32 scale, the wire carries `chunk/4` the bytes, and the receiver
dequantizes into its f32 accumulator.  Error therefore grows with hop
count, not ring size squared — each hop contributes at most
``max|chunk| / 254`` per element (symmetric round-to-nearest, 8 bits).

Fallback ladder (mirrors `ring.select_impl`):

- non-float input → `TypeError` (quantizing integer grads is a bug; the
  graftlint `collective-consistency` pass flags call sites that try);
- f64 input, tiny tensors, or ``precision="bf16"`` → bf16-compressed
  allreduce (cast → ring/lax allreduce → cast back);
- off-TPU with interpret disabled → bf16 cast around `lax.psum`.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu.util.collective.pallas import ring
from ray_tpu.util.collective.pallas.ring import (
    _cap_signal, _cap_wait, _from_block, _to_block, select_impl,
)

# Below this many elements the scale traffic dominates any wire savings.
_MIN_QUANT_ELEMS = int(os.environ.get("RAY_TPU_QAR_MIN_ELEMS", "1024"))
_QMAX = 127.0


def _quantize(chunk):
    scale = jnp.maximum(jnp.max(jnp.abs(chunk)) / _QMAX, 1e-30)
    q = jnp.clip(jnp.round(chunk / scale), -_QMAX, _QMAX).astype(jnp.int8)
    return q, scale


def _qar_kernel(n, axis_name, interpret,
                in_ref, out_ref,
                qcomm_ref, scomm_ref, qstage_ref, sstage_ref,
                qsend_sems, qrecv_sems, ssend_sems, srecv_sems, cap_sems):
    my = lax.axis_index(axis_name)
    right = lax.rem(my + 1, n)
    left = lax.rem(my + n - 1, n)
    chunk = out_ref.shape[0] // n
    total = 2 * (n - 1)

    out_ref[...] = in_ref[...]

    def hop(t, send_idx, recv_idx, accumulate):
        slot = t % 2
        q, scale = _quantize(out_ref[pl.ds(send_idx * chunk, chunk)])
        qstage_ref[...] = q
        sstage_ref[0, 0] = scale
        _cap_wait(cap_sems, slot, t, interpret)
        qrdma = pltpu.make_async_remote_copy(
            src_ref=qstage_ref, dst_ref=qcomm_ref.at[slot],
            send_sem=qsend_sems.at[slot], recv_sem=qrecv_sems.at[slot],
            device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)
        srdma = pltpu.make_async_remote_copy(
            src_ref=sstage_ref, dst_ref=scomm_ref.at[slot],
            send_sem=ssend_sems.at[slot], recv_sem=srecv_sems.at[slot],
            device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)
        qrdma.start()
        srdma.start()
        qrdma.wait()
        srdma.wait()
        deq = qcomm_ref[slot].astype(out_ref.dtype) * scomm_ref[slot, 0, 0]
        if accumulate:
            out_ref[pl.ds(recv_idx * chunk, chunk)] = (
                out_ref[pl.ds(recv_idx * chunk, chunk)] + deq)
        else:
            out_ref[pl.ds(recv_idx * chunk, chunk)] = deq
        _cap_signal(cap_sems, slot, t, total, left, interpret)

    t = 0
    for s in range(n - 1):  # reduce-scatter sweep over quantized partials
        hop(t, lax.rem(my - s + n, n), lax.rem(my - s - 1 + n, n),
            accumulate=True)
        t += 1
    for s in range(n - 1):  # allgather sweep of the reduced chunks
        hop(t, lax.rem(my - s + 1 + n, n), lax.rem(my - s + n, n),
            accumulate=False)
        t += 1


def _qar_block(x, axis_name, n, interpret):
    chunk = x.shape[0] // n
    kernel = functools.partial(_qar_kernel, n, axis_name, interpret)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, chunk) + x.shape[1:], jnp.int8),   # qcomm
            pltpu.VMEM((2, 1, 1), jnp.float32),               # scomm
            pltpu.VMEM((chunk,) + x.shape[1:], jnp.int8),     # qstage
            pltpu.VMEM((1, 1), jnp.float32),                  # sstage
            pltpu.SemaphoreType.DMA((2,)),                    # q send
            pltpu.SemaphoreType.DMA((2,)),                    # q recv
            pltpu.SemaphoreType.DMA((2,)),                    # s send
            pltpu.SemaphoreType.DMA((2,)),                    # s recv
            pltpu.SemaphoreType.REGULAR((2,)),                # capacity
        ],
        interpret=interpret,
        compiler_params=None if interpret else pltpu.TPUCompilerParams(
            collective_id=3),
    )(x)


def _bf16_fallback(x, axis_name, n, op, impl):
    out = ring.ring_allreduce(x.astype(jnp.bfloat16), axis_name, n=n,
                              op=op, impl=impl)
    return out.astype(x.dtype)


def quantized_ring_allreduce(x, axis_name: str, *, n: int, op: str = "sum",
                             precision: str = "int8", impl: str = "auto"):
    """int8 quantize→ring-allreduce→dequantize over mesh axis `axis_name`.

    Sum/avg only (quantized max/min/prod have no sane error story).  Raises
    `TypeError` on non-float input; falls back to a bf16-compressed
    allreduce for f64, tiny tensors, ``precision="bf16"``, or when the
    resolved impl is the off-TPU `lax` path.
    """
    if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
        raise TypeError(
            "quantized allreduce requires floating-point input, got "
            f"{jnp.asarray(x).dtype} — quantizing integer gradients "
            "silently corrupts them (use ring_allreduce instead)")
    if op.lower() not in ("sum", "avg", "mean"):
        raise ValueError(f"quantized allreduce supports sum/avg, got {op!r}")
    if precision not in ("int8", "bf16"):
        raise ValueError(f"precision must be int8|bf16, got {precision!r}")
    impl = select_impl(impl)
    wants_bf16 = (
        precision == "bf16"
        or jnp.asarray(x).dtype == jnp.float64
        or x.size < _MIN_QUANT_ELEMS
    )
    if impl == "lax" or n == 1 or wants_bf16:
        return _bf16_fallback(x, axis_name, n, op, impl)
    block, shape, size = _to_block(x.astype(jnp.float32), n)
    out = _qar_block(block, axis_name, n,
                     interpret=(impl == "pallas_interpret"))
    result = _from_block(out, shape, size).astype(x.dtype)
    if op.lower() in ("avg", "mean"):
        result = result / n
    return result
