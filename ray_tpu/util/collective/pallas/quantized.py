"""EQuARX-style fused quantized ring allreduce.

PAPERS.md ("EQuARX: Efficient Quantized AllReduce in XLA") shows that on
slow links an int8 allreduce with per-block scales buys ~2x wire time for a
small accuracy cost.  This kernel fuses the whole thing: at every ring hop
the outgoing chunk (a running f32 partial sum) is re-quantized to int8 with
one f32 scale, the wire carries `chunk/4` the bytes, and the receiver
dequantizes into its f32 accumulator.  Error therefore grows with hop
count, not ring size squared — each hop contributes at most
``max|chunk| / 254`` per element (symmetric round-to-nearest, 8 bits).

Fallback ladder (mirrors `ring.select_impl`):

- non-float input → `TypeError` (quantizing integer grads is a bug; the
  graftlint `collective-consistency` pass flags call sites that try);
- f64 input, tiny tensors, or ``precision="bf16"`` → bf16-compressed
  allreduce (cast → ring/lax allreduce → cast back);
- off-TPU with interpret disabled → bf16 cast around `lax.psum`.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu.util.collective.pallas import ring
from ray_tpu.util.collective.pallas.ring import (
    LANES, SplitPhaseHandle, _cap_signal, _cap_wait, _from_block,
    _numel, _to_block, select_impl,
)

# Below this many elements the scale traffic dominates any wire savings.
_MIN_QUANT_ELEMS = int(os.environ.get("RAY_TPU_QAR_MIN_ELEMS", "1024"))
_QMAX = 127.0


def _quantize(chunk):
    scale = jnp.maximum(jnp.max(jnp.abs(chunk)) / _QMAX, 1e-30)
    q = jnp.clip(jnp.round(chunk / scale), -_QMAX, _QMAX).astype(jnp.int8)
    return q, scale


def _qar_kernel(n, axis_name, interpret,
                in_ref, out_ref,
                qcomm_ref, scomm_ref, qstage_ref, sstage_ref,
                qsend_sems, qrecv_sems, ssend_sems, srecv_sems, cap_sems):
    my = lax.axis_index(axis_name)
    right = lax.rem(my + 1, n)
    left = lax.rem(my + n - 1, n)
    chunk = out_ref.shape[0] // n
    total = 2 * (n - 1)

    out_ref[...] = in_ref[...]

    def hop(t, send_idx, recv_idx, accumulate):
        slot = t % 2
        q, scale = _quantize(out_ref[pl.ds(send_idx * chunk, chunk)])
        qstage_ref[...] = q
        sstage_ref[0, 0] = scale
        _cap_wait(cap_sems, slot, t, interpret)
        qrdma = pltpu.make_async_remote_copy(
            src_ref=qstage_ref, dst_ref=qcomm_ref.at[slot],
            send_sem=qsend_sems.at[slot], recv_sem=qrecv_sems.at[slot],
            device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)
        srdma = pltpu.make_async_remote_copy(
            src_ref=sstage_ref, dst_ref=scomm_ref.at[slot],
            send_sem=ssend_sems.at[slot], recv_sem=srecv_sems.at[slot],
            device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)
        qrdma.start()
        srdma.start()
        qrdma.wait()
        srdma.wait()
        deq = qcomm_ref[slot].astype(out_ref.dtype) * scomm_ref[slot, 0, 0]
        if accumulate:
            out_ref[pl.ds(recv_idx * chunk, chunk)] = (
                out_ref[pl.ds(recv_idx * chunk, chunk)] + deq)
        else:
            out_ref[pl.ds(recv_idx * chunk, chunk)] = deq
        _cap_signal(cap_sems, slot, t, total, left, interpret)

    t = 0
    for s in range(n - 1):  # reduce-scatter sweep over quantized partials
        hop(t, lax.rem(my - s + n, n), lax.rem(my - s - 1 + n, n),
            accumulate=True)
        t += 1
    for s in range(n - 1):  # allgather sweep of the reduced chunks
        hop(t, lax.rem(my - s + 1 + n, n), lax.rem(my - s + n, n),
            accumulate=False)
        t += 1


def _qar_block(x, axis_name, n, interpret):
    chunk = x.shape[0] // n
    kernel = functools.partial(_qar_kernel, n, axis_name, interpret)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, chunk) + x.shape[1:], jnp.int8),   # qcomm
            pltpu.VMEM((2, 1, 1), jnp.float32),               # scomm
            pltpu.VMEM((chunk,) + x.shape[1:], jnp.int8),     # qstage
            pltpu.VMEM((1, 1), jnp.float32),                  # sstage
            pltpu.SemaphoreType.DMA((2,)),                    # q send
            pltpu.SemaphoreType.DMA((2,)),                    # q recv
            pltpu.SemaphoreType.DMA((2,)),                    # s send
            pltpu.SemaphoreType.DMA((2,)),                    # s recv
            pltpu.SemaphoreType.REGULAR((2,)),                # capacity
        ],
        interpret=interpret,
        compiler_params=None if interpret else pltpu.TPUCompilerParams(
            collective_id=3),
    )(x)


def _qhop_kernel(n, axis_name, in_ref, out_ref,
                 qstage_ref, sstage_ref, qcomm_ref, scomm_ref,
                 qsend, qrecv, ssend, srecv):
    """One fused quantized ring hop: quantize the outgoing f32 block to
    int8 *inside the kernel*, DMA payload+scale to the right neighbour,
    dequantize the incoming pair into f32.  The requantization of running
    partial sums lives in the DMA loop (EQuARX), not as a host pre-pass —
    the wire only ever carries int8."""
    my = lax.axis_index(axis_name)
    right = lax.rem(my + 1, n)
    q, scale = _quantize(in_ref[...])
    qstage_ref[...] = q
    sstage_ref[0, 0] = scale
    qrdma = pltpu.make_async_remote_copy(
        src_ref=qstage_ref, dst_ref=qcomm_ref,
        send_sem=qsend, recv_sem=qrecv,
        device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)
    srdma = pltpu.make_async_remote_copy(
        src_ref=sstage_ref, dst_ref=scomm_ref,
        send_sem=ssend, recv_sem=srecv,
        device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)
    qrdma.start()
    srdma.start()
    qrdma.wait()
    srdma.wait()
    out_ref[...] = qcomm_ref[...].astype(out_ref.dtype) * scomm_ref[0, 0]


def _qhop_block(x, axis_name, n, interpret):
    kernel = functools.partial(_qhop_kernel, n, axis_name)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        scratch_shapes=[
            pltpu.VMEM(x.shape, jnp.int8),       # qstage
            pltpu.VMEM((1, 1), jnp.float32),     # sstage
            pltpu.VMEM(x.shape, jnp.int8),       # qcomm
            pltpu.VMEM((1, 1), jnp.float32),     # scomm
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
        compiler_params=None if interpret else pltpu.TPUCompilerParams(
            collective_id=5),
    )(x)


def _qrs_hop(block, t, n, axis_name, interpret):
    """One host-level quantized reduce-scatter hop: same index schedule as
    `ring._reduce_scatter_kernel` step `t`, with the wire leg replaced by
    the fused quantize→DMA→dequantize kernel."""
    my = lax.axis_index(axis_name)
    chunk = block.shape[0] // n
    send_idx = lax.rem(my - t - 1 + n, n)
    recv_idx = lax.rem(my - t - 2 + 2 * n, n)
    sent = lax.dynamic_slice(
        block, (send_idx * chunk, 0), (chunk,) + block.shape[1:])
    deq = _qhop_block(sent, axis_name, n, interpret)
    cur = lax.dynamic_slice(
        block, (recv_idx * chunk, 0), (chunk,) + block.shape[1:])
    return lax.dynamic_update_slice(block, cur + deq, (recv_idx * chunk, 0))


def start_quantized_ring_reduce_scatter(x, axis_name: str, *, n: int,
                                        op: str = "sum",
                                        impl: str = "auto"
                                        ) -> SplitPhaseHandle:
    """Split-phase int8 reduce-scatter (sum/avg): hop 0's fused
    quantize→DMA→dequantize is issued now, the rest at the wait.  Same
    slab contract as `ring.ring_reduce_scatter`; same fallback ladder as
    `quantized_ring_allreduce` (bf16 compression when int8 cannot pay)."""
    if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
        raise TypeError(
            "quantized reduce-scatter requires floating-point input, got "
            f"{jnp.asarray(x).dtype} — quantizing integer gradients "
            "silently corrupts them (use ring_reduce_scatter instead)")
    if op.lower() not in ("sum", "avg", "mean"):
        raise ValueError(
            f"quantized reduce-scatter supports sum/avg, got {op!r}")
    if x.shape[0] % n:
        raise ValueError(
            f"reduce_scatter leading dim {x.shape[0]} not divisible by "
            f"ring size {n}")
    impl = select_impl(impl)
    op = "avg" if op.lower() in ("avg", "mean") else "sum"
    wants_bf16 = (
        jnp.asarray(x).dtype == jnp.float64
        or x.size < _MIN_QUANT_ELEMS
    )
    h = SplitPhaseHandle("quantized_reduce_scatter", axis_name, n, op, impl)
    if impl == "lax" or n == 1 or wants_bf16:
        # bf16-compressed fallback: the cast is the (lossy) compression;
        # the wait performs the actual collective.
        h.impl = "lax" if impl == "lax" or n == 1 else impl
        h.meta = ("bf16", x.dtype)
        h.buf = x.astype(jnp.bfloat16)
        return h
    shard_shape = (x.shape[0] // n,) + x.shape[1:]
    per_shard = _numel(shard_shape)
    slabs = x.astype(jnp.float32).reshape(n, per_shard)
    padded = ((per_shard + LANES - 1) // LANES) * LANES
    if padded != per_shard:
        slabs = jnp.pad(slabs, ((0, 0), (0, padded - per_shard)))
    block = slabs.reshape(n * (padded // LANES), LANES)
    interpret = impl == "pallas_interpret"
    h.meta = ("int8", x.dtype, shard_shape, per_shard)
    h.buf = _qrs_hop(block, 0, n, axis_name, interpret)
    h.hops_done = 1
    return h


def wait_quantized_ring_reduce_scatter(h: SplitPhaseHandle):
    """Await a `start_quantized_ring_reduce_scatter`."""
    n, op, axis_name = h.n, h.op, h.axis_name
    if h.meta and h.meta[0] == "bf16":
        _, orig_dtype = h.meta
        out = ring.ring_reduce_scatter(h.buf, axis_name, n=n, op=op,
                                       impl=h.impl)
        return out.astype(orig_dtype)
    interpret = h.impl == "pallas_interpret"
    block = h.buf
    for t in range(h.hops_done, n - 1):
        block = _qrs_hop(block, t, n, axis_name, interpret)
    my = lax.axis_index(axis_name)
    chunk = block.shape[0] // n
    mine = lax.dynamic_slice(
        block, (my * chunk, 0), (chunk,) + block.shape[1:])
    _, orig_dtype, shard_shape, per_shard = h.meta
    result = mine.reshape(-1)[:per_shard].reshape(shard_shape)
    if op == "avg":
        result = result / n
    return result.astype(orig_dtype)


def local_quantization_residual(block, n: int):
    """What this rank's data loses to the FIRST int8 compression on the
    wire: ``block - dequant(quant(block))`` with one f32 scale per ring
    chunk (the kernel's scale rule).  This is the increment an
    error-feedback accumulator keeps so systematic round-off is re-sent
    on the next step instead of silently dropped.

    `block` must be 2-D ``(rows, LANES)`` with ``rows % n == 0`` — the
    packed layout both the monolithic and split-phase quantized paths use.
    Always f32 (graftlint's ef-dtype rule: never keep EF state in int).
    """
    if block.ndim != 2 or block.shape[0] % n:
        raise ValueError(
            f"expected (rows, LANES) block with rows divisible by {n}, "
            f"got shape {block.shape}")
    if block.size < _MIN_QUANT_ELEMS:
        # Below the quantization threshold the wire carries bf16, whose
        # round-off is what EF should track there.
        b16 = block.astype(jnp.bfloat16).astype(jnp.float32)
        return block.astype(jnp.float32) - b16
    chunks = block.astype(jnp.float32).reshape(n, block.shape[0] // n,
                                               block.shape[1])
    scales = jnp.maximum(
        jnp.max(jnp.abs(chunks), axis=(1, 2), keepdims=True) / _QMAX,
        1e-30)
    q = jnp.clip(jnp.round(chunks / scales), -_QMAX, _QMAX)
    deq = (q * scales).reshape(block.shape)
    return block.astype(jnp.float32) - deq


def _bf16_fallback(x, axis_name, n, op, impl):
    out = ring.ring_allreduce(x.astype(jnp.bfloat16), axis_name, n=n,
                              op=op, impl=impl)
    return out.astype(x.dtype)


def quantized_ring_allreduce(x, axis_name: str, *, n: int, op: str = "sum",
                             precision: str = "int8", impl: str = "auto"):
    """int8 quantize→ring-allreduce→dequantize over mesh axis `axis_name`.

    Sum/avg only (quantized max/min/prod have no sane error story).  Raises
    `TypeError` on non-float input; falls back to a bf16-compressed
    allreduce for f64, tiny tensors, ``precision="bf16"``, or when the
    resolved impl is the off-TPU `lax` path.
    """
    if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
        raise TypeError(
            "quantized allreduce requires floating-point input, got "
            f"{jnp.asarray(x).dtype} — quantizing integer gradients "
            "silently corrupts them (use ring_allreduce instead)")
    if op.lower() not in ("sum", "avg", "mean"):
        raise ValueError(f"quantized allreduce supports sum/avg, got {op!r}")
    if precision not in ("int8", "bf16"):
        raise ValueError(f"precision must be int8|bf16, got {precision!r}")
    impl = select_impl(impl)
    wants_bf16 = (
        precision == "bf16"
        or jnp.asarray(x).dtype == jnp.float64
        or x.size < _MIN_QUANT_ELEMS
    )
    if impl == "lax" or n == 1 or wants_bf16:
        return _bf16_fallback(x, axis_name, n, op, impl)
    block, shape, size = _to_block(x.astype(jnp.float32), n)
    out = _qar_block(block, axis_name, n,
                     interpret=(impl == "pallas_interpret"))
    result = _from_block(out, shape, size).astype(x.dtype)
    if op.lower() in ("avg", "mean"):
        result = result / n
    return result
