"""Double-buffered ICI ring collectives as Pallas TPU kernels.

Each kernel runs per-device under `shard_map` over one mesh axis and moves
data to its right neighbour with `pltpu.make_async_remote_copy` (the ICI
RDMA primitive, SNIPPETS [1][2]).  Communication is double-buffered: step
`t` lands in comm slot `t % 2` while the previous slot is still being
consumed, and a reverse-direction capacity semaphore stops a fast sender
from clobbering a slot its right neighbour has not drained yet (skew around
a ring is bounded only by its circumference, so two slots alone are not a
proof).  The capacity handshake uses `pltpu.semaphore_signal`, which the
CPU interpreter does not model — interpret mode runs devices in lockstep,
so the handshake is compiled out there (`interpret=True` ⇒ no remote
regular-semaphore ops).

Layout contract: kernels see a 2-D `(rows, LANES)` f32/bf16/int block whose
row count divides the ring size; the public wrappers flatten, pad and
restore arbitrary pytree-leaf shapes around that.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

# TPU vector lane count — the minor dim of every kernel block (pallas guide:
# last dim should be a multiple of 128 on real hardware; the interpreter
# does not care but we keep one layout for both paths).
LANES = 128

_COMBINE: dict = {
    "sum": lambda a, b: a + b,
    "max": jnp.maximum,
    "min": jnp.minimum,
    "prod": lambda a, b: a * b,
}


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "").lower() in ("1", "true", "yes", "on")


def select_impl(requested: str = "auto") -> str:
    """Resolve a collective implementation name.

    ``auto`` → ``pallas`` on a TPU backend, ``pallas_interpret`` when
    ``RAY_TPU_PALLAS_INTERPRET=1`` forces the CPU interpreter (tests), and
    ``lax`` otherwise (the automatic off-TPU fallback demanded by the
    backend registry).  Explicit names pass through after validation.
    """
    valid = ("auto", "pallas", "pallas_interpret", "lax")
    if requested not in valid:
        raise ValueError(f"impl must be one of {valid}, got {requested!r}")
    if requested != "auto":
        return requested
    if jax.default_backend() == "tpu":
        return "pallas"
    if _env_flag("RAY_TPU_PALLAS_INTERPRET"):
        return "pallas_interpret"
    return "lax"


# ---------------------------------------------------------------------------
# Kernels.  Shared structure: a global step counter `t` indexes the comm
# slot; `_send_recv` issues one RDMA hop to the right neighbour and blocks
# until both the outgoing DMA drained and the incoming chunk (from the left
# neighbour's symmetric send) landed.
# ---------------------------------------------------------------------------

def _send_recv(src, dst, send_sems, recv_sems, slot, right):
    rdma = pltpu.make_async_remote_copy(
        src_ref=src,
        dst_ref=dst,
        send_sem=send_sems.at[slot],
        recv_sem=recv_sems.at[slot],
        device_id=right,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    rdma.start()
    rdma.wait()


def _cap_wait(cap_sems, slot, t, interpret):
    # Slot reuse starts at t == 2; before sending, wait for the right
    # neighbour's "drained" signal.  Not modelled by the interpreter.
    if not interpret and t >= 2:
        pltpu.semaphore_wait(cap_sems.at[slot], 1)


def _cap_signal(cap_sems, slot, t, total, left, interpret):
    # After consuming comm[slot], tell the left neighbour it may reuse it.
    # The last two steps never get reused, so skip the dangling signals.
    if not interpret and t < total - 2:
        pltpu.semaphore_signal(
            cap_sems.at[slot], inc=1, device_id=left,
            device_id_type=pltpu.DeviceIdType.LOGICAL)


def _allreduce_kernel(n, axis_name, op, interpret,
                      in_ref, out_ref, comm_ref,
                      send_sems, recv_sems, cap_sems):
    """Ring allreduce = reduce-scatter sweep + allgather sweep (2(n-1) hops,
    each moving 1/n of the block: bandwidth-optimal)."""
    my = lax.axis_index(axis_name)
    right = lax.rem(my + 1, n)
    left = lax.rem(my + n - 1, n)
    chunk = out_ref.shape[0] // n
    combine = _COMBINE[op]
    total = 2 * (n - 1)

    out_ref[...] = in_ref[...]

    t = 0
    for s in range(n - 1):  # reduce-scatter sweep: accumulate partials
        slot = t % 2
        send_idx = lax.rem(my - s + n, n)
        recv_idx = lax.rem(my - s - 1 + n, n)
        _cap_wait(cap_sems, slot, t, interpret)
        _send_recv(out_ref.at[pl.ds(send_idx * chunk, chunk)],
                   comm_ref.at[slot], send_sems, recv_sems, slot, right)
        out_ref[pl.ds(recv_idx * chunk, chunk)] = combine(
            out_ref[pl.ds(recv_idx * chunk, chunk)], comm_ref[slot])
        _cap_signal(cap_sems, slot, t, total, left, interpret)
        t += 1

    for s in range(n - 1):  # allgather sweep: circulate reduced chunks
        slot = t % 2
        send_idx = lax.rem(my - s + 1 + n, n)
        recv_idx = lax.rem(my - s + n, n)
        _cap_wait(cap_sems, slot, t, interpret)
        _send_recv(out_ref.at[pl.ds(send_idx * chunk, chunk)],
                   comm_ref.at[slot], send_sems, recv_sems, slot, right)
        out_ref[pl.ds(recv_idx * chunk, chunk)] = comm_ref[slot]
        _cap_signal(cap_sems, slot, t, total, left, interpret)
        t += 1


def _allgather_kernel(n, axis_name, interpret,
                      in_ref, out_ref, comm_ref,
                      send_sems, recv_sems, cap_sems):
    """Ring allgather: each shard takes n-1 hops around the ring."""
    my = lax.axis_index(axis_name)
    right = lax.rem(my + 1, n)
    left = lax.rem(my + n - 1, n)
    rows = in_ref.shape[0]
    total = n - 1

    out_ref[pl.ds(my * rows, rows)] = in_ref[...]

    for t in range(n - 1):
        slot = t % 2
        send_idx = lax.rem(my - t + n, n)
        recv_idx = lax.rem(my - t - 1 + n, n)
        _cap_wait(cap_sems, slot, t, interpret)
        _send_recv(out_ref.at[pl.ds(send_idx * rows, rows)],
                   comm_ref.at[slot], send_sems, recv_sems, slot, right)
        out_ref[pl.ds(recv_idx * rows, rows)] = comm_ref[slot]
        _cap_signal(cap_sems, slot, t, total, left, interpret)


def _reduce_scatter_kernel(n, axis_name, op, interpret,
                           in_ref, out_ref, acc_ref, comm_ref,
                           send_sems, recv_sems, cap_sems):
    """Ring reduce-scatter: after n-1 hops every device holds the fully
    reduced chunk it owns (chunk `my`, matching `lax.psum_scatter`)."""
    my = lax.axis_index(axis_name)
    right = lax.rem(my + 1, n)
    left = lax.rem(my + n - 1, n)
    chunk = in_ref.shape[0] // n
    combine = _COMBINE[op]
    total = n - 1

    acc_ref[...] = in_ref[...]

    # Schedule shifted by -1 vs the allreduce sweep so the last chunk a
    # device accumulates (the fully reduced one) is its *own* chunk `my`,
    # matching `lax.psum_scatter` ownership.
    for t in range(n - 1):
        slot = t % 2
        send_idx = lax.rem(my - t - 1 + n, n)
        recv_idx = lax.rem(my - t - 2 + 2 * n, n)
        _cap_wait(cap_sems, slot, t, interpret)
        _send_recv(acc_ref.at[pl.ds(send_idx * chunk, chunk)],
                   comm_ref.at[slot], send_sems, recv_sems, slot, right)
        acc_ref[pl.ds(recv_idx * chunk, chunk)] = combine(
            acc_ref[pl.ds(recv_idx * chunk, chunk)], comm_ref[slot])
        _cap_signal(cap_sems, slot, t, total, left, interpret)

    out_ref[...] = acc_ref[pl.ds(my * chunk, chunk)]


# ---------------------------------------------------------------------------
# pallas_call wrappers over canonical 2-D (rows, LANES) blocks.
# ---------------------------------------------------------------------------

def _sems(interpret):
    return [
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.REGULAR((2,)),
    ]


def _allreduce_block(x, axis_name, n, op, interpret):
    chunk = x.shape[0] // n
    kernel = functools.partial(_allreduce_kernel, n, axis_name, op,
                               interpret)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        scratch_shapes=[pltpu.VMEM((2, chunk) + x.shape[1:], x.dtype)]
        + _sems(interpret),
        interpret=interpret,
        compiler_params=None if interpret else pltpu.TPUCompilerParams(
            collective_id=0),
    )(x)


def _allgather_block(x, axis_name, n, interpret):
    rows = x.shape[0]
    kernel = functools.partial(_allgather_kernel, n, axis_name, interpret)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n * rows,) + x.shape[1:], x.dtype),
        scratch_shapes=[pltpu.VMEM((2, rows) + x.shape[1:], x.dtype)]
        + _sems(interpret),
        interpret=interpret,
        compiler_params=None if interpret else pltpu.TPUCompilerParams(
            collective_id=1),
    )(x)


def _reduce_scatter_block(x, axis_name, n, op, interpret):
    chunk = x.shape[0] // n
    kernel = functools.partial(_reduce_scatter_kernel, n, axis_name, op,
                               interpret)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((chunk,) + x.shape[1:], x.dtype),
        scratch_shapes=[
            pltpu.VMEM(x.shape, x.dtype),
            pltpu.VMEM((2, chunk) + x.shape[1:], x.dtype),
        ] + _sems(interpret),
        interpret=interpret,
        compiler_params=None if interpret else pltpu.TPUCompilerParams(
            collective_id=2),
    )(x)


# ---------------------------------------------------------------------------
# Shape adaptation: arbitrary leaf -> padded (rows, LANES) block and back.
# ---------------------------------------------------------------------------

def _to_block(x, multiple):
    """Flatten to (rows, LANES) with rows % multiple == 0 (zero padded)."""
    flat = x.reshape(-1)
    per_row_group = multiple * LANES
    padded = ((flat.size + per_row_group - 1) // per_row_group) \
        * per_row_group
    if padded != flat.size:
        flat = jnp.pad(flat, (0, padded - flat.size))
    return flat.reshape(-1, LANES), x.shape, x.size


def _from_block(block, shape, size):
    return block.reshape(-1)[:size].reshape(shape)


def _norm_op(op: str) -> str:
    op = op.lower()
    if op == "mean":
        op = "avg"
    if op not in ("sum", "avg", "max", "min", "prod"):
        raise ValueError(f"unsupported reduce op {op!r}")
    return op


def ring_allreduce(x, axis_name: str, *, n: int, op: str = "sum",
                   impl: str = "auto"):
    """`lax.psum`-shaped allreduce over mesh axis `axis_name` (size `n`,
    required statically for the ring schedule).  Call under `shard_map`."""
    op = _norm_op(op)
    impl = select_impl(impl)
    if impl == "lax" or n == 1:
        return _lax_allreduce(x, axis_name, op)
    kernel_op = "sum" if op == "avg" else op
    block, shape, size = _to_block(x, n)
    out = _allreduce_block(block, axis_name, n, kernel_op,
                           interpret=(impl == "pallas_interpret"))
    out = _from_block(out, shape, size)
    if op == "avg":
        out = out / n
    return out


def ring_allgather(x, axis_name: str, *, n: int, impl: str = "auto"):
    """`lax.all_gather`-shaped allgather: per-rank shards stacked along a
    new leading axis of size `n`."""
    impl = select_impl(impl)
    if impl == "lax" or n == 1:
        return lax.all_gather(x, axis_name, tiled=False)
    block, shape, size = _to_block(x, 1)
    out = _allgather_block(block, axis_name, n,
                           interpret=(impl == "pallas_interpret"))
    rows = block.shape[0]
    pieces = [
        _from_block(out[i * rows:(i + 1) * rows], shape, size)
        for i in range(n)
    ]
    return jnp.stack(pieces, axis=0)


def ring_reduce_scatter(x, axis_name: str, *, n: int, op: str = "sum",
                        impl: str = "auto"):
    """`lax.psum_scatter(..., tiled=True)`-shaped reduce-scatter along the
    leading dim, which must be divisible by `n`: rank `i` gets the reduced
    slab ``x[i*rows:(i+1)*rows]``."""
    op = _norm_op(op)
    if x.shape[0] % n:
        raise ValueError(
            f"reduce_scatter leading dim {x.shape[0]} not divisible by "
            f"ring size {n}")
    impl = select_impl(impl)
    if impl == "lax" or n == 1:
        out = lax.psum_scatter(x, axis_name, scatter_dimension=0,
                               tiled=True)
        if op == "avg":
            out = out / n
        return out
    kernel_op = "sum" if op == "avg" else op
    shard_shape = (x.shape[0] // n,) + x.shape[1:]
    per_shard = _numel(shard_shape)
    # Pad each leading-dim slab independently so ring chunk `i` is exactly
    # slab `i` (+ trailing zeros) — repacking across slab boundaries would
    # hand rank i the wrong elements.
    slabs = x.reshape(n, per_shard)
    padded = ((per_shard + LANES - 1) // LANES) * LANES
    if padded != per_shard:
        slabs = jnp.pad(slabs, ((0, 0), (0, padded - per_shard)))
    block = slabs.reshape(n * (padded // LANES), LANES)
    out = _reduce_scatter_block(block, axis_name, n, kernel_op,
                                interpret=(impl == "pallas_interpret"))
    result = out.reshape(-1)[:per_shard].reshape(shard_shape)
    if op == "avg":
        result = result / n
    return result


def _numel(shape) -> int:
    size = 1
    for d in shape:
        size *= int(d)
    return size


# ---------------------------------------------------------------------------
# Split-phase entry points: one ring hop per kernel call, so a collective
# can be ISSUED early (``start_*``: places hop 0 in the graph depending
# only on its payload) and AWAITED late (``wait_*``: runs the remaining
# hops and materializes the result).  Compute traced between the two calls
# has no data dependency on the in-flight hops, which is exactly the
# freedom XLA's latency-hiding scheduler needs to run DMA under compute —
# the monolithic kernels above are one opaque op and expose their whole
# wire time.  Hop schedules mirror the monolithic kernels element-for-
# element, so start+wait is numerically identical to the single call
# (tier-1 asserts it).  Handles are trace-scoped Python objects, not
# pytrees: start and wait must happen inside the same traced function.
# ---------------------------------------------------------------------------

def _permute_kernel(n, axis_name, in_ref, out_ref, send_sem, recv_sem):
    """One ring hop: send the whole block to the right neighbour, return
    what the left neighbour sent (the SNIPPETS [2] right-permute shape)."""
    my = lax.axis_index(axis_name)
    right = lax.rem(my + 1, n)
    rdma = pltpu.make_async_remote_copy(
        src_ref=in_ref,
        dst_ref=out_ref,
        send_sem=send_sem,
        recv_sem=recv_sem,
        device_id=right,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    rdma.start()
    rdma.wait()


def _permute_block(x, axis_name, n, interpret):
    kernel = functools.partial(_permute_kernel, n, axis_name)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
        compiler_params=None if interpret else pltpu.TPUCompilerParams(
            collective_id=4),
    )(x)


class SplitPhaseHandle:
    """An in-flight split-phase ring collective.

    Plain Python object, deliberately NOT a pytree: it holds traced
    arrays, so it is only valid between a ``start_*`` and the matching
    ``wait_*`` inside the same traced function.  Every ``start_*`` MUST
    be balanced by a ``wait_*`` (graftlint's ``collective-split-phase``
    rule enforces this statically).
    """

    __slots__ = ("kind", "axis_name", "n", "op", "impl", "buf",
                 "hops_done", "meta")

    def __init__(self, kind, axis_name, n, op, impl):
        self.kind = kind
        self.axis_name = axis_name
        self.n = n
        self.op = op
        self.impl = impl
        self.buf = None
        self.hops_done = 0
        self.meta = None


def _rs_hop(block, t, n, axis_name, op, interpret):
    """One host-level reduce-scatter hop: identical index schedule to
    `_reduce_scatter_kernel` step `t`, so the float-add order (and hence
    the bits) match the monolithic kernel."""
    my = lax.axis_index(axis_name)
    chunk = block.shape[0] // n
    combine = _COMBINE[op]
    send_idx = lax.rem(my - t - 1 + n, n)
    recv_idx = lax.rem(my - t - 2 + 2 * n, n)
    sent = lax.dynamic_slice(
        block, (send_idx * chunk, 0), (chunk,) + block.shape[1:])
    received = _permute_block(sent, axis_name, n, interpret)
    cur = lax.dynamic_slice(
        block, (recv_idx * chunk, 0), (chunk,) + block.shape[1:])
    return lax.dynamic_update_slice(
        block, combine(cur, received), (recv_idx * chunk, 0))


def start_ring_reduce_scatter(x, axis_name: str, *, n: int,
                              op: str = "sum", impl: str = "auto"
                              ) -> SplitPhaseHandle:
    """Issue a reduce-scatter (same contract as `ring_reduce_scatter`:
    leading dim divisible by `n`, rank `i` receives slab `i`).  Hop 0 is
    placed in the graph now; the rest run at `wait_ring_reduce_scatter`."""
    op = _norm_op(op)
    if x.shape[0] % n:
        raise ValueError(
            f"reduce_scatter leading dim {x.shape[0]} not divisible by "
            f"ring size {n}")
    impl = select_impl(impl)
    h = SplitPhaseHandle("reduce_scatter", axis_name, n, op, impl)
    if impl == "lax" or n == 1:
        h.buf = x
        return h
    shard_shape = (x.shape[0] // n,) + x.shape[1:]
    per_shard = _numel(shard_shape)
    slabs = x.reshape(n, per_shard)
    padded = ((per_shard + LANES - 1) // LANES) * LANES
    if padded != per_shard:
        slabs = jnp.pad(slabs, ((0, 0), (0, padded - per_shard)))
    block = slabs.reshape(n * (padded // LANES), LANES)
    interpret = impl == "pallas_interpret"
    kernel_op = "sum" if op == "avg" else op
    h.meta = (shard_shape, per_shard)
    h.buf = _rs_hop(block, 0, n, axis_name, kernel_op, interpret)
    h.hops_done = 1
    return h


def wait_ring_reduce_scatter(h: SplitPhaseHandle):
    """Await a `start_ring_reduce_scatter`: run the remaining hops and
    return this rank's reduced slab."""
    n, op, axis_name = h.n, h.op, h.axis_name
    if h.impl == "lax" or n == 1:
        out = lax.psum_scatter(h.buf, axis_name, scatter_dimension=0,
                               tiled=True)
        if op == "avg":
            out = out / n
        return out
    interpret = h.impl == "pallas_interpret"
    kernel_op = "sum" if op == "avg" else op
    block = h.buf
    for t in range(h.hops_done, n - 1):
        block = _rs_hop(block, t, n, axis_name, kernel_op, interpret)
    my = lax.axis_index(axis_name)
    chunk = block.shape[0] // n
    mine = lax.dynamic_slice(
        block, (my * chunk, 0), (chunk,) + block.shape[1:])
    shard_shape, per_shard = h.meta
    result = mine.reshape(-1)[:per_shard].reshape(shard_shape)
    if op == "avg":
        result = result / n
    return result


def _ag_hop(out, t, n, axis_name, interpret):
    """One host-level allgather hop mirroring `_allgather_kernel` step `t`."""
    my = lax.axis_index(axis_name)
    rows = out.shape[0] // n
    send_idx = lax.rem(my - t + n, n)
    recv_idx = lax.rem(my - t - 1 + n, n)
    sent = lax.dynamic_slice(
        out, (send_idx * rows, 0), (rows,) + out.shape[1:])
    received = _permute_block(sent, axis_name, n, interpret)
    return lax.dynamic_update_slice(out, received, (recv_idx * rows, 0))


def start_ring_allgather(x, axis_name: str, *, n: int,
                         impl: str = "auto") -> SplitPhaseHandle:
    """Issue an allgather of this rank's shard `x` (same contract as
    `ring_allgather`: result stacks shards on a new leading axis)."""
    impl = select_impl(impl)
    h = SplitPhaseHandle("allgather", axis_name, n, "sum", impl)
    if impl == "lax" or n == 1:
        h.buf = x
        return h
    block, shape, size = _to_block(x, 1)
    rows = block.shape[0]
    interpret = impl == "pallas_interpret"
    my = lax.axis_index(axis_name)
    out = jnp.zeros((n * rows,) + block.shape[1:], block.dtype)
    out = lax.dynamic_update_slice(out, block, (my * rows, 0))
    h.meta = (shape, size, rows)
    h.buf = _ag_hop(out, 0, n, axis_name, interpret)
    h.hops_done = 1
    return h


def wait_ring_allgather(h: SplitPhaseHandle):
    """Await a `start_ring_allgather`: remaining hops + restack shards."""
    n, axis_name = h.n, h.axis_name
    if h.impl == "lax" or n == 1:
        return lax.all_gather(h.buf, axis_name, tiled=False)
    interpret = h.impl == "pallas_interpret"
    out = h.buf
    for t in range(h.hops_done, n - 1):
        out = _ag_hop(out, t, n, axis_name, interpret)
    shape, size, rows = h.meta
    pieces = [
        _from_block(out[i * rows:(i + 1) * rows], shape, size)
        for i in range(n)
    ]
    return jnp.stack(pieces, axis=0)


def start_ring_permute(x, axis_name: str, *, n: int,
                       impl: str = "auto") -> SplitPhaseHandle:
    """Issue a right-rotation: rank `i` sends `x` to rank `(i+1) % n` and
    will receive rank `(i-1) % n`'s payload at the wait.  This is the KV
    block exchange of ring attention: issue before the attention block
    compute, await after, and the hop rides under the matmuls."""
    impl = select_impl(impl)
    h = SplitPhaseHandle("permute", axis_name, n, "sum", impl)
    if n == 1:
        h.buf = x
        h.impl = "lax"  # identity; wait returns buf as-is
        h.meta = None
        return h
    if impl == "lax":
        perm = [(i, (i + 1) % n) for i in range(n)]
        h.buf = lax.ppermute(x, axis_name, perm)
        return h
    block, shape, size = _to_block(x, 1)
    h.meta = (shape, size)
    h.buf = _permute_block(block, axis_name, n,
                           interpret=(impl == "pallas_interpret"))
    return h


def wait_ring_permute(h: SplitPhaseHandle):
    """Await a `start_ring_permute`: return the left neighbour's payload."""
    if h.impl == "lax" or h.n == 1:
        return h.buf
    shape, size = h.meta
    return _from_block(h.buf, shape, size)


def _lax_allreduce(x, axis_name, op):
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "avg":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    # product: log-space tricks are lossy; use all_gather + reduce.
    gathered = lax.all_gather(x, axis_name)
    return jnp.prod(gathered, axis=0)


# ---------------------------------------------------------------------------
# Driver-side convenience: run a ring collective over a global array.
# ---------------------------------------------------------------------------

def shard_map_collective(fn: Callable[..., Any], mesh: Mesh,
                         axis_name: str) -> Callable[..., Any]:
    """Wrap a per-shard collective `fn(x)` for global arrays sharded over
    `axis_name` (jit + shard_map with replication checks off, since Pallas
    kernels are opaque to the rep checker)."""
    return jax.jit(shard_map(
        fn, mesh=mesh, in_specs=P(axis_name), out_specs=P(axis_name),
        check_rep=False))
