"""Pallas ICI ring collectives.

Hand-written TPU collective kernels built on `pltpu.make_async_remote_copy`
double-buffered rings, runnable under `shard_map` on a mesh axis.  Every
kernel has an `interpret=True` path so the exact same code is testable on
CPU virtual devices, and every public entry point degrades to the
corresponding `jax.lax` collective when Pallas is not viable (non-TPU
backend with interpret disabled).

Public API::

    ring_allreduce(x, axis_name, ...)       # psum-shaped
    ring_allgather(x, axis_name, ...)       # all_gather(tiled=True)-shaped
    ring_reduce_scatter(x, axis_name, ...)  # psum_scatter-shaped
    quantized_ring_allreduce(x, axis_name, ...)  # EQuARX-style int8 ring
    select_impl(...)                        # backend/fallback resolution
"""

from ray_tpu.util.collective.pallas.ring import (
    ring_allgather, ring_allreduce, ring_reduce_scatter, select_impl,
)
from ray_tpu.util.collective.pallas.quantized import (
    quantized_ring_allreduce,
)

__all__ = [
    "ring_allreduce", "ring_allgather", "ring_reduce_scatter",
    "quantized_ring_allreduce", "select_impl",
]
