"""Pallas ICI ring collectives.

Hand-written TPU collective kernels built on `pltpu.make_async_remote_copy`
double-buffered rings, runnable under `shard_map` on a mesh axis.  Every
kernel has an `interpret=True` path so the exact same code is testable on
CPU virtual devices, and every public entry point degrades to the
corresponding `jax.lax` collective when Pallas is not viable (non-TPU
backend with interpret disabled).

Public API::

    ring_allreduce(x, axis_name, ...)       # psum-shaped
    ring_allgather(x, axis_name, ...)       # all_gather(tiled=True)-shaped
    ring_reduce_scatter(x, axis_name, ...)  # psum_scatter-shaped
    quantized_ring_allreduce(x, axis_name, ...)  # EQuARX-style int8 ring
    select_impl(...)                        # backend/fallback resolution

Split-phase API (compute/communication overlap) — a collective becomes a
``start_*`` that issues hop 0 and a ``wait_*`` that runs the remaining
hops, so compute traced between the two runs with the wire time hidden
under it.  Every start MUST be balanced by a wait in the same traced
function (graftlint enforces this)::

    h = start_ring_reduce_scatter(x, axis, n=n)   # hop 0 in flight
    y = heavy_compute(...)                        # comm hides under this
    shard = wait_ring_reduce_scatter(h)           # hops 1..n-1 + result
    start_ring_allgather / wait_ring_allgather    # same, allgather
    start_ring_permute / wait_ring_permute        # one-hop KV rotation
    start_quantized_ring_reduce_scatter / wait_quantized_ring_reduce_scatter
    local_quantization_residual(block, n)         # error-feedback increment
"""

from ray_tpu.util.collective.pallas.ring import (
    SplitPhaseHandle, ring_allgather, ring_allreduce, ring_reduce_scatter,
    select_impl, start_ring_allgather, start_ring_permute,
    start_ring_reduce_scatter, wait_ring_allgather, wait_ring_permute,
    wait_ring_reduce_scatter,
)
from ray_tpu.util.collective.pallas.quantized import (
    local_quantization_residual, quantized_ring_allreduce,
    start_quantized_ring_reduce_scatter, wait_quantized_ring_reduce_scatter,
)

__all__ = [
    "ring_allreduce", "ring_allgather", "ring_reduce_scatter",
    "quantized_ring_allreduce", "select_impl", "SplitPhaseHandle",
    "start_ring_reduce_scatter", "wait_ring_reduce_scatter",
    "start_ring_allgather", "wait_ring_allgather",
    "start_ring_permute", "wait_ring_permute",
    "start_quantized_ring_reduce_scatter",
    "wait_quantized_ring_reduce_scatter",
    "local_quantization_residual",
]
