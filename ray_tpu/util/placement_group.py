"""Placement groups — gang scheduling of resource bundles.

Reference: `python/ray/util/placement_group.py` + GCS-side 2PC
(`gcs_placement_group_manager.h`, raylet `placement_group_resource_manager.h:54`).

A placement group reserves N resource bundles across the cluster atomically
(STRICT_SPREAD/STRICT_PACK) or best-effort (PACK/SPREAD). Tasks/actors target
a group (optionally a specific bundle) via PlacementGroupSchedulingStrategy.

TPU note: a multi-host TPU slice is exactly a gang — the idiomatic pattern is
one bundle per TPU host ({"TPU": 4, "CPU": 1} x num_hosts, STRICT_SPREAD),
which maps one JAX process per host across the slice.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


@dataclass
class PlacementGroup:
    id: bytes
    bundles: List[Dict[str, float]]
    strategy: str = "PACK"
    name: str = ""

    def ready(self) -> "object":
        """Returns an ObjectRef resolving when the PG is created
        (API parity with the reference's `pg.ready()`)."""
        import ray_tpu

        pg_id = self.id

        @ray_tpu.remote
        def _pg_ready_waiter(pg_id_hex: str):
            from ray_tpu._private.worker import global_worker

            reply = global_worker().gcs.call(
                "wait_placement_group_ready",
                pg_id=bytes.fromhex(pg_id_hex), wait_timeout=300.0,
                timeout=310.0)
            if reply.get("state") != "CREATED":
                raise RuntimeError(
                    f"placement group not created: {reply}")
            return True

        return _pg_ready_waiter.remote(pg_id.hex())

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        from ray_tpu._private.worker import global_worker

        reply = global_worker().gcs.call(
            "wait_placement_group_ready", pg_id=self.id,
            wait_timeout=timeout_seconds, timeout=timeout_seconds + 5)
        return reply.get("state") == "CREATED"

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return list(self.bundles)

    def bundle_count(self) -> int:
        return len(self.bundles)


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "", lifetime: Optional[str] = None
                    ) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(
            f"strategy must be one of {VALID_STRATEGIES}, got {strategy!r}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    from ray_tpu._private.ids import PlacementGroupID
    from ray_tpu._private.worker import global_worker

    w = global_worker()
    pg_id = PlacementGroupID.of(w.job_id)
    w.gcs.call("create_placement_group", pg_id=pg_id.binary(),
               bundles=bundles, strategy=strategy, name=name)
    return PlacementGroup(pg_id.binary(), bundles, strategy, name)


def remove_placement_group(pg: PlacementGroup) -> None:
    from ray_tpu._private.worker import global_worker

    global_worker().gcs.call("remove_placement_group", pg_id=pg.id)


def get_placement_group(name: str) -> Optional[PlacementGroup]:
    from ray_tpu._private.worker import global_worker

    for info in global_worker().gcs.call("list_placement_groups"):
        if info and info.get("name") == name and info["state"] != "REMOVED":
            return PlacementGroup(info["pg_id"], info["bundles"],
                                  info["strategy"], info["name"])
    return None


def placement_group_table() -> List[Dict]:
    from ray_tpu._private.worker import global_worker

    return global_worker().gcs.call("list_placement_groups")
