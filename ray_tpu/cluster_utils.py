"""In-process multi-node cluster for tests.

Role-equivalent to the reference's `python/ray/cluster_utils.py:108`
(`Cluster.add_node/remove_node` at `:174,:247`): starts multiple real raylet
processes on one machine, each pretending to be a separate node — this is how
multi-node scheduling, spillback, object transfer, and node-failure tests run
without a real cluster.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu._private.node import Node


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[Dict] = None):
        self.head_node: Optional[Node] = None
        self.worker_nodes: List[Node] = []
        if initialize_head:
            self.head_node = Node(head=True, **(head_node_args or {}))

    @property
    def gcs_addr(self):
        return self.head_node.gcs_addr

    @property
    def address(self):
        return f"{self.gcs_addr[0]}:{self.gcs_addr[1]}"

    def add_node(self, wait: bool = True, **node_args) -> Node:
        node = Node(head=False, gcs_addr=self.gcs_addr,
                    session_dir=self.head_node.session_dir, **node_args)
        self.worker_nodes.append(node)
        if wait:
            self.wait_for_nodes()
        return node

    def remove_node(self, node: Node, allow_graceful: bool = False) -> None:
        """Kill a node's raylet (and its workers die with it)."""
        if node is self.head_node:
            raise ValueError("cannot remove the head node")
        node.shutdown(cleanup_session=False)
        self.worker_nodes.remove(node)

    def wait_for_nodes(self, timeout: float = 30.0) -> None:
        from ray_tpu._private.rpc import RpcClient

        expected = 1 + len(self.worker_nodes)
        client = RpcClient(*self.gcs_addr)
        deadline = time.monotonic() + timeout
        try:
            while time.monotonic() < deadline:
                nodes = client.call("get_all_nodes", timeout=10)
                alive = [n for n in nodes if n["state"] == "ALIVE"]
                if len(alive) >= expected:
                    return
                time.sleep(0.05)
            raise TimeoutError(
                f"only {len(alive)} of {expected} nodes came up")
        finally:
            client.close()

    def connect(self, **init_args):
        import ray_tpu

        return ray_tpu.init(address=self.address, **init_args)

    def shutdown(self):
        import ray_tpu

        if ray_tpu.is_initialized():
            ray_tpu.shutdown()
        for node in self.worker_nodes:
            node.shutdown(cleanup_session=False)
        self.worker_nodes.clear()
        if self.head_node is not None:
            self.head_node.shutdown()
            self.head_node = None
