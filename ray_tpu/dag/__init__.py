"""Static task/actor DAGs + compiled execution over mutable channels.

Reference: `python/ray/dag/` — `fn.bind(x)` / `actor.method.bind(x)`
build a lazy DAG around an `InputNode`; `dag.execute(x)` runs it as
ordinary tasks; `dag.experimental_compile()` (compiled_dag_node.py:141)
pre-wires the DAG over reusable shared-memory channels so repeated
executions bypass the per-call task path entirely.

TPU angle: a compiled DAG turns a fixed inference pipeline (e.g.
tokenize → prefill/decode on the chip-holding actor → detokenize) into
~100µs channel hops instead of ~ms task RPCs, keeping the TPU fed.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.experimental.channel import (
    Channel, ChannelClosedError, DEFAULT_BUFFER_SIZE,
)

COMPILED_STAGE_METHOD = "__rt_compiled_stage__"


class DAGNode:
    """Base: a lazily-bound computation with DAGNode/value args."""

    def __init__(self, args: Tuple = (), kwargs: Optional[Dict] = None):
        self._bound_args = tuple(args)
        self._bound_kwargs = dict(kwargs or {})
        self._id = uuid.uuid4().hex[:12]

    # ------------------------------------------------------------ traversal
    def _deps(self) -> List["DAGNode"]:
        out = []
        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                out.append(a)
        return out

    def _topo(self) -> List["DAGNode"]:
        order, seen = [], set()

        def visit(n: "DAGNode"):
            if n._id in seen:
                return
            seen.add(n._id)
            for d in n._deps():
                visit(d)
            order.append(n)

        visit(self)
        return order

    # ------------------------------------------------------------ execution
    def execute(self, *input_args, **input_kwargs):
        """Interpreted execution: one task/actor call per node.
        Returns ObjectRef(s) for the terminal node(s)."""
        input_value = _pack_input(input_args, input_kwargs)
        memo: Dict[str, Any] = {}
        for node in self._topo():
            memo[node._id] = node._execute_one(memo, input_value)
        return memo[self._id]

    def _execute_one(self, memo, input_value):
        raise NotImplementedError

    def _resolve(self, memo):
        args = [memo[a._id] if isinstance(a, DAGNode) else a
                for a in self._bound_args]
        kwargs = {k: memo[v._id] if isinstance(v, DAGNode) else v
                  for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def experimental_compile(
            self, _buffer_size_bytes: int = DEFAULT_BUFFER_SIZE,
            _max_in_flight: int = 2,
    ) -> "CompiledDAG":
        return CompiledDAG(self, _buffer_size_bytes, _max_in_flight)


class InputNode(DAGNode):
    """The DAG's runtime input. Usable as a context manager, matching the
    reference's `with InputNode() as inp:` idiom."""

    def __init__(self):
        super().__init__()

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def __getattr__(self, key: str) -> "InputAttributeNode":
        if key.startswith("_"):
            raise AttributeError(key)
        return InputAttributeNode(self, key, getattr)

    def __getitem__(self, key) -> "InputAttributeNode":
        return InputAttributeNode(self, key,
                                  lambda v, k: v[k])

    def _execute_one(self, memo, input_value):
        return input_value


class InputAttributeNode(DAGNode):
    """`inp.x` / `inp["x"]` — extracts a field of the input."""

    def __init__(self, parent: InputNode, key, extractor):
        super().__init__(args=(parent,))
        self._key = key
        self._extract = extractor

    def _execute_one(self, memo, input_value):
        return self._extract(input_value, self._key)


class FunctionNode(DAGNode):
    """`remote_fn.bind(...)` — a stateless task node."""

    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_one(self, memo, input_value):
        args, kwargs = self._resolve(memo)
        return self._remote_fn.remote(*args, **kwargs)


class ClassMethodNode(DAGNode):
    """`actor.method.bind(...)` — a stateful actor-method node."""

    def __init__(self, actor_method, args, kwargs):
        super().__init__(args, kwargs)
        self._method = actor_method

    @property
    def _actor_id(self) -> bytes:
        return self._method._handle._actor_id

    def _execute_one(self, memo, input_value):
        args, kwargs = self._resolve(memo)
        return self._method.remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    """Terminal fan-in: execute() returns one value per output."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__(args=tuple(outputs))

    def _execute_one(self, memo, input_value):
        return [memo[o._id] for o in self._bound_args]


def _pack_input(args, kwargs):
    if kwargs or len(args) > 1:
        raise TypeError(
            "DAG input is a single value; pack multiple inputs in a "
            "dict/tuple and split with inp['key'] / inp[i]")
    return args[0] if args else None


# ---------------------------------------------------------------------------
# Compiled execution
# ---------------------------------------------------------------------------

_DRIVER = "__driver_input__"


class CompiledDAGRef:
    """Future for one compiled execution (reference: CompiledDAGRef).
    Results come off the shared output channels FIFO, so refs must be
    consumed in execution order — get() enforces it."""

    def __init__(self, dag: "CompiledDAG", multi: bool, idx: int):
        self._dag = dag
        self._multi = multi
        self._idx = idx
        # Partially-read outputs survive a timeout so a retry resumes on
        # the not-yet-read channels instead of mispairing executions.
        self._vals: List[Any] = []
        self._done = False

    def get(self, timeout: Optional[float] = 30.0):
        if not self._done:
            if self._dag._next_read_idx != self._idx:
                raise RuntimeError(
                    f"compiled DAG results are FIFO: this ref is execution "
                    f"#{self._idx} but #{self._dag._next_read_idx} is next; "
                    f"call get() on earlier refs first")
            chans = self._dag._output_channels
            while len(self._vals) < len(chans):
                self._vals.append(chans[len(self._vals)].read(timeout))
            self._dag._next_read_idx += 1
            self._done = True
        for v in self._vals:
            if isinstance(v, _StageError):
                raise v.error
        return self._vals if self._multi else self._vals[0]


class CompiledDAG:
    """The DAG pre-wired over shm channels: every actor node runs a
    resident stage loop; `execute()` = one channel write, `get()` = one
    channel read."""

    def __init__(self, root: DAGNode, buffer_size: int,
                 max_in_flight: int = 2):
        self._buffer_size = buffer_size
        # Every channel holds ONE slot, so unconsumed executions beyond
        # the pipeline depth would deadlock the driver's write. Cap them
        # (2 = one result pending + one execution in the pipe, always
        # within any DAG's slot budget).
        self._max_in_flight = max(1, max_in_flight)
        self._next_exec_idx = 0
        self._next_read_idx = 0
        self._torn_down = False
        self._channels: List[Channel] = []

        nodes = root._topo()
        outputs = (list(root._bound_args)
                   if isinstance(root, MultiOutputNode) else [root])
        stages = [n for n in nodes if isinstance(n, ClassMethodNode)]
        for n in nodes:
            if not isinstance(n, (InputNode, InputAttributeNode,
                                  ClassMethodNode, MultiOutputNode)):
                raise TypeError(
                    "experimental_compile supports actor-method nodes only "
                    f"(got {type(n).__name__}); stateless fn.bind nodes "
                    "run via dag.execute()")
        seen_actors = set()
        for s in stages:
            if s._actor_id in seen_actors:
                raise ValueError(
                    "compiled DAGs bind at most one method per actor "
                    "(the stage loop occupies the actor for the DAG's "
                    "lifetime)")
            seen_actors.add(s._actor_id)
        for o in outputs:
            if not isinstance(o, ClassMethodNode):
                raise TypeError("DAG outputs must be actor-method nodes")

        # producer keys: driver input = _DRIVER, else node id.
        def producer_key(dep: DAGNode) -> str:
            if isinstance(dep, (InputNode, InputAttributeNode)):
                return _DRIVER
            return dep._id

        # Channels are SPSC: one per (producer, consumer) pair, shared by
        # all args between that pair.
        chan: Dict[Tuple[str, str], Channel] = {}

        def channel_for(p: str, c: str) -> Channel:
            if (p, c) not in chan:
                ch = Channel(create=True, buffer_size=buffer_size)
                chan[(p, c)] = ch
                self._channels.append(ch)
            return chan[(p, c)]

        # Driver-input channels (one per consumer that reads the input).
        self._input_channels: List[Channel] = []
        payloads: Dict[str, Dict[str, Any]] = {}
        for s in stages:
            def spec_of(a):
                if isinstance(a, (InputNode, InputAttributeNode)):
                    key = getattr(a, "_key", None)
                    extract = getattr(a, "_extract", None)
                    return ("chan", _DRIVER, key, extract)
                if isinstance(a, ClassMethodNode):
                    return ("chan", a._id, None, None)
                if isinstance(a, DAGNode):
                    raise TypeError(f"unsupported dep {type(a).__name__}")
                return ("const", a)

            arg_spec = [spec_of(a) for a in s._bound_args]
            kwarg_spec = {k: spec_of(v)
                          for k, v in s._bound_kwargs.items()}
            in_channels = {}
            for sp in list(arg_spec) + list(kwarg_spec.values()):
                if sp[0] == "chan":
                    in_channels[sp[1]] = channel_for(sp[1], s._id)
            payloads[s._id] = {
                "method": s._method._name,
                "arg_spec": arg_spec,
                "kwarg_spec": kwarg_spec,
                "in_channels": in_channels,
                "out_channels": [],
            }
        for (p, c), ch in chan.items():
            if p == _DRIVER:
                self._input_channels.append(ch)
            else:
                payloads[p]["out_channels"].append(ch)

        # Terminal outputs feed the driver.
        self._output_channels = []
        for o in outputs:
            ch = Channel(create=True, buffer_size=buffer_size)
            self._channels.append(ch)
            payloads[o._id]["out_channels"].append(ch)
            self._output_channels.append(ch)

        self._multi = isinstance(root, MultiOutputNode)
        # Launch the resident stage loops (one dedicated actor task each).
        from ray_tpu.actor import ActorMethod

        self._stage_refs = []
        for s in stages:
            loop_method = ActorMethod(s._method._handle,
                                      COMPILED_STAGE_METHOD)
            self._stage_refs.append(loop_method.remote(payloads[s._id]))

    # ------------------------------------------------------------------ api
    def execute(self, *args, _timeout: float = 30.0,
                **kwargs) -> CompiledDAGRef:
        if self._torn_down:
            raise RuntimeError("compiled DAG was torn down")
        if self._next_exec_idx - self._next_read_idx >= self._max_in_flight:
            raise RuntimeError(
                f"{self._max_in_flight} executions already in flight; "
                f"get() earlier results first (or raise _max_in_flight "
                f"at compile time)")
        value = _pack_input(args, kwargs)
        payload = Channel.serialize(value)   # once, even when fanning out
        for ch in self._input_channels:
            ch.write_serialized(payload, timeout=_timeout)
        ref = CompiledDAGRef(self, self._multi, self._next_exec_idx)
        self._next_exec_idx += 1
        return ref

    def teardown(self) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        import ray_tpu

        for ch in self._channels:
            ch.close()
        try:
            ray_tpu.get(self._stage_refs, timeout=10)
        except Exception:
            pass
        for ch in self._channels:
            ch.release()

    def __del__(self):  # pragma: no cover
        try:
            self.teardown()
        except Exception:
            pass


class _StageError:
    """An exception crossing channels: downstream stages forward it
    untouched and CompiledDAGRef.get() re-raises it on the driver, so a
    failing stage degrades to a per-execution error instead of a hung
    pipeline."""

    def __init__(self, error: Exception):
        self.error = error


def run_compiled_stage(instance, payload: Dict[str, Any]) -> Dict[str, int]:
    """Executes one node's resident loop inside its actor (dispatched by
    the worker when it sees COMPILED_STAGE_METHOD). Blocks the actor's
    executor until teardown — compiled DAGs own their actors, matching
    the reference's aDAG semantics."""
    in_channels: Dict[str, Channel] = payload["in_channels"]
    out_channels: List[Channel] = payload["out_channels"]
    iterations = 0
    # A bad method name must not strand the protocol: keep the loop
    # alive and answer every execution with the error instead.
    fatal: Optional[_StageError] = None
    method = getattr(instance, payload["method"], None)
    if method is None:
        fatal = _StageError(AttributeError(
            f"actor has no method {payload['method']!r}"))

    def build(spec, vals):
        if spec[0] == "const":
            return spec[1]
        _, pkey, key, extract = spec
        v = vals[pkey]
        return extract(v, key) if extract is not None else v

    try:
        while True:
            try:
                vals = {k: ch.read() for k, ch in in_channels.items()}
            except ChannelClosedError:
                break
            upstream_err = next((v for v in vals.values()
                                 if isinstance(v, _StageError)), None)
            if fatal is not None:
                result = fatal
            elif upstream_err is not None:
                result = upstream_err
            else:
                try:
                    args = [build(sp, vals) for sp in payload["arg_spec"]]
                    kwargs = {k: build(sp, vals)
                              for k, sp in payload["kwarg_spec"].items()}
                    result = method(*args, **kwargs)
                except Exception as e:  # noqa: BLE001
                    result = _StageError(e)
            closed = False
            for ch in out_channels:
                try:
                    ch.write(result)
                except ChannelClosedError:
                    closed = True
                    break
                except Exception as e:  # noqa: BLE001
                    # Oversized / unpicklable result: the error (small,
                    # picklable) takes the value's slot so this execution
                    # fails instead of the whole pipeline wedging.
                    try:
                        ch.write(_StageError(e))
                    except Exception:
                        closed = True
                        break
            if closed:
                break
            iterations += 1
    finally:
        for ch in list(in_channels.values()) + out_channels:
            ch.close()
    return {"iterations": iterations}


__all__ = [
    "DAGNode", "InputNode", "InputAttributeNode", "FunctionNode",
    "ClassMethodNode", "MultiOutputNode", "CompiledDAG", "CompiledDAGRef",
]
