"""Is it adamw, or the chained-vs-independent measurement?"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.models.llama import LlamaConfig, flops_per_token, init_params, loss_fn
from ray_tpu.parallel import (
    batch_sharding, build_train_step, create_train_state,
    llama_param_shardings, make_mesh, shard_params,
)

PEAK = 197e12
B, S = 8, 1024
config = LlamaConfig(
    vocab_size=32000, dim=1024, n_layers=16, n_heads=16,
    n_kv_heads=16, hidden_dim=2816, max_seq_len=S, attn_impl="flash")
mesh = make_mesh({"data": -1})


def fresh_params():
    return shard_params(init_params(config, jax.random.key(0)),
                        llama_param_shardings(config, mesh))


params = None
bsh = batch_sharding(mesh)
rng = np.random.RandomState(0)
batch = {"tokens": jax.device_put(
    rng.randint(0, config.vocab_size, (B, S)).astype("int32"), bsh)}
step_flops = flops_per_token(config, S) * B * (S - 1)


def run(tag, optimizer, iters=15):
    state = create_train_state(fresh_params(), optimizer)
    step = build_train_step(lambda p, b: loss_fn(p, b, config), optimizer,
                            mesh, llama_param_shardings(config, mesh), bsh)
    state, m = step(state, batch)
    float(m["loss"])
    t0 = time.perf_counter(); float(m["loss"]); rt = time.perf_counter() - t0
    start = time.perf_counter()
    for _ in range(iters):
        state, m = step(state, batch)
    float(m["loss"])
    el = max(time.perf_counter() - start - rt, 1e-9)
    print(f"{tag:26s} step={el/iters*1000:8.1f}ms mfu={step_flops/(el/iters)/PEAK:.3f}",
          flush=True)


which = sys.argv[1] if len(sys.argv) > 1 else "all"
if which in ("all", "sgd"):
    run("sgd", optax.sgd(0.0))
if which in ("all", "adamw"):
    run("adamw", optax.adamw(1e-4))
if which in ("all", "chaingrad"):
    # grads chained through params, no optimizer state at all
    @jax.jit
    def gstep(p, b):
        l, g = jax.value_and_grad(lambda pp: loss_fn(pp, b, config))(p)
        newp = jax.tree.map(lambda a, b_: a - 0.0 * b_, p, g)
        return newp, l
    p = fresh_params()
    p, l = gstep(p, batch); float(l)
    t0 = time.perf_counter(); float(l); rt = time.perf_counter() - t0
    start = time.perf_counter()
    for _ in range(15):
        p, l = gstep(p, batch)
    float(l)
    el = max(time.perf_counter() - start - rt, 1e-9)
    print(f"{'chained grads+0update':26s} step={el/15*1000:8.1f}ms mfu={step_flops/(el/15)/PEAK:.3f}",
          flush=True)
