"""Component-level probe: dispatch overhead, matmul ceiling, attention."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

PEAK = 197e12


def chain_time(tag, fn, args, iters=20, flops=None):
    """Time `iters` chained invocations (out feeds in)."""
    out = fn(*args)
    jax.tree.map(lambda x: x.block_until_ready(), out)
    # sync via scalar readback
    first = jax.tree.leaves(out)[0]
    float(jnp.sum(first))
    t0 = time.perf_counter(); float(jnp.sum(first)); rt = time.perf_counter() - t0
    start = time.perf_counter()
    o = args[0]
    rest = args[1:]
    for _ in range(iters):
        o = fn(o, *rest)
        if isinstance(o, tuple):
            o = o[0]
    float(jnp.sum(jax.tree.leaves(o)[0]))
    el = max(time.perf_counter() - start - rt, 1e-9)
    ms = el / iters * 1000
    line = f"{tag:36s} {ms:8.2f} ms/iter  (roundtrip {rt*1000:.0f}ms)"
    if flops:
        line += f"  mfu={flops / (el / iters) / PEAK:.3f}"
    print(line, flush=True)


which = sys.argv[1] if len(sys.argv) > 1 else "all"

if which in ("all", "disp"):
    @jax.jit
    def triv(x):
        return x + 1.0
    chain_time("trivial step (dispatch overhead)", triv, (jnp.zeros(()),), 50)

if which in ("all", "mm"):
    N = 4096
    a = jax.random.normal(jax.random.key(0), (N, N), jnp.bfloat16)
    b = jax.random.normal(jax.random.key(1), (N, N), jnp.bfloat16)

    @jax.jit
    def mm(a, b):
        # 8 chained matmuls to amortize dispatch
        for _ in range(8):
            a = (a @ b) * (1.0 / N)
        return a
    chain_time("bf16 4096^3 matmul x8", mm, (a, b), 20, flops=8 * 2 * N**3)

if which in ("all", "attn"):
    from ray_tpu.ops.attention import flash_attention
    from ray_tpu.models.llama import xla_attention

    B, S, H, D = 8, 1024, 16, 64
    q = jax.random.normal(jax.random.key(0), (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.key(1), (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.key(2), (B, S, H, D), jnp.bfloat16)
    # causal ~ half the FLOPs of full
    attn_flops = 2 * 2 * B * H * S * S * D  # qk + pv, full (causal halves)

    def mk(f):
        @jax.jit
        def fwd_bwd(q, k, v):
            def loss(q):
                return jnp.sum(f(q, k, v, True).astype(jnp.float32))
            l, g = jax.value_and_grad(loss)(q)
            return g, l
        return fwd_bwd

    chain_time("flash fwd+bwd B8 S1024 H16 D64", mk(flash_attention), (q, k, v), 10,
               flops=3 * attn_flops / 2)
    chain_time("xla   fwd+bwd B8 S1024 H16 D64", mk(lambda q, k, v, c: xla_attention(q, k, v, causal=c)), (q, k, v), 10,
               flops=3 * attn_flops / 2)
