"""Isolate embed/lm_head backward cost on the 1B model."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from ray_tpu.models.llama import LlamaConfig, flops_per_token, forward, init_params
from ray_tpu.parallel import (
    batch_sharding, create_train_state, llama_param_shardings, make_mesh,
    shard_params,
)
from ray_tpu.parallel.train_step import TrainState

PEAK = 197e12
S = 1024
K = 4
B = 8

config = LlamaConfig(
    vocab_size=32000, dim=4096, n_layers=4, n_heads=32,
    n_kv_heads=8, hidden_dim=11008, max_seq_len=S,
    attn_impl="flash", remat=True, param_dtype=jnp.bfloat16)


def loss_variant(params, toks, mode):
    if mode == "sg_embed":
        params = dict(params, embed=lax.stop_gradient(params["embed"]))
    if mode == "sg_both":
        params = dict(params, embed=lax.stop_gradient(params["embed"]),
                      lm_head=lax.stop_gradient(params["lm_head"]))
    logits = forward(params, toks[:, :-1], config)
    targets = toks[:, 1:]
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return (lse - tgt).mean()


def run(tag, mode, iters=3):
    mesh = make_mesh({"data": -1})
    opt = optax.adamw(1e-4)
    state = create_train_state(
        shard_params(init_params(config, jax.random.key(0)),
                     llama_param_shardings(config, mesh)), opt)

    def one(st, toks):
        loss, grads = jax.value_and_grad(
            lambda p: loss_variant(p, toks, mode))(st.params)
        updates, new_opt = opt.update(grads, st.opt_state, st.params)
        return TrainState(optax.apply_updates(st.params, updates), new_opt,
                          st.step + 1), loss

    @jax.jit
    def multi(st, toks_k):
        return lax.scan(one, st, toks_k)

    multi_d = jax.jit(multi, donate_argnums=(0,))
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, 32000, (K, B, S)).astype("int32"))
    state, losses = multi_d(state, toks)
    float(losses[-1])
    start = time.perf_counter()
    for _ in range(iters):
        state, losses = multi_d(state, toks)
    float(losses[-1])
    per_step = (time.perf_counter() - start) / (iters * K)
    toks_s = B * (S - 1) / per_step
    mfu = toks_s * flops_per_token(config, S) / PEAK
    print(f"{tag:22s} step={per_step*1000:7.1f}ms mfu={mfu:.3f}", flush=True)


run({"base": "1B base", "sge": "1B sg(embed)",
     "sgb": "1B sg(embed+head)"}[sys.argv[1]],
    {"base": "base", "sge": "sg_embed", "sgb": "sg_both"}[sys.argv[1]])
