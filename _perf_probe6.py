"""Bisect build_train_step jit options."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.models.llama import LlamaConfig, flops_per_token, init_params, loss_fn
from ray_tpu.parallel import (
    batch_sharding, create_train_state, llama_param_shardings, make_mesh,
    shard_params,
)
from ray_tpu.parallel.train_step import TrainState

PEAK = 197e12
B, S = 8, 1024
config = LlamaConfig(
    vocab_size=32000, dim=1024, n_layers=16, n_heads=16,
    n_kv_heads=16, hidden_dim=2816, max_seq_len=S, attn_impl="flash")
mesh = make_mesh({"data": -1})
bsh = batch_sharding(mesh)
rng = np.random.RandomState(0)
batch = {"tokens": jax.device_put(
    rng.randint(0, config.vocab_size, (B, S)).astype("int32"), bsh)}
step_flops = flops_per_token(config, S) * B * (S - 1)
optimizer = optax.adamw(1e-4)


def build(with_donate, with_insh, with_gnorm):
    def step_fn(state, b):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, b, config))(state.params)
        metrics = {"loss": loss, "step": state.step + 1}
        if with_gnorm:
            metrics["grad_norm"] = optax.global_norm(grads)
        updates, new_opt = optimizer.update(grads, state.opt_state,
                                            state.params)
        new_params = optax.apply_updates(state.params, updates)
        return TrainState(new_params, new_opt, state.step + 1), metrics

    kw = {}
    if with_insh:
        kw["in_shardings"] = (None, bsh)
    if with_donate:
        kw["donate_argnums"] = (0,)
    return jax.jit(step_fn, **kw)


def run(tag, **kws):
    step = build(**kws)
    state = create_train_state(
        shard_params(init_params(config, jax.random.key(0)),
                     llama_param_shardings(config, mesh)), optimizer)
    state, m = step(state, batch)
    float(m["loss"])
    t0 = time.perf_counter(); float(m["loss"]); rt = time.perf_counter() - t0
    iters = 10
    start = time.perf_counter()
    for _ in range(iters):
        state, m = step(state, batch)
    float(m["loss"])
    el = max(time.perf_counter() - start - rt, 1e-9)
    print(f"{tag:34s} step={el/iters*1000:8.1f}ms mfu={step_flops/(el/iters)/PEAK:.3f}",
          flush=True)


which = sys.argv[1]
if which == "full":
    run("donate+insh+gnorm", with_donate=True, with_insh=True, with_gnorm=True)
elif which == "nodonate":
    run("insh+gnorm (no donate)", with_donate=False, with_insh=True, with_gnorm=True)
elif which == "noinsh":
    run("donate+gnorm (no insh)", with_donate=True, with_insh=False, with_gnorm=True)
elif which == "nognorm":
    run("donate+insh (no gnorm)", with_donate=True, with_insh=True, with_gnorm=False)
elif which == "none":
    run("plain jit", with_donate=False, with_insh=False, with_gnorm=False)
