"""Ulysses sequence parallelism parity on the 8-device virtual CPU mesh
(reference: SURVEY §5 — all-to-all head/sequence resharding as the
config alternative to ring attention; the DeepSpeed-Ulysses pattern over
XLA collectives)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from ray_tpu.models.llama import xla_attention  # noqa: E402
from ray_tpu.ops.ulysses import (  # noqa: E402
    ulysses_attention, ulysses_attention_global,
)


def _mesh(n=8, name="sp"):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"need {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (name,))


def _rand(key, shape):
    return jax.random.normal(key, shape, jnp.float32)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_reference(causal):
    B, S, H, D = 2, 256, 8, 32
    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (_rand(ks[i], (B, S, H, D)) for i in range(3))
    mesh = _mesh()
    out = ulysses_attention_global(q, k, v, mesh, causal=causal)
    ref = xla_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_gqa_and_grads():
    """TRUE grouped-query attention (Hkv < H): the KV head shard expands
    to the query head count after the reshard; grads flow through both
    all-to-alls and the repeat."""
    B, S, H, Hkv, D = 1, 128, 16, 8, 16
    ks = jax.random.split(jax.random.key(1), 3)
    q = _rand(ks[0], (B, S, H, D))
    k = _rand(ks[1], (B, S, Hkv, D))
    v = _rand(ks[2], (B, S, Hkv, D))
    mesh = _mesh()

    def mk(f):
        def loss(q, k, v):
            o = f(q, k, v)
            w = jnp.arange(o.size, dtype=o.dtype).reshape(o.shape) / o.size
            return jnp.sum(o * w)
        return loss

    def ref_attn(q, k, v):
        rep = H // Hkv
        return xla_attention(q, jnp.repeat(k, rep, axis=2),
                             jnp.repeat(v, rep, axis=2), causal=True)

    out = ulysses_attention_global(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(ref_attn(q, k, v)),
                               rtol=2e-5, atol=2e-5)

    g_uly = jax.grad(mk(lambda q, k, v: ulysses_attention_global(
        q, k, v, mesh, causal=True)), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(mk(ref_attn), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_uly, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_ulysses_head_divisibility_enforced():
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = _mesh()
    B, S, H, D = 1, 256, 4, 16   # 4 heads on an 8-way axis: invalid
    q = _rand(jax.random.key(2), (B, S, H, D))
    spec = P(None, "sp", None, None)
    with pytest.raises(ValueError, match="must divide"):
        shard_map(lambda a, b, c: ulysses_attention(a, b, c,
                                                    axis_name="sp"),
                  mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
                  check_rep=False)(q, q, q)


def test_unbound_axis_falls_back_exact():
    B, S, H, D = 1, 128, 4, 16
    q = _rand(jax.random.key(3), (B, S, H, D))
    out = ulysses_attention(q, q, q, causal=True, axis_name="nope")
    ref = xla_attention(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_context_parallel_attention_impl_switch():
    """parallel.context_parallel_attention routes impl= to ring or
    ulysses and both train the model layer identically."""
    from ray_tpu.models.llama import LlamaConfig, forward, init_params
    from ray_tpu.parallel import context_parallel_attention

    mesh = _mesh(name="seq")
    cfg = LlamaConfig(vocab_size=64, dim=32, n_layers=1, n_heads=8,
                      n_kv_heads=8, hidden_dim=64, max_seq_len=256)
    params = init_params(cfg, jax.random.key(0))
    toks = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (1, 256)), jnp.int32)

    ref = forward(params, toks, cfg)
    for impl in ("ring", "ulysses"):
        attn = context_parallel_attention(mesh, seq_axis="seq", impl=impl)
        out = forward(params, toks, cfg, attn_impl=attn)
        # fp32 reassociation through norm+FFN amplifies attention's
        # reduction-order differences; logits tolerance reflects that.
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=0, atol=5e-3)
    with pytest.raises(ValueError, match="expected 'ring'"):
        context_parallel_attention(mesh, impl="bogus")
