"""Continuous-batching LLM engine (serve/llm): greedy parity with the
static `generate` path, slot recycling under staggered arrivals, the
compile-count guard, and the Serve deployment integration.

Compile budget: the tiny model still traces a full scan per program, so
the module caches the params, the per-(prompt, n) static references,
and ONE default-geometry engine shared by every test that doesn't need
special slots/buckets (each extra engine instance re-jits its tick +
touched insert buckets).
"""

import numpy as np
import pytest

_CACHE = {}


def _model():
    if "model" not in _CACHE:
        import jax

        from ray_tpu.models.llama import LlamaConfig, init_params

        config = LlamaConfig.tiny()
        _CACHE["model"] = (config, init_params(config, jax.random.key(0)))
    return _CACHE["model"]


def _engine(slots=4, buckets=(8, 16), S=64, **kw):
    from ray_tpu.serve.llm.engine import EngineConfig, LLMEngine

    config, params = _model()
    return LLMEngine(params, config, EngineConfig(
        num_slots=slots, max_seq_len=S, prefill_buckets=buckets, **kw))


def _shared_engine():
    """Single-step engine reused across tests (drained between); 2
    slots so queueing paths get constant exercise."""
    if "engine" not in _CACHE:
        _CACHE["engine"] = _engine(slots=2)
    return _CACHE["engine"]


def _shared_engine_multi():
    """Multi-step (decode_block=2) engine shared by the multi-step
    parity and recycling tests."""
    if "engine_multi" not in _CACHE:
        _CACHE["engine_multi"] = _engine(slots=3, decode_block=2)
    return _CACHE["engine_multi"]


def _specs(seed, pairs):
    config, _ = _model()
    rng = np.random.RandomState(seed)
    return [(rng.randint(0, config.vocab_size, p).tolist(), n)
            for p, n in pairs]


# One spec list for every parity test: reference shapes are cached, so
# reuse keeps the number of traced `generate` programs minimal.
_PARITY_PAIRS = [(3, 6), (8, 2), (11, 8), (16, 4), (5, 1), (7, 7)]


def _reference(prompt, n):
    """Per-request static path: the parity oracle (cached per shape —
    every distinct (len(prompt), n) traces a whole generate scan)."""
    key = (tuple(prompt), n)
    refs = _CACHE.setdefault("refs", {})
    if key not in refs:
        import jax.numpy as jnp

        from ray_tpu.models.llama import generate

        config, params = _model()
        out = generate(params, jnp.asarray([prompt], jnp.int32), config,
                       max_new_tokens=n)
        refs[key] = np.asarray(out)[0].tolist()
    return list(refs[key])


@pytest.mark.parametrize("decode_block", [1, 2])
def test_greedy_parity_mixed_lengths(decode_block):
    """Engine output is token-identical to per-request `generate` for
    mixed prompt/output lengths submitted together — including with
    multi-step decode blocks, where post-stop speculative tokens are
    computed on device but truncated host-side."""
    from ray_tpu.serve.llm.engine import Request

    engine = (_shared_engine() if decode_block == 1
              else _shared_engine_multi())
    specs = _specs(0, _PARITY_PAIRS)
    handles = [engine.submit(Request(prompt=p, max_tokens=n))
               for p, n in specs]
    engine.drain()
    for (p, n), h in zip(specs, handles):
        assert h.finish_reason == "length"
        assert h.tokens == _reference(p, n), (p, n)


def test_greedy_parity_any_arrival_order():
    """Same requests, staggered arrival: tokens are identical no matter
    when a request joins the running batch (slot state is isolated;
    the 2-slot shared engine forces queueing too)."""
    from ray_tpu.serve.llm.engine import Request

    specs = _specs(0, _PARITY_PAIRS)[:5]
    expected = [_reference(p, n) for p, n in specs]

    engine = _shared_engine()
    handles = []
    for i, (p, n) in enumerate(specs):
        handles.append(engine.submit(Request(prompt=p, max_tokens=n)))
        # Interleave arrivals with decode progress.
        for _ in range(i + 1):
            engine.step()
    engine.drain()
    for h, exp in zip(handles, expected):
        assert h.tokens == exp


def test_slot_recycling_under_staggered_arrivals():
    """More requests than slots: slots are evicted on completion and
    recycled for queued requests; everything completes."""
    from ray_tpu.serve.llm.engine import Request

    config, _ = _model()
    engine = _shared_engine_multi()        # 3 slots, decode_block=2
    base = engine.stats()
    rng = np.random.RandomState(2)
    handles = []
    for i in range(10):
        p = rng.randint(0, config.vocab_size, rng.randint(2, 16)).tolist()
        handles.append(engine.submit(
            Request(prompt=p, max_tokens=int(rng.randint(1, 6)))))
    engine.drain()
    st = engine.stats()
    assert st["completed"] == base["completed"] + 10
    assert st["active_slots"] == 0 and st["queued"] == 0
    assert st["slot_reuses"] >= base["slot_reuses"] + 7   # 10 reqs / 3 slots
    for h in handles:
        assert h.done() and len(h.tokens) >= 1


def test_compile_count_guard():
    """A mixed workload traces at most n_prefill_buckets + 1 engine
    programs — no per-request or per-shape recompiles."""
    from ray_tpu.serve.llm.engine import Request

    config, _ = _model()
    engine = _engine(slots=4, buckets=(8, 16))
    rng = np.random.RandomState(3)
    for i in range(12):                     # both buckets, varied lengths
        p = rng.randint(0, config.vocab_size, rng.randint(1, 16)).tolist()
        engine.submit(Request(prompt=p, max_tokens=int(rng.randint(1, 7)),
                              temperature=float(i % 2) * 0.7))
        engine.step()
    engine.drain()
    assert engine.trace_count <= len(engine.config.prefill_buckets) + 1, \
        engine.stats()


def test_eos_and_stop_tokens():
    """EOS halts and is emitted; stop tokens halt without being
    emitted; max_tokens bounds generation."""
    from ray_tpu.serve.llm.engine import Request

    prompt = list(range(1, 9))
    ref = _reference(prompt, 8)

    # Pick the reference's 3rd token as eos/stop so it actually fires.
    t3 = ref[2]
    eng = _engine(eos_id=t3)
    h = eng.submit(Request(prompt=prompt, max_tokens=8))
    eng.drain()
    assert h.finish_reason == "eos" and h.tokens == ref[:3]

    eng2 = _shared_engine()                # stop is per-request
    h2 = eng2.submit(Request(prompt=prompt, max_tokens=8, stop=(t3,)))
    eng2.drain()
    assert h2.finish_reason == "stop" and h2.tokens == ref[:2]


def test_streaming_callback_and_latency_fields():
    from ray_tpu.serve.llm.engine import Request

    engine = _shared_engine()
    seen = []
    h = engine.submit(Request(
        prompt=[1, 2, 3], max_tokens=5,
        on_token=lambda rid, tok: seen.append((rid, tok))))
    engine.drain()
    assert [t for _, t in seen] == h.tokens and len(h.tokens) == 5
    assert all(rid == h.request_id for rid, _ in seen)
    assert h.ttft_s is not None and h.ttft_s >= 0
    assert h.tpot_s is not None and h.tpot_s >= 0


def test_sampled_decode_respects_temperature():
    """Temperature > 0 goes through the categorical path and still
    terminates correctly (no parity claim)."""
    from ray_tpu.serve.llm.engine import Request

    config, _ = _model()
    engine = _shared_engine()
    h = engine.submit(Request(prompt=[5, 6, 7], max_tokens=6,
                              temperature=0.9))
    engine.drain()
    assert len(h.tokens) == 6
    assert all(0 <= t < config.vocab_size for t in h.tokens)


def test_submit_validation():
    from ray_tpu.serve.llm.engine import Request

    engine = _engine(buckets=(8,))         # never stepped: no compiles
    with pytest.raises(ValueError):
        engine.submit(Request(prompt=[], max_tokens=1))
    with pytest.raises(ValueError):
        engine.submit(Request(prompt=[1] * 9, max_tokens=1))  # > bucket
    with pytest.raises(ValueError):
        engine.submit(Request(prompt=[1], max_tokens=0))


def test_serve_llm_deployment_smoke(ray_start_regular):
    """Fast tier-1 smoke: the engine behind a Serve deployment (tiny
    config, 4 slots, 2 buckets); concurrent handle calls return the
    same tokens as the static reference."""
    from ray_tpu import serve
    from ray_tpu.serve.llm import build_llm_app

    config, _ = _model()
    try:
        handle = serve.run(build_llm_app(
            model_config=config,
            engine_config={"num_slots": 4, "max_seq_len": 64,
                           "prefill_buckets": (8, 16)},
            init_seed=0, max_ongoing_requests=8), name="llm")
        rng = np.random.RandomState(4)
        prompts = [rng.randint(0, config.vocab_size,
                               rng.randint(2, 16)).tolist()
                   for _ in range(6)]
        resps = [handle.remote({"prompt": p, "max_tokens": 4})
                 for p in prompts]
        for p, r in zip(prompts, resps):
            out = r.result(timeout=120)
            assert out["tokens"] == _reference(p, 4)
            assert out["num_tokens"] == 4
            assert out["finish_reason"] == "length"
    finally:
        serve.shutdown()


@pytest.mark.slow
def test_serve_throughput_bench_smoke():
    """The bench.py serve workload end to end on CPU (slow tier:
    exercises Poisson arrivals + continuous vs static measurement)."""
    from bench import _bench_serve

    result = _bench_serve(None, on_tpu=False, device_kind="cpu")
    assert result["metric"] == "llama_serve_tokens_per_sec"
    assert result["value"] is not None and result["value"] > 0
    d = result["detail"]
    assert d["static_tokens_per_sec"] > 0
    assert d["ttft_p50_ms"] >= 0 and d["ttft_p99_ms"] >= d["ttft_p50_ms"]
    assert d["requests"] == d["completed"]
