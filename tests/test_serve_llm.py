"""Continuous-batching LLM engine (serve/llm): greedy parity with the
static `generate` path, slot recycling under staggered arrivals, the
compile-count guard, and the Serve deployment integration.

Compile budget: the tiny model still traces a full scan per program, so
the module caches the params, the per-(prompt, n) static references,
and ONE default-geometry engine shared by every test that doesn't need
special slots/buckets (each extra engine instance re-jits its tick +
touched insert buckets).
"""

import numpy as np
import pytest

_CACHE = {}


def _model():
    if "model" not in _CACHE:
        import jax

        from ray_tpu.models.llama import LlamaConfig, init_params

        config = LlamaConfig.tiny()
        _CACHE["model"] = (config, init_params(config, jax.random.key(0)))
    return _CACHE["model"]


def _engine(slots=4, buckets=(8, 16), S=64, **kw):
    from ray_tpu.serve.llm.engine import EngineConfig, LLMEngine

    config, params = _model()
    return LLMEngine(params, config, EngineConfig(
        num_slots=slots, max_seq_len=S, prefill_buckets=buckets, **kw))


def _shared_engine():
    """Single-step engine reused across tests (drained between); 2
    slots so queueing paths get constant exercise."""
    if "engine" not in _CACHE:
        _CACHE["engine"] = _engine(slots=2)
    return _CACHE["engine"]


def _shared_engine_multi():
    """Multi-step (decode_block=2) engine shared by the multi-step
    parity and recycling tests."""
    if "engine_multi" not in _CACHE:
        _CACHE["engine_multi"] = _engine(slots=3, decode_block=2)
    return _CACHE["engine_multi"]


def _specs(seed, pairs):
    config, _ = _model()
    rng = np.random.RandomState(seed)
    return [(rng.randint(0, config.vocab_size, p).tolist(), n)
            for p, n in pairs]


# One spec list for every parity test: reference shapes are cached, so
# reuse keeps the number of traced `generate` programs minimal.
_PARITY_PAIRS = [(3, 6), (8, 2), (11, 8), (16, 4), (5, 1), (7, 7)]


def _reference(prompt, n):
    """Per-request static path: the parity oracle (cached per shape —
    every distinct (len(prompt), n) traces a whole generate scan)."""
    key = (tuple(prompt), n)
    refs = _CACHE.setdefault("refs", {})
    if key not in refs:
        import jax.numpy as jnp

        from ray_tpu.models.llama import generate

        config, params = _model()
        out = generate(params, jnp.asarray([prompt], jnp.int32), config,
                       max_new_tokens=n)
        refs[key] = np.asarray(out)[0].tolist()
    return list(refs[key])


@pytest.mark.parametrize("decode_block", [1, 2])
def test_greedy_parity_mixed_lengths(decode_block):
    """Engine output is token-identical to per-request `generate` for
    mixed prompt/output lengths submitted together — including with
    multi-step decode blocks, where post-stop speculative tokens are
    computed on device but truncated host-side."""
    from ray_tpu.serve.llm.engine import Request

    engine = (_shared_engine() if decode_block == 1
              else _shared_engine_multi())
    specs = _specs(0, _PARITY_PAIRS)
    handles = [engine.submit(Request(prompt=p, max_tokens=n))
               for p, n in specs]
    engine.drain()
    for (p, n), h in zip(specs, handles):
        assert h.finish_reason == "length"
        assert h.tokens == _reference(p, n), (p, n)


def test_greedy_parity_any_arrival_order():
    """Same requests, staggered arrival: tokens are identical no matter
    when a request joins the running batch (slot state is isolated;
    the 2-slot shared engine forces queueing too)."""
    from ray_tpu.serve.llm.engine import Request

    specs = _specs(0, _PARITY_PAIRS)[:5]
    expected = [_reference(p, n) for p, n in specs]

    engine = _shared_engine()
    handles = []
    for i, (p, n) in enumerate(specs):
        handles.append(engine.submit(Request(prompt=p, max_tokens=n)))
        # Interleave arrivals with decode progress.
        for _ in range(i + 1):
            engine.step()
    engine.drain()
    for h, exp in zip(handles, expected):
        assert h.tokens == exp


def test_slot_recycling_under_staggered_arrivals():
    """More requests than slots: slots are evicted on completion and
    recycled for queued requests; everything completes."""
    from ray_tpu.serve.llm.engine import Request

    config, _ = _model()
    engine = _shared_engine_multi()        # 3 slots, decode_block=2
    base = engine.stats()
    rng = np.random.RandomState(2)
    handles = []
    for i in range(10):
        p = rng.randint(0, config.vocab_size, rng.randint(2, 16)).tolist()
        handles.append(engine.submit(
            Request(prompt=p, max_tokens=int(rng.randint(1, 6)))))
    engine.drain()
    st = engine.stats()
    assert st["completed"] == base["completed"] + 10
    assert st["active_slots"] == 0 and st["queued"] == 0
    assert st["slot_reuses"] >= base["slot_reuses"] + 7   # 10 reqs / 3 slots
    for h in handles:
        assert h.done() and len(h.tokens) >= 1


def test_compile_count_guard():
    """A mixed workload traces at most n_prefill_buckets + 1 engine
    programs — no per-request or per-shape recompiles."""
    from ray_tpu.serve.llm.engine import Request

    config, _ = _model()
    engine = _engine(slots=4, buckets=(8, 16))
    rng = np.random.RandomState(3)
    for i in range(12):                     # both buckets, varied lengths
        p = rng.randint(0, config.vocab_size, rng.randint(1, 16)).tolist()
        engine.submit(Request(prompt=p, max_tokens=int(rng.randint(1, 7)),
                              temperature=float(i % 2) * 0.7))
        engine.step()
    engine.drain()
    assert engine.trace_count <= len(engine.config.prefill_buckets) + 1, \
        engine.stats()


def test_eos_and_stop_tokens():
    """EOS halts and is emitted; stop tokens halt without being
    emitted; max_tokens bounds generation."""
    from ray_tpu.serve.llm.engine import Request

    prompt = list(range(1, 9))
    ref = _reference(prompt, 8)

    # Pick the reference's 3rd token as eos/stop so it actually fires.
    t3 = ref[2]
    eng = _engine(eos_id=t3)
    h = eng.submit(Request(prompt=prompt, max_tokens=8))
    eng.drain()
    assert h.finish_reason == "eos" and h.tokens == ref[:3]

    eng2 = _shared_engine()                # stop is per-request
    h2 = eng2.submit(Request(prompt=prompt, max_tokens=8, stop=(t3,)))
    eng2.drain()
    assert h2.finish_reason == "stop" and h2.tokens == ref[:2]


def test_streaming_callback_and_latency_fields():
    from ray_tpu.serve.llm.engine import Request

    engine = _shared_engine()
    seen = []
    h = engine.submit(Request(
        prompt=[1, 2, 3], max_tokens=5,
        on_token=lambda rid, tok: seen.append((rid, tok))))
    engine.drain()
    assert [t for _, t in seen] == h.tokens and len(h.tokens) == 5
    assert all(rid == h.request_id for rid, _ in seen)
    assert h.ttft_s is not None and h.ttft_s >= 0
    assert h.tpot_s is not None and h.tpot_s >= 0


def test_sampled_decode_respects_temperature():
    """Temperature > 0 goes through the categorical path and still
    terminates correctly (no parity claim)."""
    from ray_tpu.serve.llm.engine import Request

    config, _ = _model()
    engine = _shared_engine()
    h = engine.submit(Request(prompt=[5, 6, 7], max_tokens=6,
                              temperature=0.9))
    engine.drain()
    assert len(h.tokens) == 6
    assert all(0 <= t < config.vocab_size for t in h.tokens)


def test_submit_validation():
    from ray_tpu.serve.llm.engine import Request

    engine = _engine(buckets=(8,))         # never stepped: no compiles
    with pytest.raises(ValueError):
        engine.submit(Request(prompt=[], max_tokens=1))
    with pytest.raises(ValueError):
        engine.submit(Request(prompt=[1] * 9, max_tokens=1))  # > bucket
    with pytest.raises(ValueError):
        engine.submit(Request(prompt=[1], max_tokens=0))


# --------------------------------------------------------------- paged KV


def _shared_paged():
    """Paged-layout engine shared by the paged parity/prefix tests
    (every extra engine instance re-jits its tick + insert buckets)."""
    if "engine_paged" not in _CACHE:
        _CACHE["engine_paged"] = _engine(
            slots=3, kv_layout="paged", kv_block_size=4)
    return _CACHE["engine_paged"]


def test_paged_greedy_parity_and_compile_count():
    """Paged attention (block tables + pool gather) is token-exact
    against the dense static reference for mixed lengths, inside the
    same compile budget: n_prefill_buckets + 1 programs."""
    from ray_tpu.serve.llm.engine import Request

    engine = _shared_paged()
    specs = _specs(0, _PARITY_PAIRS)
    handles = [engine.submit(Request(prompt=p, max_tokens=n))
               for p, n in specs]
    engine.drain()
    for (p, n), h in zip(specs, handles):
        assert h.finish_reason == "length"
        assert h.tokens == _reference(p, n), (p, n)
    assert engine.trace_count <= len(engine.config.prefill_buckets) + 1, \
        engine.stats()


def test_paged_prefix_hit_skips_prefill_and_keeps_parity():
    """A second request sharing a block-aligned prompt prefix hits the
    prefix cache — its cached blocks skip prefill — and the output is
    still token-identical to the full static path."""
    from ray_tpu.serve.llm.engine import Request

    config, _ = _model()
    engine = _shared_paged()
    rng = np.random.RandomState(7)
    sys_p = rng.randint(0, config.vocab_size, 8).tolist()
    p1 = sys_p + rng.randint(0, config.vocab_size, 4).tolist()
    p2 = sys_p + rng.randint(0, config.vocab_size, 5).tolist()
    before = engine.stats()["prefix_cache"]
    h1 = engine.submit(Request(prompt=p1, max_tokens=4))
    engine.drain()                           # p1's blocks now cached
    h2 = engine.submit(Request(prompt=p2, max_tokens=4))
    engine.drain()
    after = engine.stats()["prefix_cache"]
    assert h1.tokens == _reference(p1, 4)
    assert h2.tokens == _reference(p2, 4)
    assert after["hits"] >= before["hits"] + 1
    assert after["hit_tokens"] >= before["hit_tokens"] + len(sys_p)


def test_paged_pool_exhaustion_queues_not_crash():
    """Block demand beyond the pool: admission parks requests in the
    queue and completes them as finishing sequences free blocks; only a
    request that can NEVER fit is rejected, at submit time."""
    from ray_tpu.serve.llm.engine import Request

    config, _ = _model()
    engine = _engine(slots=4, buckets=(8,), S=32, kv_layout="paged",
                     kv_block_size=4, num_kv_blocks=6,
                     prefix_cache=False)
    with pytest.raises(ValueError):          # worst case 8 blocks > 6
        engine.submit(Request(prompt=[1] * 8, max_tokens=32))
    rng = np.random.RandomState(5)
    handles = [engine.submit(Request(
        prompt=rng.randint(0, config.vocab_size, 8).tolist(),
        max_tokens=4)) for _ in range(5)]    # 3 blocks each, pool of 6
    engine.step()
    st = engine.stats()
    assert st["queued"] >= 1                 # exhaustion queued, no crash
    assert st["kv"]["used_blocks"] <= 6
    engine.drain()
    assert all(h.done() and len(h.tokens) == 4 for h in handles)
    assert engine.stats()["kv"]["used_blocks"] == 0


def test_llm_server_quantize_default_and_optout():
    """The serve config defaults to weight-only int8 decode (BENCH_r05:
    1.28x decode throughput); "bf16" opts out; anything else is
    rejected before weights load."""
    from ray_tpu.serve.llm.deployment import LLMServer

    config, _ = _model()
    econf = {"num_slots": 2, "max_seq_len": 32, "prefill_buckets": (8,)}
    srv = LLMServer(model_config=config, engine_config=econf)
    assert srv.quantize == "int8"
    assert srv.stats()["quantize"] == "int8"
    assert set(srv.load()) == {"queued", "active_slots", "free_slots",
                               "lanes", "index_id"}
    srv_bf16 = LLMServer(model_config=config, engine_config=econf,
                         quantize="bf16")
    assert srv_bf16.quantize == "bf16"
    with pytest.raises(ValueError):
        LLMServer(model_config=config, engine_config=econf,
                  quantize="fp4")


# ----------------------------------------------------------------- router


def test_p2c_pick_prefers_light_replicas():
    import random as _random

    from ray_tpu.serve.llm.router import p2c_pick

    rng = _random.Random(0)
    load = {"light": 0.0, "heavy": 5.0}
    picks = [p2c_pick(["light", "heavy"], load, rng) for _ in range(40)]
    assert picks.count("light") == 40        # 2 replicas: always compared


def test_router_stalled_replica_sheds_traffic():
    """A replica whose load probe fails scores float('inf'), so p2c
    assignment shifts all traffic to the live replica."""
    import random as _random
    import threading

    from ray_tpu.serve.llm.router import LLMRouter, p2c_pick

    r = LLMRouter.__new__(LLMRouter)         # policy only: no controller
    r._lock = threading.Lock()
    r._replicas = ["live", "stalled"]
    r._inflight = {"live": 3, "stalled": 0}
    r._depth = {"live": 2.0, "stalled": float("inf")}
    replicas, load = r._score()
    assert load["stalled"] == float("inf")
    rng = _random.Random(1)
    assert all(p2c_pick(replicas, load, rng) == "live"
               for _ in range(25))


def test_routed_llm_two_replicas_smoke(ray_start_regular):
    """Router over two LLM replicas: results match the static
    reference and traffic spreads across both replicas."""
    from ray_tpu import serve
    from ray_tpu.serve.llm import build_routed_llm_app

    config, _ = _model()
    try:
        handle = serve.run(build_routed_llm_app(
            model_config=config,
            engine_config={"num_slots": 2, "max_seq_len": 64,
                           "prefill_buckets": (8, 16)},
            num_replicas=2, quantize="bf16", max_ongoing_requests=8,
            probe_interval_s=0.1), name="llm-routed")
        rng = np.random.RandomState(4)       # same trace as the plain
        prompts = [rng.randint(0, config.vocab_size,  # smoke: refs cached
                               rng.randint(2, 16)).tolist()
                   for _ in range(6)]
        resps = [handle.remote({"prompt": p, "max_tokens": 4})
                 for p in prompts]
        for p, r in zip(prompts, resps):
            out = r.result(timeout=120)
            assert out["tokens"] == _reference(p, 4)
        st = handle.stats.remote().result(timeout=60)
        assert st["replicas"] == 2
        assert sum(st["routed"].values()) == len(prompts)
        assert len(st["routed"]) == 2        # both replicas took traffic
    finally:
        serve.shutdown()


def test_serve_llm_deployment_smoke(ray_start_regular):
    """Fast tier-1 smoke: the engine behind a Serve deployment (tiny
    config, 4 slots, 2 buckets); concurrent handle calls return the
    same tokens as the static reference. quantize="bf16" keeps
    bit-parity with the bf16 reference (int8 is the serve default)."""
    from ray_tpu import serve
    from ray_tpu.serve.llm import build_llm_app

    config, _ = _model()
    try:
        handle = serve.run(build_llm_app(
            model_config=config,
            engine_config={"num_slots": 4, "max_seq_len": 64,
                           "prefill_buckets": (8, 16)},
            init_seed=0, quantize="bf16", max_ongoing_requests=8),
            name="llm")
        rng = np.random.RandomState(4)
        prompts = [rng.randint(0, config.vocab_size,
                               rng.randint(2, 16)).tolist()
                   for _ in range(6)]
        resps = [handle.remote({"prompt": p, "max_tokens": 4})
                 for p in prompts]
        for p, r in zip(prompts, resps):
            out = r.result(timeout=120)
            assert out["tokens"] == _reference(p, 4)
            assert out["num_tokens"] == 4
            assert out["finish_reason"] == "length"
    finally:
        serve.shutdown()


@pytest.mark.slow
def test_serve_throughput_bench_smoke():
    """The bench.py serve workload end to end on CPU (slow tier:
    exercises Poisson arrivals + continuous vs static measurement)."""
    from bench import _bench_serve

    result = _bench_serve(None, on_tpu=False, device_kind="cpu")
    assert result["metric"] == "llama_serve_tokens_per_sec"
    assert result["value"] is not None and result["value"] > 0
    d = result["detail"]
    assert d["static_tokens_per_sec"] > 0
    assert d["ttft_p50_ms"] >= 0 and d["ttft_p99_ms"] >= d["ttft_p50_ms"]
    assert d["requests"] == d["completed"]


@pytest.mark.slow
def test_serve_paged_bench_smoke():
    """The bench.py paged/router workload end to end on CPU (slow tier:
    dense-vs-paged parity load, prefix TTFT, simulated-device replica
    scaling)."""
    from bench import _bench_serve_paged

    result = _bench_serve_paged(False, "cpu")
    assert result["metric"] == "llama_serve_paged"
    assert result["value"] is not None and result["value"] > 0
    d = result["detail"]
    # + 2: decode tick plus the (single, bounded) adopt scatter that
    # tier promotes share with disagg migration — still no per-request
    # or per-shape recompiles.
    assert d["engine_traces"] <= len(d["prefill_buckets"]) + 2
    assert d["two_vs_one_p99"] < 1.0      # second replica relieves p99
    assert d["prefix_hit_rate"] > 0.3     # 60%-shared trace must hit
    assert d["kv_blocks"]["num_blocks"] > 0
