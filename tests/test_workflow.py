"""Workflow: durable DAGs, checkpointed steps, crash resume (reference:
`python/ray/workflow/workflow_executor.py:32`,
`workflow_state_from_storage.py`)."""

import os

import pytest

import ray_tpu
from ray_tpu import workflow


@pytest.fixture(autouse=True)
def _wf_storage(tmp_path):
    workflow.init(str(tmp_path / "wf"))
    yield


@workflow.step
def double(x):
    return 2 * x


@workflow.step
def add(a, b):
    return a + b


def test_dag_runs_and_returns(ray_start_regular):
    out = add.step(double.step(3), double.step(4)).run("basic")
    assert out == 14
    assert workflow.get_status("basic") == "SUCCEEDED"
    assert workflow.get_output("basic") == 14


def test_steps_checkpoint_and_replay(ray_start_regular, tmp_path):
    marker = str(tmp_path / "runs")
    os.makedirs(marker)

    @workflow.step
    def tracked(x):
        import time

        open(os.path.join(marker, f"run_{time.time_ns()}"), "w").close()
        return x + 1

    dag = tracked.step(10)
    assert dag.run("replay") == 11
    assert len(os.listdir(marker)) == 1
    # Re-running the same workflow id replays from storage: no re-execution.
    dag2 = tracked.step(10)
    assert dag2.run("replay") == 11
    assert len(os.listdir(marker)) == 1


def test_resume_after_failure(ray_start_regular, tmp_path):
    marker = str(tmp_path / "m")
    os.makedirs(marker)

    @workflow.step
    def flaky(x):
        if not os.path.exists(os.path.join(marker, "ok")):
            raise RuntimeError("first attempt dies")
        return x * 100

    @workflow.step
    def stable(x):
        open(os.path.join(marker, f"stable_{x}"), "w").close()
        return x

    dag = flaky.step(add.step(stable.step(1), stable.step(2)))
    with pytest.raises(Exception):
        dag.run("resumable")
    assert workflow.get_status("resumable") == "FAILED"
    # The completed prefix (stable x2 + add) is checkpointed.
    assert len([f for f in os.listdir(marker)
                if f.startswith("stable")]) == 2

    open(os.path.join(marker, "ok"), "w").close()
    out = workflow.resume("resumable")
    assert out == 300
    # stable steps replayed from storage, not re-executed.
    assert len([f for f in os.listdir(marker)
                if f.startswith("stable")]) == 2
    assert workflow.get_status("resumable") == "SUCCEEDED"


def test_list_all(ray_start_regular):
    double.step(1).run("wf_a")
    double.step(2).run("wf_b")
    listed = {w["workflow_id"]: w["status"] for w in workflow.list_all()}
    assert listed == {"wf_a": "SUCCEEDED", "wf_b": "SUCCEEDED"}


def test_step_options_retries_and_catch(ray_start_regular, tmp_path):
    """max_retries re-executes a flaky step; catch_exceptions checkpoints
    (result, err) pairs (reference: workflow.options)."""
    marker = tmp_path / "flaky_tries"

    @workflow.step(max_retries=3)
    def flaky():
        n = int(marker.read_text()) if marker.exists() else 0
        marker.write_text(str(n + 1))
        if n < 2:
            raise RuntimeError("transient")
        return "recovered"

    assert flaky.step().run("wf_retry") == "recovered"
    assert int(marker.read_text()) == 3

    @workflow.step
    def always_fails():
        raise ValueError("boom")

    @workflow.step
    def handle(pair):
        result, err = pair
        return f"handled:{type(err).__name__}" if err else result

    out = handle.step(
        always_fails.step().options(catch_exceptions=True)).run("wf_catch")
    assert out == "handled:ValueError"
    assert workflow.get_status("wf_catch") == "SUCCEEDED"

    # Without catch_exceptions the workflow fails.
    with pytest.raises(Exception):
        handle.step(always_fails.step()).run("wf_nocatch")
    assert workflow.get_status("wf_nocatch") == "FAILED"
