"""Workflow: durable DAGs, checkpointed steps, crash resume (reference:
`python/ray/workflow/workflow_executor.py:32`,
`workflow_state_from_storage.py`)."""

import os

import pytest

import ray_tpu
from ray_tpu import workflow


@pytest.fixture(autouse=True)
def _wf_storage(tmp_path):
    workflow.init(str(tmp_path / "wf"))
    yield


@workflow.step
def double(x):
    return 2 * x


@workflow.step
def add(a, b):
    return a + b


def test_dag_runs_and_returns(ray_start_regular):
    out = add.step(double.step(3), double.step(4)).run("basic")
    assert out == 14
    assert workflow.get_status("basic") == "SUCCEEDED"
    assert workflow.get_output("basic") == 14


def test_steps_checkpoint_and_replay(ray_start_regular, tmp_path):
    marker = str(tmp_path / "runs")
    os.makedirs(marker)

    @workflow.step
    def tracked(x):
        import time

        open(os.path.join(marker, f"run_{time.time_ns()}"), "w").close()
        return x + 1

    dag = tracked.step(10)
    assert dag.run("replay") == 11
    assert len(os.listdir(marker)) == 1
    # Re-running the same workflow id replays from storage: no re-execution.
    dag2 = tracked.step(10)
    assert dag2.run("replay") == 11
    assert len(os.listdir(marker)) == 1


def test_resume_after_failure(ray_start_regular, tmp_path):
    marker = str(tmp_path / "m")
    os.makedirs(marker)

    @workflow.step
    def flaky(x):
        if not os.path.exists(os.path.join(marker, "ok")):
            raise RuntimeError("first attempt dies")
        return x * 100

    @workflow.step
    def stable(x):
        open(os.path.join(marker, f"stable_{x}"), "w").close()
        return x

    dag = flaky.step(add.step(stable.step(1), stable.step(2)))
    with pytest.raises(Exception):
        dag.run("resumable")
    assert workflow.get_status("resumable") == "FAILED"
    # The completed prefix (stable x2 + add) is checkpointed.
    assert len([f for f in os.listdir(marker)
                if f.startswith("stable")]) == 2

    open(os.path.join(marker, "ok"), "w").close()
    out = workflow.resume("resumable")
    assert out == 300
    # stable steps replayed from storage, not re-executed.
    assert len([f for f in os.listdir(marker)
                if f.startswith("stable")]) == 2
    assert workflow.get_status("resumable") == "SUCCEEDED"


def test_list_all(ray_start_regular):
    double.step(1).run("wf_a")
    double.step(2).run("wf_b")
    listed = {w["workflow_id"]: w["status"] for w in workflow.list_all()}
    assert listed == {"wf_a": "SUCCEEDED", "wf_b": "SUCCEEDED"}


def test_step_options_retries_and_catch(ray_start_regular, tmp_path):
    """max_retries re-executes a flaky step; catch_exceptions checkpoints
    (result, err) pairs (reference: workflow.options)."""
    marker = tmp_path / "flaky_tries"

    @workflow.step(max_retries=3)
    def flaky():
        n = int(marker.read_text()) if marker.exists() else 0
        marker.write_text(str(n + 1))
        if n < 2:
            raise RuntimeError("transient")
        return "recovered"

    assert flaky.step().run("wf_retry") == "recovered"
    assert int(marker.read_text()) == 3

    @workflow.step
    def always_fails():
        raise ValueError("boom")

    @workflow.step
    def handle(pair):
        result, err = pair
        return f"handled:{type(err).__name__}" if err else result

    out = handle.step(
        always_fails.step().options(catch_exceptions=True)).run("wf_catch")
    assert out == "handled:ValueError"
    assert workflow.get_status("wf_catch") == "SUCCEEDED"

    # Without catch_exceptions the workflow fails.
    with pytest.raises(Exception):
        handle.step(always_fails.step()).run("wf_nocatch")
    assert workflow.get_status("wf_nocatch") == "FAILED"


# ------------------------------------------------------------------ events
def test_event_step_options_keeps_listener():
    """wait_for_event(...).options(...) must stay an EventStep — Step's
    copy semantics would drop the listener and crash at execution."""
    from ray_tpu.workflow import EventStep

    ev = workflow.wait_for_event("approved", timeout=3.0)
    tuned = ev.options(max_retries=2, catch_exceptions=True)
    assert isinstance(tuned, EventStep)
    assert tuned.listener is ev.listener
    assert tuned.timeout == 3.0
    assert tuned.max_retries == 2 and tuned.catch_exceptions
    # untouched original (copy semantics preserved)
    assert ev.max_retries == 0 and not ev.catch_exceptions


def test_wait_for_event_delivers_and_checkpoints(ray_start_regular,
                                                 tmp_path):
    """A workflow parks on wait_for_event until the HTTP provider
    delivers; after success the event payload is CHECKPOINTED — resume
    replays it even with the event file gone (reference:
    workflow/event_listener.py + http_event_provider.py)."""
    import json
    import threading
    import urllib.request

    @workflow.step
    def combine(evt, y):
        return f"{evt['order']}-{y}"

    provider = workflow.HTTPEventProvider(
        storage_dir=workflow._storage()).start()
    try:
        dag = combine.step(workflow.wait_for_event("payment"), 7)

        out = {}

        def run_wf():
            out["result"] = dag.run("wf_evt")

        t = threading.Thread(target=run_wf)
        t.start()
        import time

        time.sleep(1.0)
        assert t.is_alive()  # parked on the event

        req = urllib.request.Request(
            f"http://127.0.0.1:{provider.port}/event/wf_evt/payment",
            data=json.dumps({"order": "A17"}).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert json.loads(resp.read())["delivered"]
        t.join(timeout=60)
        assert not t.is_alive()
        assert out["result"] == "A17-7"

        # GET reads the delivered event back.
        with urllib.request.urlopen(
                f"http://127.0.0.1:{provider.port}/event/wf_evt/payment",
                timeout=10) as resp:
            assert json.loads(resp.read())["payload"] == {"order": "A17"}

        # Durability: delete the event file; resume must REPLAY the
        # checkpointed payload, not re-wait.
        evt_file = os.path.join(workflow._storage(), "wf_evt", "events",
                                "payment.json")
        os.remove(evt_file)
        assert workflow.resume("wf_evt") == "A17-7"
    finally:
        provider.stop()


def test_wait_for_event_timeout(ray_start_regular, tmp_path):
    @workflow.step
    def use(evt):
        return evt

    dag = use.step(workflow.wait_for_event("never", timeout=0.5))
    with pytest.raises(Exception, match="never"):
        dag.run("wf_evt_to")
    assert workflow.get_status("wf_evt_to") == "FAILED"


def test_event_checkpointed_ack_fires_after_durable(ray_start_regular,
                                                    tmp_path):
    acks = []

    class AckListener(workflow.FileEventListener):
        def event_checkpointed(self, event):
            acks.append(event)

    @workflow.step
    def use(evt):
        return evt["n"]

    from ray_tpu.workflow.events import deliver_event

    deliver_event(workflow._storage(), "wf_ack", "go", {"n": 5})
    dag = use.step(workflow.wait_for_event(lambda: AckListener("go")))
    assert dag.run("wf_ack") == 5
    assert acks == [{"n": 5}]
