"""Rainbow DQN (C51 + n-step + PER + dueling) and evaluation workers.

Reference parity: `rllib/algorithms/dqn/dqn_rainbow_learner.py`
(categorical projection), `rllib/utils/replay_buffers/
prioritized_episode_buffer.py`, `rllib/evaluation/worker_set.py`.
"""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def rl_cluster():
    import ray_tpu

    info = ray_tpu.init(num_cpus=8, num_tpus=0,
                        object_store_memory=256 * 1024 * 1024,
                        ignore_reinit_error=True)
    yield info
    ray_tpu.shutdown()


# --------------------------------------------------------------- projection
def test_categorical_projection_mass_and_terminal():
    import jax.numpy as jnp

    from ray_tpu.rllib.algorithms.rainbow import categorical_projection

    k = 11
    z = jnp.linspace(-5.0, 5.0, k)
    rng = np.random.RandomState(0)
    probs = rng.dirichlet(np.ones(k), size=4).astype(np.float32)
    rewards = jnp.asarray([0.0, 1.0, -2.0, 3.0])
    discounts = jnp.full((4,), 0.9 ** 3)

    m = categorical_projection(jnp.asarray(probs), rewards,
                               jnp.asarray([1.0, 1.0, 1.0, 0.0]),
                               discounts, z, -5.0, 5.0)
    m = np.asarray(m)
    # Projection preserves probability mass.
    np.testing.assert_allclose(m.sum(-1), 1.0, atol=1e-5)
    # Terminal row: all mass lands on the atoms bracketing the reward.
    row = m[3]
    b = (3.0 - (-5.0)) / 1.0          # delta = 1.0 -> index 8 exactly
    assert row[int(b)] == pytest.approx(1.0, abs=1e-5)


def test_categorical_projection_matches_bruteforce():
    import jax.numpy as jnp

    from ray_tpu.rllib.algorithms.rainbow import categorical_projection

    k = 7
    v_min, v_max = -2.0, 2.0
    z = np.linspace(v_min, v_max, k)
    delta = (v_max - v_min) / (k - 1)
    rng = np.random.RandomState(1)
    probs = rng.dirichlet(np.ones(k), size=8).astype(np.float32)
    rewards = rng.uniform(-1, 1, 8).astype(np.float32)
    nt = rng.randint(0, 2, 8).astype(np.float32)
    disc = np.full(8, 0.97, np.float32)

    expect = np.zeros((8, k))
    for i in range(8):
        for j in range(k):
            tz = np.clip(rewards[i] + nt[i] * disc[i] * z[j], v_min, v_max)
            b = (tz - v_min) / delta
            lo, hi = int(np.floor(b)), int(np.ceil(b))
            if lo == hi:
                expect[i, lo] += probs[i, j]
            else:
                expect[i, lo] += probs[i, j] * (hi - b)
                expect[i, hi] += probs[i, j] * (b - lo)

    got = np.asarray(categorical_projection(
        jnp.asarray(probs), jnp.asarray(rewards), jnp.asarray(nt),
        jnp.asarray(disc), jnp.asarray(z), v_min, v_max))
    np.testing.assert_allclose(got, expect, atol=1e-5)


# ---------------------------------------------------------------------- PER
def test_prioritized_buffer_bias_and_weights():
    from ray_tpu.rllib.algorithms.rainbow import PrioritizedReplayBuffer

    buf = PrioritizedReplayBuffer(64, (2,), alpha=1.0)
    obs = np.zeros((4, 2), np.float32)
    buf.add_batch(obs, np.zeros(4, np.int32), np.zeros(4, np.float32),
                  obs, np.zeros(4, np.float32), np.ones(4, np.float32))
    # Give index 3 a 100x priority; it should dominate samples.
    buf.update_priorities(np.arange(4), np.array([0.01, 0.01, 0.01, 1.0]))
    rng = np.random.RandomState(0)
    batch, idx = buf.sample(512, rng, beta=1.0)
    frac = (idx == 3).mean()
    assert frac > 0.8, frac
    # Importance weights: rare transitions get the LARGER weight; the
    # most-sampled one is normalized to the batch minimum.
    w_hot = batch["weights"][idx == 3]
    w_cold = batch["weights"][idx != 3]
    if len(w_cold):
        assert w_cold.min() > w_hot.max()
    assert batch["weights"].max() == pytest.approx(1.0)


def test_prioritized_buffer_wraps_and_tracks_max():
    from ray_tpu.rllib.algorithms.rainbow import PrioritizedReplayBuffer

    buf = PrioritizedReplayBuffer(8, (1,), alpha=0.5)
    for i in range(3):
        obs = np.full((4, 1), i, np.float32)
        buf.add_batch(obs, np.zeros(4, np.int32),
                      np.zeros(4, np.float32), obs,
                      np.zeros(4, np.float32), np.ones(4, np.float32))
    assert len(buf) == 8
    rng = np.random.RandomState(1)
    batch, idx = buf.sample(32, rng, beta=0.4)
    assert batch["obs"].min() >= 1.0   # oldest batch overwritten


# ------------------------------------------------------------------- n-step
def test_nstep_composition():
    from ray_tpu.rllib.algorithms.rainbow import nstep_from_fragment

    # One lane, T=5, episode terminates at t=2.
    T = 5
    ro = {
        "obs": np.arange(T, dtype=np.float32).reshape(T, 1, 1),
        "actions": np.zeros((T, 1), np.int64),
        "rewards": np.array([[1.0], [2.0], [4.0], [8.0], [16.0]],
                            np.float32),
        "dones": np.array([[0], [0], [1], [0], [0]], np.float32),
        "terminateds": np.array([[0], [0], [1], [0], [0]], np.float32),
        "next_obs": (np.arange(T, dtype=np.float32) + 1).reshape(T, 1, 1),
    }
    out = nstep_from_fragment(ro, n_step=3, gamma=0.5)
    # t=0: 1 + .5*2 + .25*4 = 3, ends at t=2 (terminal), disc=0.5^3
    assert out["rewards"][0] == pytest.approx(3.0)
    assert out["dones"][0] == 1.0
    assert out["next_obs"][0, 0] == pytest.approx(3.0)
    assert out["discounts"][0] == pytest.approx(0.125)
    # t=1: 2 + .5*4 = 4 — accumulation stops AT the terminal step.
    assert out["rewards"][1] == pytest.approx(4.0)
    assert out["dones"][1] == 1.0
    assert out["discounts"][1] == pytest.approx(0.25)
    # t=3: crosses no boundary, truncated by fragment end at t=4:
    # 8 + .5*16 = 16, non-terminal (bootstraps), disc=0.25.
    assert out["rewards"][3] == pytest.approx(16.0)
    assert out["dones"][3] == 0.0
    assert out["next_obs"][3, 0] == pytest.approx(5.0)
    assert out["discounts"][3] == pytest.approx(0.25)


# ---------------------------------------------------------- learner + algo
def test_rainbow_learner_reduces_loss_and_reports_priorities():
    import jax

    from ray_tpu.rllib.algorithms.rainbow import (
        PRIORITY_KEY, RainbowLearner, RainbowModule)
    from ray_tpu.rllib.core.rl_module import RLModuleSpec
    from ray_tpu.rllib.env.spaces import Box, Discrete

    spec = RLModuleSpec(
        Box(low=-np.ones(3), high=np.ones(3)), Discrete(2),
        hidden=(32,),
        module_class=lambda o, a, h: RainbowModule(
            o, a, h, num_atoms=21, v_min=-5, v_max=5))
    learner = RainbowLearner(spec, {"lr": 5e-3, "gamma": 0.9})
    learner.build()
    rng = np.random.RandomState(0)
    batch = {
        "obs": rng.randn(64, 3).astype(np.float32),
        "next_obs": rng.randn(64, 3).astype(np.float32),
        "actions": rng.randint(0, 2, 64).astype(np.int32),
        "rewards": rng.randn(64).astype(np.float32),
        "dones": (rng.rand(64) < 0.2).astype(np.float32),
        "discounts": np.full(64, 0.9 ** 3, np.float32),
        "weights": np.ones(64, np.float32),
    }
    losses = []
    for i in range(40):
        m = learner.update(batch, rng_seed=i)
        losses.append(m["td_loss"])
        assert PRIORITY_KEY in m
        assert m[PRIORITY_KEY].shape == (64,)
        assert np.all(m[PRIORITY_KEY] >= 0)
    assert losses[-1] < losses[0]
    # After 40 online updates the (stale) target differs from params;
    # sync_target snapshots them equal again.
    t0 = np.asarray(jax.tree.leaves(learner._state["target"]["net"])[0])
    p0 = np.asarray(jax.tree.leaves(learner._state["params"]["net"])[0])
    assert not np.array_equal(t0, p0)
    learner.sync_target()
    assert jax.tree.all(jax.tree.map(
        lambda a, b: bool((np.asarray(a) == np.asarray(b)).all()),
        learner._state["target"], learner._state["params"]))


def test_rainbow_cartpole_improves(rl_cluster):
    from ray_tpu.rllib import RainbowConfig

    config = (RainbowConfig()
              .environment("CartPole-v1")
              .training(lr=1e-3, train_batch_size=64)
              .env_runners(num_env_runners=1, num_envs_per_runner=4)
              .learners(num_learners=1, jax_platform="cpu")
              .rl_module(hidden=(64, 64)))
    config.learning_starts = 300
    config.rollout_fragment_length = 32
    config.epsilon_decay_steps = 4000
    config.num_updates_per_iteration = 48
    config.target_update_freq = 100
    config.n_step = 3
    config.num_atoms = 31
    config.v_min = 0.0
    config.v_max = 120.0        # CartPole returns are non-negative
    algo = config.build()
    try:
        first, best = None, -np.inf
        for _ in range(60):
            m = algo.train()
            r = m.get("episode_return_mean")
            if r is not None:
                if first is None:
                    first = r
                best = max(best, r)
            if best >= 60:
                break
        assert first is not None
        assert best >= 60, (first, best)
    finally:
        algo.stop()


def test_sac_forward_inference_is_deterministic_mean():
    """Greedy evaluation must work for continuous policies: SACModule's
    forward_inference returns the squashed mean, within action bounds."""
    import jax

    from ray_tpu.rllib.algorithms.sac import SACModule
    from ray_tpu.rllib.env.spaces import Box

    mod = SACModule(Box(low=-np.ones(3), high=np.ones(3)),
                    Box(low=-2 * np.ones(1), high=2 * np.ones(1)),
                    hidden=(16,))
    params = mod.init(jax.random.key(0))
    obs = np.random.RandomState(0).randn(5, 3).astype(np.float32)
    a1 = np.asarray(mod.forward_inference(params, obs)["actions"])
    a2 = np.asarray(mod.forward_inference(params, obs)["actions"])
    np.testing.assert_array_equal(a1, a2)
    assert a1.shape == (5, 1)
    assert np.all(np.abs(a1) <= 2.0)


def test_evaluation_workers(rl_cluster):
    from ray_tpu.rllib import DQNConfig

    config = (DQNConfig()
              .environment("CartPole-v1")
              .training(lr=1e-3, train_batch_size=32)
              .env_runners(num_env_runners=1, num_envs_per_runner=2)
              .learners(num_learners=1, jax_platform="cpu")
              .evaluation(evaluation_interval=2, evaluation_duration=4,
                          evaluation_num_env_runners=2))
    config.learning_starts = 64
    config.rollout_fragment_length = 16
    config.num_updates_per_iteration = 4
    algo = config.build()
    try:
        m1 = algo.train()
        assert "evaluation" not in m1          # iteration 1, interval 2
        m2 = algo.train()
        ev = m2["evaluation"]
        assert ev["num_episodes"] == 4
        assert np.isfinite(ev["episode_return_mean"])
        assert ev["episode_return_max"] >= ev["episode_return_mean"] \
            >= ev["episode_return_min"]
        assert ev["episode_len_mean"] > 0
        # Direct evaluate() also works between train() calls.
        ev2 = algo.evaluate()
        assert ev2["num_episodes"] == 4
    finally:
        algo.stop()
