"""Multi-agent RLlib: MultiAgentEnv API, MultiRLModule, masked-lane
rollouts, and multi-agent PPO (shared and per-agent policies).

Reference: `rllib/env/multi_agent_env.py`,
`rllib/core/rl_module/multi_rl_module.py`,
`rllib/examples/multi_agent/rock_paper_scissors_*.py` (learned best
response vs a scripted opponent is the reference's own smoke target).
"""

import numpy as np
import pytest

import jax

from ray_tpu.rllib import PPOConfig
from ray_tpu.rllib.core.multi_rl_module import MultiRLModuleSpec
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.env.multi_agent_env import (MultiAgentCartPole,
                                               RockPaperScissors)


@pytest.fixture(scope="module")
def ma_cluster():
    import ray_tpu

    info = ray_tpu.init(num_cpus=8, num_tpus=0,
                        object_store_memory=256 * 1024 * 1024,
                        ignore_reinit_error=True)
    yield info
    ray_tpu.shutdown()


# --------------------------------------------------------------- env units
def test_multi_agent_cartpole_api():
    env = MultiAgentCartPole(num_agents=3, seed=0)
    assert env.possible_agents == ["agent_0", "agent_1", "agent_2"]
    obs, _ = env.reset(seed=0)
    assert set(obs) == set(env.possible_agents)
    for a in env.possible_agents:
        assert obs[a].shape == (4,)

    # Step until some agent terminates; its key must vanish from obs.
    done_agents = set()
    for _ in range(600):
        acts = {a: 1 for a in env.possible_agents if a not in done_agents}
        obs, rew, term, trunc, _ = env.step(acts)
        for a in acts:
            assert rew[a] == 1.0
            if term[a] or trunc[a]:
                done_agents.add(a)
                assert a not in obs
        if term["__all__"]:
            break
    assert term["__all__"] and done_agents == set(env.possible_agents)


def test_rock_paper_scissors_zero_sum():
    env = RockPaperScissors(episode_len=5, seed=0)
    obs, _ = env.reset()
    assert obs["player_0"][3] == 1.0  # first-move flag
    # paper (1) beats rock (0)
    obs, rew, term, trunc, _ = env.step({"player_0": 1, "player_1": 0})
    assert rew["player_0"] == 1.0 and rew["player_1"] == -1.0
    # next obs one-hot encodes the opponent's previous move
    assert obs["player_0"][0] == 1.0  # opponent played rock
    assert obs["player_1"][1] == 1.0  # opponent played paper
    # draws are 0/0; episode terminates at episode_len
    for _ in range(4):
        obs, rew, term, trunc, _ = env.step({"player_0": 2, "player_1": 2})
        assert rew["player_0"] == 0.0 == rew["player_1"]
    assert term["__all__"]


def test_rps_scripted_opponent_ignores_player1_action():
    env = RockPaperScissors(episode_len=3, scripted_opponent="rock")
    env.reset()
    _, rew, _, _, _ = env.step({"player_0": 1, "player_1": 2})
    assert rew["player_0"] == 1.0  # paper beats the scripted rock


# ----------------------------------------------------------- module units
def test_multi_rl_module_disjoint_params_and_forward():
    env = RockPaperScissors()
    spec = MultiRLModuleSpec({
        mid: RLModuleSpec(observation_space=env.get_observation_space(a),
                          action_space=env.get_action_space(a),
                          hidden=(16,))
        for mid, a in (("p0", "player_0"), ("p1", "player_1"))})
    module = spec.build()
    params = module.init(jax.random.key(0))
    assert set(params) == {"p0", "p1"}
    # Disjoint init: per-module param trees differ (independent RNG keys).
    w0 = next(l for l in jax.tree.leaves(params["p0"]) if l.ndim == 2)
    w1 = next(l for l in jax.tree.leaves(params["p1"]) if l.ndim == 2)
    assert not np.allclose(np.asarray(w0), np.asarray(w1))

    obs = {"p0": np.zeros((7, 4), np.float32),
           "p1": np.ones((5, 4), np.float32)}
    out = module.forward_exploration(params, obs, jax.random.key(1))
    assert out["p0"]["actions"].shape == (7,)
    assert out["p1"]["logp"].shape == (5,)


# ---------------------------------------------------- turn-based mechanics
def test_masked_gae_bootstraps_through_gaps():
    """An agent's advantage must bootstrap from its own next acted step,
    never from the stale vf recorded while it wasn't acting."""
    from ray_tpu.rllib.algorithms.ppo import _gae

    mask = np.array([[1.0], [0.0], [1.0]], np.float32)
    rew = np.array([[0.0], [0.0], [1.0]], np.float32)
    vf = np.array([[0.5], [99.0], [0.7]], np.float32)  # gap row is garbage
    dones = np.zeros((3, 1), bool)
    adv = _gae(rew, vf, dones, np.array([0.2], np.float32),
               gamma=1.0, lam=1.0, mask=mask)
    np.testing.assert_allclose(adv[:, 0], [0.7, 0.0, 0.5], atol=1e-6)


class _AlternatingTurnEnv:
    """Two agents alternate turns; each step's reward is delivered to the
    agent that is NOT acting (as in board games: your move pays off on
    the opponent's turn).  Exercises the runner's retro-credit path."""

    possible_agents = ["a", "b"]

    def __init__(self, episode_len=6, seed=None):
        from ray_tpu.rllib.env.spaces import Box, Discrete

        self._len = episode_len
        space = Box(np.zeros(2, np.float32), np.ones(2, np.float32))
        self.observation_spaces = {x: space for x in self.possible_agents}
        self.action_spaces = {x: Discrete(2) for x in self.possible_agents}
        self._t = 0

    def get_observation_space(self, a):
        return self.observation_spaces[a]

    def get_action_space(self, a):
        return self.action_spaces[a]

    def _obs(self):
        actor = self.possible_agents[self._t % 2]
        return {actor: np.array([self._t % 2, self._t / 10.0], np.float32)}

    def reset(self, *, seed=None):
        self._t = 0
        return self._obs(), {}

    def step(self, action_dict):
        waiting = self.possible_agents[1 - self._t % 2]
        self._t += 1
        done = self._t >= self._len
        term = {x: done for x in self.possible_agents}
        term["__all__"] = done
        trunc = {x: False for x in self.possible_agents}
        trunc["__all__"] = False
        obs = self._obs() if not done else {}
        return obs, {waiting: 1.0}, term, trunc, {}


def test_turn_based_rewards_retro_credit():
    from ray_tpu.rllib.env.multi_agent_env_runner import MultiAgentEnvRunner

    env = _AlternatingTurnEnv()
    spec = MultiRLModuleSpec({"default_policy": RLModuleSpec(
        observation_space=env.get_observation_space("a"),
        action_space=env.get_action_space("a"), hidden=(8,))})
    runner = MultiAgentEnvRunner._cls(
        _AlternatingTurnEnv, spec, None, num_envs=1, seed=0)
    out = runner.sample(12)  # two full 6-step episodes
    frag = out["modules"]["default_policy"]

    # Lane order is env's possible_agents order: a=lane0, b=lane1.
    mask = frag["mask"]
    np.testing.assert_allclose(mask[:, 0], [1, 0] * 6)  # a acts even steps
    np.testing.assert_allclose(mask[:, 1], [0, 1] * 6)

    # a's rewards arrive on b's turns and retro-credit a's acted rows: all
    # 3 per episode land in training.  b's t=0 reward arrives before b has
    # any acted row (dropped from training, kept in metrics): 2 per episode.
    assert frag["rewards"][:, 0].sum() == pytest.approx(6.0)
    assert frag["rewards"][:, 1].sum() == pytest.approx(4.0)
    # a terminates while inactive (episode ends on b's turn): retro-done
    # on a's last acted row, so GAE never bootstraps across episodes.
    assert bool(frag["dones"][4, 0]) and bool(frag["dones"][10, 0])
    assert bool(frag["terminateds"][4, 0])
    # Episode-return metrics see the full delivered rewards for both.
    assert out["agent_episode_returns"]["a"] == [3.0, 3.0]
    assert out["agent_episode_returns"]["b"] == [3.0, 3.0]
    assert out["episode_returns"] == [6.0, 6.0]


def test_env_without_all_key_still_resets():
    """Envs that mark every agent done per-key but never set '__all__'
    must still end the episode (otherwise every lane goes inactive and
    the env never resets — a silent livelock)."""
    from ray_tpu.rllib.env.multi_agent_env_runner import MultiAgentEnvRunner

    class NoAllEnv(_AlternatingTurnEnv):
        def step(self, action_dict):
            obs, rew, term, trunc, info = super().step(action_dict)
            term.pop("__all__", None)
            trunc.pop("__all__", None)
            return obs, rew, term, trunc, info

    spec = MultiRLModuleSpec({"default_policy": RLModuleSpec(
        observation_space=NoAllEnv().get_observation_space("a"),
        action_space=NoAllEnv().get_action_space("a"), hidden=(8,))})
    runner = MultiAgentEnvRunner._cls(NoAllEnv, spec, None,
                                      num_envs=1, seed=0)
    out = runner.sample(12)
    assert out["episode_returns"] == [6.0, 6.0]  # two episodes completed
    frag = out["modules"]["default_policy"]
    assert frag["mask"].sum() == 12  # lanes kept acting after episode 1


# ------------------------------------------------------------------- e2e
def test_mappo_shared_policy_cartpole_improves(ma_cluster):
    """All agents share one policy; mean per-agent return improves well
    beyond the random-policy plateau (~20-30 per agent)."""
    config = (
        PPOConfig()
        .environment(lambda: MultiAgentCartPole(num_agents=2))
        .multi_agent(policies=["default_policy"])
        .training(lr=1e-3, train_batch_size=1024, num_epochs=6,
                  minibatch_size=256, entropy_coeff=0.01)
        .env_runners(num_env_runners=2, num_envs_per_runner=4)
        .learners(num_learners=1, jax_platform="cpu")
    )
    algo = config.build()
    try:
        best = 0.0
        for _ in range(16):
            result = algo.train()
            # Env-level return sums both agents; /2 -> per-agent.
            best = max(best, result.get("episode_return_mean", 0.0) / 2)
            if best >= 80:
                break
        # Random policy plateaus ~22 per agent; 80 is unambiguous learning
        # (RPS e2e below covers convergence-to-optimal).
        assert best >= 80, f"shared-policy MAPPO best {best} < 80"
    finally:
        algo.stop()


def _rps_mapping(agent_id):
    return {"player_0": "p0", "player_1": "p1"}[agent_id]


def test_mappo_two_policies_exploit_scripted_opponent(ma_cluster):
    """Separate policies per player; player_0 learns the best response
    (paper) to a frozen rock-playing opponent -> near-max exploitation."""
    config = (
        PPOConfig()
        .environment(lambda: RockPaperScissors(episode_len=10,
                                               scripted_opponent="rock"))
        .multi_agent(policies=["p0", "p1"],
                     policy_mapping_fn=_rps_mapping)
        .training(lr=3e-3, train_batch_size=640, num_epochs=6,
                  minibatch_size=128, entropy_coeff=0.0)
        .env_runners(num_env_runners=1, num_envs_per_runner=8)
        .learners(num_learners=1, jax_platform="cpu")
    )
    algo = config.build()
    try:
        best = -10.0
        for _ in range(15):
            result = algo.train()
            p0 = result.get("episode_return_mean/player_0")
            if p0 is not None:
                best = max(best, p0)
            if best >= 8.0:
                break
        # 10 steps/episode, +1 per win: >= 8 means near-always paper.
        assert best >= 8.0, f"player_0 best return {best} < 8"
        # Per-module metrics flow through with module-id prefixes.
        assert any(k.startswith("p0/") for k in result)
    finally:
        algo.stop()
