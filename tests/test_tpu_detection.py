"""Hardware-free coverage of every TPU autodetect tier and scheduling
helper (reference: `python/ray/tests/accelerators/test_tpu.py:14-264`).

Each detection tier is exercised by mocking its probe surface: env fakes,
/dev/accel* and vfio globs, an already-initialized jax, and the GCE
metadata server — no TPU (or network) required."""

import sys
import types

import pytest

import ray_tpu.accelerators.tpu as tpu_mod
from ray_tpu.accelerators.tpu import (
    TPU_CHIPS_PER_HOST_BOUNDS_ENV, TPU_HOST_BOUNDS_ENV,
    TPU_VISIBLE_CHIPS_ENV, TPUAcceleratorManager, pod_head_resource,
)


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for var in ("RAY_TPU_FAKE_CHIPS", "RAY_TPU_FAKE_POD_TYPE",
                "RAY_TPU_FAKE_POD_NAME", "RAY_TPU_FAKE_WORKER_ID",
                TPU_VISIBLE_CHIPS_ENV, TPU_CHIPS_PER_HOST_BOUNDS_ENV,
                TPU_HOST_BOUNDS_ENV):
        monkeypatch.delenv(var, raising=False)
    yield


def _mock_globs(monkeypatch, accel=(), vfio=()):
    def fake_glob(pattern):
        if pattern.startswith("/dev/accel"):
            return list(accel)
        if pattern.startswith("/dev/vfio"):
            return list(vfio)
        return []
    monkeypatch.setattr(tpu_mod.glob, "glob", fake_glob)


def _mock_metadata(monkeypatch, table):
    monkeypatch.setattr(tpu_mod, "_gce_metadata",
                        lambda path: table.get(path))


# ------------------------------------------------------------- detection

def test_chip_count_env_fake(monkeypatch):
    monkeypatch.setenv("RAY_TPU_FAKE_CHIPS", "4")
    assert TPUAcceleratorManager.get_current_node_num_accelerators() == 4


def test_chip_count_dev_accel(monkeypatch):
    _mock_globs(monkeypatch,
                accel=[f"/dev/accel{i}" for i in range(4)])
    assert TPUAcceleratorManager.get_current_node_num_accelerators() == 4


def test_chip_count_vfio(monkeypatch):
    # Newer TPU-VM images expose vfio devices instead of /dev/accel*.
    _mock_globs(monkeypatch, accel=[],
                vfio=["/dev/vfio/0", "/dev/vfio/1"])
    assert TPUAcceleratorManager.get_current_node_num_accelerators() == 2


def test_chip_count_jax_enumeration(monkeypatch):
    _mock_globs(monkeypatch)

    class Dev:
        platform = "tpu"
        device_kind = "TPU v5 lite"

    fake_jax = types.SimpleNamespace(devices=lambda: [Dev(), Dev()])
    monkeypatch.setitem(sys.modules, "jax", fake_jax)
    assert TPUAcceleratorManager.get_current_node_num_accelerators() == 2


def test_chip_count_nothing_found(monkeypatch):
    _mock_globs(monkeypatch)
    monkeypatch.setitem(sys.modules, "jax", None)
    assert TPUAcceleratorManager.get_current_node_num_accelerators() == 0


def test_accelerator_type_from_metadata(monkeypatch):
    _mock_metadata(monkeypatch, {
        "instance/attributes/accelerator-type": "v5litepod-16"})
    assert (TPUAcceleratorManager.get_current_node_accelerator_type()
            == "v5litepod-16")


def test_accelerator_type_absent(monkeypatch):
    _mock_metadata(monkeypatch, {})
    assert TPUAcceleratorManager.get_current_node_accelerator_type() is None


def test_pod_name_and_worker_count(monkeypatch):
    monkeypatch.setenv("RAY_TPU_FAKE_CHIPS", "4")
    _mock_metadata(monkeypatch, {
        "instance/attributes/accelerator-type": "v5e-16",
        "instance/attributes/instance-id": "my-slice-abc",
    })
    assert TPUAcceleratorManager.get_current_pod_name() == "my-slice-abc"
    # 16 chips / 4 per host = 4 workers.
    assert TPUAcceleratorManager.get_current_pod_worker_count() == 4


# ------------------------------------------------------- request quantity

@pytest.mark.parametrize("qty", [1, 2, 4, 0, 0.5])
def test_valid_chip_requests(qty):
    ok, err = TPUAcceleratorManager.validate_resource_request_quantity(qty)
    assert ok, err


@pytest.mark.parametrize("qty", [3, 5, 8, 1.5])
def test_invalid_chip_requests(qty):
    ok, err = TPUAcceleratorManager.validate_resource_request_quantity(qty)
    assert not ok
    assert err


# ------------------------------------------------------- visibility envs

def test_visible_chips_single(monkeypatch):
    import os

    TPUAcceleratorManager.set_current_process_visible_accelerator_ids(["0"])
    assert os.environ[TPU_VISIBLE_CHIPS_ENV] == "0"
    # A 1-chip process must shrink host bounds (reference tpu.py:158).
    assert os.environ[TPU_CHIPS_PER_HOST_BOUNDS_ENV] == "1,1,1"
    assert os.environ[TPU_HOST_BOUNDS_ENV] == "1,1,1"


def test_visible_chips_pair(monkeypatch):
    import os

    TPUAcceleratorManager.set_current_process_visible_accelerator_ids(
        ["1", "2"])
    assert os.environ[TPU_VISIBLE_CHIPS_ENV] == "1,2"
    assert os.environ[TPU_CHIPS_PER_HOST_BOUNDS_ENV] == "1,2,1"


def test_visible_chips_full_host(monkeypatch):
    import os

    os.environ[TPU_CHIPS_PER_HOST_BOUNDS_ENV] = "1,1,1"
    TPUAcceleratorManager.set_current_process_visible_accelerator_ids(
        ["0", "1", "2", "3"])
    assert os.environ[TPU_VISIBLE_CHIPS_ENV] == "0,1,2,3"
    # Full host: bounds unset so the runtime sees the whole topology.
    assert TPU_CHIPS_PER_HOST_BOUNDS_ENV not in os.environ


# ---------------------------------------------------------- pod resources

def test_pod_gang_resources_worker0(monkeypatch):
    monkeypatch.setenv("RAY_TPU_FAKE_CHIPS", "4")
    _mock_metadata(monkeypatch, {
        "instance/attributes/accelerator-type": "v5e-16",
        "instance/attributes/instance-id": "slice-x",
        "instance/attributes/agent-worker-number": "0",
    })
    out = TPUAcceleratorManager.get_current_node_extra_resources()
    assert out["TPU-v5e"] == 4
    assert out["slice-x"] == 1
    assert out["TPU-v5e-16-head"] == 1  # exactly worker 0 carries the head


def test_pod_gang_resources_other_worker(monkeypatch):
    monkeypatch.setenv("RAY_TPU_FAKE_CHIPS", "4")
    _mock_metadata(monkeypatch, {
        "instance/attributes/accelerator-type": "v5e-16",
        "instance/attributes/instance-id": "slice-x",
        "instance/attributes/agent-worker-number": "2",
    })
    out = TPUAcceleratorManager.get_current_node_extra_resources()
    assert out["TPU-v5e"] == 4
    assert "TPU-v5e-16-head" not in out


def test_pod_gang_resources_no_metadata(monkeypatch):
    _mock_metadata(monkeypatch, {})
    assert TPUAcceleratorManager.get_current_node_extra_resources() == {}


def test_pod_head_resource_helper():
    assert pod_head_resource("v5e-16") == {"TPU-v5e-16-head": 1}


def test_accel_version_parsing():
    assert tpu_mod._accel_version("v5litepod-16") == "v5litepod"
    assert tpu_mod._accel_version("v4-8") == "v4"
    assert tpu_mod._accel_version("weird") is None
    assert tpu_mod._pod_chip_count("v5e-16") == 16
    assert tpu_mod._pod_chip_count("nope") is None
