"""Data library: all-to-all sort/groupby, file sinks, jax batch feed
(reference: `data/_internal/planner/exchange/`, `data/grouped_data.py`,
`data/iterator.py:258` iter_torch_batches)."""

import json
import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata


def test_distributed_sort(ray_start_regular):
    rng = np.random.RandomState(0)
    vals = rng.permutation(2000)
    ds = rdata.from_numpy(vals, column="x").repartition(8).sort("x")
    out = [r["x"] for r in ds.take_all()]
    assert out == sorted(vals.tolist())

    desc = rdata.from_numpy(vals, column="x").repartition(4).sort(
        "x", descending=True)
    out = [r["x"] for r in desc.take_all()]
    assert out == sorted(vals.tolist(), reverse=True)


def test_groupby_aggregations(ray_start_regular):
    rows = [{"k": i % 3, "v": float(i)} for i in range(30)]
    ds = rdata.from_items(rows).repartition(5)

    sums = {r["k"]: r["v_sum"] for r in ds.groupby("k").sum("v").take_all()}
    expect = {}
    for r in rows:
        expect[r["k"]] = expect.get(r["k"], 0.0) + r["v"]
    assert sums == expect

    counts = {r["k"]: r["k_count"]
              for r in ds.groupby("k").count().take_all()}
    assert counts == {0: 10, 1: 10, 2: 10}

    means = {r["k"]: r["v_mean"]
             for r in ds.groupby("k").mean("v").take_all()}
    assert means[0] == pytest.approx(expect[0] / 10)


def test_write_and_read_roundtrip(ray_start_regular, tmp_path):
    rows = [{"a": i, "b": f"s{i}"} for i in range(100)]
    ds = rdata.from_items(rows).repartition(4)

    pq_dir = str(tmp_path / "pq")
    files = ds.write_parquet(pq_dir)
    assert files and all(f.endswith(".parquet") for f in files)
    back = rdata.read_parquet(pq_dir)
    assert sorted(r["a"] for r in back.take_all()) == list(range(100))

    js_dir = str(tmp_path / "js")
    ds.write_json(js_dir)
    back = rdata.read_json(js_dir)
    assert sorted(r["a"] for r in back.take_all()) == list(range(100))

    csv_dir = str(tmp_path / "csv")
    ds.write_csv(csv_dir)
    back = rdata.read_csv(csv_dir)
    assert sorted(r["a"] for r in back.take_all()) == list(range(100))


def test_iter_jax_batches(ray_start_regular):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    ds = rdata.from_numpy(np.arange(64, dtype=np.float32), column="x")
    mesh = jax.make_mesh((jax.device_count(),), ("data",))
    sharding = NamedSharding(mesh, P("data"))
    seen = 0
    for batch in ds.iterator().iter_jax_batches(
            batch_size=16, sharding=sharding):
        assert isinstance(batch["x"], jax.Array)
        assert batch["x"].sharding == sharding
        seen += int(batch["x"].shape[0])
    assert seen == 64


def test_union_and_zip(ray_start_regular):
    """Multi-input plans: union concatenates streams; zip merges columns
    row-aligned with _1 suffix on collisions (reference: Dataset.union,
    Dataset.zip)."""
    left = rdata.range(4).map(lambda r: {"id": r["id"], "x": r["id"] * 10})
    right = rdata.range(4).map(lambda r: {"id": r["id"] + 100,
                                          "y": r["id"]})

    u = left.union(right)
    assert u.count() == 8
    ids = [r["id"] for r in u.take_all()]
    assert ids[:4] == [0, 1, 2, 3] and set(ids[4:]) == {100, 101, 102, 103}

    z = left.zip(right)
    rows = z.take_all()
    assert len(rows) == 4
    assert rows[1] == {"id": 1, "x": 10, "id_1": 101, "y": 1}

    # Downstream ops compose after the multi-input stage.
    assert left.union(right).filter(
        lambda r: r["id"] >= 100).count() == 4

    # Length mismatch is an error, not silent truncation.
    with pytest.raises(Exception, match="zip"):
        rdata.range(3).zip(rdata.range(5)).take_all()


def test_row_ops_honor_resource_options(ray_start_regular):
    """map/filter/flat_map honor concurrency/num_cpus by routing through
    the distributed map_batches machinery, and RAISE on unknown kwargs —
    the old **_ignored silently ran serial (VERDICT r4 weak-5)."""
    import os

    out = rdata.range(16, override_num_blocks=4).map(
        lambda r: {"v": r["id"] * 2, "pid": os.getpid()},
        concurrency=2).take_all()
    assert sorted(r["v"] for r in out) == [i * 2 for i in range(16)]
    # Ran in worker processes, not the driver.
    assert all(r["pid"] != os.getpid() for r in out)

    assert rdata.range(16).filter(
        lambda r: r["id"] < 4, num_cpus=0.5).count() == 4
    assert rdata.range(4).flat_map(
        lambda r: [r, r], concurrency=2).count() == 8

    with pytest.raises(TypeError, match="bogus"):
        rdata.range(4).map(lambda r: r, bogus=1)
    with pytest.raises(TypeError, match="unsupported"):
        rdata.range(4).filter(lambda r: True, scheduling_strategy="SPREAD")
