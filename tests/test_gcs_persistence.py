"""GCS snapshot persistence: durable tables survive a GCS restart
(reference: redis-backed GCS FT, `store_client/redis_store_client.h:33`)."""

import asyncio
import os

import pytest

from ray_tpu._private.gcs_server import GcsServer
from ray_tpu._private.rpc import get_io_loop


def _call(gcs, name, **kw):
    return get_io_loop().submit(
        getattr(gcs, f"_h_{name}")(**kw)).result(timeout=10)


def test_kv_and_jobs_survive_restart(tmp_path):
    snap = str(tmp_path / "snap.pkl")

    gcs1 = GcsServer("127.0.0.1", 0)
    gcs1.enable_snapshots(snap)
    _call(gcs1, "kv_put", namespace="ns", key="k", value=b"v1")
    _call(gcs1, "register_job", job_id=b"\x01" * 4,
          driver_addr=("127.0.0.1", 1), metadata={"who": "test"})
    gcs1._write_snapshot(gcs1._build_snapshot())

    # A fresh GCS (simulated restart) loads the durable tables.
    gcs2 = GcsServer("127.0.0.1", 0)
    gcs2.enable_snapshots(snap)
    assert _call(gcs2, "kv_get", namespace="ns", key="k") == b"v1"
    jobs = _call(gcs2, "list_jobs")
    assert any(j["job_id"] == b"\x01" * 4 and j["metadata"]["who"] == "test"
               for j in jobs)


def test_snapshot_is_atomic(tmp_path):
    snap = str(tmp_path / "snap.pkl")
    gcs = GcsServer("127.0.0.1", 0)
    gcs.enable_snapshots(snap)
    for i in range(5):
        _call(gcs, "kv_put", namespace="ns", key=f"k{i}", value=b"x" * 100)
        gcs._write_snapshot(gcs._build_snapshot())
    assert os.path.exists(snap)
    assert not os.path.exists(snap + ".tmp")
