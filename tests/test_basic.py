"""Core API behavior: put/get/wait, tasks, dependencies, errors, options.
(Reference model: `python/ray/tests/test_basic.py`.)"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import exceptions as exc


@ray_tpu.remote
def echo(x):
    return x


@ray_tpu.remote
def add(a, b):
    return a + b


class TestPutGet:
    def test_small_roundtrip(self, ray_start_regular):
        ref = ray_tpu.put({"k": 1})
        assert ray_tpu.get(ref) == {"k": 1}

    def test_large_object_via_plasma(self, ray_start_regular):
        arr = np.random.rand(512, 1024)  # 4 MiB > inline threshold
        ref = ray_tpu.put(arr)
        out = ray_tpu.get(ref)
        np.testing.assert_array_equal(out, arr)

    def test_get_list(self, ray_start_regular):
        refs = [ray_tpu.put(i) for i in range(5)]
        assert ray_tpu.get(refs) == list(range(5))

    def test_put_of_ref_rejected(self, ray_start_regular):
        with pytest.raises(TypeError):
            ray_tpu.put(ray_tpu.put(1))


class TestTasks:
    def test_basic_task(self, ray_start_regular):
        assert ray_tpu.get(add.remote(1, 2)) == 3

    def test_kwargs(self, ray_start_regular):
        assert ray_tpu.get(add.remote(1, b=41)) == 42

    def test_ref_arg_resolution(self, ray_start_regular):
        a = ray_tpu.put(10)
        assert ray_tpu.get(add.remote(a, 5)) == 15

    def test_chained_tasks(self, ray_start_regular):
        r = add.remote(1, 1)
        for _ in range(5):
            r = add.remote(r, 1)
        assert ray_tpu.get(r, timeout=60) == 7

    def test_large_arg_and_return(self, ray_start_regular):
        arr = np.ones((256, 1024))

        @ray_tpu.remote
        def double(x):
            return x * 2

        out = ray_tpu.get(double.remote(arr), timeout=60)
        np.testing.assert_array_equal(out, arr * 2)

    def test_num_returns(self, ray_start_regular):
        @ray_tpu.remote(num_returns=3)
        def three():
            return 1, 2, 3

        r1, r2, r3 = three.remote()
        assert ray_tpu.get([r1, r2, r3]) == [1, 2, 3]

    def test_num_returns_zero(self, ray_start_regular):
        @ray_tpu.remote(num_returns=0)
        def fire_and_forget():
            return None

        assert fire_and_forget.remote() is None

    def test_options_override(self, ray_start_regular):
        assert ray_tpu.get(echo.options(name="custom").remote(7)) == 7

    def test_parallel_tasks(self, ray_start_regular):
        @ray_tpu.remote
        def slow(i):
            time.sleep(0.2)
            return i

        # Warm the worker pool first; then parallelism must be real.
        ray_tpu.get([slow.remote(i) for i in range(8)], timeout=60)
        start = time.monotonic()
        out = ray_tpu.get([slow.remote(i) for i in range(8)], timeout=60)
        elapsed = time.monotonic() - start
        assert out == list(range(8))
        # 8 tasks x 0.2s on a warm 8-CPU pool must overlap substantially.
        assert elapsed < 1.2

    def test_nested_tasks(self, ray_start_regular):
        @ray_tpu.remote
        def outer(n):
            return ray_tpu.get(add.remote(n, 1))

        assert ray_tpu.get(outer.remote(1), timeout=60) == 2

    def test_invalid_option_rejected(self, ray_start_regular):
        with pytest.raises(ValueError):
            @ray_tpu.remote(bogus_option=1)
            def f():
                pass

    def test_direct_call_rejected(self, ray_start_regular):
        with pytest.raises(TypeError):
            echo(1)


class TestErrors:
    def test_task_error_propagates(self, ray_start_regular):
        @ray_tpu.remote
        def boom():
            raise ValueError("boom!")

        with pytest.raises(ValueError, match="boom!"):
            ray_tpu.get(boom.remote(), timeout=30)

    def test_error_is_ray_task_error_too(self, ray_start_regular):
        @ray_tpu.remote
        def boom():
            raise KeyError("k")

        with pytest.raises(exc.RayTaskError):
            ray_tpu.get(boom.remote(), timeout=30)

    def test_dependent_task_poisoned(self, ray_start_regular):
        @ray_tpu.remote
        def boom():
            raise ValueError("poisoned upstream")

        bad = boom.remote()
        with pytest.raises(ValueError, match="poisoned upstream"):
            ray_tpu.get(add.remote(bad, 1), timeout=30)

    def test_get_timeout(self, ray_start_regular):
        @ray_tpu.remote
        def sleepy():
            time.sleep(60)

        ref = sleepy.remote()
        with pytest.raises(exc.GetTimeoutError):
            ray_tpu.get(ref, timeout=0.2)
        ray_tpu.cancel(ref, force=True)

    def test_retry_exceptions(self, ray_start_regular):
        @ray_tpu.remote(max_retries=3, retry_exceptions=True)
        def flaky(marker):
            # Uses a plasma object as cross-attempt state via a side file.
            import os
            import tempfile

            path = f"{tempfile.gettempdir()}/flaky-{marker}"
            if not os.path.exists(path):
                open(path, "w").close()
                raise RuntimeError("first attempt fails")
            os.unlink(path)
            return "recovered"

        import uuid

        assert ray_tpu.get(flaky.remote(uuid.uuid4().hex),
                           timeout=60) == "recovered"


class TestWait:
    def test_wait_basic(self, ray_start_regular):
        @ray_tpu.remote
        def slow(t):
            time.sleep(t)
            return t

        fast = slow.remote(0.05)
        slow_ref = slow.remote(5)
        ready, not_ready = ray_tpu.wait([fast, slow_ref], num_returns=1,
                                        timeout=10)
        assert ready == [fast]
        assert not_ready == [slow_ref]
        ray_tpu.cancel(slow_ref, force=True)

    def test_wait_timeout(self, ray_start_regular):
        @ray_tpu.remote
        def sleepy():
            time.sleep(30)

        ref = sleepy.remote()
        ready, not_ready = ray_tpu.wait([ref], num_returns=1, timeout=0.2)
        assert ready == []
        assert not_ready == [ref]
        ray_tpu.cancel(ref, force=True)

    def test_wait_duplicate_rejected(self, ray_start_regular):
        ref = ray_tpu.put(1)
        with pytest.raises(ValueError):
            ray_tpu.wait([ref, ref])


class TestRuntimeContext:
    def test_context_in_task(self, ray_start_regular):
        @ray_tpu.remote
        def ctx_info():
            ctx = ray_tpu.get_runtime_context()
            return ctx.get_job_id(), ctx.get_node_id(), ctx.get_task_id()

        job_id, node_id, task_id = ray_tpu.get(ctx_info.remote(), timeout=30)
        assert ray_tpu.get_runtime_context().get_job_id() == job_id
        assert task_id is not None

    def test_cluster_resources(self, ray_start_regular):
        res = ray_tpu.cluster_resources()
        assert res.get("CPU", 0) >= 8
        assert len(ray_tpu.nodes()) == 1
