"""Borrower-protocol tests: unit state machine (style of
`reference_count_test.cc`) and e2e free-after-borrow behavior that was
impossible before round 4 (serialized-out refs were pinned forever)."""

import time

import pytest

from ray_tpu._private.reference_count import ReferenceCounter


class TestBorrowerStateMachine:
    def _rc(self):
        freed = []
        released = []
        contained = []
        rc = ReferenceCounter(
            on_free=lambda oid, locs: freed.append(oid),
            on_borrow_release=lambda oid, addr: released.append((oid, addr)),
            on_contained_free=lambda outer, inners: contained.append(
                (outer, inners)))
        return rc, freed, released, contained

    def test_pending_share_expires(self):
        rc, freed, _, _ = self._rc()
        rc.add_owned(b"x")
        rc.add_pending_share(b"x")
        rc.expire_pending(ttl_s=3600)
        assert not freed  # young share survives the sweep
        time.sleep(0.02)
        rc.expire_pending(ttl_s=0.01)
        assert freed == [b"x"]  # unclaimed share expired -> freed

    def test_registration_consumes_one_share(self):
        rc, freed, _, _ = self._rc()
        rc.add_owned(b"x")
        rc.add_pending_share(b"x")
        rc.add_pending_share(b"x")  # two copies in flight
        assert rc.register_borrower(b"x", b"w1", ("h", 1))
        assert rc.snapshot(b"x")["pending_shares"] == 1
        assert rc.register_borrower(b"x", b"w2", ("h", 2))
        assert rc.snapshot(b"x")["pending_shares"] == 0
        rc.release_borrower(b"x", b"w1")
        assert not freed
        rc.release_borrower(b"x", b"w2")
        assert freed == [b"x"]

    def test_duplicate_registration_is_noop(self):
        """RPC retries must not double-consume pending shares."""
        rc, _, _, _ = self._rc()
        rc.add_owned(b"x")
        rc.add_pending_share(b"x")
        rc.add_pending_share(b"x")
        assert rc.register_borrower(b"x", b"w1", ("h", 1))
        assert rc.register_borrower(b"x", b"w1", ("h", 1))
        assert rc.snapshot(b"x")["pending_shares"] == 1

    def test_late_registration_after_free(self):
        rc, freed, _, _ = self._rc()
        rc.add_owned(b"x")
        rc.add_pending_share(b"x")
        time.sleep(0.02)
        rc.expire_pending(ttl_s=0.01)
        assert freed == [b"x"]
        assert rc.register_borrower(b"x", b"w1", ("h", 1)) is False

    def test_borrower_side_release_fires_once(self):
        rc, freed, released, _ = self._rc()
        rc.add_borrowed(b"x", ("owner", 5))
        rc.add_local_ref(b"x")
        rc.add_local_ref(b"x")
        rc.remove_local_ref(b"x")
        assert not released
        rc.remove_local_ref(b"x")
        assert released == [(b"x", ("owner", 5))]
        assert not freed  # borrowers never free the object themselves
        # Entry dropped: a re-borrow recreates it cleanly.
        rc.add_borrowed(b"x", ("owner", 5))
        rc.add_local_ref(b"x")
        rc.remove_local_ref(b"x")
        assert len(released) == 2

    def test_borrower_pending_share_defers_release(self):
        """A borrower that serialized the ref onward must not release
        until its own in-flight share is claimed or expires."""
        rc, _, released, _ = self._rc()
        rc.add_borrowed(b"x", ("owner", 5))
        rc.add_local_ref(b"x")
        rc.add_pending_share(b"x")  # forwarded to a third worker
        rc.remove_local_ref(b"x")
        assert not released
        time.sleep(0.02)
        rc.expire_pending(ttl_s=0.01)
        assert released == [(b"x", ("owner", 5))]

    def test_nested_refs_released_with_outer(self):
        rc, freed, _, contained_freed = self._rc()
        rc.add_owned(b"inner")
        rc.add_owned(b"outer")
        rc.add_local_ref(b"outer")
        # inner serialized into outer's value: object-keyed borrow.
        rc.add_pending_share(b"inner")
        rc.register_borrower(b"inner", b"obj:outer", None)
        rc.set_contained(b"outer", [(b"inner", None)])
        assert not freed
        rc.remove_local_ref(b"outer")
        # outer freed -> callback reports its contained refs.
        assert b"outer" in freed
        assert contained_freed == [(b"outer", [(b"inner", None)])]
        # The worker callback releases the object-keyed borrow:
        rc.release_borrower(b"inner", b"obj:outer")
        assert b"inner" in freed

    def test_task_dep_and_borrower_combine(self):
        rc, freed, _, _ = self._rc()
        rc.add_owned(b"x")
        rc.add_task_dependency(b"x")
        rc.add_pending_share(b"x")
        rc.register_borrower(b"x", b"w1", ("h", 1))
        rc.remove_task_dependency(b"x")
        assert not freed
        rc.release_borrower(b"x", b"w1")
        assert freed == [b"x"]


# --------------------------------------------------------------------- e2e

@pytest.fixture(scope="module")
def borrow_cluster():
    import ray_tpu

    info = ray_tpu.init(num_cpus=4, num_tpus=0,
                        object_store_memory=128 * 1024 * 1024,
                        _system_config={"borrow_pending_ttl_s": 3.0},
                        ignore_reinit_error=True)
    yield info
    ray_tpu.shutdown()


def _wait_for(pred, timeout=30.0, msg=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.2)
    raise AssertionError(f"condition not met within {timeout}s: {msg}")


def test_ref_freed_after_actor_borrow_drains(borrow_cluster):
    """Pass a ref into an actor, drop it everywhere, and the owner frees
    the store entry (the round-3 design pinned it forever)."""
    import numpy as np

    import ray_tpu
    from ray_tpu._private.worker import global_worker

    @ray_tpu.remote
    class Holder:
        def __init__(self):
            self.ref = None

        def hold(self, ref):
            self.ref = ref  # keeps a borrowed ref alive in actor state
            return "held"

        def peek(self):
            return float(ray_tpu.get(self.ref[0])[0, 0])

        def drop(self):
            self.ref = None
            import gc

            gc.collect()
            return "dropped"

    w = global_worker()
    ref = ray_tpu.put(np.full((512, 1024), 3.0))  # 4 MiB -> plasma
    oid = ref.binary()
    holder = Holder.remote()
    # Pass the ref wrapped in a list so it is NOT unwrapped into the raw
    # value by arg resolution — the actor holds the ObjectRef itself.
    assert ray_tpu.get(holder.hold.remote([ref]), timeout=60) == "held"

    # The actor registered as a borrower with the owner (us).
    def borrower_known():
        snap = w.reference_counter.snapshot(oid)
        return snap is not None and any(
            not k.startswith(b"obj:") for k in snap["borrowers"])
    _wait_for(borrower_known, msg="actor never registered as borrower")

    # Drop the owner's local ref: object must stay alive for the actor.
    del ref
    import gc

    gc.collect()
    time.sleep(4.0)  # > borrow_pending_ttl_s: pending pins expired too
    assert ray_tpu.get(holder.peek.remote(), timeout=60) == 3.0
    assert not w.reference_counter.is_freed(oid)

    # Actor drops its copy -> borrow released -> owner frees the entry.
    assert ray_tpu.get(holder.drop.remote(), timeout=60) == "dropped"
    _wait_for(lambda: w.reference_counter.is_freed(oid),
              msg="owner never freed after borrower drained")


def test_repeated_shares_to_same_borrower_drain(borrow_cluster):
    """N sends of the same ref to an already-registered borrower must not
    leave N-1 pending shares pinning the object until the TTL sweep
    (ADVICE r4 low): the duplicate-deserialize path sends
    consume_pending_share instead."""
    import ray_tpu
    from ray_tpu._private.worker import global_worker

    @ray_tpu.remote
    class Sink:
        def take(self, wrapped):
            return float(ray_tpu.get(wrapped[0]))

    w = global_worker()
    ref = ray_tpu.put(7.0)
    oid = ref.binary()
    sink = Sink.remote()
    for _ in range(6):
        assert ray_tpu.get(sink.take.remote([ref]), timeout=60) == 7.0

    # Every serialize-out appended a share; only the first registration
    # consumed one. The duplicates must drain via the consume RPC well
    # before the 3 s TTL sweep would get to them.
    def shares_drained():
        snap = w.reference_counter.snapshot(oid)
        return snap is not None and snap["pending_shares"] <= 1

    _wait_for(shares_drained, timeout=2.5,
              msg="unconsumed pending shares lingered")


def test_ref_nested_in_put_freed_with_outer(borrow_cluster):
    import gc

    import numpy as np

    import ray_tpu
    from ray_tpu._private.worker import global_worker

    w = global_worker()
    inner = ray_tpu.put(np.ones((256, 1024)))  # 2 MiB
    inner_oid = inner.binary()
    outer = ray_tpu.put({"payload": inner})
    del inner
    gc.collect()
    time.sleep(3.5)  # let the TTL sweep expire the serialize-out pin
    # The outer object's object-keyed borrow keeps inner alive.
    assert not w.reference_counter.is_freed(inner_oid)
    got = ray_tpu.get(outer, timeout=60)
    assert float(ray_tpu.get(got["payload"])[0, 0]) == 1.0
    del got
    del outer
    gc.collect()
    _wait_for(lambda: w.reference_counter.is_freed(inner_oid),
              msg="inner never freed after outer dropped")


def test_ref_returned_from_task_freed_after_drop(borrow_cluster):
    """A task that puts an object and returns the ref: ownership stays
    with the executing worker; the caller's borrow keeps it alive until
    the caller drops it (nested return refs)."""
    import gc

    import numpy as np

    import ray_tpu

    @ray_tpu.remote
    def make():
        return [ray_tpu.put(np.full((256, 1024), 7.0))]

    (inner,) = ray_tpu.get(make.remote(), timeout=60)
    # The inner object lives on the executing worker; we borrowed it.
    assert float(ray_tpu.get(inner, timeout=60)[0, 0]) == 7.0
    del inner
    gc.collect()
    # Nothing to assert owner-side (other process); the release RPC path
    # is covered by not leaking: a second round-trip still works.
    (inner2,) = ray_tpu.get(make.remote(), timeout=60)
    assert float(ray_tpu.get(inner2, timeout=60)[0, 0]) == 7.0
