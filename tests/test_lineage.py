"""Lineage reconstruction of lost objects (reference:
`object_recovery_manager.h:90`, `task_manager.cc:896`)."""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import exceptions as exc


def test_lineage_reconstruction_on_node_loss(ray_start_cluster, tmp_path):
    from ray_tpu._private.node import Node

    cluster = ray_start_cluster
    cluster.head_node = Node(head=True, num_cpus=2, num_tpus=0)
    node2 = cluster.add_node(num_cpus=2, resources={"side": 1})
    ray_tpu.init(address=cluster.address)
    try:
        marker = str(tmp_path)

        @ray_tpu.remote(max_retries=3)
        def make_big(marker):
            nid = ray_tpu.get_runtime_context().get_node_id()
            open(os.path.join(marker, f"run_{nid}"), "w").close()
            return np.arange(500_000, dtype=np.float64)

        ref = make_big.options(resources={"side": 0.1}).remote(marker)
        ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=60)
        assert ready and len(os.listdir(marker)) == 1

        cluster.remove_node(node2)                       # only copy dies
        node3 = cluster.add_node(num_cpus=2, resources={"side": 1})

        val = ray_tpu.get(ref, timeout=180)              # reconstructs
        assert val[-1] == 499_999.0
        runs = os.listdir(marker)
        assert len(runs) == 2
        assert any(node3.node_id.hex() in r for r in runs)
    finally:
        ray_tpu.shutdown()


def test_unreconstructable_object_raises(ray_start_cluster):
    from ray_tpu._private.node import Node

    cluster = ray_start_cluster
    cluster.head_node = Node(head=True, num_cpus=2, num_tpus=0)
    node2 = cluster.add_node(num_cpus=2, resources={"side": 1})
    ray_tpu.init(address=cluster.address)
    try:
        # max_retries=0: no lineage kept -> loss is permanent.
        @ray_tpu.remote(max_retries=0)
        def make_big():
            return np.zeros(500_000)

        ref = make_big.options(resources={"side": 0.1}).remote()
        ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=60)
        assert ready
        cluster.remove_node(node2)
        with pytest.raises(exc.ObjectLostError):
            ray_tpu.get(ref, timeout=60)
    finally:
        ray_tpu.shutdown()
