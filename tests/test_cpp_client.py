"""C++ user frontend e2e (reference: `cpp/` user API + thin-client
protocol): build cpp/build/xlang_demo with make, start a cluster +
client server, register cross-language fixtures, and run the binary —
every check it prints must PASS.
"""

import os
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CPP = os.path.join(REPO, "cpp")


def _xlang_matmul_t(m):
    """Cluster-side jax compute on a C++-shipped array: m @ m.T."""
    import jax.numpy as jnp

    out = jnp.asarray(m) @ jnp.asarray(m).T
    return np.asarray(out)


def _xlang_square(x):
    return x * x


def _xlang_boom():
    raise RuntimeError("boom from the cluster")


@pytest.fixture(scope="module")
def cpp_binary():
    subprocess.run(["make", "-s"], cwd=CPP, check=True, timeout=120)
    path = os.path.join(CPP, "build", "xlang_demo")
    assert os.path.exists(path)
    return path


def test_cpp_client_end_to_end(cpp_binary):
    import ray_tpu
    from ray_tpu import cross_language
    from ray_tpu.client.server import serve

    ray_tpu.init(num_cpus=4, num_tpus=0,
                 object_store_memory=128 * 1024 * 1024,
                 ignore_reinit_error=True)
    cross_language.register("xlang_matmul_t", _xlang_matmul_t)
    cross_language.register("xlang_square", _xlang_square)
    cross_language.register("xlang_boom", _xlang_boom)
    srv = serve(port=0, host="127.0.0.1")
    try:
        proc = subprocess.run([cpp_binary, str(srv.port)],
                              capture_output=True, text=True, timeout=180)
        print(proc.stdout)
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
        assert len(lines) >= 7
        assert all(ln.startswith("PASS") for ln in lines), proc.stdout
    finally:
        srv.stop()
        ray_tpu.shutdown()


def test_msgpack_value_codec_roundtrip():
    """The C++ msgpack_lite subset against the Python msgpack encoder:
    cross-decode both directions through the cross_language value codec."""
    import msgpack

    from ray_tpu import cross_language

    arr = np.arange(6, dtype=np.int32).reshape(2, 3)
    tree = {"a": [1, -2, 3.5, "s", b"b", None, True],
            "nd": cross_language.encode(arr)}
    packed = msgpack.packb(tree, use_bin_type=True)
    back = msgpack.unpackb(packed, raw=False)
    dec = cross_language.decode(back)
    assert dec["a"][:3] == [1, -2, 3.5]
    np.testing.assert_array_equal(dec["nd"], arr)
