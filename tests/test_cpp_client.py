"""C++ user frontend e2e (reference: `cpp/` user API + thin-client
protocol): build cpp/build/xlang_demo with make, start a cluster +
client server, register cross-language fixtures, and run the binary —
every check it prints must PASS.
"""

import os
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CPP = os.path.join(REPO, "cpp")


def _xlang_matmul_t(m):
    """Cluster-side jax compute on a C++-shipped array: m @ m.T."""
    import jax.numpy as jnp

    out = jnp.asarray(m) @ jnp.asarray(m).T
    return np.asarray(out)


def _xlang_square(x):
    return x * x


def _xlang_boom():
    raise RuntimeError("boom from the cluster")


@pytest.fixture(scope="module")
def cpp_binary():
    subprocess.run(["make", "-s"], cwd=CPP, check=True, timeout=120)
    path = os.path.join(CPP, "build", "xlang_demo")
    assert os.path.exists(path)
    return path


@pytest.fixture(scope="module")
def cpp_tasks_lib(cpp_binary):
    path = os.path.join(CPP, "build", "libtasks.so")
    assert os.path.exists(path), path
    return path


def test_cpp_client_end_to_end(cpp_binary, cpp_tasks_lib):
    import ray_tpu
    from ray_tpu import cross_language
    from ray_tpu.client.server import serve

    ray_tpu.init(num_cpus=4, num_tpus=0,
                 object_store_memory=128 * 1024 * 1024,
                 ignore_reinit_error=True)
    cross_language.register("xlang_matmul_t", _xlang_matmul_t)
    cross_language.register("xlang_square", _xlang_square)
    cross_language.register("xlang_boom", _xlang_boom)
    # C++-to-C++ circle: the C++ driver calls a C++ task-library fn and
    # drives a C++ actor class.
    cross_language.register(
        "cpp_fib", cross_language.cpp_function(cpp_tasks_lib, "fib"))
    cross_language.register(
        "CppCounter",
        cross_language.cpp_actor_class(cpp_tasks_lib, "Counter"))
    srv = serve(port=0, host="127.0.0.1")
    try:
        proc = subprocess.run([cpp_binary, str(srv.port), "with_cpp_tasks"],
                              capture_output=True, text=True, timeout=180)
        print(proc.stdout)
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
        assert len(lines) >= 14
        assert all(ln.startswith("PASS") for ln in lines), proc.stdout
        for probe in ("cpp_to_cpp_task", "cpp_to_cpp_actor",
                      "cpp_actor_ndarray", "cpp_actor_survives_error",
                      "cpp_named_actor_lookup"):
            assert any(probe in ln for ln in lines), (probe, proc.stdout)
    finally:
        srv.stop()
        ray_tpu.shutdown()


def test_cpp_function_as_cluster_task(cpp_tasks_lib):
    """C++ task-library functions run as ordinary cluster tasks
    (reference: cpp worker RAY_REMOTE; architecture note in
    task_lib.hpp)."""
    import ray_tpu
    from ray_tpu.cross_language import cpp_function

    ray_tpu.init(num_cpus=4, num_tpus=0,
                 object_store_memory=128 * 1024 * 1024,
                 ignore_reinit_error=True)
    try:
        fib = ray_tpu.remote(cpp_function(cpp_tasks_lib, "fib"))
        assert ray_tpu.get(fib.remote(20), timeout=60) == 6765

        scale = ray_tpu.remote(cpp_function(cpp_tasks_lib, "scale"))
        out = ray_tpu.get(
            scale.remote(np.array([1.0, 2.0], np.float32), 3.0),
            timeout=60)
        np.testing.assert_allclose(out, [3.0, 6.0])

        boom = ray_tpu.remote(cpp_function(cpp_tasks_lib, "fail"))
        with pytest.raises(Exception, match="exploded"):
            ray_tpu.get(boom.remote(), timeout=60)
    finally:
        ray_tpu.shutdown()


def test_cpp_actor_class_as_cluster_actor(cpp_tasks_lib):
    """C++ actor classes run as ordinary cluster actors from Python
    (reference: cpp worker RAY_REMOTE actor classes; architecture note
    in task_lib.hpp)."""
    import ray_tpu
    from ray_tpu.cross_language import cpp_actor_class

    ray_tpu.init(num_cpus=4, num_tpus=0,
                 object_store_memory=128 * 1024 * 1024,
                 ignore_reinit_error=True)
    try:
        Counter = ray_tpu.remote(cpp_actor_class(cpp_tasks_lib, "Counter"))
        c = Counter.remote(10)
        assert ray_tpu.get(c.inc.remote(), timeout=60) == 11
        assert ray_tpu.get(c.inc.remote(5), timeout=60) == 16
        out = ray_tpu.get(
            c.accumulate.remote(np.array([1.0, 2.0], np.float32)),
            timeout=60)
        assert out == 19

        # C++ exceptions surface as task errors; state survives.
        with pytest.raises(Exception, match="exploded"):
            ray_tpu.get(c.fail.remote(), timeout=60)
        assert ray_tpu.get(c.get.remote(), timeout=60) == 19

        # Two instances do not share state.
        c2 = Counter.remote()
        assert ray_tpu.get(c2.get.remote(), timeout=60) == 0
        ray_tpu.kill(c)
        ray_tpu.kill(c2)
    finally:
        ray_tpu.shutdown()


def test_cpp_function_shipped_via_working_dir(cpp_tasks_lib, tmp_path):
    """The documented multi-node mechanism: ship the .so via
    runtime_env working_dir and reference it by RELATIVE path — the
    worker resolves it in its unpacked working dir (cross_language
    docstrings; reference: runtime_env code shipping)."""
    import shutil

    import ray_tpu
    from ray_tpu.cross_language import cpp_function

    shutil.copy(cpp_tasks_lib, tmp_path / "shipped_tasks.so")
    ray_tpu.init(num_cpus=2, num_tpus=0,
                 object_store_memory=128 * 1024 * 1024,
                 ignore_reinit_error=True)
    try:
        fib = ray_tpu.remote(cpp_function("shipped_tasks.so", "fib"))
        fib = fib.options(runtime_env={"working_dir": str(tmp_path)})
        assert ray_tpu.get(fib.remote(10), timeout=120) == 55
    finally:
        ray_tpu.shutdown()


def test_msgpack_value_codec_roundtrip():
    """The C++ msgpack_lite subset against the Python msgpack encoder:
    cross-decode both directions through the cross_language value codec."""
    import msgpack

    from ray_tpu import cross_language

    arr = np.arange(6, dtype=np.int32).reshape(2, 3)
    tree = {"a": [1, -2, 3.5, "s", b"b", None, True],
            "nd": cross_language.encode(arr)}
    packed = msgpack.packb(tree, use_bin_type=True)
    back = msgpack.unpackb(packed, raw=False)
    dec = cross_language.decode(back)
    assert dec["a"][:3] == [1, -2, 3.5]
    np.testing.assert_array_equal(dec["nd"], arr)
