"""Wire trace contexts survive the replica request envelope's
ObjectRef materialization path (the PR-15 regression surface).

`Replica.handle_request` hides the logical call args inside a
(method_name, args, kwargs) envelope, so the replica materializes
ObjectRef elements itself with `ray_tpu.get` — an extra in-process
resolution step that runs AFTER the executing worker has restored the
caller's wire trace context. These tests pin the contract that the
restored context is still active when the user callable runs: the
inner `ray_tpu.get` must neither clobber nor re-parent it.

Kept separate from tests/test_tracing.py (which is an exact 13-test
executable spec).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.util.tracing import current_trace, trace_root


@pytest.fixture(autouse=True)
def _serve_cleanup(ray_start_regular):
    yield
    serve.shutdown()


@serve.deployment
class TraceProbe:
    """Reports the trace context active when the callable body runs,
    alongside the (materialized) argument it received."""

    def __call__(self, payload, extra=None):
        ctx = current_trace()
        return {
            "payload_type": type(payload).__name__,
            "payload": payload,
            "extra": extra,
            "trace_id": ctx.trace_id if ctx else None,
            "span_id": ctx.span_id if ctx else None,
        }


def test_wire_context_survives_ref_arg_materialization():
    handle = serve.run(TraceProbe.bind(), name="probe-args")
    ref = ray_tpu.put([1, 2, 3])
    with trace_root("envelope.test") as tc:
        active = current_trace()
        out = handle.remote(ref).result(timeout=60)
    # The ref materialized in the replica process (list, not ObjectRef)...
    assert out["payload_type"] == "list"
    assert out["payload"] == [1, 2, 3]
    # ...and the callable still saw the caller's ACTIVE context: same
    # trace, parented at the span the caller had live at submit time.
    assert out["trace_id"] == tc.trace_id
    assert out["span_id"] == active.span_id


def test_wire_context_survives_ref_kwarg_materialization():
    handle = serve.run(TraceProbe.bind(), name="probe-kwargs")
    arr = np.arange(8, dtype=np.int32)
    with trace_root("envelope.kwargs") as tc:
        out = handle.remote(0, extra=ray_tpu.put(arr)).result(timeout=60)
    assert np.array_equal(out["extra"], arr)
    assert out["trace_id"] == tc.trace_id


def test_untraced_envelope_call_stays_untraced():
    # No ambient context at submit -> the replica must not invent one,
    # even though it runs ray_tpu.get internally to materialize the ref.
    handle = serve.run(TraceProbe.bind(), name="probe-untraced")
    assert current_trace() is None
    out = handle.remote(ray_tpu.put("x")).result(timeout=60)
    assert out["payload"] == "x"
    assert out["trace_id"] is None


def test_contexts_stay_separated_across_envelope_calls():
    # Two sequential traced calls through the same replica: the second
    # request's restored context must be its own, not a leak of the
    # first (the thread-pool worker thread is reused).
    handle = serve.run(TraceProbe.bind(), name="probe-sep")
    seen = []
    for i in range(2):
        with trace_root(f"envelope.sep{i}") as tc:
            out = handle.remote(ray_tpu.put(i)).result(timeout=60)
        assert out["trace_id"] == tc.trace_id
        seen.append(out["trace_id"])
    assert seen[0] != seen[1]
