import os
import subprocess
import sys
import time


def test_spawn_speed(capsys):
    msgs = []
    for label, env in [
        ("inherit", dict(os.environ)),
        ("clean", {"PATH": os.environ["PATH"]}),
    ]:
        t = time.monotonic()
        subprocess.run([sys.executable, "-c", "pass"], env=env, check=True)
        msgs.append(f"{label}={time.monotonic()-t:.2f}s")
    with capsys.disabled():
        print("\nspawn: " + " ".join(msgs), flush=True)
