"""KubeRay-equivalent provider against a mocked Kubernetes API server
(reference: kuberay node-provider tests run operator-free the same way).
Covers: declarative replica scaling, operator-materialized pods,
multi-host TPU gangs as replica-indexed pod groups, workersToDelete
termination, CR write conflicts, and the v2 reconciler driving the whole
lifecycle end to end."""

import copy

import pytest

import ray_tpu
from ray_tpu.autoscaler.kuberay_provider import KubeRayError, KubeRayProvider

GCS_ADDR = ("10.0.0.1", 6379)

PROVIDER_CFG = {"type": "kuberay", "namespace": "ns1",
                "cluster_name": "rc-test"}


def make_cr(groups):
    return {
        "apiVersion": "ray.io/v1", "kind": "RayCluster",
        "metadata": {"name": "rc-test", "namespace": "ns1",
                     "resourceVersion": "1"},
        "spec": {"workerGroupSpecs": [
            {"groupName": name, "replicas": 0, "numOfHosts": hosts,
             "maxReplicas": 10}
            for name, hosts in groups]},
    }


class FakeKubeApi:
    """API server + a minimal kuberay-operator emulator: every CR write
    reconciles pods to replicas x numOfHosts per group, honoring
    scaleStrategy.workersToDelete (matching pods are deleted first and
    the list is cleared, exactly like the operator)."""

    def __init__(self, cr, conflict_every: int = 0):
        self.cr = cr
        self.pods = {}           # name -> pod dict
        self.counter = 0
        self.writes = 0
        self.conflict_every = conflict_every
        self._reconcile()

    # -------------------------------------------------------- transport
    def __call__(self, method, path, body=None, **kw):
        if "/rayclusters/" in path:
            if method == "GET":
                return copy.deepcopy(self.cr)
            if method == "PUT":
                self.writes += 1
                if (self.conflict_every
                        and self.writes % self.conflict_every == 0):
                    raise RuntimeError("HTTP 409 Conflict")
                if (body["metadata"]["resourceVersion"]
                        != self.cr["metadata"]["resourceVersion"]):
                    raise RuntimeError("HTTP 409 Conflict")
                self.cr = copy.deepcopy(body)
                self.cr["metadata"]["resourceVersion"] = str(
                    int(self.cr["metadata"]["resourceVersion"]) + 1)
                self._reconcile()
                return copy.deepcopy(self.cr)
        if path.startswith("/api/v1/namespaces/ns1/pods"):
            sel = dict(kv.split("=") for kv in
                       path.split("labelSelector=")[1].split(","))
            items = [copy.deepcopy(p) for p in self.pods.values()
                     if all(p["metadata"]["labels"].get(k) == v
                            for k, v in sel.items())]
            return {"items": items}
        raise AssertionError((method, path))

    # ------------------------------------------------- operator emulator
    def _reconcile(self):
        for spec in self.cr["spec"]["workerGroupSpecs"]:
            group = spec["groupName"]
            hosts = int(spec.get("numOfHosts", 1))
            want = int(spec.get("replicas", 0))
            doomed = spec.get("scaleStrategy", {}).get(
                "workersToDelete", [])
            for name in doomed:
                self.pods.pop(name, None)
            if doomed:
                spec["scaleStrategy"]["workersToDelete"] = []
            have = {}
            for p in self.pods.values():
                if p["metadata"]["labels"]["ray.io/group"] == group:
                    have.setdefault(
                        p["metadata"]["labels"].get("ray.io/replica-index")
                        or p["metadata"]["name"], []).append(p)
            # Scale up: create missing replicas (each = `hosts` pods).
            while len(have) < want:
                self.counter += 1
                ridx = f"{group}-rep-{self.counter}"
                members = []
                for h in range(hosts):
                    name = f"{ridx}-{h}"
                    labels = {"ray.io/cluster": "rc-test",
                              "ray.io/node-type": "worker",
                              "ray.io/group": group}
                    if hosts > 1:
                        labels["ray.io/replica-index"] = ridx
                    pod = {"metadata": {"name": name, "labels": labels},
                           "status": {"phase": "Running",
                                      "podIP": f"10.2.{self.counter}.{h}"}}
                    self.pods[name] = pod
                    members.append(pod)
                have[ridx] = members
            # Scale down beyond workersToDelete: drop newest replicas.
            while len(have) > want:
                ridx = sorted(have)[-1]
                for p in have.pop(ridx):
                    self.pods.pop(p["metadata"]["name"], None)


def _provider(api, gcs_addr=GCS_ADDR):
    return KubeRayProvider(PROVIDER_CFG, gcs_addr, transport=api,
                           ready_timeout_s=5, poll_interval_s=0.01)


def test_create_node_scales_replicas_and_waits_for_pod():
    api = FakeKubeApi(make_cr([("cpu-group", 1)]))
    p = _provider(api)
    pid = p.create_node("cpu-group", {})
    assert api.cr["spec"]["workerGroupSpecs"][0]["replicas"] == 1
    assert p.non_terminated_nodes() == [pid]
    assert p.node_type_of(pid) == "cpu-group"


def test_gang_create_makes_numOfHosts_pods():
    api = FakeKubeApi(make_cr([("tpu-v5e-16", 4)]))
    p = _provider(api)
    gid = p.create_node_group("tpu-v5e-16", {}, 4)
    assert p.node_groups() == [gid]
    assert len(p.group_nodes(gid)) == 4
    assert p.group_type_of(gid) == "tpu-v5e-16"
    # One replica of the multi-host group, not four.
    assert api.cr["spec"]["workerGroupSpecs"][0]["replicas"] == 1


def test_gang_size_mismatch_rejected():
    api = FakeKubeApi(make_cr([("tpu-v5e-16", 4)]))
    p = _provider(api)
    with pytest.raises(KubeRayError, match="numOfHosts"):
        p.create_node_group("tpu-v5e-16", {}, 8)


def test_unknown_group_rejected():
    api = FakeKubeApi(make_cr([("cpu-group", 1)]))
    p = _provider(api)
    with pytest.raises(KubeRayError, match="no workerGroupSpec"):
        p.create_node("nope", {})


def test_terminate_uses_workersToDelete():
    api = FakeKubeApi(make_cr([("cpu-group", 1)]))
    p = _provider(api)
    pid = p.create_node("cpu-group", {})
    p.terminate_node(pid)
    assert p.non_terminated_nodes() == []
    assert api.cr["spec"]["workerGroupSpecs"][0]["replicas"] == 0


def test_terminating_one_gang_member_kills_the_slice():
    api = FakeKubeApi(make_cr([("tpu-v5e-16", 4)]))
    p = _provider(api)
    gid = p.create_node_group("tpu-v5e-16", {}, 4)
    victim = p.group_nodes(gid)[2]
    p.terminate_node(victim)
    assert p.non_terminated_nodes() == []


def test_cr_write_conflicts_are_retried():
    api = FakeKubeApi(make_cr([("cpu-group", 1)]), conflict_every=2)
    p = _provider(api)
    pid = p.create_node("cpu-group", {})
    assert p.non_terminated_nodes() == [pid]


def test_yaml_wiring():
    from ray_tpu.autoscaler.config import make_provider, validate_cluster_config

    cfg = validate_cluster_config({
        "cluster_name": "demo",
        "provider": PROVIDER_CFG,
        "available_node_types": {"cpu-group": {"node_config": {}}},
    })
    provider = make_provider(cfg, GCS_ADDR, "/tmp/nowhere")
    assert isinstance(provider, KubeRayProvider)


def test_reconciler_drives_kuberay_lifecycle(ray_start_isolated):
    """v2 reconciler end to end over the mocked k8s API: pending demand
    -> replica bump -> operator pods -> ALLOCATED instances; vanished
    pod -> instance TERMINATED; explicit terminate -> workersToDelete."""
    import ray_tpu
    from ray_tpu._private.worker import global_worker
    from ray_tpu.autoscaler.v2.instance_manager import InstanceStatus
    from ray_tpu.autoscaler.v2.reconciler import Reconciler

    w = global_worker()
    api = FakeKubeApi(make_cr([("bigk8s-group", 1)]))
    provider = _provider(api, w.gcs_addr)
    types = {"bigk8s-group": {"resources": {"CPU": 2, "bigk8s": 1},
                              "min_workers": 0, "max_workers": 3}}
    rec = Reconciler(w.gcs_addr, provider, types, max_workers=3,
                     idle_timeout_s=2.0)

    @ray_tpu.remote(resources={"bigk8s": 0.5})
    def needs():
        return 1

    ref = needs.remote()  # pending demand the cluster can't satisfy
    try:
        import time

        launched = 0
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and launched == 0:
            launched = rec.reconcile()["launched"]
            time.sleep(0.2)
        assert launched == 1
        assert api.cr["spec"]["workerGroupSpecs"][0]["replicas"] == 1
        allocated = rec.im.with_status(InstanceStatus.ALLOCATED)
        assert len(allocated) == 1
        cid = allocated[0].cloud_instance_id
        assert cid in provider.non_terminated_nodes()

        # Pod vanishes out from under the autoscaler (preemption):
        # instance is retired on the next pass.
        api.pods.pop(cid)
        provider._pods_cache.clear()  # advance past the listing TTL
        rec.reconcile()
        inst = rec.im.instances[allocated[0].instance_id]
        assert inst.status == InstanceStatus.TERMINATED
    finally:
        ray_tpu.cancel(ref, force=True)
