"""Fleet-wide XLA program cost & roofline attribution
(observability/xla.py + chipspec.py, the TrackedJit capture/sample
hooks, the GCS program ring, and the dashboard surface).

Unit tier: the chip-spec lookup table (kind normalization, CPU tagging,
unknown-kind degradation), mesh.device_inventory over fake device
objects, cost/memory capture on the CPU backend against hand-computed
matmul FLOPs, the MFU/MBU/roofline derivation of a sampled wall, the
``xla_wall_sample_every=0`` guarantee (zero ``block_until_ready`` on the
hot path), the AOT surface (compiled()/eval_shape never inflate trace
counters; clear_cache re-arms both caches), and the regression
sentinel's once-per-episode state machine over fake compiled artifacts.

Cluster tier: synthetic program rows through the real
``report_xla_programs`` RPC drive the bounded ring, the latest-view
rollup (``util.state.xla_summary()``), malformed-row drop, a real tiny
LLM engine whose bucket programs all land with nonzero
FLOPs/HBM/MFU/MBU + verdict (CPU-tagged: plumbing, not performance),
the shape-drift recompile emitting exactly ONE typed PERF_REGRESSION
naming program and drifted dimension, ``GET /api/programs``, and the
``rtpu_xla_program_*`` metric exposition.
"""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest


# --------------------------------------------------------------- unit tier

class TestChipSpec:
    def test_kind_normalization(self):
        from ray_tpu.observability import chipspec

        assert chipspec.lookup("TPU v5 lite").spec == "v5e"
        assert chipspec.lookup("TPU v5e").spec == "v5e"
        assert chipspec.lookup("TPU v5p").spec == "v5p"
        # Bare "v5" is what some v5p hosts report; the v5e patterns
        # must win before it.
        assert chipspec.lookup("TPU v5").spec == "v5p"
        assert chipspec.lookup("TPU v4").spec == "v4"
        v5e = chipspec.lookup("TPU v5 lite")
        assert v5e.peak_flops == pytest.approx(197e12)
        assert v5e.peak_hbm_bytes_per_s == pytest.approx(819e9)
        assert v5e.measurement == "tpu" and v5e.known

    def test_cpu_is_tagged_plumbing_only(self):
        from ray_tpu.observability import chipspec

        cpu = chipspec.lookup("cpu")
        assert cpu.measurement == "cpu" and cpu.known
        # Tier-1 runs on the CPU backend: the local spec must resolve
        # to the nominal cpu row, never to unknown.
        assert chipspec.local_spec().measurement == "cpu"

    def test_unknown_degrades_without_fabricating_peaks(self):
        from ray_tpu.observability import chipspec

        spec = chipspec.lookup("Gaudi 3")
        assert spec.spec == "unknown" and not spec.known
        assert spec.peak_flops is None
        assert spec.peak_hbm_bytes_per_s is None
        assert chipspec.lookup(None) is chipspec.UNKNOWN
        assert chipspec.lookup("") is chipspec.UNKNOWN


class _FakeDev:
    def __init__(self, platform, kind):
        self.platform = platform
        self.device_kind = kind


class TestDeviceInventory:
    def test_v5e_fleet(self):
        from ray_tpu.parallel.mesh import device_inventory

        inv = device_inventory([_FakeDev("tpu", "TPU v5 lite")] * 4)
        assert inv["devices"] == 4
        assert inv["platforms"] == ["tpu"]
        assert inv["device_kinds"] == ["TPU v5 lite"]
        assert inv["spec"] == "v5e" and inv["measurement"] == "tpu"
        assert inv["peak_flops"] == pytest.approx(197e12)
        assert inv["peak_hbm_bytes_per_s"] == pytest.approx(819e9)

    def test_cpu_backend(self):
        from ray_tpu.parallel.mesh import device_inventory

        inv = device_inventory()     # tier-1: the real CPU backend
        assert inv["devices"] >= 1
        assert inv["platforms"] == ["cpu"]
        assert inv["spec"] == "cpu" and inv["measurement"] == "cpu"

    def test_unknown_and_heterogeneous_degrade(self):
        from ray_tpu.parallel.mesh import device_inventory

        inv = device_inventory([_FakeDev("xpu", "Gaudi 3")] * 2)
        assert inv["spec"] == "unknown"
        assert inv["peak_flops"] is None
        # Mixed generations share no roofline: degrade, never average.
        mixed = device_inventory([_FakeDev("tpu", "TPU v4"),
                                  _FakeDev("tpu", "TPU v5e")])
        assert mixed["spec"] == "unknown"
        assert mixed["device_kinds"] == ["TPU v4", "TPU v5e"]
        assert mixed["peak_flops"] is None


# ------------------------------------------------------------ capture tier

@pytest.fixture
def registry():
    from ray_tpu.observability import xla

    xla.flush_captures()             # strand no straggler in the reg
    reg = xla.program_registry()
    reg.clear()
    yield reg
    xla.flush_captures()
    reg.clear()


def _flush():
    """Captures compile on a background worker: tests synchronize on
    the queue before asserting registry/GCS state."""
    from ray_tpu.observability import xla

    assert xla.flush_captures()


def _matmul_tracked(name, **kw):
    from ray_tpu.observability.jit import tracked_jit

    return tracked_jit(lambda a, b: a @ b, name=name, trace_budget=0,
                       **kw)


class TestCostCapture:
    def test_compile_captures_cost_and_memory(self, registry):
        import jax.numpy as jnp

        from ray_tpu.observability.jit import _arg_signature

        n = 64
        f = _matmul_tracked("xla_capture_matmul")
        x = jnp.ones((n, n), jnp.float32)
        np.asarray(f(x, x))
        _flush()
        sig = _arg_signature((x, x), {})
        row = registry.row("xla_capture_matmul", sig)
        assert row is not None
        # XLA's own count for an n x n x n matmul: 2n^3.
        assert row["flops"] == pytest.approx(2 * n ** 3)
        # Two f32 inputs + one output is the floor on traffic/footprint.
        assert row["bytes_accessed"] >= 3 * n * n * 4
        assert row["peak_hbm_bytes"] >= 3 * n * n * 4
        assert row["compile_seconds"] > 0
        assert row["spec"] == "cpu" and row["measurement"] == "cpu"
        # No wall sampled yet: no utilization claim.
        assert row["verdict"] == "unsampled"
        assert row["wall_s"] is None and row["mfu"] is None
        # The baseline is this function's first program.
        base = registry.baseline("xla_capture_matmul")
        assert base["flops"] == pytest.approx(2 * n ** 3)
        assert base["signature"] == sig

    def test_sampled_wall_derives_mfu_mbu_and_roofline(self, registry,
                                                       monkeypatch):
        import jax.numpy as jnp

        monkeypatch.setenv("RAY_TPU_xla_wall_sample_every", "1")
        n = 64
        f = _matmul_tracked("xla_sample_matmul")
        x = jnp.ones((n, n), jnp.float32)
        np.asarray(f(x, x))          # compiles (not sampled)
        _flush()                     # the capture row must exist first
        np.asarray(f(x, x))          # steady state: fenced + sampled
        rows = [r for r in registry.rows()
                if r["fn"] == "xla_sample_matmul"]
        assert len(rows) == 1
        row = rows[0]
        assert row["samples"] >= 1 and row["wall_s"] > 0
        # The derivation is exact arithmetic over the cpu spec
        # (100e9 FLOP/s, 100e9 B/s) — ratios prove plumbing on CPU.
        assert row["achieved_flops_per_s"] == pytest.approx(
            row["flops"] / row["wall_s"])
        assert row["mfu"] == pytest.approx(
            row["achieved_flops_per_s"] / 100e9)
        assert row["mbu"] == pytest.approx(
            row["achieved_bytes_per_s"] / 100e9)
        ideal = max(row["flops"] / 100e9, row["bytes_accessed"] / 100e9)
        assert row["lost_roofline_s_per_call"] == pytest.approx(
            max(row["wall_s"] - ideal, 0.0))
        assert row["lost_roofline_s_total"] == pytest.approx(
            row["lost_roofline_s_per_call"] * row["calls"])
        assert row["verdict"] in ("compute-bound", "memory-bound")
        # The sampled wall seeded the baseline for the wall sentinel.
        assert registry.baseline("xla_sample_matmul")["wall_s"] \
            == pytest.approx(row["wall_s"])

    def test_sampling_off_keeps_fence_off_hot_path(self, registry,
                                                   monkeypatch):
        import jax
        import jax.numpy as jnp

        monkeypatch.setenv("RAY_TPU_xla_wall_sample_every", "0")
        fences = {"n": 0}
        real = jax.block_until_ready

        def counting(tree):
            fences["n"] += 1
            return real(tree)

        monkeypatch.setattr(jax, "block_until_ready", counting)
        f = _matmul_tracked("xla_unfenced_matmul")
        x = jnp.ones((16, 16), jnp.float32)
        for _ in range(10):
            f(x, x)
        _flush()
        assert fences["n"] == 0
        rows = [r for r in registry.rows()
                if r["fn"] == "xla_unfenced_matmul"]
        # The compile capture still happened; walls never did.
        assert len(rows) == 1
        assert rows[0]["samples"] == 0 and rows[0]["wall_s"] is None

    def test_aot_surface_never_inflates_trace_counters(self, registry):
        import jax.numpy as jnp

        f = _matmul_tracked("xla_aot_matmul")
        x = jnp.ones((8, 8), jnp.float32)
        # eval_shape goes through the RAW function: no probe, no trace.
        shape = f.eval_shape(x, x)
        assert shape.shape == (8, 8)
        assert f.traces == 0
        np.asarray(f(x, x))
        _flush()
        assert f.traces == 1
        # The attribution hook already built (and cached) the AOT
        # artifact for this signature: compiled() hands back the SAME
        # object without re-lowering or inflating the counters.
        c1 = f.compiled(x, x)
        assert c1 is not None and f.traces == 1
        assert f.compiled(x, x) is c1
        assert [r["fn"] for r in registry.rows()] == ["xla_aot_matmul"]
        # clear_cache drops both caches: next call re-traces (and
        # re-counts), and compiled() re-lowers a fresh artifact.
        f.clear_cache()
        np.asarray(f(x, x))
        _flush()
        assert f.traces == 2
        assert f.compiled(x, x) is not c1
        # compiled() on a never-called wrapper lowers under the
        # suppression flag: speculative AOT queries stay invisible to
        # the user-facing trace counters.
        g = _matmul_tracked("xla_aot_precompiled")
        assert g.compiled(x, x) is not None
        assert g.traces == 0


# ----------------------------------------------------------- sentinel tier

class _FakeMem:
    def __init__(self, arg=1024, out=512, temp=256, alias=0):
        self.argument_size_in_bytes = arg
        self.output_size_in_bytes = out
        self.temp_size_in_bytes = temp
        self.alias_size_in_bytes = alias


class _FakeCompiled:
    """Just enough of a jax Compiled to drive record_compile."""

    def __init__(self, flops, bytes_accessed=1e5, mem=None):
        self._cost = {"flops": float(flops),
                      "bytes accessed": float(bytes_accessed),
                      "transcendentals": 0.0}
        self._mem = mem or _FakeMem()

    def cost_analysis(self):
        return [self._cost]          # the CPU-backend list shape

    def memory_analysis(self):
        return self._mem


@pytest.fixture
def sentinel(registry, monkeypatch):
    from ray_tpu.observability import xla

    fired = []
    monkeypatch.setattr(
        xla, "_emit_regression",
        lambda fn, row, dim, ratio, base, cur: fired.append(
            {"fn": fn, "dim": dim, "ratio": ratio, "base": base,
             "cur": cur}))
    return registry, fired


class TestRegressionSentinel:
    def test_recompile_drift_fires_once_per_episode(self, sentinel):
        reg, fired = sentinel
        reg.record_compile("drift_fn", "sigA", _FakeCompiled(1000), 0.1)
        assert fired == []           # the baseline itself never fires
        reg.record_compile("drift_fn", "sigB", _FakeCompiled(8000), 0.1)
        assert len(fired) == 1
        assert fired[0]["dim"] == "flops"
        assert fired[0]["ratio"] == pytest.approx(8.0)
        assert fired[0]["base"] == pytest.approx(1000.0)
        # Still drifted: the episode already fired, stay silent.
        reg.record_compile("drift_fn", "sigC", _FakeCompiled(16000), 0.1)
        assert len(fired) == 1
        # Back within the ratio: the dimension re-arms...
        reg.record_compile("drift_fn", "sigD", _FakeCompiled(1100), 0.1)
        assert len(fired) == 1
        # ...and a fresh drift is a NEW episode.
        reg.record_compile("drift_fn", "sigE", _FakeCompiled(9000), 0.1)
        assert len(fired) == 2

    def test_dimensions_fire_independently(self, sentinel):
        reg, fired = sentinel
        reg.record_compile("mem_fn", "sigA", _FakeCompiled(1000), 0.1)
        # Same flops, 10x the footprint: only peak_hbm_bytes drifts.
        reg.record_compile(
            "mem_fn", "sigB",
            _FakeCompiled(1000, mem=_FakeMem(arg=10240, out=5120,
                                             temp=2560)), 0.1)
        assert [f["dim"] for f in fired] == ["peak_hbm_bytes"]
        assert fired[0]["ratio"] == pytest.approx(10.0)

    def test_wall_drift_fires_once(self, sentinel):
        reg, fired = sentinel
        reg.record_compile("wall_fn", "sig", _FakeCompiled(1000), 0.1)
        reg.record_sample("wall_fn", "sig", 0.01)   # seeds the baseline
        assert fired == []
        for _ in range(6):                          # EWMA climbs past 1.5x
            reg.record_sample("wall_fn", "sig", 0.1)
        assert len(fired) == 1
        assert fired[0]["fn"] == "wall_fn"
        assert fired[0]["dim"] == "wall_s"

    def test_ratio_zero_disables(self, sentinel, monkeypatch):
        monkeypatch.setenv("RAY_TPU_xla_regression_ratio", "0")
        reg, fired = sentinel
        reg.record_compile("off_fn", "sigA", _FakeCompiled(1000), 0.1)
        reg.record_compile("off_fn", "sigB", _FakeCompiled(99000), 0.1)
        assert fired == []

    def test_sample_of_unknown_program_is_noop(self, registry):
        assert registry.record_sample("ghost", "sig", 0.5) is None


# ------------------------------------------------------------ cluster tier

@pytest.fixture(scope="module")
def xla_cluster():
    import ray_tpu

    # Small ring so the bound is observable; sample every call so the
    # engine's steady-state programs all derive utilization. Config
    # resolution is env-first, so the GCS and every TrackedJit built
    # after this point pick these up live.
    os.environ["RAY_TPU_xla_programs_buffer_size"] = "32"
    os.environ["RAY_TPU_xla_wall_sample_every"] = "1"
    info = ray_tpu.init(num_cpus=4, num_tpus=0,
                        object_store_memory=128 * 1024 * 1024,
                        include_dashboard=True,
                        ignore_reinit_error=True)
    yield info
    ray_tpu.shutdown()
    os.environ.pop("RAY_TPU_xla_programs_buffer_size", None)
    os.environ.pop("RAY_TPU_xla_wall_sample_every", None)


def _get(url: str):
    with urllib.request.urlopen(url, timeout=15) as resp:
        return resp.status, resp.read()


def _xrow(**kw):
    row = {"fn": "synth_fn", "signature": "(float32[8,8])",
           "flops": 1e6, "bytes_accessed": 3e5, "transcendentals": 0.0,
           "arg_bytes": 2e5, "out_bytes": 1e5, "temp_bytes": 0.0,
           "alias_bytes": 0.0, "peak_hbm_bytes": 3e5,
           "compile_seconds": 0.2, "calls": 10, "samples": 2,
           "wall_s": 0.01, "achieved_flops_per_s": 1e8,
           "achieved_bytes_per_s": 3e7, "mfu": 0.001, "mbu": 0.0003,
           "exposed_comm_fraction": 0.0, "verdict": "compute-bound",
           "lost_roofline_s_per_call": 0.005,
           "lost_roofline_s_total": 0.05, "spec": "cpu",
           "measurement": "cpu", "pid": 4242}
    row.update(kw)
    return row


def test_ring_list_and_summary(xla_cluster):
    from ray_tpu._private.worker import global_worker
    from ray_tpu.util import state

    gcs = global_worker().gcs
    for i in range(3):
        gcs.call("report_xla_programs", row=_xrow(
            fn="synth_a", signature=f"(float32[{8 << i},8])",
            flops=1e6 * (i + 1)))
    gcs.call("report_xla_programs", row=_xrow(
        fn="synth_b", verdict="memory-bound",
        node_id=b"\x5b\x7e\xc0\x14"))
    gcs.call("report_xla_programs", row=_xrow(
        fn="synth_hog", flops=1e9, calls=100,
        lost_roofline_s_total=9.0))

    rows = state.list_xla_programs(fn="synth_a")
    assert len(rows) == 3 and all(r["fn"] == "synth_a" for r in rows)
    assert rows[-1]["signature"] == "(float32[32,8])"   # newest-last
    assert len(state.list_xla_programs(fn="synth_a", limit=2)) == 2
    only = state.list_xla_programs(verdict="memory-bound")
    assert only and all(r["verdict"] == "memory-bound" for r in only)
    # Raw-bytes node ids land as hex — these rows feed JSON surfaces.
    assert only[-1]["node_id"] == "5b7ec014"

    summary = state.xla_summary()
    assert summary["programs"] >= 5
    assert summary["rows_recorded"] >= 5
    # Cumulative FLOPs rank: the hog's 1e9 x 100 calls dwarfs the rest.
    assert summary["top_by_flops"][0]["fn"] == "synth_hog"
    assert summary["top_by_headroom"][0]["fn"] == "synth_hog"
    assert summary["verdicts"]["compute-bound"] >= 4
    assert summary["verdicts"]["memory-bound"] >= 1
    # All-cpu measurements mark the ratios as plumbing proof.
    assert summary["measurements"]["cpu"] >= 5
    assert summary["total_flops"] >= 1e9 * 100
    assert summary["total_peak_hbm_bytes"] >= 5 * 3e5
    assert summary["lost_roofline_s_total"] >= 9.0


def test_ring_is_bounded(xla_cluster):
    from ray_tpu._private.worker import global_worker
    from ray_tpu.util import state

    gcs = global_worker().gcs
    before = state.xla_summary()["rows_recorded"]
    for i in range(100):
        gcs.call("report_xla_programs",
                 row=_xrow(fn="bulk", signature=f"(s{i})"))
    summary = state.xla_summary()
    assert summary["rows_recorded"] == before + 100
    assert summary["rows_in_buffer"] <= 32
    # The latest-view is bounded by the same knob as the ring.
    assert summary["programs"] <= 32


def test_malformed_row_dropped_not_fatal(xla_cluster):
    from ray_tpu._private.worker import global_worker
    from ray_tpu.util import state

    gcs = global_worker().gcs
    before = state.xla_summary()["rows_recorded"]
    assert gcs.call("report_xla_programs", row={"fn": "evil"})
    assert gcs.call("report_xla_programs",
                    row=_xrow(fn="evil2", flops="bogus"))
    assert state.xla_summary()["rows_recorded"] == before
    # The GCS is still alive and ingesting.
    gcs.call("report_xla_programs", row=_xrow(fn="after"))
    assert state.xla_summary()["rows_recorded"] == before + 1


def test_engine_bucket_programs_attributed(xla_cluster):
    """The acceptance run: a real (tiny) engine's programs all land in
    the fleet summary with nonzero FLOPs/HBM and — once sampled —
    MFU/MBU + a roofline verdict, every row CPU-tagged in tier-1."""
    import jax

    from ray_tpu.models.llama import LlamaConfig, init_params
    from ray_tpu.serve.llm.engine import EngineConfig, LLMEngine, Request
    from ray_tpu.util import state

    config = LlamaConfig.tiny()
    params = init_params(config, jax.random.key(0))
    engine = LLMEngine(params, config, EngineConfig(
        num_slots=2, max_seq_len=32, prefill_buckets=(8,)))
    rng = np.random.RandomState(3)

    def _wave():
        for _ in range(3):
            engine.submit(Request(
                prompt=rng.randint(0, config.vocab_size, 5).tolist(),
                max_tokens=4))
        engine.drain()

    _wave()          # compiles the bucket programs (captures queued)
    _flush()         # every program row is in the registry now
    _wave()          # steady state: every call samples a wall

    for fn in ("llm_engine_tick", "llm_engine_insert"):
        rows = state.list_xla_programs(fn=fn)
        assert rows, f"no program rows for {fn}"
        for r in rows:
            assert r["flops"] > 0
            assert r["peak_hbm_bytes"] > 0
            assert r["measurement"] == "cpu"
        # sample_every=1: every steady-state call after the compile
        # sampled a wall, so the newest row carries utilization.
        last = rows[-1]
        assert last["samples"] > 0 and last["wall_s"] > 0
        assert last["mfu"] > 0 and last["mbu"] > 0
        assert last["verdict"] in ("compute-bound", "memory-bound",
                                   "comm-bound")


def test_shape_drift_emits_one_perf_regression(xla_cluster):
    """A recompile whose FLOPs drift past xla_regression_ratio emits
    exactly ONE typed PERF_REGRESSION naming the program and the
    drifted dimension — and only that dimension (the k=2 -> k=8 loop
    quadruples FLOPs while peak HBM grows just 1.33x, inside the
    ratio)."""
    import jax.numpy as jnp

    from ray_tpu.observability.jit import tracked_jit
    from ray_tpu.util import state

    def body(a, k):
        for _ in range(k):
            a = a @ a
        return a

    f = tracked_jit(body, name="drift_probe", static_argnums=(1,),
                    trace_budget=0)
    x = jnp.ones((64, 64), jnp.float32)
    np.asarray(f(x, 2))              # baseline program
    np.asarray(f(x, 8))              # recompile: 4x the FLOPs
    _flush()                         # captures land in compile order

    def _events():
        return [e for e in
                state.list_cluster_events(event_type="PERF_REGRESSION")
                if e.get("fn") == "drift_probe"]

    events = _events()
    assert len(events) == 1
    ev = events[0]
    assert ev["severity"] == "WARNING"
    assert ev["dimension"] == "flops"
    assert ev["ratio"] == pytest.approx(4.0)
    assert "drift_probe" in ev["message"]
    assert "flops" in ev["message"]
    assert ev["measurement"] == "cpu"
    # Still drifted on the next recompile: same episode, no new event.
    np.asarray(f(x, 16))
    _flush()
    assert len(_events()) == 1


def test_api_programs_contract(xla_cluster):
    from ray_tpu import _local_node
    from ray_tpu._private.worker import global_worker

    global_worker().gcs.call("report_xla_programs",
                             row=_xrow(fn="dash_fn"))
    base = _local_node.dashboard_url

    status, body = _get(base + "/api/programs")
    assert status == 200
    payload = json.loads(body)
    assert set(payload) == {"summary", "programs", "metrics"}
    assert payload["summary"]["programs"] >= 1
    assert payload["programs"]

    status, body = _get(base + "/api/programs?fn=dash_fn&limit=1")
    payload = json.loads(body)
    assert len(payload["programs"]) == 1
    assert payload["programs"][0]["fn"] == "dash_fn"

    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(base + "/api/programs?limit=bogus")
    assert ei.value.code == 400


def test_xla_metrics_exported(xla_cluster):
    import jax.numpy as jnp

    from ray_tpu._private.worker import global_worker
    from ray_tpu.observability.jit import tracked_jit
    from ray_tpu.util import metrics

    f = tracked_jit(lambda a, b: a @ b, name="xla_metric_probe",
                    trace_budget=0)
    x = jnp.ones((16, 16), jnp.float32)
    np.asarray(f(x, x))              # compile: flops/bytes gauges
    _flush()
    np.asarray(f(x, x))              # sample: mfu/mbu + wall histogram
    assert metrics.flush()
    text = global_worker().gcs.call("metrics_text")
    assert "rtpu_xla_program_flops" in text
    assert 'fn="xla_metric_probe"' in text
    assert "rtpu_xla_program_bytes_hbm" in text
    assert "rtpu_xla_program_mfu" in text
    assert "rtpu_xla_program_mbu" in text
    assert "rtpu_xla_program_wall_seconds_bucket" in text
