"""pyarrow-fs checkpoint storage (reference: train/_internal/storage.py)."""

import os

import numpy as np
import pytest


def _mock_fs():
    import pyarrow.fs as pafs

    return pafs._MockFileSystem()


class TestStorageContext:
    def test_upload_download_roundtrip(self, tmp_path):
        from ray_tpu.train.storage import StorageContext, download_dir

        src = tmp_path / "src"
        (src / "sub").mkdir(parents=True)
        (src / "a.txt").write_text("alpha")
        (src / "sub" / "b.bin").write_bytes(b"\x00\x01\x02")

        fs = _mock_fs()
        storage = StorageContext("exp", "trial1", filesystem=fs)
        storage.makedirs()
        storage.upload_dir(str(src), "ckpt_0")
        assert storage.exists("ckpt_0")
        assert storage.exists("ckpt_0/a.txt")

        dest = tmp_path / "dest"
        download_dir(fs, storage.join("ckpt_0"), str(dest))
        assert (dest / "a.txt").read_text() == "alpha"
        assert (dest / "sub" / "b.bin").read_bytes() == b"\x00\x01\x02"

        storage.delete("ckpt_0")
        assert not storage.exists("ckpt_0")

    def test_local_uri(self, tmp_path):
        from ray_tpu.train.storage import StorageContext

        src = tmp_path / "data"
        src.mkdir()
        (src / "x").write_text("1")
        storage = StorageContext(f"file://{tmp_path}/store", "run")
        storage.makedirs()
        storage.upload_dir(str(src), "c")
        assert (tmp_path / "store" / "run" / "c" / "x").read_text() == "1"


class TestCheckpointUri:
    def test_pytree_roundtrip_through_mock_fs(self, tmp_path):
        import jax.numpy as jnp

        from ray_tpu.train.checkpoint import Checkpoint

        fs = _mock_fs()
        tree = {"w": jnp.arange(6.0).reshape(2, 3), "step": jnp.asarray(7)}
        ckpt = Checkpoint.from_pytree(tree)
        remote = ckpt.to_uri("bucket/ckpts/c1", filesystem=fs)
        assert remote.uri == "bucket/ckpts/c1"

        # Fresh object: downloads lazily on first .path access.
        back = Checkpoint.from_uri("bucket/ckpts/c1", filesystem=fs)
        restored = back.to_pytree()
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.arange(6.0).reshape(2, 3))
        assert int(restored["step"]) == 7


def test_trainer_syncs_checkpoints_to_storage(tmp_path):
    """JaxTrainer with URI storage: every reported checkpoint syncs to
    the pyarrow filesystem; Result.checkpoint restores from the URI.
    (file:// here — mock fs is not picklable across trial actors;
    real object-store filesystems are.)"""
    import ray_tpu
    from ray_tpu import train
    from ray_tpu.train import Checkpoint, JaxTrainer
    from ray_tpu.train.config import (
        CheckpointConfig, RunConfig, ScalingConfig,
    )
    from ray_tpu.train.jax_backend import JaxConfig

    def loop(config):
        for step in range(3):
            ckpt = Checkpoint.from_dict({"step": step})
            train.report({"loss": 1.0 / (step + 1)}, checkpoint=ckpt)

    ray_tpu.init(num_cpus=4, num_tpus=0,
                 object_store_memory=128 * 1024 * 1024,
                 ignore_reinit_error=True)
    try:
        trainer = JaxTrainer(
            loop,
            jax_config=JaxConfig(platform="cpu"),
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(
                name="storage_e2e",
                storage_path=f"file://{tmp_path}/bucket",
                checkpoint_config=CheckpointConfig(num_to_keep=2)))
        result = trainer.fit()
        assert result.checkpoint is not None
        assert result.checkpoint.uri is not None
        # Restore through the URI only (fresh download path).
        restored = Checkpoint.from_uri(result.checkpoint.uri)
        assert restored.to_dict()["step"] == 2
        # num_to_keep=2 held remotely too: exactly 2 checkpoint dirs.
        bucket = tmp_path / "bucket"
        trial_dirs = list(bucket.rglob("checkpoint_*"))
        assert len({d.name for d in trial_dirs}) == 2, trial_dirs
    finally:
        ray_tpu.shutdown()


def test_put_pressure_spill_restore_roundtrip(tmp_path):
    """12x8MB puts into a 32MB arena force spills; every object must
    still be readable (restore spills newer primaries to make room).
    Runs in a subprocess driver so the tiny store doesn't affect other
    tests. Regression guard for a flaky 'arena exhausted and nothing
    spillable' seen on this exact pattern."""
    import os
    import subprocess
    import sys

    script = tmp_path / "spill_driver.py"
    script.write_text(
        "import numpy as np\n"
        "import ray_tpu\n"
        "ray_tpu.init(num_cpus=2, object_store_memory=32*1024*1024)\n"
        "refs = [ray_tpu.put(np.full((1024, 1024), float(i)))\n"
        "        for i in range(12)]\n"
        "for i, r in enumerate(refs):\n"
        "    v = ray_tpu.get(r, timeout=120)\n"
        "    assert float(v[0, 0]) == float(i), (i, v[0, 0])\n"
        "print('SPILL-ROUNDTRIP-OK')\n"
        "ray_tpu.shutdown()\n")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, str(script)], capture_output=True, text=True,
        timeout=300, env={**os.environ, "JAX_PLATFORMS": "cpu",
                          "PYTHONPATH": repo})
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "SPILL-ROUNDTRIP-OK" in proc.stdout
