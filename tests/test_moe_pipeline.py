"""Expert + pipeline parallelism (SURVEY §2.7 net-new strategies).

Runs on the 8-virtual-device CPU mesh from conftest."""

import numpy as np
import pytest


# ----------------------------------------------------------------- MoE
class TestMoE:
    def test_moe_shapes_and_determinism(self):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.moe import MoEConfig, init_moe_params, moe_layer

        cfg = MoEConfig(dim=32, hidden_dim=64, n_experts=4, top_k=2)
        params = init_moe_params(cfg, jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (2, 16, 32),
                              dtype=jnp.float32).astype(cfg.dtype)
        out, aux = jax.jit(lambda x: moe_layer(x, params, cfg))(x)
        assert out.shape == x.shape
        assert float(aux) > 0
        out2, _ = jax.jit(lambda x: moe_layer(x, params, cfg))(x)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))

    def test_single_expert_equals_dense_ffn(self):
        """n_experts=1, top_k=1, ample capacity: MoE degenerates to the
        plain silu-gated FFN — an exact correctness oracle."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.moe import MoEConfig, init_moe_params, moe_layer

        cfg = MoEConfig(dim=16, hidden_dim=32, n_experts=1, top_k=1,
                        capacity_factor=2.0, dtype=jnp.float32)
        params = init_moe_params(cfg, jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (2, 8, 16))
        out, _ = moe_layer(x, params, cfg)

        xt = x.reshape(-1, 16)
        w_g, w_u, w_d = (params["w_gate"][0], params["w_up"][0],
                         params["w_down"][0])
        dense = (jax.nn.silu(xt @ w_g) * (xt @ w_u)) @ w_d
        # router prob for the only expert is 1.0 -> exact match
        np.testing.assert_allclose(np.asarray(out.reshape(-1, 16)),
                                   np.asarray(dense), rtol=1e-5, atol=1e-5)

    def test_expert_parallel_matches_replicated(self):
        """Sharding experts over the mesh 'expert' axis must not change
        the math — GSPMD inserts the all-to-alls."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from ray_tpu.models.moe import (
            MoEConfig, init_moe_params, moe_layer, moe_param_specs,
        )
        from ray_tpu.parallel import make_mesh

        cfg = MoEConfig(dim=32, hidden_dim=64, n_experts=4, top_k=2,
                        dtype=jnp.float32)
        params = init_moe_params(cfg, jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (4, 16, 32))

        ref_out, ref_aux = jax.jit(
            lambda x, p: moe_layer(x, p, cfg))(x, params)

        mesh = make_mesh({"data": 2, "expert": 4})
        specs = moe_param_specs()
        sharded_params = {
            k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in params.items()
        }
        x_sharded = jax.device_put(
            x, NamedSharding(mesh, P("data", None, None)))
        ep_out, ep_aux = jax.jit(
            lambda x, p: moe_layer(x, p, cfg))(x_sharded, sharded_params)
        np.testing.assert_allclose(np.asarray(ref_out), np.asarray(ep_out),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(float(ref_aux), float(ep_aux), rtol=1e-4)

    def test_moe_trains(self):
        """Gradients flow through dispatch/combine and the router."""
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.models.moe import MoEConfig, init_moe_params, moe_layer

        cfg = MoEConfig(dim=16, hidden_dim=32, n_experts=4, top_k=2,
                        dtype=jnp.float32)
        params = init_moe_params(cfg, jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (4, 8, 16))
        y = jax.random.normal(jax.random.key(2), (4, 8, 16))

        def loss_fn(p):
            out, aux = moe_layer(x, p, cfg)
            return jnp.mean((out - y) ** 2) + aux

        opt = optax.adam(1e-2)
        opt_state = opt.init(params)
        step = jax.jit(lambda p, s: _step(p, s, loss_fn, opt))
        losses = []
        for _ in range(20):
            params, opt_state, l = step(params, opt_state)
            losses.append(float(l))
        assert losses[-1] < losses[0]
        # Router weights actually moved (gradient reached them).
        assert float(jnp.abs(params["router"]).max()) > 0


def _step(p, s, loss_fn, opt):
    import jax
    import optax

    l, g = jax.value_and_grad(loss_fn)(p)
    updates, s = opt.update(g, s, p)
    return optax.apply_updates(p, updates), s, l


class TestMoELlama:
    def test_moe_llama_trains_on_expert_mesh(self):
        """Full MoE-Llama train step over a data x expert mesh: loss
        (incl. router aux) decreases; expert weights shard over EP."""
        import jax
        import numpy as np
        import optax

        from ray_tpu.models.llama import LlamaConfig, init_params, loss_fn
        from ray_tpu.parallel import (
            batch_sharding, build_train_step, create_train_state,
            llama_param_shardings, make_mesh, shard_params,
        )

        config = LlamaConfig.tiny(n_experts=4, moe_top_k=2, hidden_dim=64)
        mesh = make_mesh({"data": 2, "expert": 4})
        params = init_params(config, jax.random.key(0))
        assert params["layers"]["w_gate"].ndim == 4        # [L, E, D, F]
        sh = llama_param_shardings(config, mesh)
        optimizer = optax.adamw(1e-3)
        state = create_train_state(shard_params(params, sh), optimizer)
        step = build_train_step(lambda p, b: loss_fn(p, b, config),
                                optimizer, mesh, sh, batch_sharding(mesh))
        rng = np.random.RandomState(0)
        batch = {"tokens": jax.device_put(
            rng.randint(0, config.vocab_size, (8, 33)).astype("int32"),
            batch_sharding(mesh))}
        losses = []
        for _ in range(5):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0], losses

    def test_moe_decode_raises(self):
        import jax
        import jax.numpy as jnp
        import pytest

        from ray_tpu.models.llama import (
            LlamaConfig, decode_step, init_kv_cache, init_params,
        )

        config = LlamaConfig.tiny(n_experts=2)
        params = init_params(config, jax.random.key(0))
        cache = init_kv_cache(config, 1, max_len=8)
        with pytest.raises(NotImplementedError, match="MoE"):
            decode_step(params, cache, jnp.zeros((1,), jnp.int32),
                        jnp.zeros((1,), jnp.int32), config)


# ------------------------------------------------------------- pipeline
class TestPipeline:
    def _stages(self, key, n_stages, width):
        import jax

        ks = jax.random.split(key, n_stages)
        return {
            "w": jax.numpy.stack([
                jax.random.normal(k, (width, width)) / width ** 0.5
                for k in ks]),
            "b": jax.numpy.stack([
                jax.random.normal(k, (width,)) * 0.01 for k in ks]),
        }

    @staticmethod
    def _stage_fn(params, x):
        import jax

        return jax.nn.tanh(x @ params["w"] + params["b"])

    def test_pipeline_matches_sequential(self):
        import jax

        from ray_tpu.parallel import make_mesh
        from ray_tpu.parallel.pipeline import microbatch, pipeline_apply

        P_, W, M, MB = 4, 16, 8, 4
        params = self._stages(jax.random.key(0), P_, W)
        x = jax.random.normal(jax.random.key(1), (M * MB, W))

        seq = x
        for i in range(P_):
            seq = self._stage_fn(
                jax.tree.map(lambda p: p[i], params), seq)

        mesh = make_mesh({"pipe": 4, "data": 2})
        out = pipeline_apply(self._stage_fn, params, microbatch(x, M),
                             mesh, axis="pipe")
        np.testing.assert_allclose(
            np.asarray(out.reshape(-1, W)), np.asarray(seq),
            rtol=1e-5, atol=1e-5)

    def test_pipeline_is_differentiable(self):
        """GPipe backward falls out of autodiff through ppermute."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.parallel import make_mesh
        from ray_tpu.parallel.pipeline import microbatch, pipeline_apply

        P_, W, M, MB = 4, 8, 4, 2
        params = self._stages(jax.random.key(0), P_, W)
        x = jax.random.normal(jax.random.key(1), (M * MB, W))
        mesh = make_mesh({"pipe": 4, "data": 2})

        def loss_pipe(p):
            out = pipeline_apply(self._stage_fn, p, microbatch(x, M),
                                 mesh, axis="pipe")
            return jnp.sum(out ** 2)

        def loss_seq(p):
            h = x
            for i in range(P_):
                h = self._stage_fn(jax.tree.map(lambda q: q[i], p), h)
            return jnp.sum(h ** 2)

        g_pipe = jax.grad(loss_pipe)(params)
        g_seq = jax.grad(loss_seq)(params)
        for k in ("w", "b"):
            np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                       np.asarray(g_seq[k]),
                                       rtol=1e-4, atol=1e-5)
