"""Dask-on-ray_tpu scheduler (reference: `python/ray/util/dask/
scheduler.py` ray_dask_get). The scheduler consumes plain dask graph
dicts, so it's tested without dask installed."""

from operator import add, mul

import pytest

import ray_tpu
from ray_tpu.util.dask import ray_dask_get


@pytest.fixture(scope="module")
def dask_cluster():
    info = ray_tpu.init(num_cpus=4, num_tpus=0,
                        object_store_memory=128 * 1024 * 1024,
                        ignore_reinit_error=True)
    yield info
    ray_tpu.shutdown()


def test_simple_graph(dask_cluster):
    dsk = {
        "a": 1,
        "b": 2,
        "c": (add, "a", "b"),
        "d": (mul, "c", 10),
    }
    assert ray_dask_get(dsk, "d") == 30
    assert ray_dask_get(dsk, ["c", "d"]) == [3, 30]
    assert ray_dask_get(dsk, [["a"], ["d"]]) == [[1], [30]]


def test_tuple_keys_and_fanin(dask_cluster):
    # dask.array-style tuple keys with a fan-in over a list of keys.
    dsk = {
        ("x", 0): (add, 1, 2),
        ("x", 1): (add, 3, 4),
        "total": (sum, [("x", 0), ("x", 1)]),
    }
    assert ray_dask_get(dsk, "total") == 10


def test_inline_nested_task(dask_cluster):
    dsk = {"y": (add, (mul, 2, 3), 4)}     # nested task as an argument
    assert ray_dask_get(dsk, "y") == 10


def test_alias_and_literal_keys(dask_cluster):
    dsk = {"raw": [1, 2, 3], "alias": "raw",
           "n": (len, "alias")}
    assert ray_dask_get(dsk, "n") == 3


def test_cycle_detection(dask_cluster):
    with pytest.raises(ValueError, match="cycle"):
        ray_dask_get({"a": (add, "b", 1), "b": (add, "a", 1)}, "a")


def test_deep_chain_toposort_is_iterative():
    """A 5000-link chain must not hit Python's recursion limit."""
    from ray_tpu.util.dask import _toposort

    n = 5000
    dsk = {"k0": 7}
    dsk.update({f"k{i}": (abs, f"k{i - 1}") for i in range(1, n)})
    order = _toposort(dsk)
    assert len(order) == n
    assert order.index("k0") < order.index(f"k{n - 1}")


def test_linear_chain_executes(dask_cluster):
    def inc(x):
        return x + 1

    n = 200
    dsk = {"k0": 0}
    dsk.update({f"k{i}": (inc, f"k{i - 1}") for i in range(1, n)})
    assert ray_dask_get(dsk, f"k{n - 1}") == n - 1


def test_parallel_wide_graph(dask_cluster):
    dsk = {f"leaf-{i}": (mul, i, i) for i in range(16)}
    dsk["out"] = (sum, [f"leaf-{i}" for i in range(16)])
    assert ray_dask_get(dsk, "out") == sum(i * i for i in range(16))