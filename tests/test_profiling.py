"""Live profiling plane (reference: `ray stack` / py-spy-backed
`dashboard/modules/reporter/profile_manager.py`): the in-process
StackSampler, cluster stack dumps + flamegraphs via util.state, the
SIGUSR2 all-thread dump, and the scheduling-latency phase breakdown."""

import io
import os
import signal
import threading
import time

import pytest

import ray_tpu
from ray_tpu.observability.profiling import (
    SCHED_PHASES,
    SCHED_SEGMENT_LABELS,
    StackSampler,
    collapse,
    render_speedscope,
)


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _wait_for(pred, timeout=30.0, period=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(period)
    return False


# ---------------------------------------------------------------------------
# StackSampler units (no cluster)
# ---------------------------------------------------------------------------

def test_sampler_attributes_busy_thread():
    """A busy-spinning thread gets >=80% of its samples attributed to
    the spin function, and the aggregate renders to collapsed-stack
    text and valid speedscope JSON."""
    stop = threading.Event()

    def _busy_marker_spin():
        while not stop.is_set():
            pass

    t = threading.Thread(target=_busy_marker_spin, name="busy-spin",
                         daemon=True)
    t.start()
    try:
        s = StackSampler(hz=200, max_unique_stacks=10_000).start()
        time.sleep(0.8)
        snap = s.stop()
    finally:
        stop.set()
        t.join(timeout=5)

    busy = snap["counts"].get("busy-spin", {})
    total = sum(busy.values())
    assert total >= 10, snap
    marked = sum(n for folded, n in busy.items()
                 if "_busy_marker_spin" in folded)
    assert marked / total >= 0.8, busy
    assert snap["samples"] == sum(
        n for per in snap["counts"].values() for n in per.values())
    assert snap["duration_s"] >= 0.7

    folded_text = collapse(snap["counts"])
    assert "busy-spin;" in folded_text
    # hottest-first: every line is "thread;frame;...;frame count"
    first = folded_text.splitlines()[0]
    assert first.rsplit(" ", 1)[1].isdigit()

    sco = render_speedscope(snap["counts"], name="unit test")
    assert sco["$schema"].startswith("https://www.speedscope.app/")
    prof = {p["name"]: p for p in sco["profiles"]}["busy-spin"]
    assert prof["type"] == "sampled"
    assert sum(prof["weights"]) == total
    assert len(prof["samples"]) == len(prof["weights"])
    frames = [f["name"] for f in sco["shared"]["frames"]]
    assert any("_busy_marker_spin" in n for n in frames)
    # sample rows index into the shared frame table
    for row in prof["samples"]:
        assert all(0 <= i < len(frames) for i in row)


def test_sampler_bounded_memory_drops_not_allocates():
    """A workload generating unboundedly many distinct stacks cannot
    grow the count table past max_unique_stacks: overflow lands in
    `dropped`."""
    stop = time.monotonic() + 0.6

    def _deep(n):
        if n <= 0:
            until = time.monotonic() + 0.002
            while time.monotonic() < until:
                pass
            return
        _deep(n - 1)

    def _churn():
        d = 0
        while time.monotonic() < stop:
            _deep(d % 40 + 1)
            d += 1

    t = threading.Thread(target=_churn, name="stack-churn", daemon=True)
    t.start()
    s = StackSampler(hz=250, max_unique_stacks=4).start()
    t.join()
    snap = s.stop()
    unique = sum(len(per) for per in snap["counts"].values())
    assert unique <= 4, snap["counts"]
    assert snap["dropped"] > 0
    assert snap["samples"] == sum(
        n for per in snap["counts"].values() for n in per.values())


def test_sampler_idle_overhead_bounded():
    """Sampling an idle process at the default-ish rate costs a small
    fraction of a CPU (the sampler must be safe to leave running
    against a live worker)."""
    window = 1.0
    cpu0 = time.process_time()
    s = StackSampler(hz=100).start()
    time.sleep(window)
    snap = s.stop()
    cpu = time.process_time() - cpu0
    # Generous bound: the whole process (sampler included) stays under
    # half a core while idle. Typical observed cost is a few percent.
    assert cpu < 0.5 * window, f"sampler burned {cpu:.3f}s CPU in {window}s"
    assert snap["samples"] > 0
    # Re-start is a programming error, not silent corruption.
    with pytest.raises(RuntimeError):
        s.start()


def test_sampler_hz_clamped_and_snapshot_while_running():
    s = StackSampler(hz=10_000)
    assert s.hz == 1000.0
    assert StackSampler(hz=0.01).hz == 1.0
    s = StackSampler(hz=100).start()
    try:
        time.sleep(0.3)
        live = s.snapshot()  # partial profiles of a dying worker use this
        assert live["samples"] > 0
        assert live["duration_s"] > 0
    finally:
        s.stop()


# ---------------------------------------------------------------------------
# SIGUSR2 all-thread dump (satellite a)
# ---------------------------------------------------------------------------

def test_dump_thread_stacks_direct():
    from ray_tpu._private import rpc as rpc_mod

    buf = io.StringIO()
    rpc_mod.dump_thread_stacks(file=buf)
    text = buf.getvalue()
    assert "Python thread stacks" in text
    assert "--- thread MainThread" in text
    # the dump sees *this* frame on the main thread
    assert "test_dump_thread_stacks_direct" in text


def test_sigusr2_dumps_coroutines_and_threads(capsys):
    from ray_tpu._private import rpc as rpc_mod

    old = signal.getsignal(signal.SIGUSR2)
    try:
        rpc_mod.install_coroutine_dump_signal()
        os.kill(os.getpid(), signal.SIGUSR2)
        time.sleep(0.1)  # handler runs between bytecodes on main thread
        err = capsys.readouterr().err
        assert "Python thread stacks" in err
        assert "MainThread" in err
    finally:
        signal.signal(signal.SIGUSR2, old)


# ---------------------------------------------------------------------------
# Chrome-trace builder (satellite b)
# ---------------------------------------------------------------------------

def _ev(tid, state, ts, **extra):
    e = {"task_id": tid, "state": state, "ts": ts, "name": "f",
         "owner_pid": 7}
    e.update(extra)
    return e


def test_timeline_incomplete_tasks_render_monotone():
    from ray_tpu.observability.timeline import build_chrome_trace

    t0 = 1000.0
    events = [
        _ev(b"t1", "PENDING", t0),
        _ev(b"t1", "RUNNING", t0 + 1, worker_addr=["h", 1]),
        # a later event sets the ring horizon the open bar extends to
        _ev(b"t2", "RUNNING", t0 + 5, name="g", worker_addr=["h", 1]),
    ]
    a = build_chrome_trace(events)
    time.sleep(0.05)
    b = build_chrome_trace(events)
    assert a == b, "render must be a pure function of the event ring"

    bars = {e["args"]["task_id"]: e for e in a if e["cat"] == "task"}
    t1 = bars[b"t1".hex()]
    assert t1["args"]["state"] == "RUNNING"
    assert t1["args"]["incomplete"] is True
    assert t1["dur"] == pytest.approx(4 * 1e6)  # to horizon, not time.time()
    t2 = bars[b"t2".hex()]
    assert t2["dur"] == 0
    assert t2["args"]["incomplete"] is True


def test_timeline_clamps_negative_durations():
    from ray_tpu.observability.timeline import build_chrome_trace

    t0 = 2000.0
    events = [
        # skewed clocks: FINISHED stamped before RUNNING
        _ev(b"t1", "RUNNING", t0 + 1.0, worker_addr=["h", 1]),
        _ev(b"t1", "FINISHED", t0 + 0.5),
        _ev(b"s1", "SPAN", t0, name="sp", dur=-5.0),
    ]
    trace = build_chrome_trace(events)
    bar = [e for e in trace if e["cat"] == "task"][0]
    assert bar["dur"] == 0
    assert bar["args"]["state"] == "FINISHED"
    assert "incomplete" not in bar["args"]
    span = [e for e in trace if e["cat"] == "span"][0]
    assert span["dur"] == 0


def test_timeline_phase_segments():
    """All five lifecycle phases present -> four named submit segments,
    and the refined (worker-stamped) RUNNING supersedes the owner's
    push-time RUNNING for the execution bar."""
    from ray_tpu.observability.timeline import build_chrome_trace

    t0 = 3000.0
    ts = {p: t0 + i * 0.01 for i, p in enumerate(SCHED_PHASES)}
    events = [_ev(b"t1", p, ts[p]) for p in SCHED_PHASES]
    # owner's coarse push-time RUNNING, recorded *before* the refined one
    events.insert(2, _ev(b"t1", "RUNNING", ts["LEASE_GRANTED"] + 0.001,
                         worker_addr=["h", 1]))
    events[-1]["worker_addr"] = ["h", 1]
    events.append(_ev(b"t1", "FINISHED", t0 + 1.0))

    trace = build_chrome_trace(events)
    bar = [e for e in trace if e["cat"] == "task"][0]
    assert bar["ts"] == pytest.approx(ts["RUNNING"] * 1e6)  # refined wins

    segs = [e for e in trace if e["cat"] == "submit"]
    assert [s["args"]["phase"] for s in segs] == \
        [SCHED_SEGMENT_LABELS[p] for p in SCHED_PHASES[1:]]
    assert {s["name"] for s in segs} == \
        {f"f:{SCHED_SEGMENT_LABELS[p]}" for p in SCHED_PHASES[1:]}
    # segments tile the submit->exec window without gaps
    for (a, b) in zip(segs, segs[1:]):
        assert a["ts"] + a["dur"] == pytest.approx(b["ts"])
    assert segs[0]["ts"] == pytest.approx(ts["PENDING"] * 1e6)
    assert segs[-1]["ts"] + segs[-1]["dur"] == \
        pytest.approx(ts["RUNNING"] * 1e6)
    assert all(s["pid"] == "driver-7" for s in segs)

    # with only the legacy two events, a single exec_start segment remains
    legacy = build_chrome_trace([
        _ev(b"t9", "PENDING", t0),
        _ev(b"t9", "RUNNING", t0 + 0.2, worker_addr=["h", 1]),
        _ev(b"t9", "FINISHED", t0 + 0.4),
    ])
    legacy_segs = [e for e in legacy if e["cat"] == "submit"]
    assert len(legacy_segs) == 1
    assert legacy_segs[0]["dur"] == pytest.approx(0.2 * 1e6)


def test_observe_sched_phases_clamps_and_skips():
    """Unit: cross-host clock skew never produces a negative
    observation, and missing middle phases widen the next segment."""
    from ray_tpu.observability import profiling as prof

    recorded = []

    class _FakeHist:
        def observe(self, v, tags=None):
            recorded.append((tags["phase"], v))

    orig = prof._sched_metrics
    prof._sched_metrics = _FakeHist()
    try:
        prof.observe_sched_phases({
            "PENDING": 100.0,
            "LEASE_GRANTED": 100.010,
            # WORKER_STARTED missing (evicted) -> args_fetch widens
            "ARGS_READY": 100.030,
            "RUNNING": 100.025,  # skewed: earlier than ARGS_READY
        })
    finally:
        prof._sched_metrics = orig
    assert recorded == [
        ("lease_grant", pytest.approx(0.010)),
        ("args_fetch", pytest.approx(0.020)),
        ("exec_start", 0.0),  # clamped, not negative
    ]


# ---------------------------------------------------------------------------
# check_metrics histogram-suffix rule (satellite c)
# ---------------------------------------------------------------------------

def test_check_metrics_histogram_suffix_rule(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_metrics",
        os.path.join(_repo_root(), "scripts", "check_metrics.py"))
    cm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cm)

    bad = tmp_path / "bad.py"
    bad.write_text(
        "from ray_tpu.util.metrics import Histogram\n"
        "h = Histogram('serve_latency_ms', tag_keys=('route',))\n")
    problems = cm.check_paths(str(tmp_path))
    assert any("serve_latency_ms" in p and "_seconds" in p
               for p in problems), problems

    bad.write_text(
        "from ray_tpu.util.metrics import Histogram\n"
        "h = Histogram('sched_phase_seconds', tag_keys=('phase',))\n"
        "b = Histogram('object_store_spill_bytes')\n")
    assert cm.check_paths(str(tmp_path)) == []


# ---------------------------------------------------------------------------
# Cluster end-to-end
# ---------------------------------------------------------------------------

@ray_tpu.remote
class _Spinner:
    def ping(self):
        return "pong"

    def spin_marker_method(self, seconds):
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline:
            pass
        return "spun"


def test_state_stack_covers_workers(ray_start_regular):
    """util.state.stack() returns live all-thread stacks for every
    worker on the node, and the actor selector narrows to one."""
    from ray_tpu._private.worker import global_worker
    from ray_tpu.util import state

    a = _Spinner.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"

    rows = global_worker().gcs.call("list_workers", timeout=30)
    worker_ids = {r["worker_id"].hex() for r in rows
                  if r.get("mode") == "worker"}
    assert worker_ids

    out = state.stack()
    assert worker_ids <= set(out), (worker_ids, set(out))
    for whex in worker_ids:
        entry = out[whex]
        assert entry["pid"] > 0
        assert "--- thread MainThread" in entry["stacks"]
        names = {t["thread_name"] for t in entry["threads"]}
        assert "MainThread" in names

    narrowed = state.stack(actor_id=a._actor_id.hex())
    assert len(narrowed) == 1
    (whex,) = narrowed
    assert whex in worker_ids

    with pytest.raises(ValueError):
        state.stack(node_id="ab", worker_id="cd")


def test_state_profile_attributes_busy_actor(ray_start_regular):
    """util.state.profile(actor_id=..., duration=1) returns a non-empty
    collapsed-stack + speedscope payload attributing the busy method."""
    from ray_tpu.util import state

    a = _Spinner.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"
    ref = a.spin_marker_method.remote(3.0)

    out = state.profile(actor_id=a._actor_id.hex(), duration=1.0, hz=200)
    assert out["partial"] is False
    assert out["exit"] is None
    assert out["samples"] > 0
    assert out["pid"] > 0
    assert "spin_marker_method" in out["folded"]
    sco = out["speedscope"]
    assert sco["profiles"], sco
    assert any("spin_marker_method" in f["name"]
               for f in sco["shared"]["frames"])
    assert ray_tpu.get(ref, timeout=60) == "spun"

    with pytest.raises(ValueError):
        state.profile()  # needs exactly one selector


def test_sched_phases_in_timeline_and_metrics(ray_start_regular):
    """Executed tasks carry the full phase chain: segmented submit
    arrows in ray_tpu.timeline() and rtpu_sched_phase_seconds{phase}
    on the GCS /metrics exposition."""
    from ray_tpu._private.worker import global_worker

    @ray_tpu.remote
    def add(x, y):
        return x + y

    assert ray_tpu.get([add.remote(i, i) for i in range(5)],
                       timeout=60) == [2 * i for i in range(5)]

    want = set(SCHED_SEGMENT_LABELS.values())

    def _phases_rendered():
        segs = [e for e in ray_tpu.timeline()
                if e["cat"] == "submit" and e["name"].startswith("add:")]
        return want <= {s["args"]["phase"] for s in segs}

    assert _wait_for(_phases_rendered, timeout=30), \
        [e["name"] for e in ray_tpu.timeline() if e["cat"] == "submit"]

    w = global_worker()

    def _metric_exported():
        text = w.gcs.call("metrics_text", timeout=30)
        return ("rtpu_sched_phase_seconds_bucket" in text
                and 'phase="exec_start"' in text)

    assert _wait_for(_metric_exported, timeout=30)
    text = w.gcs.call("metrics_text", timeout=30)
    assert "# TYPE rtpu_sched_phase_seconds histogram" in text


def test_tpu_profile_noop_with_reason_on_cpu(ray_start_regular):
    """On CPU CI the device-trace bracket must refuse loudly-but-safely:
    a `skipped` reason, not an error (and not a hang)."""
    from ray_tpu.util import state

    a = _Spinner.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"
    out = state.tpu_profile(actor_id=a._actor_id.hex(), duration=0.1)
    if "skipped" in out:  # CPU CI path
        assert "tpu" in out["skipped"]
    else:  # real TPU host
        assert out.get("artifact")


@pytest.fixture
def profiling_isolated():
    """Fresh per-test cluster for the death test; tears down the
    module-shared cluster first (init() refuses to double-init)."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    info = ray_tpu.init(num_cpus=4, num_tpus=0,
                        object_store_memory=128 * 1024 * 1024)
    yield info
    ray_tpu.shutdown()


def test_profile_partial_when_worker_dies(profiling_isolated):
    """A target that dies mid-window yields the samples gathered so far,
    tagged with the raylet's exit classification — never a hang."""
    from ray_tpu.observability import WORKER_EXIT_TYPES
    from ray_tpu.util import state

    @ray_tpu.remote
    class _Doomed:
        def ping(self):
            return "ok"

        def busy_then_die(self, busy_s):
            deadline = time.monotonic() + busy_s
            while time.monotonic() < deadline:
                pass
            os._exit(3)

    a = _Doomed.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "ok"
    a.busy_then_die.remote(1.2)

    out = state.profile(actor_id=a._actor_id.hex(), duration=6.0, hz=100)
    assert out["partial"] is True
    assert out["duration_s"] < 5.0  # stopped at death, not the full window
    assert out["exit"] is not None
    assert out["exit"]["exit_type"] in WORKER_EXIT_TYPES
    assert out["exit"]["exit_type"] == "USER_ERROR"  # os._exit(3)
    assert out["samples"] > 0
    assert "busy_then_die" in out["folded"]
