"""DQN + IMPALA (reference: `rllib/algorithms/{dqn,impala}`)."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def rl_cluster():
    import ray_tpu

    info = ray_tpu.init(num_cpus=8, num_tpus=0,
                        object_store_memory=256 * 1024 * 1024,
                        ignore_reinit_error=True)
    yield info
    ray_tpu.shutdown()


def test_vtrace_reduces_to_returns_on_policy():
    """With identical policies (rho=c=1) and no discount truncation,
    V-trace vs equals the n-step bootstrapped return."""
    import jax.numpy as jnp

    from ray_tpu.rllib.algorithms.impala import vtrace

    T, B = 5, 3
    rng = np.random.RandomState(0)
    logp = jnp.asarray(rng.randn(T, B).astype(np.float32))
    rewards = jnp.asarray(rng.randn(T, B).astype(np.float32))
    dones = jnp.zeros((T, B), jnp.float32)
    values = jnp.asarray(rng.randn(T, B).astype(np.float32))
    bootstrap = jnp.asarray(rng.randn(B).astype(np.float32))
    gamma = 0.9

    vs, pg_adv = vtrace(logp, logp, rewards, dones, values, bootstrap,
                        gamma)
    # On-policy (rho=c=1): vs_t = sum_{k>=t} gamma^{k-t} r_k + gamma^{T-t} V_T
    expect = np.zeros((T, B), np.float32)
    acc = np.asarray(bootstrap)
    for t in range(T - 1, -1, -1):
        acc = np.asarray(rewards[t]) + gamma * acc
        expect[t] = acc
    np.testing.assert_allclose(np.asarray(vs), expect, rtol=1e-4, atol=1e-4)

    # A done cuts the recursion.
    dones2 = dones.at[2].set(1.0)
    vs2, _ = vtrace(logp, logp, rewards, dones2, values, bootstrap, gamma)
    np.testing.assert_allclose(np.asarray(vs2[2]), np.asarray(rewards[2]),
                               rtol=1e-4, atol=1e-4)


def test_qmodule_epsilon_greedy():
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.algorithms.dqn import QModule
    from ray_tpu.rllib.env.spaces import Box, Discrete

    mod = QModule(Box(low=-np.ones(4), high=np.ones(4)), Discrete(2), (16,))
    params = mod.init(jax.random.key(0))
    obs = jnp.zeros((8, 4), jnp.float32)

    # epsilon=0 -> deterministic greedy
    params["epsilon"] = jnp.asarray(0.0, jnp.float32)
    a1 = mod.forward_exploration(params, obs, jax.random.key(1))["actions"]
    a2 = mod.forward_exploration(params, obs, jax.random.key(2))["actions"]
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))

    # epsilon=1 -> uniform random (both actions appear across keys)
    params["epsilon"] = jnp.asarray(1.0, jnp.float32)
    seen = set()
    for i in range(6):
        a = mod.forward_exploration(params, obs,
                                    jax.random.key(i))["actions"]
        seen.update(np.asarray(a).tolist())
    assert seen == {0, 1}


def test_dqn_learner_units():
    """TD loss decreases on a fixed synthetic batch; target sync works."""
    import jax

    from ray_tpu.rllib.algorithms.dqn import DQNLearner, QModule
    from ray_tpu.rllib.core.rl_module import RLModuleSpec
    from ray_tpu.rllib.env.spaces import Box, Discrete

    spec = RLModuleSpec(Box(low=-np.ones(4), high=np.ones(4)), Discrete(2),
                        hidden=(32,), module_class=QModule)
    learner = DQNLearner(spec, {"lr": 1e-2, "gamma": 0.9})
    learner.build()
    rng = np.random.RandomState(0)
    batch = {
        "obs": rng.randn(64, 4).astype(np.float32),
        "next_obs": rng.randn(64, 4).astype(np.float32),
        "actions": rng.randint(0, 2, 64).astype(np.int32),
        "rewards": rng.randn(64).astype(np.float32),
        "dones": (rng.rand(64) < 0.1).astype(np.float32),
    }
    losses = [learner.update(batch, rng_seed=i)["td_loss"]
              for i in range(30)]
    assert losses[-1] < losses[0]
    learner.sync_target()
    t = learner._state["target"]
    p = learner._state["params"]
    assert jax.tree.all(jax.tree.map(
        lambda a, b: bool((np.asarray(a) == np.asarray(b)).all()), t, p))


def test_dqn_cartpole_improves(rl_cluster):
    from ray_tpu.rllib import DQNConfig

    config = (DQNConfig()
              .environment("CartPole-v1")
              .training(lr=1e-3, train_batch_size=64)
              .env_runners(num_env_runners=1, num_envs_per_runner=4)
              .learners(num_learners=1, jax_platform="cpu")
              .rl_module(hidden=(64, 64)))
    config.learning_starts = 300
    config.rollout_fragment_length = 32      # 128 env steps / iteration
    config.epsilon_decay_steps = 4000
    config.num_updates_per_iteration = 48
    config.target_update_freq = 100
    algo = config.build()
    try:
        first = None
        best = -np.inf
        for i in range(60):
            m = algo.train()
            r = m.get("episode_return_mean")
            if r is not None:
                if first is None:
                    first = r
                best = max(best, r)
            if best >= 60:
                break
        assert first is not None
        assert best >= 60, (first, best)
    finally:
        algo.stop()


def test_impala_cartpole_improves(rl_cluster):
    from ray_tpu.rllib import IMPALAConfig

    config = (IMPALAConfig()
              .environment("CartPole-v1")
              .training(lr=5e-4)
              .env_runners(num_env_runners=2, num_envs_per_runner=4)
              .learners(num_learners=1, jax_platform="cpu"))
    config.rollout_fragment_length = 32
    config.num_rollouts_per_iteration = 8
    algo = config.build()
    try:
        best = -np.inf
        for i in range(60):
            m = algo.train()
            r = m.get("episode_return_mean")
            if r is not None:
                best = max(best, r)
            if best >= 100:
                break
        assert best >= 100, best
    finally:
        algo.stop()


# --------------------------------------------------------------------- SAC

def test_pendulum_env_units():
    from ray_tpu.rllib.env.pendulum import PendulumEnv

    env = PendulumEnv(seed=0)
    obs, _ = env.reset(seed=1)
    assert obs.shape == (3,)
    assert env.action_space.shape == (1,)
    total = 0.0
    for t in range(200):
        obs, r, term, trunc, _ = env.step(np.array([0.5]))
        assert -1.001 <= obs[0] <= 1.001 and abs(obs[2]) <= 8.0
        assert r <= 0.0          # cost-shaped reward
        total += r
        assert not term
    assert trunc                 # 200-step horizon
    assert total < 0.0


def test_sac_module_and_learner_units():
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.algorithms.sac import SACLearner, SACModule
    from ray_tpu.rllib.core.rl_module import RLModuleSpec
    from ray_tpu.rllib.env.spaces import Box

    obs_space = Box(low=-np.ones(3), high=np.ones(3))
    act_space = Box(low=np.array([-2.0]), high=np.array([2.0]))
    mod = SACModule(obs_space, act_space, (16,))
    params = mod.init(jax.random.key(0))
    obs = jnp.zeros((32, 3), jnp.float32)
    act, logp = mod.sample_action(params["actor"], obs,
                                  jax.random.key(1))
    assert act.shape == (32, 1) and logp.shape == (32,)
    assert np.all(np.abs(np.asarray(act)) <= 2.0)  # squashed + scaled

    learner = SACLearner(
        RLModuleSpec(observation_space=obs_space, action_space=act_space,
                     hidden=(16,), module_class=SACModule),
        config={"lr": 3e-4, "seed": 0, "target_entropy": -1.0,
                "tau": 0.5})
    learner.build()
    batch = {
        "obs": np.random.RandomState(0).randn(32, 3).astype(np.float32),
        "next_obs": np.random.RandomState(1).randn(32, 3).astype(
            np.float32),
        "actions": np.random.RandomState(2).uniform(
            -2, 2, (32, 1)).astype(np.float32),
        "rewards": np.zeros(32, np.float32),
        "dones": np.zeros(32, np.float32),
    }
    before_target = learner._state["target"]["q1"]
    before_leaf = np.asarray(
        __import__("jax").tree.leaves(before_target)[0]).copy()
    metrics = learner.update(batch)
    for key in ("critic_loss", "actor_loss", "alpha", "entropy"):
        assert key in metrics
    # Polyak ran inside the jitted update (tau=0.5 moves targets visibly).
    after_leaf = np.asarray(
        __import__("jax").tree.leaves(learner._state["target"]["q1"])[0])
    assert not np.allclose(before_leaf, after_leaf)


def test_sac_pendulum_improves(rl_cluster):
    """SAC swing-up: returns improve well above the random-policy floor
    (~-1200 avg) within a few iterations."""
    from ray_tpu.rllib import SACConfig

    config = (SACConfig()
              .environment("Pendulum-v1")
              .training(lr=1e-3, train_batch_size=256)
              .env_runners(num_env_runners=1, num_envs_per_runner=4)
              .learners(num_learners=1, jax_platform="cpu")
              .rl_module(hidden=(64, 64)))
    config.learning_starts = 500
    config.rollout_fragment_length = 50      # 200 env steps / iteration
    config.num_updates_per_iteration = 100
    config.tau = 0.02                        # fast target tracking
    config.metrics_episode_window = 20
    algo = config.build()
    try:
        best = -np.inf
        for i in range(60):
            m = algo.train()
            r = m.get("episode_return_mean")
            if r is not None:
                best = max(best, r)
            if best >= -500:
                break
        assert best >= -500, best
    finally:
        algo.stop()


# ---------------------------------------------------------------------- BC

def test_bc_clones_expert(rl_cluster):
    """BC on a scripted CartPole expert: the cloned policy far outlasts
    random play (reference: `rllib/algorithms/bc`)."""
    from ray_tpu.rllib import BCConfig
    from ray_tpu.rllib.env.cartpole import CartPoleEnv

    # Scripted expert: push the cart toward the pole's lean.
    env = CartPoleEnv(seed=0)
    rows = []
    for ep in range(40):
        obs, _ = env.reset(seed=ep)
        done = False
        while not done:
            a = int(obs[2] + 0.3 * obs[3] > 0)
            rows.append({"obs": obs.astype(np.float32), "actions": a})
            obs, _, term, trunc, _ = env.step(a)
            done = term or trunc

    config = (BCConfig()
              .environment("CartPole-v1")
              .training(lr=3e-3, train_batch_size=256)
              .learners(num_learners=1, jax_platform="cpu")
              .rl_module(hidden=(32, 32))
              .offline_data(rows))
    config.num_batches_per_iteration = 40
    algo = config.build()
    try:
        for _ in range(15):
            m = algo.train()
            if m["bc_accuracy"] > 0.92:
                break
        assert m["bc_accuracy"] > 0.9, m
        ev = algo.evaluate(num_episodes=5)
        assert ev["episode_return_mean"] >= 100, ev
    finally:
        algo.stop()


def test_bc_over_data_dataset(rl_cluster):
    """BC ingests a ray_tpu.data Dataset (offline-RL over the Data
    library, reference: `rllib/offline/`)."""
    from ray_tpu import data as rdata
    from ray_tpu.rllib import BCConfig

    rng = np.random.RandomState(0)
    obs = rng.randn(512, 4).astype(np.float32)
    actions = (obs[:, 2] > 0).astype(np.int64)   # linearly separable
    ds = rdata.from_items([{"obs": o, "actions": int(a)}
                           for o, a in zip(obs, actions)])

    config = (BCConfig()
              .environment("CartPole-v1")
              .training(lr=3e-3, train_batch_size=128)
              .learners(num_learners=1, jax_platform="cpu")
              .rl_module(hidden=(32,))
              .offline_data(ds))
    config.num_batches_per_iteration = 30
    algo = config.build()
    try:
        for _ in range(4):
            m = algo.train()
        assert m["bc_accuracy"] > 0.9, m
    finally:
        algo.stop()


# -------------------------------------------------------------------- APPO

def test_appo_cartpole_improves(rl_cluster):
    """APPO = IMPALA architecture + PPO clip on V-trace advantages
    (reference: rllib/algorithms/appo)."""
    from ray_tpu.rllib import APPOConfig

    config = (APPOConfig()
              .environment("CartPole-v1")
              .training(lr=5e-4)
              .env_runners(num_env_runners=2, num_envs_per_runner=4)
              .learners(num_learners=1, jax_platform="cpu")
              .rl_module(hidden=(64, 64)))
    config.rollout_fragment_length = 32
    config.num_rollouts_per_iteration = 8
    config.num_rollouts_per_update = 2
    config.metrics_episode_window = 30
    algo = config.build()
    try:
        best = -np.inf
        for i in range(40):
            m = algo.train()
            r = m.get("episode_return_mean")
            if r is not None:
                best = max(best, r)
            if best >= 100:
                break
        assert best >= 100, best
        # The surrogate's clip metrics flow through (engagement depends
        # on how off-policy the sampled rollouts happened to be).
        assert "clip_frac" in m and "mean_ratio" in m
    finally:
        algo.stop()


# --------------------------------------------------------------- TD3 / DDPG

def test_td3_module_and_learner_units():
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.algorithms.td3 import TD3Learner, TD3Module
    from ray_tpu.rllib.core.rl_module import RLModuleSpec
    from ray_tpu.rllib.env.spaces import Box

    obs_space = Box(low=-np.ones(3), high=np.ones(3))
    act_space = Box(low=np.array([-2.0]), high=np.array([2.0]))

    # DDPG flavor: no twin critic in the param tree.
    single = TD3Module(obs_space, act_space, (16,), twin_q=False)
    p = single.init(jax.random.key(0))
    assert "q2" not in p
    q1, q2 = single.q_values(p, jnp.zeros((4, 3)), jnp.zeros((4, 1)))
    assert np.allclose(np.asarray(q1), np.asarray(q2))  # aliased

    mod = TD3Module(obs_space, act_space, (16,), twin_q=True,
                    exploration_sigma=0.3)
    params = mod.init(jax.random.key(0))
    obs = jnp.zeros((32, 3), jnp.float32)
    det = mod.forward_inference(params, obs)["actions"]
    noisy = mod.forward_exploration(params, obs, jax.random.key(1))
    assert noisy["actions"].shape == (32, 1)
    assert np.all(np.abs(np.asarray(noisy["actions"])) <= 2.0)
    assert not np.allclose(np.asarray(det), np.asarray(noisy["actions"]))

    learner = TD3Learner(
        RLModuleSpec(observation_space=obs_space, action_space=act_space,
                     hidden=(16,), module_class=TD3Module,
                     module_kwargs={"twin_q": True}),
        config={"lr": 1e-3, "seed": 0, "tau": 0.5, "policy_delay": 2,
                "target_noise": 0.2})
    learner.build()
    batch = {
        "obs": np.random.RandomState(0).randn(32, 3).astype(np.float32),
        "next_obs": np.random.RandomState(1).randn(32, 3).astype(
            np.float32),
        "actions": np.random.RandomState(2).uniform(
            -2, 2, (32, 1)).astype(np.float32),
        "rewards": np.ones(32, np.float32),
        "dones": np.zeros(32, np.float32),
    }
    leaf = lambda s: np.asarray(  # noqa: E731
        jax.tree.leaves(s["target"]["actor"])[0]).copy()
    actor_leaf = lambda s: np.asarray(  # noqa: E731
        jax.tree.leaves(s["params"]["actor"])[0]).copy()
    t0, a0 = leaf(learner._state), actor_leaf(learner._state)
    metrics = learner.update(batch)
    for key in ("critic_loss", "actor_loss", "q1_mean", "target_q_mean"):
        assert key in metrics
    t1, a1 = leaf(learner._state), actor_leaf(learner._state)
    assert not np.allclose(t0, t1)     # step 0: mask=1 -> polyak ran
    assert not np.allclose(a0, a1)     # step 0: actor stepped
    metrics = learner.update(batch)
    t2, a2 = leaf(learner._state), actor_leaf(learner._state)
    assert np.allclose(t1, t2)         # step 1: mask=0 -> targets frozen
    # Step 1: actor params EXACTLY frozen — the interval optimizer must
    # not leak Adam momentum into skipped steps (a zeroed loss alone
    # would still move the actor).
    assert np.array_equal(a1, a2)
    learner.update(batch)
    assert not np.allclose(t2, leaf(learner._state))  # step 2: mask=1 again
    assert not np.allclose(a2, actor_leaf(learner._state))


def test_td3_action_space_affine_map_and_validation():
    """Asymmetric Box bounds map through center + tanh * scale;
    unbounded or degenerate boxes fail at module construction."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.algorithms.td3 import TD3Module
    from ray_tpu.rllib.env.spaces import Box

    obs_space = Box(low=-np.ones(3), high=np.ones(3))
    act_space = Box(low=np.array([0.0, -1.0]), high=np.array([4.0, 3.0]))
    mod = TD3Module(obs_space, act_space, (8,), twin_q=False,
                    exploration_sigma=0.5)
    params = mod.init(jax.random.key(0))
    obs = jax.random.normal(jax.random.key(1), (64, 3))
    det = np.asarray(mod.forward_inference(params, obs)["actions"])
    lo, hi = np.array([0.0, -1.0]), np.array([4.0, 3.0])
    assert det.shape == (64, 2)
    assert (det >= lo - 1e-6).all() and (det <= hi + 1e-6).all()
    noisy = np.asarray(
        mod.forward_exploration(params, obs, jax.random.key(2))["actions"])
    assert (noisy >= lo - 1e-6).all() and (noisy <= hi + 1e-6).all()
    # Zero-mean mu hits the center of the box, not zero.
    zero_mu = np.asarray(mod._act_center + jnp.tanh(0.0) * mod._act_scale)
    assert np.allclose(zero_mu, (lo + hi) / 2)

    with pytest.raises(ValueError):
        TD3Module(obs_space, Box(low=np.array([-np.inf]),
                                 high=np.array([np.inf])))
    with pytest.raises(ValueError):
        TD3Module(obs_space, Box(low=np.array([1.0]),
                                 high=np.array([1.0])))


def test_td3_pendulum_improves(rl_cluster):
    """TD3 swing-up clears the same bar as SAC (random floor ~-1200)."""
    from ray_tpu.rllib import TD3Config

    config = (TD3Config()
              .environment("Pendulum-v1")
              .training(lr=1e-3, train_batch_size=256)
              .env_runners(num_env_runners=1, num_envs_per_runner=4)
              .learners(num_learners=1, jax_platform="cpu")
              .rl_module(hidden=(64, 64)))
    config.learning_starts = 500
    config.rollout_fragment_length = 50      # 200 env steps / iteration
    config.num_updates_per_iteration = 100
    config.tau = 0.02
    config.exploration_sigma = 0.15
    config.metrics_episode_window = 20
    algo = config.build()
    try:
        best = -np.inf
        for i in range(60):
            m = algo.train()
            r = m.get("episode_return_mean")
            if r is not None:
                best = max(best, r)
            if best >= -500:
                break
        assert best >= -500, best
    finally:
        algo.stop()


def test_ddpg_smoke(rl_cluster):
    """DDPG builds (single critic, no delay/smoothing) and trains without
    NaNs; learning quality is TD3's job."""
    from ray_tpu.rllib import DDPGConfig

    config = (DDPGConfig()
              .environment("Pendulum-v1")
              .training(lr=1e-3, train_batch_size=128)
              .env_runners(num_env_runners=1, num_envs_per_runner=2)
              .learners(num_learners=1, jax_platform="cpu")
              .rl_module(hidden=(32,)))
    config.learning_starts = 200
    config.rollout_fragment_length = 50
    config.num_updates_per_iteration = 10
    algo = config.build()
    try:
        for _ in range(3):
            m = algo.train()
        assert m["num_gradient_updates"] > 0
        assert np.isfinite(m["critic_loss"])
    finally:
        algo.stop()


# ----------------------------------------------------------------- ES / ARS

def test_centered_ranks_units():
    from ray_tpu.rllib.algorithms.es import _centered_ranks

    r = _centered_ranks(np.array([10.0, -5.0, 3.0, 100.0]))
    assert np.isclose(r.max(), 0.5) and np.isclose(r.min(), -0.5)
    assert r[3] == 0.5 and r[1] == -0.5      # rank order, not magnitude
    assert np.isclose(r.sum(), 0.0, atol=1e-6)
    # Shape-preserving for the (P, 2) antithetic layout.
    m = _centered_ranks(np.arange(6, dtype=np.float32).reshape(3, 2))
    assert m.shape == (3, 2)


def test_es_cartpole_improves(rl_cluster):
    """Gradient-free ES clears the CartPole bar using only episode
    returns (no backprop anywhere in the update path)."""
    from ray_tpu.rllib import ESConfig

    config = (ESConfig()
              .environment("CartPole-v1")
              .training(lr=0.05)
              .env_runners(num_env_runners=2, num_envs_per_runner=1)
              .learners(num_learners=1, jax_platform="cpu")
              .rl_module(hidden=(32,)))
    config.noise_stdev = 0.1
    config.num_perturbations = 24
    config.metrics_episode_window = 48
    algo = config.build()
    try:
        best = -np.inf
        for i in range(30):
            m = algo.train()
            best = max(best, m["perturbed_return_max"])
            if m.get("episode_return_mean", 0) >= 100:
                break
        assert best >= 150, best
    finally:
        algo.stop()


def test_ars_smoke(rl_cluster):
    """ARS variant: top-k direction selection + std shaping run end to
    end and report selection metrics."""
    from ray_tpu.rllib import ARSConfig

    config = (ARSConfig()
              .environment("CartPole-v1")
              .training(lr=0.05)
              .env_runners(num_env_runners=2, num_envs_per_runner=1)
              .learners(num_learners=1, jax_platform="cpu")
              .rl_module(hidden=(16,)))
    config.num_perturbations = 8
    algo = config.build()
    try:
        m = algo.train()
        assert m["directions_kept"] == 4        # top_fraction 0.5
        assert np.isfinite(m["perturbed_return_mean"])
        assert np.isfinite(m["update_norm"])
    finally:
        algo.stop()
