"""DQN + IMPALA (reference: `rllib/algorithms/{dqn,impala}`)."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def rl_cluster():
    import ray_tpu

    info = ray_tpu.init(num_cpus=8, num_tpus=0,
                        object_store_memory=256 * 1024 * 1024,
                        ignore_reinit_error=True)
    yield info
    ray_tpu.shutdown()


def test_vtrace_reduces_to_returns_on_policy():
    """With identical policies (rho=c=1) and no discount truncation,
    V-trace vs equals the n-step bootstrapped return."""
    import jax.numpy as jnp

    from ray_tpu.rllib.algorithms.impala import vtrace

    T, B = 5, 3
    rng = np.random.RandomState(0)
    logp = jnp.asarray(rng.randn(T, B).astype(np.float32))
    rewards = jnp.asarray(rng.randn(T, B).astype(np.float32))
    dones = jnp.zeros((T, B), jnp.float32)
    values = jnp.asarray(rng.randn(T, B).astype(np.float32))
    bootstrap = jnp.asarray(rng.randn(B).astype(np.float32))
    gamma = 0.9

    vs, pg_adv = vtrace(logp, logp, rewards, dones, values, bootstrap,
                        gamma)
    # On-policy (rho=c=1): vs_t = sum_{k>=t} gamma^{k-t} r_k + gamma^{T-t} V_T
    expect = np.zeros((T, B), np.float32)
    acc = np.asarray(bootstrap)
    for t in range(T - 1, -1, -1):
        acc = np.asarray(rewards[t]) + gamma * acc
        expect[t] = acc
    np.testing.assert_allclose(np.asarray(vs), expect, rtol=1e-4, atol=1e-4)

    # A done cuts the recursion.
    dones2 = dones.at[2].set(1.0)
    vs2, _ = vtrace(logp, logp, rewards, dones2, values, bootstrap, gamma)
    np.testing.assert_allclose(np.asarray(vs2[2]), np.asarray(rewards[2]),
                               rtol=1e-4, atol=1e-4)


def test_qmodule_epsilon_greedy():
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.algorithms.dqn import QModule
    from ray_tpu.rllib.env.spaces import Box, Discrete

    mod = QModule(Box(low=-np.ones(4), high=np.ones(4)), Discrete(2), (16,))
    params = mod.init(jax.random.key(0))
    obs = jnp.zeros((8, 4), jnp.float32)

    # epsilon=0 -> deterministic greedy
    params["epsilon"] = jnp.asarray(0.0, jnp.float32)
    a1 = mod.forward_exploration(params, obs, jax.random.key(1))["actions"]
    a2 = mod.forward_exploration(params, obs, jax.random.key(2))["actions"]
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))

    # epsilon=1 -> uniform random (both actions appear across keys)
    params["epsilon"] = jnp.asarray(1.0, jnp.float32)
    seen = set()
    for i in range(6):
        a = mod.forward_exploration(params, obs,
                                    jax.random.key(i))["actions"]
        seen.update(np.asarray(a).tolist())
    assert seen == {0, 1}


def test_dqn_learner_units():
    """TD loss decreases on a fixed synthetic batch; target sync works."""
    import jax

    from ray_tpu.rllib.algorithms.dqn import DQNLearner, QModule
    from ray_tpu.rllib.core.rl_module import RLModuleSpec
    from ray_tpu.rllib.env.spaces import Box, Discrete

    spec = RLModuleSpec(Box(low=-np.ones(4), high=np.ones(4)), Discrete(2),
                        hidden=(32,), module_class=QModule)
    learner = DQNLearner(spec, {"lr": 1e-2, "gamma": 0.9})
    learner.build()
    rng = np.random.RandomState(0)
    batch = {
        "obs": rng.randn(64, 4).astype(np.float32),
        "next_obs": rng.randn(64, 4).astype(np.float32),
        "actions": rng.randint(0, 2, 64).astype(np.int32),
        "rewards": rng.randn(64).astype(np.float32),
        "dones": (rng.rand(64) < 0.1).astype(np.float32),
    }
    losses = [learner.update(batch, rng_seed=i)["td_loss"]
              for i in range(30)]
    assert losses[-1] < losses[0]
    learner.sync_target()
    t = learner._state["target"]
    p = learner._state["params"]
    assert jax.tree.all(jax.tree.map(
        lambda a, b: bool((np.asarray(a) == np.asarray(b)).all()), t, p))


def test_dqn_cartpole_improves(rl_cluster):
    from ray_tpu.rllib import DQNConfig

    config = (DQNConfig()
              .environment("CartPole-v1")
              .training(lr=1e-3, train_batch_size=64)
              .env_runners(num_env_runners=1, num_envs_per_runner=4)
              .learners(num_learners=1, jax_platform="cpu")
              .rl_module(hidden=(64, 64)))
    config.learning_starts = 300
    config.rollout_fragment_length = 32      # 128 env steps / iteration
    config.epsilon_decay_steps = 4000
    config.num_updates_per_iteration = 48
    config.target_update_freq = 100
    algo = config.build()
    try:
        first = None
        best = -np.inf
        for i in range(60):
            m = algo.train()
            r = m.get("episode_return_mean")
            if r is not None:
                if first is None:
                    first = r
                best = max(best, r)
            if best >= 60:
                break
        assert first is not None
        assert best >= 60, (first, best)
    finally:
        algo.stop()


def test_impala_cartpole_improves(rl_cluster):
    from ray_tpu.rllib import IMPALAConfig

    config = (IMPALAConfig()
              .environment("CartPole-v1")
              .training(lr=5e-4)
              .env_runners(num_env_runners=2, num_envs_per_runner=4)
              .learners(num_learners=1, jax_platform="cpu"))
    config.rollout_fragment_length = 32
    config.num_rollouts_per_iteration = 8
    algo = config.build()
    try:
        best = -np.inf
        for i in range(60):
            m = algo.train()
            r = m.get("episode_return_mean")
            if r is not None:
                best = max(best, r)
            if best >= 100:
                break
        assert best >= 100, best
    finally:
        algo.stop()
