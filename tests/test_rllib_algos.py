"""DQN + IMPALA (reference: `rllib/algorithms/{dqn,impala}`)."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def rl_cluster():
    import ray_tpu

    info = ray_tpu.init(num_cpus=8, num_tpus=0,
                        object_store_memory=256 * 1024 * 1024,
                        ignore_reinit_error=True)
    yield info
    ray_tpu.shutdown()


def test_vtrace_reduces_to_returns_on_policy():
    """With identical policies (rho=c=1) and no discount truncation,
    V-trace vs equals the n-step bootstrapped return."""
    import jax.numpy as jnp

    from ray_tpu.rllib.algorithms.impala import vtrace

    T, B = 5, 3
    rng = np.random.RandomState(0)
    logp = jnp.asarray(rng.randn(T, B).astype(np.float32))
    rewards = jnp.asarray(rng.randn(T, B).astype(np.float32))
    dones = jnp.zeros((T, B), jnp.float32)
    values = jnp.asarray(rng.randn(T, B).astype(np.float32))
    bootstrap = jnp.asarray(rng.randn(B).astype(np.float32))
    gamma = 0.9

    vs, pg_adv = vtrace(logp, logp, rewards, dones, values, bootstrap,
                        gamma)
    # On-policy (rho=c=1): vs_t = sum_{k>=t} gamma^{k-t} r_k + gamma^{T-t} V_T
    expect = np.zeros((T, B), np.float32)
    acc = np.asarray(bootstrap)
    for t in range(T - 1, -1, -1):
        acc = np.asarray(rewards[t]) + gamma * acc
        expect[t] = acc
    np.testing.assert_allclose(np.asarray(vs), expect, rtol=1e-4, atol=1e-4)

    # A done cuts the recursion.
    dones2 = dones.at[2].set(1.0)
    vs2, _ = vtrace(logp, logp, rewards, dones2, values, bootstrap, gamma)
    np.testing.assert_allclose(np.asarray(vs2[2]), np.asarray(rewards[2]),
                               rtol=1e-4, atol=1e-4)


def test_qmodule_epsilon_greedy():
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.algorithms.dqn import QModule
    from ray_tpu.rllib.env.spaces import Box, Discrete

    mod = QModule(Box(low=-np.ones(4), high=np.ones(4)), Discrete(2), (16,))
    params = mod.init(jax.random.key(0))
    obs = jnp.zeros((8, 4), jnp.float32)

    # epsilon=0 -> deterministic greedy
    params["epsilon"] = jnp.asarray(0.0, jnp.float32)
    a1 = mod.forward_exploration(params, obs, jax.random.key(1))["actions"]
    a2 = mod.forward_exploration(params, obs, jax.random.key(2))["actions"]
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))

    # epsilon=1 -> uniform random (both actions appear across keys)
    params["epsilon"] = jnp.asarray(1.0, jnp.float32)
    seen = set()
    for i in range(6):
        a = mod.forward_exploration(params, obs,
                                    jax.random.key(i))["actions"]
        seen.update(np.asarray(a).tolist())
    assert seen == {0, 1}


def test_dqn_learner_units():
    """TD loss decreases on a fixed synthetic batch; target sync works."""
    import jax

    from ray_tpu.rllib.algorithms.dqn import DQNLearner, QModule
    from ray_tpu.rllib.core.rl_module import RLModuleSpec
    from ray_tpu.rllib.env.spaces import Box, Discrete

    spec = RLModuleSpec(Box(low=-np.ones(4), high=np.ones(4)), Discrete(2),
                        hidden=(32,), module_class=QModule)
    learner = DQNLearner(spec, {"lr": 1e-2, "gamma": 0.9})
    learner.build()
    rng = np.random.RandomState(0)
    batch = {
        "obs": rng.randn(64, 4).astype(np.float32),
        "next_obs": rng.randn(64, 4).astype(np.float32),
        "actions": rng.randint(0, 2, 64).astype(np.int32),
        "rewards": rng.randn(64).astype(np.float32),
        "dones": (rng.rand(64) < 0.1).astype(np.float32),
    }
    losses = [learner.update(batch, rng_seed=i)["td_loss"]
              for i in range(30)]
    assert losses[-1] < losses[0]
    learner.sync_target()
    t = learner._state["target"]
    p = learner._state["params"]
    assert jax.tree.all(jax.tree.map(
        lambda a, b: bool((np.asarray(a) == np.asarray(b)).all()), t, p))


def test_dqn_cartpole_improves(rl_cluster):
    from ray_tpu.rllib import DQNConfig

    config = (DQNConfig()
              .environment("CartPole-v1")
              .training(lr=1e-3, train_batch_size=64)
              .env_runners(num_env_runners=1, num_envs_per_runner=4)
              .learners(num_learners=1, jax_platform="cpu")
              .rl_module(hidden=(64, 64)))
    config.learning_starts = 300
    config.rollout_fragment_length = 32      # 128 env steps / iteration
    config.epsilon_decay_steps = 4000
    config.num_updates_per_iteration = 48
    config.target_update_freq = 100
    algo = config.build()
    try:
        first = None
        best = -np.inf
        for i in range(60):
            m = algo.train()
            r = m.get("episode_return_mean")
            if r is not None:
                if first is None:
                    first = r
                best = max(best, r)
            if best >= 60:
                break
        assert first is not None
        assert best >= 60, (first, best)
    finally:
        algo.stop()


def test_impala_cartpole_improves(rl_cluster):
    from ray_tpu.rllib import IMPALAConfig

    config = (IMPALAConfig()
              .environment("CartPole-v1")
              .training(lr=5e-4)
              .env_runners(num_env_runners=2, num_envs_per_runner=4)
              .learners(num_learners=1, jax_platform="cpu"))
    config.rollout_fragment_length = 32
    config.num_rollouts_per_iteration = 8
    algo = config.build()
    try:
        best = -np.inf
        for i in range(60):
            m = algo.train()
            r = m.get("episode_return_mean")
            if r is not None:
                best = max(best, r)
            if best >= 100:
                break
        assert best >= 100, best
    finally:
        algo.stop()


# --------------------------------------------------------------------- SAC

def test_pendulum_env_units():
    from ray_tpu.rllib.env.pendulum import PendulumEnv

    env = PendulumEnv(seed=0)
    obs, _ = env.reset(seed=1)
    assert obs.shape == (3,)
    assert env.action_space.shape == (1,)
    total = 0.0
    for t in range(200):
        obs, r, term, trunc, _ = env.step(np.array([0.5]))
        assert -1.001 <= obs[0] <= 1.001 and abs(obs[2]) <= 8.0
        assert r <= 0.0          # cost-shaped reward
        total += r
        assert not term
    assert trunc                 # 200-step horizon
    assert total < 0.0


def test_sac_module_and_learner_units():
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.algorithms.sac import SACLearner, SACModule
    from ray_tpu.rllib.core.rl_module import RLModuleSpec
    from ray_tpu.rllib.env.spaces import Box

    obs_space = Box(low=-np.ones(3), high=np.ones(3))
    act_space = Box(low=np.array([-2.0]), high=np.array([2.0]))
    mod = SACModule(obs_space, act_space, (16,))
    params = mod.init(jax.random.key(0))
    obs = jnp.zeros((32, 3), jnp.float32)
    act, logp = mod.sample_action(params["actor"], obs,
                                  jax.random.key(1))
    assert act.shape == (32, 1) and logp.shape == (32,)
    assert np.all(np.abs(np.asarray(act)) <= 2.0)  # squashed + scaled

    learner = SACLearner(
        RLModuleSpec(observation_space=obs_space, action_space=act_space,
                     hidden=(16,), module_class=SACModule),
        config={"lr": 3e-4, "seed": 0, "target_entropy": -1.0,
                "tau": 0.5})
    learner.build()
    batch = {
        "obs": np.random.RandomState(0).randn(32, 3).astype(np.float32),
        "next_obs": np.random.RandomState(1).randn(32, 3).astype(
            np.float32),
        "actions": np.random.RandomState(2).uniform(
            -2, 2, (32, 1)).astype(np.float32),
        "rewards": np.zeros(32, np.float32),
        "dones": np.zeros(32, np.float32),
    }
    before_target = learner._state["target"]["q1"]
    before_leaf = np.asarray(
        __import__("jax").tree.leaves(before_target)[0]).copy()
    metrics = learner.update(batch)
    for key in ("critic_loss", "actor_loss", "alpha", "entropy"):
        assert key in metrics
    # Polyak ran inside the jitted update (tau=0.5 moves targets visibly).
    after_leaf = np.asarray(
        __import__("jax").tree.leaves(learner._state["target"]["q1"])[0])
    assert not np.allclose(before_leaf, after_leaf)


def test_sac_pendulum_improves(rl_cluster):
    """SAC swing-up: returns improve well above the random-policy floor
    (~-1200 avg) within a few iterations."""
    from ray_tpu.rllib import SACConfig

    config = (SACConfig()
              .environment("Pendulum-v1")
              .training(lr=1e-3, train_batch_size=256)
              .env_runners(num_env_runners=1, num_envs_per_runner=4)
              .learners(num_learners=1, jax_platform="cpu")
              .rl_module(hidden=(64, 64)))
    config.learning_starts = 500
    config.rollout_fragment_length = 50      # 200 env steps / iteration
    config.num_updates_per_iteration = 100
    config.tau = 0.02                        # fast target tracking
    config.metrics_episode_window = 20
    algo = config.build()
    try:
        best = -np.inf
        for i in range(60):
            m = algo.train()
            r = m.get("episode_return_mean")
            if r is not None:
                best = max(best, r)
            if best >= -500:
                break
        assert best >= -500, best
    finally:
        algo.stop()


# ---------------------------------------------------------------------- BC

def test_bc_clones_expert(rl_cluster):
    """BC on a scripted CartPole expert: the cloned policy far outlasts
    random play (reference: `rllib/algorithms/bc`)."""
    from ray_tpu.rllib import BCConfig
    from ray_tpu.rllib.env.cartpole import CartPoleEnv

    # Scripted expert: push the cart toward the pole's lean.
    env = CartPoleEnv(seed=0)
    rows = []
    for ep in range(40):
        obs, _ = env.reset(seed=ep)
        done = False
        while not done:
            a = int(obs[2] + 0.3 * obs[3] > 0)
            rows.append({"obs": obs.astype(np.float32), "actions": a})
            obs, _, term, trunc, _ = env.step(a)
            done = term or trunc

    config = (BCConfig()
              .environment("CartPole-v1")
              .training(lr=3e-3, train_batch_size=256)
              .learners(num_learners=1, jax_platform="cpu")
              .rl_module(hidden=(32, 32))
              .offline_data(rows))
    config.num_batches_per_iteration = 40
    algo = config.build()
    try:
        for _ in range(15):
            m = algo.train()
            if m["bc_accuracy"] > 0.92:
                break
        assert m["bc_accuracy"] > 0.9, m
        ev = algo.evaluate(num_episodes=5)
        assert ev["episode_return_mean"] >= 100, ev
    finally:
        algo.stop()


def test_bc_over_data_dataset(rl_cluster):
    """BC ingests a ray_tpu.data Dataset (offline-RL over the Data
    library, reference: `rllib/offline/`)."""
    from ray_tpu import data as rdata
    from ray_tpu.rllib import BCConfig

    rng = np.random.RandomState(0)
    obs = rng.randn(512, 4).astype(np.float32)
    actions = (obs[:, 2] > 0).astype(np.int64)   # linearly separable
    ds = rdata.from_items([{"obs": o, "actions": int(a)}
                           for o, a in zip(obs, actions)])

    config = (BCConfig()
              .environment("CartPole-v1")
              .training(lr=3e-3, train_batch_size=128)
              .learners(num_learners=1, jax_platform="cpu")
              .rl_module(hidden=(32,))
              .offline_data(ds))
    config.num_batches_per_iteration = 30
    algo = config.build()
    try:
        for _ in range(4):
            m = algo.train()
        assert m["bc_accuracy"] > 0.9, m
    finally:
        algo.stop()


# -------------------------------------------------------------------- APPO

def test_appo_cartpole_improves(rl_cluster):
    """APPO = IMPALA architecture + PPO clip on V-trace advantages
    (reference: rllib/algorithms/appo)."""
    from ray_tpu.rllib import APPOConfig

    config = (APPOConfig()
              .environment("CartPole-v1")
              .training(lr=5e-4)
              .env_runners(num_env_runners=2, num_envs_per_runner=4)
              .learners(num_learners=1, jax_platform="cpu")
              .rl_module(hidden=(64, 64)))
    config.rollout_fragment_length = 32
    config.num_rollouts_per_iteration = 8
    config.num_rollouts_per_update = 2
    config.metrics_episode_window = 30
    algo = config.build()
    try:
        best = -np.inf
        for i in range(40):
            m = algo.train()
            r = m.get("episode_return_mean")
            if r is not None:
                best = max(best, r)
            if best >= 100:
                break
        assert best >= 100, best
        # The surrogate's clip metrics flow through (engagement depends
        # on how off-policy the sampled rollouts happened to be).
        assert "clip_frac" in m and "mean_ratio" in m
    finally:
        algo.stop()
