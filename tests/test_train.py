"""Ray-Train-equivalent e2e: JaxTrainer data-parallel training on a fake
2-host x 4-device CPU mesh — THE e2e milestone from SURVEY §7 M5."""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (
    Checkpoint, CheckpointConfig, FailureConfig, JaxConfig, JaxTrainer,
    RunConfig, ScalingConfig, TrainingFailedError,
)


def _jax_cpu_multiprocess_supported() -> bool:
    """jax < 0.5 raises INVALID_ARGUMENT on any cross-process CPU
    computation (no gloo transport); the jax_num_cpu_devices config option
    landed in the same release line and is a cheap capability probe."""
    import jax

    return hasattr(jax.config, "jax_num_cpu_devices")


_needs_cpu_multiprocess = pytest.mark.skipif(
    not _jax_cpu_multiprocess_supported(),
    reason="installed jax lacks multiprocess CPU collectives (gloo)")


def mlp_train_loop(config):
    """Data-parallel MLP regression with a pjit'd step over the global mesh.
    Runs inside each train worker (2 processes x 4 virtual CPU devices)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu import train

    ctx = train.get_context()
    world = ctx.get_world_size()
    rank = ctx.get_world_rank()

    # Global mesh over ALL devices of the gang (both processes).
    mesh = jax.make_mesh((jax.device_count(),), ("data",))
    repl = NamedSharding(mesh, P())
    data_sharded = NamedSharding(mesh, P("data"))

    rng = np.random.RandomState(0)
    w_true = rng.randn(8, 1).astype(np.float32)

    def init_params(key):
        k1, k2 = jax.random.split(key)
        return {
            "w1": jax.random.normal(k1, (8, 32)) * 0.1,
            "b1": jnp.zeros(32),
            "w2": jax.random.normal(k2, (32, 1)) * 0.1,
            "b2": jnp.zeros(1),
        }

    start_epoch = 0
    ckpt = ctx.get_checkpoint()
    if ckpt is not None:
        state = ckpt.to_pytree()
        params = jax.device_put(state["params"], repl)
        start_epoch = int(state["epoch"]) + 1
    else:
        params = jax.device_put(init_params(jax.random.key(0)), repl)

    def loss_fn(p, x, y):
        h = jnp.tanh(x @ p["w1"] + p["b1"])
        pred = h @ p["w2"] + p["b2"]
        return jnp.mean((pred - y) ** 2)

    @jax.jit
    def step(p, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(p, x, y)
        new_p = jax.tree.map(lambda a, g: a - 0.1 * g, p, grads)
        return new_p, loss

    batch_global = 64
    epochs = config.get("epochs", 4)
    for epoch in range(start_epoch, epochs):
        # Each process contributes its local shard of the global batch.
        x_local = rng.randn(batch_global // world, 8).astype(np.float32)
        y_local = x_local @ w_true
        from jax.experimental import multihost_utils

        x = multihost_utils.host_local_array_to_global_array(
            x_local, mesh, P("data"))
        y = multihost_utils.host_local_array_to_global_array(
            y_local, mesh, P("data"))
        params, loss = step(params, x, y)
        loss_val = float(loss)

        checkpoint = None
        if rank == 0:
            checkpoint = Checkpoint.from_pytree(
                {"params": jax.device_get(params), "epoch": epoch})
        train.report({"loss": loss_val, "epoch": epoch},
                     checkpoint=checkpoint)


@pytest.fixture(scope="module")
def train_cluster():
    import ray_tpu

    info = ray_tpu.init(num_cpus=8, num_tpus=0,
                        object_store_memory=256 * 1024 * 1024,
                        ignore_reinit_error=True)
    yield info
    ray_tpu.shutdown()


class TestJaxTrainer:
    @_needs_cpu_multiprocess
    def test_dp_training_2workers(self, train_cluster, tmp_path):
        trainer = JaxTrainer(
            mlp_train_loop,
            train_loop_config={"epochs": 4},
            scaling_config=ScalingConfig(num_workers=2),
            jax_config=JaxConfig(platform="cpu", num_cpu_devices=4),
            run_config=RunConfig(name="mlp-dp", storage_path=str(tmp_path)),
        )
        result = trainer.fit()
        assert result.metrics["epoch"] == 3
        assert len(result.metrics_dataframe) == 4
        losses = [m["loss"] for m in result.metrics_dataframe]
        assert losses[-1] < losses[0]  # actually learning
        assert result.checkpoint is not None
        state = result.checkpoint.to_pytree()
        assert state["epoch"] == 3

    @_needs_cpu_multiprocess
    def test_resume_from_checkpoint(self, train_cluster, tmp_path):
        trainer = JaxTrainer(
            mlp_train_loop,
            train_loop_config={"epochs": 2},
            scaling_config=ScalingConfig(num_workers=2),
            jax_config=JaxConfig(platform="cpu", num_cpu_devices=4),
            run_config=RunConfig(name="mlp-r1", storage_path=str(tmp_path)),
        )
        r1 = trainer.fit()
        assert r1.metrics["epoch"] == 1

        trainer2 = JaxTrainer(
            mlp_train_loop,
            train_loop_config={"epochs": 4},
            scaling_config=ScalingConfig(num_workers=2),
            jax_config=JaxConfig(platform="cpu", num_cpu_devices=4),
            run_config=RunConfig(name="mlp-r2", storage_path=str(tmp_path)),
            resume_from_checkpoint=r1.checkpoint,
        )
        r2 = trainer2.fit()
        # Resumed at epoch 2, so only epochs 2..3 ran.
        assert r2.metrics["epoch"] == 3
        assert len(r2.metrics_dataframe) == 2

    def test_single_worker(self, train_cluster, tmp_path):
        trainer = JaxTrainer(
            mlp_train_loop,
            train_loop_config={"epochs": 2},
            scaling_config=ScalingConfig(num_workers=1),
            jax_config=JaxConfig(platform="cpu", num_cpu_devices=4),
            run_config=RunConfig(name="mlp-1w", storage_path=str(tmp_path)),
        )
        result = trainer.fit()
        assert result.metrics["epoch"] == 1

    def test_failure_restart(self, train_cluster, tmp_path):
        """Worker crash mid-training: gang restarts from latest checkpoint
        (FailureConfig.max_failures, reference backend_executor._restart)."""

        def crashing_loop(config):
            import os

            from ray_tpu import train
            from ray_tpu.train import Checkpoint

            ctx = train.get_context()
            start = 0
            ckpt = ctx.get_checkpoint()
            if ckpt is not None:
                start = ckpt.to_dict()["epoch"] + 1
            marker = config["marker"]
            for epoch in range(start, 4):
                if epoch == 2 and ctx.get_world_rank() == 0 \
                        and not os.path.exists(marker):
                    open(marker, "w").close()
                    os._exit(1)  # hard crash, like a dead TPU host
                checkpoint = None
                if ctx.get_world_rank() == 0:
                    checkpoint = Checkpoint.from_dict({"epoch": epoch})
                train.report({"epoch": epoch}, checkpoint=checkpoint)

        marker = str(tmp_path / "crashed.marker")
        trainer = JaxTrainer(
            crashing_loop,
            train_loop_config={"marker": marker},
            scaling_config=ScalingConfig(num_workers=2),
            jax_config=JaxConfig(platform="cpu", num_cpu_devices=2),
            run_config=RunConfig(
                name="mlp-ft", storage_path=str(tmp_path),
                failure_config=FailureConfig(max_failures=1)),
        )
        result = trainer.fit()
        assert os.path.exists(marker)  # the crash really happened
        assert result.metrics["epoch"] == 3

    def test_failure_budget_exhausted(self, train_cluster, tmp_path):
        def always_fail(config):
            raise RuntimeError("deliberate")

        trainer = JaxTrainer(
            always_fail,
            scaling_config=ScalingConfig(num_workers=1),
            jax_config=JaxConfig(platform="cpu", num_cpu_devices=1),
            run_config=RunConfig(name="mlp-fail", storage_path=str(tmp_path)),
        )
        with pytest.raises(TrainingFailedError):
            trainer.fit()


def ingestion_train_loop(config):
    """Consumes a streaming_split Data shard (Train<->Data ingestion,
    reference `train/_internal/data_config.py`)."""
    import numpy as np

    from ray_tpu import train

    it = train.get_dataset_shard("train")
    assert it is not None, "dataset shard missing"
    w = np.zeros(4, np.float32)
    for epoch in range(config.get("epochs", 2)):
        n_rows = 0
        loss_sum = 0.0
        for batch in it.iter_batches(batch_size=16):
            x = np.stack(batch["x"]).astype(np.float32)
            y = np.asarray(batch["y"], np.float32)
            pred = x @ w
            err = pred - y
            loss_sum += float((err ** 2).sum())
            n_rows += len(y)
            w -= 0.05 * (x.T @ err) / max(len(y), 1)  # SGD on the shard
        train.report({"loss": loss_sum / max(n_rows, 1), "rows": n_rows,
                      "epoch": epoch})


class TestTrainDataIngestion:
    def test_streaming_split_feeds_two_workers(self, train_cluster, tmp_path):
        from ray_tpu import data as rdata

        rng = np.random.RandomState(7)
        xs = rng.randn(256, 4).astype(np.float32)
        w_true = np.array([1.0, -2.0, 0.5, 3.0], np.float32)
        ys = xs @ w_true
        ds = rdata.from_items(
            [{"x": xs[i], "y": float(ys[i])} for i in range(256)],
            override_num_blocks=8,
        ).map_batches(lambda b: b)  # exercise a fused transform stage

        trainer = JaxTrainer(
            ingestion_train_loop,
            train_loop_config={"epochs": 2},
            datasets={"train": ds},
            scaling_config=ScalingConfig(num_workers=2),
            jax_config=JaxConfig(platform="cpu", num_cpu_devices=1),
            run_config=RunConfig(name="ingest", storage_path=str(tmp_path)),
        )
        result = trainer.fit()
        history = result.metrics_dataframe
        # Both epochs ran and the split streamed every row exactly once
        # per epoch across the two workers (rank-0 metrics are recorded;
        # totals are per-worker so just check rows > 0 and loss decreased).
        assert result.metrics["epoch"] == 1
        assert all(m["rows"] > 0 for m in history)
        assert history[-1]["loss"] < history[0]["loss"]


# ------------------------------------------------------------ torch tier

def torch_ddp_loop(config):
    """DDP linear regression: gradients allreduce over gloo."""
    import numpy as np
    import torch
    import torch.distributed as dist

    from ray_tpu import train

    ctx = train.get_context()
    assert dist.is_initialized() and dist.get_world_size() == 2
    assert dist.get_rank() == ctx.get_world_rank()

    torch.manual_seed(0)
    model = torch.nn.Linear(4, 1)
    ddp = torch.nn.parallel.DistributedDataParallel(model)
    opt = torch.optim.SGD(ddp.parameters(), lr=0.1)
    rng = np.random.RandomState(ctx.get_world_rank())
    w_true = np.arange(1.0, 5.0, dtype=np.float32)
    for i in range(30):
        x = torch.from_numpy(rng.randn(16, 4).astype(np.float32))
        y = (x @ torch.from_numpy(w_true))[:, None]
        loss = torch.nn.functional.mse_loss(ddp(x), y)
        opt.zero_grad(); loss.backward(); opt.step()
        train.report({"loss": float(loss)})
    # DDP sync proof, asserted ACROSS ranks: allreduce would be a no-op
    # on identical replicas, so gather both ranks' weights and compare.
    w = model.weight.detach().clone()
    gathered = [torch.zeros_like(w) for _ in range(2)]
    dist.all_gather(gathered, w)
    np.testing.assert_allclose(gathered[0].numpy(), gathered[1].numpy(),
                               rtol=0, atol=1e-6)
    train.report({"loss": float(loss), "synced": True})


def test_torch_trainer_ddp_gloo(ray_start_regular):
    """TorchTrainer forms a gloo process group over the same worker-group
    machinery as JaxTrainer (reference: train/torch/config.py:146)."""
    from ray_tpu.train import ScalingConfig, TorchTrainer

    trainer = TorchTrainer(
        torch_ddp_loop,
        scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["loss"] < 0.2, result.metrics


def test_torch_config_rejects_nccl():
    from ray_tpu.train.torch_backend import TorchBackend, TorchConfig

    with pytest.raises(ValueError, match="gloo"):
        TorchBackend().on_start(
            type("G", (), {"num_workers": 2, "metadata": lambda s: [],
                           "execute_single": lambda s, *a: 0,
                           "workers": []})(),
            TorchConfig(backend="nccl"))


def test_torch_trainer_single_worker_group_forms(ray_start_regular):
    """world_size=1 still forms the gloo group: the docstring's DDP
    pattern must work at any scale."""
    from ray_tpu.train import ScalingConfig, TorchTrainer

    def loop(config):
        import torch
        import torch.distributed as dist

        from ray_tpu import train

        assert dist.is_initialized() and dist.get_world_size() == 1
        model = torch.nn.parallel.DistributedDataParallel(
            torch.nn.Linear(2, 1))
        out = model(torch.zeros(3, 2))
        train.report({"ok": float(out.shape[0])})

    result = TorchTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1)).fit()
    assert result.error is None, result.error
    assert result.metrics["ok"] == 3.0
