"""Concurrent operator execution + new training-ingest sources.

Covers VERDICT round-3 item 6: stage-2 tasks running while stage-1 still
produces (concurrent scheduler), per-op budgets/backpressure plumbing,
and TFRecord / WebDataset ingest."""

import os
import struct
import time

import pytest


# ------------------------------------------------------------ pipelining

def test_stage2_runs_while_stage1_producing(ray_start_regular, tmp_path):
    """With a slow source and an actor-map stage, the first map
    completion must land BEFORE the last source task finishes — i.e. the
    stages genuinely overlap (reference: streaming_executor.py operator
    loop)."""
    import ray_tpu
    from ray_tpu import data as rdata
    from ray_tpu.data.datasource import Datasource

    log = tmp_path / "events.log"

    class SlowSource(Datasource):
        """Tasks 0..n-2 produce after a short sleep; the LAST task
        refuses to finish until the log proves a map already ran. A
        serialized executor (maps gated on all sources) deadlocks here
        and hits the 45s timeout marker; a pipelined one sails through."""

        def __init__(self, n_tasks, log_path):
            self._n = n_tasks
            self._log = str(log_path)

        def get_read_tasks(self, parallelism):
            tasks = []
            for i in range(self._n):
                def make(i=i, log=self._log, last=(i == self._n - 1)):
                    def read():
                        import os as _os
                        import time as _t

                        from ray_tpu.data.block import BlockAccessor

                        if last:
                            deadline = _t.monotonic() + 45
                            while _t.monotonic() < deadline:
                                if (_os.path.exists(log) and any(
                                        ln.startswith("M")
                                        for ln in open(log))):
                                    with open(log, "a") as f:
                                        f.write("GATED-OK\n")
                                    break
                                _t.sleep(0.2)
                            else:
                                with open(log, "a") as f:
                                    f.write("GATED-TIMEOUT\n")
                        else:
                            _t.sleep(0.5)
                        with open(log, "a") as f:
                            f.write(f"S{i} {_t.monotonic()}\n")
                        yield BlockAccessor.from_rows(
                            [{"v": i * 10 + j} for j in range(4)])
                    return read
                tasks.append(make(i))
            return tasks

    logp = str(log)

    def mark(batch):
        with open(logp, "a") as f:
            f.write(f"M {time.monotonic()}\n")
        batch["v"] = batch["v"] * 2
        return batch

    class Marker:
        def __call__(self, batch):
            return mark(batch)

    ds = rdata.read_datasource(SlowSource(6, log)).map_batches(
        Marker, concurrency=2)
    rows = ds.take_all()
    assert sorted(r["v"] for r in rows) == sorted(
        (i * 10 + j) * 2 for i in range(6) for j in range(4))

    text = log.read_text()
    # Causal overlap proof: the last source task observed a completed
    # map while it was still running.
    assert "GATED-OK" in text, (
        "map stage only ran after ALL source tasks finished — "
        "stages are serialized, not pipelined:\n" + text)


def test_concurrent_executor_budget_and_policies(ray_start_regular):
    """Budget slots derive from cluster CPUs; chains complete correctly
    through the concurrent scheduler."""
    from ray_tpu import data as rdata
    from ray_tpu.data._internal.concurrent_executor import (
        ConcurrentExecutor,
    )

    slots = ConcurrentExecutor.budgets(2)
    assert slots >= 2

    ds = rdata.range(64, override_num_blocks=8).map_batches(
        lambda b: {"id": b["id"] + 1}).map_batches(
        _Plus2, concurrency=2)
    assert sorted(r["id"] for r in ds.take_all()) == list(range(3, 67))


def test_tiny_output_buffer_with_straggler_no_deadlock(ray_start_regular):
    """Regression: a straggling FIRST source task parks many later
    sequence numbers in the final reorder buffer. With a tiny
    OutputBufferPolicy cap this must still complete — the final op is
    exempt from the output-buffer count, else the straggler's own map
    task could never launch (permanent deadlock + busy spin)."""
    from ray_tpu.data._internal import plan as plan_mod
    from ray_tpu.data._internal.concurrent_executor import (
        ConcurrencyCapPolicy, OutputBufferPolicy, build_pipeline,
    )
    from ray_tpu.data.datasource import Datasource
    from ray_tpu.data.block import BlockAccessor

    class StragglerFirst(Datasource):
        def get_read_tasks(self, parallelism):
            tasks = []
            for i in range(20):
                def make(i=i):
                    def read():
                        import time as _t

                        if i == 0:
                            _t.sleep(2.5)  # every other task beats it
                        yield BlockAccessor.from_rows([{"v": i}])
                    return read
                tasks.append(make(i))
            return tasks

    pipe = build_pipeline(
        plan_mod.Read(StragglerFirst(), -1), None,
        [[plan_mod.MapBatches(lambda b: {"v": b["v"] * 3},
                              batch_size=None, batch_format="numpy")]],
        policies=(ConcurrencyCapPolicy(), OutputBufferPolicy(2)))
    assert pipe is not None
    import time as _t

    t0 = _t.monotonic()
    blocks = list(pipe.stream())
    assert _t.monotonic() - t0 < 60
    vals = sorted(int(r["v"]) for b in blocks
                  for r in BlockAccessor(b).rows())
    assert vals == [i * 3 for i in range(20)]


class _Plus2:
    def __call__(self, batch):
        batch["id"] = batch["id"] + 2
        return batch


# ------------------------------------------------------------- tfrecords

def _varint(n: int) -> bytes:
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _ld(field: int, payload: bytes) -> bytes:
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


def _tf_example(features: dict) -> bytes:
    body = b""
    for key, value in features.items():
        if isinstance(value, bytes):
            flist = _ld(1, _ld(1, value))                  # bytes_list
        elif isinstance(value, float):
            flist = _ld(2, _ld(1, struct.pack("<f", value)))  # float_list
        else:
            flist = _ld(3, _ld(1, _varint(int(value))))    # int64_list
        entry = _ld(1, key.encode()) + _ld(2, flist)
        body += _ld(1, entry)
    return _ld(1, body)  # Example.features


def _write_tfrecord(path, examples):
    with open(path, "wb") as f:
        for ex in examples:
            payload = _tf_example(ex)
            f.write(struct.pack("<Q", len(payload)))
            f.write(b"\x00\x00\x00\x00")  # length crc (unverified)
            f.write(payload)
            f.write(b"\x00\x00\x00\x00")  # data crc


def test_read_tfrecords(ray_start_regular, tmp_path):
    from ray_tpu import data as rdata

    path = tmp_path / "data.tfrecord"
    _write_tfrecord(path, [
        {"label": 3, "text": b"hello", "weight": 1.5},
        {"label": 7, "text": b"world", "weight": 2.5},
    ])
    rows = rdata.read_tfrecords(str(path)).take_all()
    assert len(rows) == 2
    by_label = {r["label"]: r for r in rows}
    assert by_label[3]["text"] == b"hello"
    assert by_label[7]["weight"] == pytest.approx(2.5)


def test_read_tfrecords_list_features(ray_start_regular, tmp_path):
    """Multi-value feature lists survive as lists; packed int64 lists
    decode."""
    from ray_tpu import data as rdata

    # int64_list with three packed varints.
    flist = _ld(3, _ld(1, _varint(1) + _varint(200) + _varint(300000)))
    entry = _ld(1, b"ids") + _ld(2, flist)
    payload = _ld(1, _ld(1, entry))
    path = tmp_path / "lists.tfrecord"
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(payload)) + b"\0\0\0\0"
                + payload + b"\0\0\0\0")
    rows = rdata.read_tfrecords(str(path)).take_all()
    assert list(rows[0]["ids"]) == [1, 200, 300000]


# ------------------------------------------------------------- webdataset

def test_read_webdataset(ray_start_regular, tmp_path):
    import io
    import json
    import tarfile

    from ray_tpu import data as rdata

    shard = tmp_path / "shard-000000.tar"
    with tarfile.open(shard, "w") as tar:
        def add(name, data: bytes):
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))

        add("sample_a.jpg", b"\xff\xd8JPGDATA")
        add("sample_a.cls", b"3")
        add("sample_a.json", json.dumps({"caption": "a cat"}).encode())
        add("sample_b.jpg", b"\xff\xd8OTHER")
        add("sample_b.cls", b"7")

    rows = rdata.read_webdataset(str(shard)).take_all()
    assert len(rows) == 2
    by_key = {r["__key__"]: r for r in rows}
    assert by_key["sample_a"]["cls"] == 3
    assert by_key["sample_a"]["jpg"] == b"\xff\xd8JPGDATA"
    assert by_key["sample_a"]["json"]["caption"] == "a cat"
    assert by_key["sample_b"]["cls"] == 7
