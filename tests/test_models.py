"""Model + parallel stack: llama forward/loss and dp/fsdp/tp parity on the
8-virtual-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models.llama import (
    LlamaConfig, forward, init_params, loss_fn,
)
from ray_tpu.parallel import (
    TrainState, batch_sharding, build_train_step, create_train_state,
    llama_param_shardings, make_mesh, shard_params,
)

CFG = LlamaConfig.tiny()


def _batch(bsz=8, seq=16, seed=0):
    rng = np.random.RandomState(seed)
    return {"tokens": jnp.asarray(
        rng.randint(0, CFG.vocab_size, (bsz, seq)), jnp.int32)}


class TestLlamaModel:
    def test_forward_shapes(self):
        params = init_params(CFG, jax.random.key(0))
        logits = forward(params, _batch()["tokens"], CFG)
        assert logits.shape == (8, 16, CFG.vocab_size)
        assert logits.dtype == jnp.float32

    def test_loss_finite_and_near_uniform(self):
        params = init_params(CFG, jax.random.key(0))
        loss = loss_fn(params, _batch(), CFG)
        assert np.isfinite(float(loss))
        # Random init => loss close to ln(vocab).
        assert abs(float(loss) - np.log(CFG.vocab_size)) < 1.0

    def test_causality(self):
        """Changing a future token must not affect earlier logits."""
        params = init_params(CFG, jax.random.key(0))
        toks = _batch(2, 16)["tokens"]
        logits1 = forward(params, toks, CFG)
        toks2 = toks.at[:, -1].set((toks[:, -1] + 1) % CFG.vocab_size)
        logits2 = forward(params, toks2, CFG)
        np.testing.assert_allclose(np.asarray(logits1[:, :-1]),
                                   np.asarray(logits2[:, :-1]),
                                   rtol=1e-4, atol=1e-4)

    def test_gqa_heads(self):
        cfg = LlamaConfig.tiny(n_heads=4, n_kv_heads=1)
        params = init_params(cfg, jax.random.key(0))
        logits = forward(params, _batch()["tokens"], cfg)
        assert logits.shape[-1] == cfg.vocab_size

    def test_remat_matches(self):
        cfg = LlamaConfig.tiny(remat=True)
        params = init_params(CFG, jax.random.key(0))
        l1 = loss_fn(params, _batch(), CFG)
        l2 = loss_fn(params, _batch(), cfg)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)

    def test_num_params_matches(self):
        params = init_params(CFG, jax.random.key(0))
        actual = sum(x.size for x in jax.tree.leaves(params))
        assert actual == CFG.num_params()


def _reference_step(params, batch, lr=0.01):
    loss, grads = jax.value_and_grad(
        lambda p, b: loss_fn(p, b, CFG))(params, batch)
    new = jax.tree.map(lambda a, g: a - lr * g, params, grads)
    return new, loss


class TestShardedTraining:
    @pytest.mark.parametrize("axes", [
        {"data": -1},                       # pure DP over 8
        {"fsdp": -1},                       # ZeRO-style over 8
        {"data": 2, "fsdp": 2, "tensor": 2},  # 3-way combo
        {"data": 4, "tensor": 2},           # DP x TP
    ])
    def test_parity_with_single_device(self, axes):
        """A sharded pjit step must produce the same loss trajectory as the
        unsharded single-device step (GSPMD correctness)."""
        mesh = make_mesh(axes)
        params = init_params(CFG, jax.random.key(0))
        sh = llama_param_shardings(CFG, mesh)
        bs = batch_sharding(mesh)
        opt = optax.sgd(0.01)
        sharded_params = shard_params(params, sh)
        state = create_train_state(sharded_params, opt)
        step = build_train_step(
            lambda p, b: loss_fn(p, b, CFG), opt, mesh, sh, bs)

        # Fresh tree: device_put may alias buffers that donation later
        # invalidates, so the reference must not share storage.
        ref_params = init_params(CFG, jax.random.key(0))
        for i in range(3):
            batch = _batch(seed=i)
            gbatch = jax.device_put(batch, bs)
            state, metrics = step(state, gbatch)
            ref_params, ref_loss = _reference_step(ref_params, batch)
            np.testing.assert_allclose(float(metrics["loss"]),
                                       float(ref_loss), rtol=2e-2, atol=2e-2)

    def test_grad_accum(self):
        mesh = make_mesh({"data": -1})
        params = init_params(CFG, jax.random.key(0))
        sh = llama_param_shardings(CFG, mesh)
        bs = batch_sharding(mesh)
        opt = optax.sgd(0.01)
        state = create_train_state(shard_params(params, sh), opt)
        step = build_train_step(lambda p, b: loss_fn(p, b, CFG), opt, mesh,
                                sh, bs, grad_accum=2)
        state, metrics = step(state, jax.device_put(_batch(16, 16), bs))
        assert np.isfinite(float(metrics["loss"]))

    def test_tp_must_divide_kv_heads(self):
        mesh = make_mesh({"tensor": 8})
        with pytest.raises(ValueError, match="n_kv_heads"):
            llama_param_shardings(LlamaConfig.tiny(n_kv_heads=2), mesh)


class TestMakeMeshErrors:
    """Mesh-shape mismatches must say what JAX actually discovered."""

    def test_mismatch_lists_devices_and_platform(self):
        with pytest.raises(ValueError) as e:
            make_mesh({"data": 3, "tensor": 5})   # 15 != 8
        msg = str(e.value)
        assert "needs 15 devices but 8 are available" in msg
        assert "discovered 8 device(s)" in msg
        assert "platform cpu" in msg
        assert "TFRT_CPU_0" in msg   # the actual device listing

    def test_indivisible_wildcard_names_the_axis(self):
        with pytest.raises(ValueError) as e:
            make_mesh({"data": -1, "tensor": 3})  # 8 % 3 != 0
        msg = str(e.value)
        assert "cannot infer axis 'data'" in msg
        assert "not divisible by the fixed-axis product 3" in msg
        assert "discovered 8 device(s)" in msg


class TestMultiHostDiscovery:
    """discover_devices joins jax.distributed exactly once, and only
    when coordinator env vars mark a multi-host launch (MULTICHIP_r05:
    make_mesh saw 1 local device and rejected fsdp=4 because the global
    list is only visible after the join)."""

    def _reset(self, monkeypatch):
        from ray_tpu.parallel import mesh as mesh_mod
        for v in mesh_mod._COORDINATOR_VARS:
            monkeypatch.delenv(v, raising=False)
        monkeypatch.setattr(mesh_mod, "_distributed_join_attempted",
                            False)
        return mesh_mod

    def test_single_host_never_initializes(self, monkeypatch):
        mesh_mod = self._reset(monkeypatch)
        calls = []
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda *a, **k: calls.append(1))
        assert len(mesh_mod.discover_devices()) == 8
        assert not calls                     # no coordinator: no join

    def test_multihost_env_joins_once(self, monkeypatch):
        mesh_mod = self._reset(monkeypatch)
        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:8476")
        calls = []
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda *a, **k: calls.append(1))
        mesh_mod.discover_devices()
        mesh_mod.discover_devices()          # once-guard
        assert len(calls) == 1

    def test_failed_join_falls_back_to_local(self, monkeypatch):
        mesh_mod = self._reset(monkeypatch)
        monkeypatch.setenv("COORDINATOR_ADDRESS", "10.0.0.1:8476")

        def boom(*a, **k):
            raise RuntimeError("unreachable coordinator")

        monkeypatch.setattr(jax.distributed, "initialize", boom)
        assert len(mesh_mod.discover_devices()) == 8
        assert make_mesh({"data": -1}).devices.size == 8

    def test_make_mesh_uses_global_discovery(self, monkeypatch):
        """The multi-axis request that failed in the field must work
        once discovery goes through the distributed join."""
        mesh_mod = self._reset(monkeypatch)
        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:8476")
        calls = []
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda *a, **k: calls.append(1))
        mesh = make_mesh({"fsdp": 4, "tensor": 2})
        assert calls and mesh.shape["fsdp"] == 4

    def test_mesh_errors_report_process_topology(self):
        with pytest.raises(ValueError) as e:
            make_mesh({"data": 3, "tensor": 5})
        assert "process 0 of 1" in str(e.value)
