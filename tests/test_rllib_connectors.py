"""Connector pipelines (reference: `rllib/connectors/connector_v2.py` —
env→module and module→learner transformation stages). Unit tests per
stage + PPO CartPole learning through a 3-stage pipeline without the
runner hard-coding any preprocessing."""

import numpy as np
import pytest

from ray_tpu.rllib.connectors import (
    ClipObs, ConnectorPipeline, FrameStack, ObsNormalizer, RecurrentState,
    build_pipeline,
)


@pytest.fixture(scope="module")
def conn_cluster():
    import ray_tpu

    info = ray_tpu.init(num_cpus=8, num_tpus=0,
                        object_store_memory=256 * 1024 * 1024,
                        ignore_reinit_error=True)
    yield info
    ray_tpu.shutdown()


# ------------------------------------------------------------------- units
def test_obs_normalizer_stats_and_clip():
    norm = ObsNormalizer(clip=2.0)
    rng = np.random.RandomState(0)
    data = rng.normal(5.0, 3.0, (200, 4)).astype(np.float32)
    for i in range(0, 200, 8):
        out = norm.env_to_module(data[i:i + 8])
    assert out.shape == (8, 4)
    # After 200 samples the normalized stream is ~zero-mean unit-var.
    normed = norm.peek(data)
    assert abs(float(normed.mean())) < 0.2
    assert abs(float(normed.std()) - 1.0) < 0.3
    assert float(np.max(np.abs(normed))) <= 2.0  # clip applied
    # peek must not advance the stats.
    before = norm.get_state()["count"]
    norm.peek(data)
    assert norm.get_state()["count"] == before
    # module_to_learner normalizes next_obs with the same stats.
    b = norm.module_to_learner({"next_obs": data[:10]})
    np.testing.assert_allclose(b["next_obs"], normed[:10], atol=1e-5)


def test_frame_stack_lanes_and_resets():
    fs = FrameStack(k=3)
    fs.reset(2)
    o1 = np.array([[1.0, 1.0], [10.0, 10.0]], np.float32)
    o2 = o1 * 2
    o3 = o1 * 3
    s1 = fs.env_to_module(o1, np.zeros(2, bool))
    np.testing.assert_allclose(s1[0], [0, 0, 0, 0, 1, 1])  # zero-padded
    s2 = fs.env_to_module(o2, np.zeros(2, bool))
    s3 = fs.env_to_module(o3, np.zeros(2, bool))
    np.testing.assert_allclose(s3[0], [1, 1, 2, 2, 3, 3])
    # Lane 1 resets: its stack clears, lane 0's survives.
    s4 = fs.env_to_module(o1 * 4, np.array([False, True]))
    np.testing.assert_allclose(s4[0], [2, 2, 3, 3, 4, 4])
    np.testing.assert_allclose(s4[1], [0, 0, 0, 0, 40, 40])
    # peek simulates the next stack without committing it.
    peeked = fs.peek(o1 * 5)
    np.testing.assert_allclose(peeked[0], [3, 3, 4, 4, 5, 5])
    np.testing.assert_allclose(fs._buf[0, -1], [4, 4])  # unchanged

    # module_to_learner: next stack = drop oldest + append successor.
    batch = {"obs": np.stack([s2, s3])[:, :1],          # [T=2, N=1, 6]
             "next_obs": np.stack([o3, o1 * 4])[:, :1]}  # [T=2, N=1, 2]
    out = fs.module_to_learner(batch)
    np.testing.assert_allclose(out["next_obs"][0, 0], [1, 1, 2, 2, 3, 3])
    np.testing.assert_allclose(out["next_obs"][1, 0], [2, 2, 3, 3, 4, 4])


def test_frame_stack_widens_observation_space():
    from ray_tpu.rllib.env.spaces import Box

    space = Box(low=np.full(4, -1.0, np.float32),
                high=np.full(4, 1.0, np.float32))
    wide = FrameStack(k=2).transform_observation_space(space)
    assert int(np.prod(wide.shape)) == 8


def test_frame_stack_bounds_match_data_layout():
    """Stacked obs are frame-major; bounds must tile whole frames so
    bound[i] brackets element [i] of the actual stacked vector."""
    from ray_tpu.rllib.env.spaces import Box

    space = Box(low=np.array([0.0, -5.0], np.float32),
                high=np.array([1.0, 5.0], np.float32))
    fs = FrameStack(k=2)
    wide = fs.transform_observation_space(space)
    np.testing.assert_array_equal(wide.low, [0.0, -5.0, 0.0, -5.0])
    np.testing.assert_array_equal(wide.high, [1.0, 5.0, 1.0, 5.0])
    fs.reset(1)
    stacked = fs.env_to_module(np.array([[0.5, -4.0]], np.float32))
    stacked = fs.env_to_module(np.array([[1.0, 4.0]], np.float32))
    assert np.all(stacked[0] >= wide.low - 1e-6)
    assert np.all(stacked[0] <= wide.high + 1e-6)


def test_recurrent_state_resets_and_trace():
    rs = RecurrentState(state_size=3)
    rs.reset(2)
    s0 = rs.state_for_step(2, None)
    assert (s0 == 0).all()
    rs.observe_state_out(np.ones((2, 3), np.float32))
    s1 = rs.state_for_step(2, np.array([False, True]))
    np.testing.assert_allclose(s1[0], [1, 1, 1])
    np.testing.assert_allclose(s1[1], [0, 0, 0])  # lane reset
    batch = rs.module_to_learner({"obs": np.zeros((2, 2, 1))})
    assert batch["state_in"].shape == (2, 2, 3)
    np.testing.assert_allclose(batch["state_in"][0], 0.0)


def test_pipeline_composition_and_state_roundtrip():
    pipe = build_pipeline([lambda: ObsNormalizer(clip=5.0),
                           lambda: FrameStack(2), ClipObs(-4, 4)])
    assert isinstance(pipe, ConnectorPipeline)
    pipe.reset(2)
    obs = np.array([[1.0, -1.0], [2.0, -2.0]], np.float32)
    out = pipe.env_to_module(obs, np.zeros(2, bool))
    assert out.shape == (2, 4)            # stacked by the middle stage
    state = pipe.get_state()
    pipe2 = build_pipeline([lambda: ObsNormalizer(clip=5.0),
                            lambda: FrameStack(2), ClipObs(-4, 4)])
    pipe2.set_state(state)
    np.testing.assert_allclose(pipe2.peek(obs), pipe.peek(obs))


# --------------------------------------------------------------------- e2e
def test_ppo_learns_through_three_stage_pipeline(conn_cluster):
    """PPO CartPole through ObsNormalizer -> FrameStack(2) -> ClipObs:
    the module's input is the WIDENED, normalized view, preprocessing is
    pipeline config (no runner edits), and learning still works
    (VERDICT r4 next-4)."""
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    config = (
        PPOConfig()
        .environment("CartPole-v1")
        .training(lr=1e-3, train_batch_size=2048, num_epochs=10,
                  minibatch_size=256, gamma=0.99, gae_lambda=0.95,
                  entropy_coeff=0.01)
        .env_runners(num_env_runners=2, num_envs_per_runner=8,
                     connectors=[lambda: ObsNormalizer(clip=10.0),
                                 lambda: FrameStack(2),
                                 lambda: ClipObs(-10, 10)])
        .learners(num_learners=1, jax_platform="cpu")
    )
    algo = config.build()
    try:
        # The module was built over the stacked (2x4=8-dim) space.
        assert int(np.prod(
            algo.module_spec.observation_space.shape)) == 8
        best = 0.0
        for _ in range(30):
            result = algo.train()
            best = max(best, result.get("episode_return_mean", 0.0))
            if best >= 300:
                break
        assert best >= 300, f"pipeline PPO best return {best} < 300"
        # Runner-side pipeline state is observable (normalizer saw data).
        import ray_tpu

        st = ray_tpu.get(
            algo.env_runners[0].get_connector_state.remote(), timeout=60)
        assert st[0]["count"] > 1000
    finally:
        algo.stop()
